"""Mixtral-style sparse Mixture-of-Experts decoder, functional JAX.

Second model family of the in-tree serving/training path (the reference
runtime has no model math — SURVEY.md §2.9; this widens the TPU build's
model zoo alongside :mod:`kukeon_tpu.models.llama` and gives the ``expert``
mesh axis a real workload).

TPU-first design:

- **Same attention trunk as Llama** (GQA + RoPE + RMSNorm, stacked layers
  under ``lax.scan``, the shared KVCache layout) — the MoE block replaces
  only the dense SwiGLU MLP, exactly like Mixtral-vs-Mistral.
- **Dense-dispatch MoE (GShard/Switch formulation)**: routing is expressed
  as two einsums against a static-capacity one-hot dispatch tensor instead
  of gather/scatter with dynamic shapes. Everything is a fixed-shape batched
  matmul over a leading ``E`` axis — MXU-friendly, one compiled program —
  and sharding ``E`` over the mesh's ``expert`` axis makes GSPMD insert the
  dispatch/combine all-to-alls over ICI.
- **Static capacity**: each expert processes at most
  ``capacity_factor * tokens * top_k / num_experts`` tokens; overflow tokens
  fall through the residual (standard GShard semantics). Tests use a
  capacity factor that guarantees no drops when checking numerics.
- **Aux losses for training**: Switch load-balance loss + router z-loss,
  returned by :func:`forward_with_aux`; :func:`forward` keeps the exact
  serving signature of ``llama.forward`` (logits, cache).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kukeon_tpu.models import llama
from kukeon_tpu.models.llama import KVCache, _cache_insert, _embed, _mm
from kukeon_tpu.ops.attention import gqa_attention
from kukeon_tpu.ops.norms import rms_norm
from kukeon_tpu.ops.rope import apply_rope

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    num_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 2.0
    rope_theta: float = 1_000_000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    router_z_coef: float = 1e-3
    load_balance_coef: float = 1e-2
    # Route quantized decode matmuls (attention trunk via llama._mm, expert
    # stacks via ops.int8_matmul.int8_matmul_expert) through the Pallas
    # int8 kernel — same contract as LlamaConfig.int8_pallas, same engine
    # auto-routing, XLA fallback off-TPU. Prefill always keeps XLA's
    # dequant-fused dots (MXU-bound there).
    int8_pallas: bool = False

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim


def mixtral_8x7b() -> MoEConfig:
    """Mixtral-8x7B shapes (public architecture)."""
    return MoEConfig()


def moe_tiny() -> MoEConfig:
    """Test-size config: fast on a CPU mesh; 4 experts so expert=2 shards."""
    return MoEConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        num_experts=4, experts_per_token=2, capacity_factor=8.0,
        rope_theta=10_000.0, max_seq_len=256, dtype=jnp.float32,
        tie_embeddings=True,
    )


def init_params(key: jax.Array, cfg: MoEConfig) -> Params:
    """Random-init. Layout (stacked layers axis 0, experts axis 1):

      embed:   [V, H]
      layers:  attn_norm/mlp_norm [L, H], wq [L, H, NH*D], wk/wv [L, H, KV*D],
               wo [L, NH*D, H], router [L, H, E],
               w_gate/w_up [L, E, H, I], w_down [L, E, I, H]
      final_norm: [H];  lm_head: [H, V] (absent when tie_embeddings)
    """
    c = cfg
    keys = iter(jax.random.split(key, 16))

    def dense(k, shape, fan_in):
        scale = fan_in ** -0.5
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(c.dtype)

    L, H, I, V, E = (c.num_layers, c.hidden_size, c.intermediate_size,
                     c.vocab_size, c.num_experts)
    params: Params = {
        "embed": dense(next(keys), (V, H), H),
        "layers": {
            "attn_norm": jnp.ones((L, H), c.dtype),
            "wq": dense(next(keys), (L, H, c.q_dim), H),
            "wk": dense(next(keys), (L, H, c.kv_dim), H),
            "wv": dense(next(keys), (L, H, c.kv_dim), H),
            "wo": dense(next(keys), (L, c.q_dim, H), c.q_dim),
            "mlp_norm": jnp.ones((L, H), c.dtype),
            # Router in f32: tiny, and routing decisions should not wobble
            # with the activation dtype.
            "router": jax.random.normal(next(keys), (L, H, E), jnp.float32) * (H ** -0.5),
            "w_gate": dense(next(keys), (L, E, H, I), H),
            "w_up": dense(next(keys), (L, E, H, I), H),
            "w_down": dense(next(keys), (L, E, I, H), I),
        },
        "final_norm": jnp.ones((H,), c.dtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense(next(keys), (H, V), H)
    return params


def quantize_params(params: Params) -> Params:
    """bf16 MoE pytree -> int8 ({"q", "s"} leaves for every dense matrix).

    Attention/embed quantize exactly like the Llama tree (llama._mm
    consumes them); expert stacks [L, E, in, out] quantize per output
    channel along the contraction axis (s: [L, E, out], applied fused in
    the expert einsums). The router stays f32 — it is tiny and routing
    decisions must not wobble with quantization noise. Weights-only int8
    halves HBM bytes/token, the decode bottleneck (mixtral-8x7b: ~93 GB
    bf16 -> ~47 GB int8 across a v5e-8)."""

    def q(w, axis):
        qw, s = llama._int8_sym(w, axis)
        return {"q": qw, "s": jnp.squeeze(s, axis=axis)}

    L = params["layers"]
    out: Params = {
        "embed": q(params["embed"], 1),
        "layers": {
            "attn_norm": L["attn_norm"],
            "wq": q(L["wq"], 1), "wk": q(L["wk"], 1), "wv": q(L["wv"], 1),
            "wo": q(L["wo"], 1),
            "mlp_norm": L["mlp_norm"],
            "router": L["router"],
            "w_gate": q(L["w_gate"], 2),       # [L, E, H, I] -> s [L, E, I]
            "w_up": q(L["w_up"], 2),
            "w_down": q(L["w_down"], 2),       # [L, E, I, H] -> s [L, E, H]
        },
        "final_norm": params["final_norm"],
    }
    if "lm_head" in params:
        out["lm_head"] = q(params["lm_head"], 0)
    return out


def init_quantized_params_host(cfg: MoEConfig, seed: int = 0) -> Params:
    """Random-init DIRECTLY in int8 on the host, leaf by leaf (mirrors
    llama.init_quantized_params_host: a mixtral-8x7b bf16 tree is ~93 GB —
    it cannot be materialized on a 16 GB chip just to be quantized)."""
    import numpy as np

    from kukeon_tpu.models.llama import quantize_np

    c = cfg
    rng = np.random.default_rng(seed)
    L, H, I, V, E = (c.num_layers, c.hidden_size, c.intermediate_size,
                     c.vocab_size, c.num_experts)
    ndtype = np.dtype(c.dtype)

    def q(shape, fan_in, axis):
        w = rng.standard_normal(shape, np.float32) * (fan_in ** -0.5)
        return quantize_np(w, axis)

    params: Params = {
        "embed": q((V, H), H, 1),
        "layers": {
            "attn_norm": np.ones((L, H), ndtype),
            "wq": q((L, H, c.q_dim), H, 1),
            "wk": q((L, H, c.kv_dim), H, 1),
            "wv": q((L, H, c.kv_dim), H, 1),
            "wo": q((L, c.q_dim, H), c.q_dim, 1),
            "mlp_norm": np.ones((L, H), ndtype),
            "router": (rng.standard_normal((L, H, E), np.float32)
                       * (H ** -0.5)),
            "w_gate": q((L, E, H, I), H, 2),
            "w_up": q((L, E, H, I), H, 2),
            "w_down": q((L, E, I, H), I, 2),
        },
        "final_norm": np.ones((H,), ndtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = q((H, V), H, 0)
    return params


def _expert_mm(x: jnp.ndarray, w, eq: str, pallas: bool = False) -> jnp.ndarray:
    """Per-expert batched matmul ('ech,ehi->eci' or 'eci,eih->ech') for
    plain or int8 ({"q","s"}) expert stacks; dequant fuses into the dot.

    ``pallas=True`` routes int8 stacks through the Pallas decode kernel
    (both einsums above are x [E, C, K] @ w [E, K, N], so one helper covers
    them); the helper itself falls back to the XLA fused einsum for odd
    shapes, prefill-sized C, or non-TPU backends."""
    if llama._is_q(w):
        if pallas:
            from kukeon_tpu.ops.int8_matmul import int8_matmul_expert

            return int8_matmul_expert(x, w["q"], w["s"])
        raw = jnp.einsum(eq, x, w["q"].astype(x.dtype))
        return raw * w["s"][:, None, :].astype(x.dtype)
    return jnp.einsum(eq, x, w)


def _capacity(cfg: MoEConfig, n_tokens: int, inference: bool = False) -> int:
    """Per-expert token capacity.

    Training uses the GShard drop policy (capacity_factor × fair share;
    overflow tokens fall through the residual — standard, and the
    load-balance loss keeps drops rare). Inference must not silently drop
    expert compute (reference Mixtral always runs both top-k experts):
    decode-sized batches get FULL capacity (C = N, exact for any routing —
    the dispatch tensor is a few KB), and prefill gets a 2× wider buffer
    than training, making drops possible only under extreme routing
    concentration (>8× the fair share for the 8x7B config)."""
    E, K = cfg.num_experts, cfg.experts_per_token
    if inference:
        if n_tokens <= 64:
            return n_tokens
        factor = max(cfg.capacity_factor, 2.0) * 2.0
        return min(n_tokens, max(int(factor * n_tokens * K / E), K))
    cap = int(cfg.capacity_factor * n_tokens * K / E)
    return max(cap, K)


def moe_block(h: jnp.ndarray, w: dict, cfg: MoEConfig,
              inference: bool = False,
              pallas: bool = False) -> tuple[jnp.ndarray, dict]:
    """Sparse-MoE SwiGLU over [B, S, H] -> ([B, S, H], aux losses).

    GShard dense-dispatch: top-k routing -> static-capacity one-hot dispatch
    tensor -> two einsums around batched per-expert matmuls. All shapes are
    static; with ``w_gate``'s E axis sharded on the mesh's ``expert`` axis,
    XLA partitions the expert matmuls per chip and inserts all-to-alls for
    the dispatch/combine einsums.
    """
    c = cfg
    B, S, H = h.shape
    N = B * S
    E, K = c.num_experts, c.experts_per_token
    C = _capacity(c, N, inference)
    x = h.reshape(N, H)

    router_logits = x.astype(jnp.float32) @ w["router"]          # [N, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, K)              # [N, K]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # Priority dispatch: choice slot 0 of every token beats slot 1 (GShard).
    # mask: [K, N, E]; position_in_expert via a cumulative count over the
    # flattened (K, N) order.
    mask = jax.nn.one_hot(expert_idx.T, E, dtype=jnp.float32)    # [K, N, E]
    flat = mask.reshape(K * N, E)
    pos = jnp.cumsum(flat, axis=0) - flat                        # tokens ahead
    keep = (pos < C).astype(jnp.float32) * flat                  # drop overflow
    # dispatch [N, E, C]: one-hot of each kept (token, choice) -> its slot.
    slot = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)
    dispatch = (keep[..., None] * slot).reshape(K, N, E, C).sum(axis=0)
    combine = dispatch * (
        (mask * gate_vals.T[..., None]).sum(axis=0)[..., None]   # [N, E, 1]
    )

    # Dispatch -> per-expert batches -> SwiGLU -> combine.
    xe = jnp.einsum("nec,nh->ech", dispatch, x).astype(c.dtype)  # [E, C, H]
    gate = jax.nn.silu(
        _expert_mm(xe, w["w_gate"], "ech,ehi->eci", pallas).astype(jnp.float32)
    ).astype(c.dtype)
    up = _expert_mm(xe, w["w_up"], "ech,ehi->eci", pallas)
    ye = _expert_mm(gate * up, w["w_down"], "eci,eih->ech", pallas)  # [E, C, H]
    y = jnp.einsum("nec,ech->nh", combine.astype(c.dtype), ye)

    # Aux losses (f32): Switch load-balance (E * sum_e f_e * P_e; 1.0 at
    # perfect balance) over FIRST-choice assignments, + router z-loss.
    f = jnp.mean(mask[0], axis=0)                                # [E]
    p = jnp.mean(probs, axis=0)                                  # [E]
    lb = E * jnp.sum(f * p)
    z = jnp.mean(jax.nn.logsumexp(router_logits, axis=-1) ** 2)
    return y.reshape(B, S, H), {"load_balance": lb, "router_z": z}


def _decode_forward(
    params: Params,
    c: MoEConfig,
    x: jnp.ndarray,
    positions: jnp.ndarray,
    cache: KVCache,
    B: int,
) -> tuple[jnp.ndarray, KVCache]:
    """Single-token decode, HBM-optimal (mirrors llama._decode_forward: the
    layer scan reads the cache as a read-only input and emits only the tiny
    per-layer new K/V; the cache is updated once per step with per-slot
    in-place slice writes — cache bytes stream through HBM exactly once).
    The MoE block runs at N = B tokens, where dense dispatch is a few KB
    and capacity is exact (no drops). With ``cfg.int8_pallas`` every
    quantized matmul — attention trunk and expert stacks — reads int8
    straight from HBM through the Pallas kernel instead of materializing a
    dequantized copy per step."""
    from kukeon_tpu.ops.attention import decode_gqa_attention

    offsets = cache.lengths
    pl8 = c.int8_pallas

    def layer_step(x, layer):
        w, ck, cv = layer
        h = rms_norm(x, w["attn_norm"], c.rms_norm_eps)
        q = _mm(h, w["wq"], pl8).reshape(B, 1, c.num_heads, c.head_dim)
        k = _mm(h, w["wk"], pl8).reshape(B, 1, c.num_kv_heads, c.head_dim)
        v = _mm(h, w["wv"], pl8).reshape(B, 1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, positions, c.rope_theta)
        k = apply_rope(k, positions, c.rope_theta)

        attn = decode_gqa_attention(q, k, v, ck, cv, offsets)
        x = x + _mm(attn.reshape(B, 1, c.q_dim), w["wo"], pl8)

        h = rms_norm(x, w["mlp_norm"], c.rms_norm_eps)
        y, _ = moe_block(h, w, c, inference=True, pallas=pl8)
        return x + y, (k, v)

    x, (new_k, new_v) = jax.lax.scan(
        lambda carry, layer: layer_step(carry, (layer[0], layer[1], layer[2])),
        x,
        (params["layers"], cache.k, cache.v),
    )
    k_upd, v_upd = cache.k, cache.v
    for b in range(B):
        start = (0, b, offsets[b], 0, 0)
        k_upd = jax.lax.dynamic_update_slice(k_upd, new_k[:, b : b + 1], start)
        v_upd = jax.lax.dynamic_update_slice(v_upd, new_v[:, b : b + 1], start)
    new_cache = KVCache(k=k_upd, v=v_upd, lengths=cache.lengths + 1)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    return llama._logits(params, c, x, pl8), new_cache


def forward_with_aux(
    params: Params,
    cfg: MoEConfig,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    cache: KVCache | None = None,
    attn_impl: str = "auto",
    logit_positions: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, KVCache | None, dict]:
    """Run the MoE decoder; returns (logits, cache', aux-loss dict).

    Cache semantics identical to ``llama.forward`` (same KVCache layout, so
    the serving engine's insert/decode programs carry over unchanged);
    ``logit_positions`` [B] restricts the LM head to one position per
    sequence exactly as in ``llama.forward`` (logits come back [B, 1, V]).

    A cache marks the inference path: expert capacity switches to the
    no-drop/wide policy (see :func:`_capacity`) — serving must not silently
    zero overflow tokens' expert compute the way the training drop policy
    legitimately does."""
    c = cfg
    B, S = tokens.shape
    inference = cache is not None
    x = _embed(params, tokens, c.dtype)

    if cache is not None and S == 1 and attn_impl in ("auto", "reference"):
        logits, new_cache = _decode_forward(params, c, x, positions, cache, B)
        return logits, new_cache, {"load_balance": jnp.float32(0.0),
                                   "router_z": jnp.float32(0.0)}

    offsets = cache.lengths if cache is not None else None

    def layer_step(carry, layer):
        x, lb_sum, z_sum = carry
        w, layer_cache = layer
        h = rms_norm(x, w["attn_norm"], c.rms_norm_eps)
        q = _mm(h, w["wq"]).reshape(B, S, c.num_heads, c.head_dim)
        k = _mm(h, w["wk"]).reshape(B, S, c.num_kv_heads, c.head_dim)
        v = _mm(h, w["wv"]).reshape(B, S, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, positions, c.rope_theta)
        k = apply_rope(k, positions, c.rope_theta)

        if layer_cache is not None:
            ck, cv = layer_cache
            ck = _cache_insert(ck, k, offsets)
            cv = _cache_insert(cv, v, offsets)
            kv_positions = jnp.broadcast_to(
                jnp.arange(ck.shape[1], dtype=jnp.int32)[None, :], (B, ck.shape[1])
            )
            attn = gqa_attention(
                q, ck, cv,
                q_positions=positions, kv_positions=kv_positions,
                kv_length=offsets + S, impl=attn_impl,
            )
            new_layer_cache = (ck, cv)
        else:
            attn = gqa_attention(
                q, k, v,
                q_positions=positions, kv_positions=positions, impl=attn_impl,
            )
            new_layer_cache = None

        x = x + _mm(attn.reshape(B, S, c.q_dim), w["wo"])

        h = rms_norm(x, w["mlp_norm"], c.rms_norm_eps)
        y, aux = moe_block(h, w, c, inference=inference)
        x = x + y
        return (x, lb_sum + aux["load_balance"], z_sum + aux["router_z"]), new_layer_cache

    layer_ws = params["layers"]
    init = (x, jnp.float32(0.0), jnp.float32(0.0))
    if cache is not None:
        (x, lb, z), (new_k, new_v) = jax.lax.scan(
            lambda carry, layer: layer_step(carry, (layer[0], (layer[1], layer[2]))),
            init, (layer_ws, cache.k, cache.v),
        )
        new_cache = KVCache(k=new_k, v=new_v, lengths=cache.lengths + S)
    else:
        (x, lb, z), _ = jax.lax.scan(
            lambda carry, w: layer_step(carry, (w, None)), init, layer_ws
        )
        new_cache = None

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    if logit_positions is not None:
        x = jnp.take_along_axis(x, logit_positions[:, None, None], axis=1)
    logits = llama._logits(params, c, x)
    aux = {"load_balance": lb / c.num_layers, "router_z": z / c.num_layers}
    return logits, new_cache, aux


def forward(
    params: Params,
    cfg: MoEConfig,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    cache: KVCache | None = None,
    attn_impl: str = "auto",
    logit_positions: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, KVCache | None]:
    """Serving-signature forward (drop-in for ``llama.forward``)."""
    logits, new_cache, _ = forward_with_aux(
        params, cfg, tokens, positions, cache, attn_impl, logit_positions
    )
    return logits, new_cache
