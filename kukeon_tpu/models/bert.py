"""BERT-family encoder (bge-base embedding model), functional JAX.

BASELINE.json config 5 pairs a Llama chat cell with a "bge-base embedding
cell (2 chips)"; this is that embedding model. bge-base IS BERT-base with
CLS pooling + L2 normalization, so the module implements the BERT encoder
the TPU-first way (same design stance as models/llama.py):

- **Pure functional**: params are a plain pytree; forward is jittable and
  shardable with the same ``parallel.sharding`` rules as the decoder.
- **Stacked layers + ``lax.scan``**: one stacked weight set, O(1) compile
  in depth.
- **bf16 matmuls, f32 norms/softmax**: MXU-friendly without numeric drift.
- **Bidirectional attention with a padding mask** — no causal mask, no KV
  cache (encoders embed whole sequences in one pass; serving batches them).

The reference runtime (eminwux/kukeon) has no model math; this file exists
for the TPU build's multi-model Session story (SURVEY.md §7 step 6).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def param_count(self) -> int:
        H, I, L = self.hidden_size, self.intermediate_size, self.num_layers
        embed = (self.vocab_size + self.max_position_embeddings
                 + self.type_vocab_size) * H + 2 * H
        attn = 4 * (H * H + H)
        mlp = H * I + I + I * H + H
        norms = 4 * H
        return embed + L * (attn + mlp + norms)


def bge_base() -> BertConfig:
    """BAAI/bge-base-en shapes (= BERT-base)."""
    return BertConfig()


def bge_tiny() -> BertConfig:
    """Test-size config: fast on a CPU mesh."""
    return BertConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, max_position_embeddings=128,
        dtype=jnp.float32,
    )


# --- Init --------------------------------------------------------------------

def init_params(key: jax.Array, cfg: BertConfig) -> Params:
    """Random-init parameter pytree (stacked layers on axis 0).

    Layout:
      embed:      word [V, H], position [P, H], type [T, H],
                  norm_scale/bias [H]
      layers:     wq/wk/wv/wo [L, H, H] (+ biases [L, H]),
                  attn_norm_scale/bias [L, H],
                  w_in [L, H, I] + b_in [L, I], w_out [L, I, H] + b_out [L, H],
                  mlp_norm_scale/bias [L, H]
    """
    c = cfg
    keys = iter(jax.random.split(key, 16))

    def dense(k, shape, fan_in):
        scale = fan_in ** -0.5
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(c.dtype)

    L, H, I = c.num_layers, c.hidden_size, c.intermediate_size
    return {
        "embed": {
            "word": dense(next(keys), (c.vocab_size, H), H),
            "position": dense(next(keys), (c.max_position_embeddings, H), H),
            "type": dense(next(keys), (c.type_vocab_size, H), H),
            "norm_scale": jnp.ones((H,), c.dtype),
            "norm_bias": jnp.zeros((H,), c.dtype),
        },
        "layers": {
            "wq": dense(next(keys), (L, H, H), H),
            "bq": jnp.zeros((L, H), c.dtype),
            "wk": dense(next(keys), (L, H, H), H),
            "bk": jnp.zeros((L, H), c.dtype),
            "wv": dense(next(keys), (L, H, H), H),
            "bv": jnp.zeros((L, H), c.dtype),
            "wo": dense(next(keys), (L, H, H), H),
            "bo": jnp.zeros((L, H), c.dtype),
            "attn_norm_scale": jnp.ones((L, H), c.dtype),
            "attn_norm_bias": jnp.zeros((L, H), c.dtype),
            "w_in": dense(next(keys), (L, H, I), H),
            "b_in": jnp.zeros((L, I), c.dtype),
            "w_out": dense(next(keys), (L, I, H), I),
            "b_out": jnp.zeros((L, H), c.dtype),
            "mlp_norm_scale": jnp.ones((L, H), c.dtype),
            "mlp_norm_bias": jnp.zeros((L, H), c.dtype),
        },
    }


# --- Forward -----------------------------------------------------------------

def _layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
                eps: float) -> jnp.ndarray:
    """Full LayerNorm (mean + variance) in f32 — BERT is post-LN and
    mean-sensitive, unlike the decoder's RMSNorm."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def forward(
    params: Params,
    cfg: BertConfig,
    tokens: jnp.ndarray,
    mask: jnp.ndarray,
    token_types: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Encode. tokens/mask: [B, S] (mask 1 = real token, 0 = pad).
    Returns the final hidden states [B, S, H] in f32."""
    c = cfg
    B, S = tokens.shape
    pos = jnp.arange(S, dtype=jnp.int32)[None, :]
    tt = token_types if token_types is not None else jnp.zeros_like(tokens)

    e = params["embed"]
    x = (
        jnp.take(e["word"], tokens, axis=0)
        + jnp.take(e["position"], pos, axis=0)
        + jnp.take(e["type"], tt, axis=0)
    ).astype(c.dtype)
    x = _layer_norm(x, e["norm_scale"], e["norm_bias"], c.layer_norm_eps)

    # Additive attention bias: padded keys get -inf for every query.
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
    attn_bias = jnp.where(mask[:, None, None, :].astype(bool), 0.0, neg)  # [B,1,1,S]
    scale = c.head_dim ** -0.5

    def layer_step(x, w):
        def proj(name, bname):
            return (x @ w[name] + w[bname]).reshape(B, S, c.num_heads, c.head_dim)

        q = proj("wq", "bq")
        k = proj("wk", "bk")
        v = proj("wv", "bv")
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        probs = jax.nn.softmax(logits + attn_bias, axis=-1).astype(c.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, c.hidden_size)
        attn = attn @ w["wo"] + w["bo"]
        x = _layer_norm(x + attn, w["attn_norm_scale"], w["attn_norm_bias"],
                        c.layer_norm_eps)

        h = jax.nn.gelu((x @ w["w_in"] + w["b_in"]).astype(jnp.float32),
                        approximate=False).astype(c.dtype)
        h = h @ w["w_out"] + w["b_out"]
        x = _layer_norm(x + h, w["mlp_norm_scale"], w["mlp_norm_bias"],
                        c.layer_norm_eps)
        return x, None

    x, _ = jax.lax.scan(layer_step, x, params["layers"])
    return x.astype(jnp.float32)


def embed(
    params: Params,
    cfg: BertConfig,
    tokens: jnp.ndarray,
    mask: jnp.ndarray,
    pooling: str = "cls",
) -> jnp.ndarray:
    """Sentence embeddings, bge-style: encode, pool, L2-normalize.
    Returns [B, H] f32 unit vectors. ``pooling``: "cls" (bge default) or
    "mean" (mask-weighted)."""
    hidden = forward(params, cfg, tokens, mask)
    if pooling == "cls":
        pooled = hidden[:, 0, :]
    elif pooling == "mean":
        m = mask.astype(jnp.float32)[:, :, None]
        pooled = (hidden * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
    else:
        raise ValueError(f"unknown pooling {pooling!r}")
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12
    )
