from kukeon_tpu.models.llama import (  # noqa: F401
    KVCache,
    LlamaConfig,
    forward,
    init_params,
    llama3_1b,
    llama3_8b,
    llama_tiny,
)
from kukeon_tpu.models import bert, moe  # noqa: F401
