"""Training checkpoint/resume: orbax-backed TrainState persistence.

The runtime side already has metadata-first resume (every resource record a
JSON file; SURVEY.md §5.4); this is the compute-side analog for training
jobs: step-numbered checkpoints of the full TrainState (params + optimizer
state + step) that restore DIRECTLY into the mesh shardings of the resuming
job — restore is a sharded read (each host/device reads its own slices),
and resuming on a different mesh layout reshards transparently because the
abstract target carries the new NamedShardings.

Layout: ``<root>/step_00000042/`` per checkpoint, newest wins for resume.
Writes go through orbax's atomic-rename protocol, so a killed writer never
leaves a checkpoint that :func:`latest_step` would pick up.
"""

from __future__ import annotations

import os
import re

import jax

from kukeon_tpu.training.train_step import TrainState

_STEP_RE = re.compile(r"^step_(\d{8})$")


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step:08d}")


def latest_step(root: str) -> int | None:
    """Newest complete checkpoint step under ``root``; None when empty."""
    try:
        entries = os.listdir(root)
    except FileNotFoundError:
        return None
    steps = []
    for e in entries:
        m = _STEP_RE.match(e)
        # Orbax writes to a tmp name and renames; only final names match.
        if m and os.path.isdir(os.path.join(root, e)):
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def save_checkpoint(root: str, state: TrainState) -> str:
    """Write ``state`` as ``<root>/step_<state.step>``; returns the path.
    Idempotent per step: a completed checkpoint for this exact step is
    left as-is (a save-every boundary coinciding with the final save must
    not error)."""
    import orbax.checkpoint as ocp

    step = int(state.step)
    path = _step_dir(root, step)
    if os.path.isdir(path):
        return path
    os.makedirs(root, exist_ok=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state)
    ckptr.wait_until_finished()
    return path


def abstract_like(state: TrainState) -> TrainState:
    """ShapeDtypeStruct mirror of a live state, carrying its shardings —
    the restore target. Build the template with create_train_state on the
    RESUMING job's mesh; restore then reads straight into that layout."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state,
    )


def restore_checkpoint(root: str, template: TrainState,
                       step: int | None = None) -> TrainState:
    """Restore the checkpoint at ``step`` (default: newest) into the
    template's shardings. ``template`` is a live or abstract TrainState of
    identical structure (e.g. a freshly created one on the resuming mesh)."""
    import orbax.checkpoint as ocp

    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    abstract = template if _is_abstract(template) else abstract_like(template)
    # Leaves whose template sharding is single-device (optimizer counts and
    # other scalars minted by an un-annotated jit) must restore as
    # mesh-REPLICATED: a restore commits its outputs, and a scalar committed
    # to device 0 next to mesh-wide params makes every later jitted step
    # reject the mixed device sets. Borrow the mesh from any NamedSharded
    # leaf (the params always are).
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = next((a.sharding.mesh for a in jax.tree.leaves(abstract)
                 if isinstance(a.sharding, NamedSharding)), None)
    if mesh is not None:
        repl = NamedSharding(mesh, PartitionSpec())

        def widen(a):
            if isinstance(a.sharding, NamedSharding):
                return a
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=repl)

        abstract = jax.tree.map(widen, abstract)
    restored = ocp.StandardCheckpointer().restore(_step_dir(root, step),
                                                  abstract)
    # Belt for orbax versions that ignore the target sharding on scalar
    # leaves: re-place onto it (a no-op where the layout already matches).
    shardings = jax.tree.map(lambda a: a.sharding, abstract)
    return jax.device_put(restored, shardings)


def _is_abstract(tree) -> bool:
    leaves = jax.tree.leaves(tree)
    return bool(leaves) and isinstance(leaves[0], jax.ShapeDtypeStruct)
