"""Training checkpoint/resume: orbax-backed TrainState persistence.

The runtime side already has metadata-first resume (every resource record a
JSON file; SURVEY.md §5.4); this is the compute-side analog for training
jobs: step-numbered checkpoints of the full TrainState (params + optimizer
state + step) that restore DIRECTLY into the mesh shardings of the resuming
job — restore is a sharded read (each host/device reads its own slices),
and resuming on a different mesh layout reshards transparently because the
abstract target carries the new NamedShardings.

Layout: ``<root>/step_00000042/`` per checkpoint, newest wins for resume.
Saves are crash-atomic at THIS layer, belt and suspenders over whatever the
orbax version does internally: orbax writes into a temp-named directory in
the same root, the directory entries are fsynced, and only then does a
single ``os.replace`` publish the final ``step_*`` name. A writer killed at
any point (fault seam ``checkpoint.save``) leaves at most a temp directory
that :func:`latest_step` never matches — the previous checkpoint stays the
resume target, never a truncated one.
"""

from __future__ import annotations

import os
import re
import shutil

import jax

from kukeon_tpu import faults
from kukeon_tpu.training.train_step import TrainState

_STEP_RE = re.compile(r"^step_(\d{8})$")
_TMP_PREFIX = "tmp-"


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step:08d}")


def _fsync_dir(path: str) -> None:
    """fsync a directory's entries (durability for the rename protocol);
    best-effort on filesystems that reject directory fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def latest_step(root: str) -> int | None:
    """Newest complete checkpoint step under ``root``; None when empty."""
    try:
        entries = os.listdir(root)
    except FileNotFoundError:
        return None
    steps = []
    for e in entries:
        m = _STEP_RE.match(e)
        # Orbax writes to a tmp name and renames; only final names match.
        if m and os.path.isdir(os.path.join(root, e)):
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def save_checkpoint(root: str, state: TrainState) -> str:
    """Write ``state`` as ``<root>/step_<state.step>``; returns the path.
    Idempotent per step: a completed checkpoint for this exact step is
    left as-is (a save-every boundary coinciding with the final save must
    not error).

    Crash-atomic: the full checkpoint lands under a temp name in the same
    directory first; the final name appears via one ``os.replace`` after
    fsync. A kill anywhere before the replace leaves the previous
    checkpoint as the newest complete one (tests interrupt the save via
    the ``checkpoint.save`` fault point to pin this)."""
    import orbax.checkpoint as ocp

    step = int(state.step)
    path = _step_dir(root, step)
    if os.path.isdir(path):
        return path
    os.makedirs(root, exist_ok=True)
    # Same-directory temp name: os.replace must stay a same-filesystem
    # rename. PID-suffixed so a dead writer's leftovers never collide with
    # a live retry; stale temps from previous crashes are swept here.
    tmp = os.path.join(root, f"{_TMP_PREFIX}step_{step:08d}.{os.getpid()}")
    shutil.rmtree(tmp, ignore_errors=True)
    ckptr = ocp.StandardCheckpointer()
    try:
        ckptr.save(tmp, state)
        ckptr.wait_until_finished()
        # The injected mid-save kill: everything is written under the temp
        # name, nothing published yet — exactly what a SIGKILL here does.
        faults.maybe_fail("checkpoint.save")
        _fsync_dir(tmp)
        os.replace(tmp, path)
        _fsync_dir(root)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return path


def abstract_like(state: TrainState) -> TrainState:
    """ShapeDtypeStruct mirror of a live state, carrying its shardings —
    the restore target. Build the template with create_train_state on the
    RESUMING job's mesh; restore then reads straight into that layout."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state,
    )


def restore_checkpoint(root: str, template: TrainState,
                       step: int | None = None) -> TrainState:
    """Restore the checkpoint at ``step`` (default: newest) into the
    template's shardings. ``template`` is a live or abstract TrainState of
    identical structure (e.g. a freshly created one on the resuming mesh)."""
    import orbax.checkpoint as ocp

    faults.maybe_fail("checkpoint.load")
    if step is None:
        step = latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {root}")
    abstract = template if _is_abstract(template) else abstract_like(template)
    # Leaves whose template sharding is single-device (optimizer counts and
    # other scalars minted by an un-annotated jit) must restore as
    # mesh-REPLICATED: a restore commits its outputs, and a scalar committed
    # to device 0 next to mesh-wide params makes every later jitted step
    # reject the mixed device sets. Borrow the mesh from any NamedSharded
    # leaf (the params always are).
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = next((a.sharding.mesh for a in jax.tree.leaves(abstract)
                 if isinstance(a.sharding, NamedSharding)), None)
    if mesh is not None:
        repl = NamedSharding(mesh, PartitionSpec())

        def widen(a):
            if isinstance(a.sharding, NamedSharding):
                return a
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=repl)

        abstract = jax.tree.map(widen, abstract)
    restored = ocp.StandardCheckpointer().restore(_step_dir(root, step),
                                                  abstract)
    # Belt for orbax versions that ignore the target sharding on scalar
    # leaves: re-place onto it (a no-op where the layout already matches).
    shardings = jax.tree.map(lambda a: a.sharding, abstract)
    return jax.device_put(restored, shardings)


def _is_abstract(tree) -> bool:
    leaves = jax.tree.leaves(tree)
    return bool(leaves) and isinstance(leaves[0], jax.ShapeDtypeStruct)
