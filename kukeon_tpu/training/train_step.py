"""Sharded training step for the Llama family.

GSPMD training: params/optimizer state live with the canonical shardings
(:mod:`kukeon_tpu.parallel.sharding` — fsdp × tensor), the batch is sharded
over (data, fsdp) and — when the mesh has a ``seq`` axis — the sequence
dimension is sharded too, with attention routed through the ring-attention
path. XLA inserts all collectives: per-layer all-gather of fsdp-sharded
weights in forward, reduce-scatter of grads in backward, psums for tensor
parallelism, and ppermute rings for sequence parallelism.

The step donates (params, opt_state) so weights are updated in place in HBM.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import sharding as shd
from kukeon_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP, AXIS_SEQ


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Mean next-token cross entropy over masked positions.

    logits: [B, S, V] f32; targets: [B, S] int32; mask: [B, S] {0,1}.
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    total = jnp.sum(nll * mask)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return total / denom


def make_optimizer(learning_rate: float = 3e-4, weight_decay: float = 0.1,
                   warmup_steps: int = 100, total_steps: int = 10_000) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def create_train_state(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    key: jax.Array,
    optimizer: optax.GradientTransformation | None = None,
    *,
    init_fn=None,
    specs=None,
) -> tuple[TrainState, optax.GradientTransformation]:
    """Init params + optimizer state directly with fsdp/tensor shardings.

    ``init_fn(key) -> params`` and ``specs`` (a PartitionSpec pytree)
    override the Llama defaults — the MoE family passes its own
    (moe.init_params, shd.moe_specs_for_params)."""
    optimizer = optimizer or make_optimizer()
    init_fn = init_fn or (lambda k: llama.init_params(k, cfg))
    # Abstract-init to get the tree structure without materializing twice.
    abstract = jax.eval_shape(init_fn, key)
    if specs is None:
        specs = shd.specs_for_params(abstract, fsdp=True)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )
    params = jax.jit(init_fn, out_shardings=shardings)(key)
    opt_state = jax.jit(
        optimizer.init,
        out_shardings=None,  # optax state mirrors param shardings via init tracing
    )(params)
    state = TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))
    return state, optimizer


def make_train_step(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    *,
    use_ring_attention: bool | None = None,
    remat: bool = True,
):
    """Build the jitted, donated train step.

    use_ring_attention: default = True iff the mesh's ``seq`` axis is >1.
    remat: checkpoint each transformer layer (trade FLOPs for HBM — the
      standard TPU recipe for long sequences).
    """
    if use_ring_attention is None:
        use_ring_attention = mesh.shape.get(AXIS_SEQ, 1) > 1
    attn_impl = "ring" if use_ring_attention else "auto"

    batch_sharding = NamedSharding(mesh, P((AXIS_DATA, AXIS_FSDP), AXIS_SEQ))

    def loss_fn(params, tokens, targets, mask, positions):
        fwd = functools.partial(llama.forward, attn_impl=attn_impl)
        if remat:
            fwd = jax.checkpoint(fwd, static_argnums=(1,))
        logits, _ = fwd(params, cfg, tokens, positions)
        return cross_entropy_loss(logits, targets, mask)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, tokens, targets, mask):
        B, S = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        positions = jax.lax.with_sharding_constraint(positions, batch_sharding)
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, targets, mask, positions
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=new_params, opt_state=new_opt, step=state.step + 1),
            loss,
        )

    return train_step, batch_sharding


def create_moe_train_state(cfg, mesh: Mesh, key: jax.Array,
                           optimizer: optax.GradientTransformation | None = None):
    """MoE variant of :func:`create_train_state` (expert-sharded weights)."""
    from kukeon_tpu.models import moe

    abstract = jax.eval_shape(lambda k: moe.init_params(k, cfg), key)
    return create_train_state(
        cfg, mesh, key, optimizer,
        init_fn=lambda k: moe.init_params(k, cfg),
        specs=shd.moe_specs_for_params(abstract, fsdp=True),
    )


def make_moe_train_step(cfg, mesh: Mesh, optimizer: optax.GradientTransformation,
                        *, remat: bool = True):
    """Jitted, donated MoE train step: next-token CE + Switch load-balance
    loss + router z-loss (coefficients from the config). Same sharding
    story as the dense step, plus expert parallelism from the weight specs
    (all-to-alls inserted by GSPMD at the dispatch/combine einsums)."""
    from kukeon_tpu.models import moe

    batch_sharding = NamedSharding(mesh, P((AXIS_DATA, AXIS_FSDP), AXIS_SEQ))

    def loss_fn(params, tokens, targets, mask, positions):
        fwd = moe.forward_with_aux
        if remat:
            fwd = jax.checkpoint(fwd, static_argnums=(1,))
        logits, _, aux = fwd(params, cfg, tokens, positions)
        ce = cross_entropy_loss(logits, targets, mask)
        total = (ce
                 + cfg.load_balance_coef * aux["load_balance"]
                 + cfg.router_z_coef * aux["router_z"])
        return total, (ce, aux)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, tokens, targets, mask):
        B, S = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        positions = jax.lax.with_sharding_constraint(positions, batch_sharding)
        (loss, (ce, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, tokens, targets, mask, positions
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss, "ce": ce,
                   "load_balance": aux["load_balance"],
                   "router_z": aux["router_z"]}
        return (
            TrainState(params=new_params, opt_state=new_opt, step=state.step + 1),
            metrics,
        )

    return train_step, batch_sharding
