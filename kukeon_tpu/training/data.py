"""Training data: memmapped token streams + deterministic sharded batches.

The data-side complement to training/checkpointing.py's resume story: a
training job that restarts from step N must see EXACTLY the batches it
would have seen without the restart. Batches are therefore a pure function
of (seed, step) — a counter-based RNG per step, no iterator state to
persist — and the loader places each batch onto the mesh with the train
step's batch sharding, so each host only materializes its own shard's
pages (memmap reads are lazy).

Format: a flat ``.bin`` of token ids (uint16 when vocab < 65536, else
uint32) with a sibling ``<name>.meta.json`` {"dtype", "num_tokens"} —
the standard nanoGPT-style layout, trivially produced by any tokenizer
pipeline.
"""

from __future__ import annotations

import json
import os

import numpy as np


class TokenDataset:
    """Read-only memmapped token stream."""

    def __init__(self, path: str):
        meta_path = path.rsplit(".bin", 1)[0] + ".meta.json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            dtype = np.dtype(meta["dtype"])
        else:
            dtype = np.dtype(np.uint16)
        self.path = path
        self.tokens = np.memmap(path, dtype=dtype, mode="r")

    def __len__(self) -> int:
        return int(self.tokens.shape[0])

    @staticmethod
    def write(path: str, tokens, dtype=None) -> "TokenDataset":
        """Write a token array as a dataset (tools/tests)."""
        tokens = np.asarray(tokens)
        if dtype is None:
            dtype = np.uint16 if tokens.max(initial=0) < 65536 else np.uint32
        arr = tokens.astype(dtype)
        arr.tofile(path)
        with open(path.rsplit(".bin", 1)[0] + ".meta.json", "w") as f:
            json.dump({"dtype": np.dtype(dtype).name,
                       "num_tokens": int(arr.shape[0])}, f)
        return TokenDataset(path)


def sample_batch(ds: TokenDataset, step: int, batch_size: int, seq_len: int,
                 *, seed: int = 0):
    """(tokens, targets, mask) numpy batch for ``step`` — deterministic:
    the same (seed, step) always yields the same batch, so a job resumed
    from a checkpoint at step N continues on the exact data schedule."""
    n = len(ds)
    if n < seq_len + 1:
        raise ValueError(
            f"dataset {ds.path} has {n} tokens < seq_len+1 ({seq_len + 1})"
        )
    rng = np.random.default_rng([seed, step])
    # Exclusive high: the last valid window starts at n - seq_len - 1
    # (targets slice reaches o + seq_len + 1 == n).
    offsets = rng.integers(0, n - seq_len, size=batch_size)
    tokens = np.stack([np.asarray(ds.tokens[o:o + seq_len]) for o in offsets])
    targets = np.stack(
        [np.asarray(ds.tokens[o + 1:o + seq_len + 1]) for o in offsets]
    )
    mask = np.ones((batch_size, seq_len), np.float32)
    return tokens.astype(np.int32), targets.astype(np.int32), mask


def batches(ds: TokenDataset, batch_size: int, seq_len: int, *,
            start_step: int = 0, num_steps: int | None = None,
            seed: int = 0, sharding=None):
    """Yield (step, tokens, targets, mask) from ``start_step`` (resume
    point), device_put onto ``sharding`` when given (the train step's
    batch sharding — jit then consumes the batch without a relayout)."""
    import itertools

    import jax

    steps = (range(start_step, start_step + num_steps)
             if num_steps is not None else itertools.count(start_step))
    for step in steps:
        tokens, targets, mask = sample_batch(
            ds, step, batch_size, seq_len, seed=seed
        )
        if sharding is not None:
            tokens = jax.device_put(tokens, sharding)
            targets = jax.device_put(targets, sharding)
            mask = jax.device_put(mask, sharding)
        yield step, tokens, targets, mask
