from kukeon_tpu.training.train_step import (  # noqa: F401
    TrainState,
    create_moe_train_state,
    create_train_state,
    make_moe_train_step,
    make_train_step,
)
from kukeon_tpu.training.checkpointing import (  # noqa: F401
    abstract_like,
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from kukeon_tpu.training.data import (  # noqa: F401
    TokenDataset,
    batches,
    sample_batch,
)
