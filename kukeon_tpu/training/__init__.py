from kukeon_tpu.training.train_step import (  # noqa: F401
    TrainState,
    create_train_state,
    make_train_step,
)
