"""Training entrypoint: data pipeline + sharded train step + checkpoints.

    python -m kukeon_tpu.training.cli \
        --dataset /data/tokens.bin --model llama3-8b \
        --tensor 4 --fsdp 2 --steps 10000 --ckpt-dir /ckpts --save-every 500

Composes the framework's training pieces end to end: memmapped token
batches (deterministic, resume-aligned), the dense / MoE / pipeline train
steps over the canonical mesh axes, and orbax checkpoints (auto-resume
from the newest step in --ckpt-dir). The compute path is jit-compiled
once; the loop is pure orchestration.
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="kukeon-train")
    ap.add_argument("--dataset", required=True, help="token .bin file")
    ap.add_argument("--model", default="tiny",
                    choices=["tiny", "llama3-1b", "llama3-8b",
                             "mixtral-tiny", "mixtral-8x7b"])
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--warmup-steps", type=int, default=100)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--save-every", type=int, default=500)
    ap.add_argument("--log-every", type=int, default=10)
    for axis in ("data", "fsdp", "tensor", "seq", "expert", "pipe"):
        ap.add_argument(f"--{axis}", type=int, default=1)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import jax

    from kukeon_tpu.models import llama, moe
    from kukeon_tpu.parallel import make_mesh, set_mesh
    from kukeon_tpu.training import (
        TokenDataset,
        batches,
        create_moe_train_state,
        create_train_state,
        latest_step,
        restore_checkpoint,
        save_checkpoint,
    )
    from kukeon_tpu.training.train_step import (
        make_moe_train_step,
        make_optimizer,
        make_train_step,
    )

    is_moe = args.model.startswith("mixtral")
    cfgs = {
        "tiny": llama.llama_tiny, "llama3-1b": llama.llama3_1b,
        "llama3-8b": llama.llama3_8b,
        "mixtral-tiny": moe.moe_tiny, "mixtral-8x7b": moe.mixtral_8x7b,
    }
    cfg = cfgs[args.model]()

    import math

    n = len(jax.devices())
    sizes = {a: getattr(args, a) for a in
             ("data", "fsdp", "tensor", "seq", "expert", "pipe")}
    specified = 1
    for v in sizes.values():
        specified *= v
    if specified == 1 and n > 1:
        # Default: pure data parallelism over as many devices as the batch
        # divides into (a 4-sample batch on an 8-device host uses 4).
        sizes["data"] = math.gcd(n, args.batch)
        specified = sizes["data"]
    mesh = make_mesh(**sizes, devices=jax.devices()[:specified])
    print(f"train: model={args.model} mesh={dict(mesh.shape)} "
          f"batch={args.batch} seq={args.seq_len}", flush=True)

    ds = TokenDataset(args.dataset)
    optimizer = make_optimizer(
        learning_rate=args.lr, warmup_steps=args.warmup_steps,
        total_steps=max(args.steps, args.warmup_steps + 1),
    )

    with set_mesh(mesh):
        if is_moe:
            if sizes["pipe"] > 1:
                print("error: pipeline parallelism is llama-only for now",
                      file=sys.stderr)
                return 2
            state, optimizer = create_moe_train_state(
                cfg, mesh, jax.random.key(args.seed), optimizer)
            step_fn, batch_sharding = make_moe_train_step(cfg, mesh, optimizer)
        elif sizes["pipe"] > 1:
            from kukeon_tpu.parallel.pipeline import (
                make_pp_train_step,
                pp_specs_for_params,
            )

            state, optimizer = create_train_state(
                cfg, mesh, jax.random.key(args.seed), optimizer,
                init_fn=lambda k: llama.init_params(k, cfg),
                specs=pp_specs_for_params(
                    jax.eval_shape(lambda k: llama.init_params(k, cfg),
                                   jax.random.key(args.seed))
                ),
            )
            step_fn = make_pp_train_step(cfg, mesh, optimizer)
            batch_sharding = None
        else:
            state, optimizer = create_train_state(
                cfg, mesh, jax.random.key(args.seed), optimizer)
            step_fn, batch_sharding = make_train_step(cfg, mesh, optimizer)

        start = 0
        if args.ckpt_dir and latest_step(args.ckpt_dir) is not None:
            from kukeon_tpu.training import abstract_like

            # Free the throwaway init BEFORE the restore reads the
            # checkpoint copy in — otherwise peak HBM is 2x the model
            # state and an 8B resume OOMs where from-scratch trains fine.
            template = abstract_like(state)
            state = None
            state = restore_checkpoint(args.ckpt_dir, template)
            start = int(state.step)
            print(f"train: resumed from step {start}", flush=True)

        t0 = time.monotonic()
        last_logged = start
        for step, tok, tgt, mask in batches(
            ds, args.batch, args.seq_len, start_step=start,
            num_steps=args.steps - start, seed=args.seed,
            sharding=batch_sharding,
        ):
            state, out = step_fn(state, tok, tgt, mask)
            loss = out["loss"] if isinstance(out, dict) else out
            if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
                dt = time.monotonic() - t0
                window = step + 1 - last_logged   # may be < log_every at the tail
                tput = args.batch * args.seq_len * window / max(dt, 1e-9)
                extra = ""
                if isinstance(out, dict):
                    extra = f" lb={float(out['load_balance']):.3f}"
                print(f"step {step + 1} loss {float(loss):.4f}{extra} "
                      f"({tput:.0f} tok/s)", flush=True)
                t0 = time.monotonic()
                last_logged = step + 1
            if (args.ckpt_dir and args.save_every
                    and (step + 1) % args.save_every == 0):
                save_checkpoint(args.ckpt_dir, state)
        if args.ckpt_dir:
            save_checkpoint(args.ckpt_dir, state)
            print(f"train: checkpoint at step {int(state.step)} -> "
                  f"{args.ckpt_dir}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
