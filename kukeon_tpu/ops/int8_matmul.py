"""Weights-only int8 matmul Pallas kernel (decode fast path).

TPU decode is HBM-bound: every weight byte streams through HBM once per
step, so int8 weights halve step time *if* int8 is what actually crosses
HBM. XLA's ``astype``-dequant materializes a full bf16 copy (and the
s8->bf16 relayout is slow), so the win never lands; this kernel reads the
int8 block into VMEM, dequantizes in-register on the VPU, and feeds the
MXU — HBM traffic is the int8 bytes plus activations.

Shapes: ``h [B, K] @ q [K, N] * s [N] -> [B, N]`` (or ``q [N, K]`` with
``transpose=True`` for tied-embedding LM heads). B is the decode batch
(a few slots), padded to the bf16 sublane tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# VMEM budget for one weight block (~half of the ~16 MB/core VMEM stays
# free for h/out/accumulators and double buffering).
_BLOCK_BYTES = 4 * 1024 * 1024
_MIN_TILE = 256


def _tile_n(k: int, n: int) -> int:
    t = max(_MIN_TILE, min(2048, _BLOCK_BYTES // max(k, 1)))
    t = min(t, n)
    # Lane dim must stay a multiple of 128; shrink to divide n evenly.
    t = max(128, (t // 128) * 128)
    while n % t:
        t -= 128
    return max(t, 128)


def _kernel(h_ref, q_ref, s_ref, o_ref):
    w = q_ref[:].astype(jnp.bfloat16)           # dequant in VMEM (VPU)
    acc = jnp.dot(h_ref[:], w, preferred_element_type=jnp.float32)
    o_ref[:] = (acc * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _kernel_t(h_ref, q_ref, s_ref, o_ref):
    w = q_ref[:].astype(jnp.bfloat16)           # [T, K] block
    acc = jax.lax.dot_general(
        h_ref[:], w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = (acc * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("transpose",))
def int8_matmul(h: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray,
                *, transpose: bool = False) -> jnp.ndarray:
    """h [B, K] bf16 @ int8 weights, dequantized on-chip.

    ``transpose=False``: q [K, N], s [N] -> out [B, N]
    ``transpose=True``:  q [N, K], s [N] -> out [B, N]
    """
    B, K = h.shape
    N = q.shape[0] if transpose else q.shape[1]
    if (K % 128) or (N % 128) or B > 64 or jax.default_backend() != "tpu":
        # Odd shapes (tests, tiny models), prefill-sized batches (the [Bp, K]
        # activation block must stay far under VMEM; prefill is MXU-bound so
        # XLA's dequant-fused dot is the right tool there), and non-TPU
        # backends: plain XLA fallback.
        w = q.astype(h.dtype)
        out = jax.lax.dot_general(
            h, w, (((1,), (1 if transpose else 0,)), ((), ())))
        return out * s.astype(h.dtype)

    # Pad B up to the bf16 sublane tile so the MXU operand is well-formed.
    Bp = max(16, ((B + 15) // 16) * 16)
    if Bp != B:
        h = jnp.pad(h, ((0, Bp - B), (0, 0)))

    T = _tile_n(K, N)
    grid = (N // T,)
    s2 = s.reshape(1, N)
    if transpose:
        kernel, q_spec = _kernel_t, pl.BlockSpec((T, K), lambda j: (j, 0))
    else:
        kernel, q_spec = _kernel, pl.BlockSpec((K, T), lambda j: (0, j))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((Bp, N), h.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((Bp, K), lambda j: (0, 0)),
            q_spec,
            pl.BlockSpec((1, T), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((Bp, T), lambda j: (0, j)),
    )(h, q, s2)
    return out[:B] if Bp != B else out


def int8_matmul_expert(x: jnp.ndarray, q: jnp.ndarray,
                       s: jnp.ndarray) -> jnp.ndarray:
    """Per-expert batched int8 matmul: x [E, C, K] @ q [E, K, N] * s [E, N]
    -> [E, C, N] (the MoE decode expert stacks: w_gate/w_up/w_down).

    On TPU at decode-sized C the E expert blocks run through the Pallas
    kernel one expert at a time (E is small and static, so this is a fixed
    unroll, and each weight block streams HBM as int8); everywhere else —
    CPU, odd shapes, prefill-sized C — the XLA dequant-fused einsum is the
    right tool and the fallback.
    """
    E, C, K = x.shape
    N = q.shape[-1]
    if (K % 128) or (N % 128) or C > 64 or jax.default_backend() != "tpu":
        raw = jnp.einsum("eck,ekn->ecn", x, q.astype(x.dtype))
        return raw * s[:, None, :].astype(x.dtype)
    return jnp.stack([int8_matmul(x[e], q[e], s[e]) for e in range(E)])
