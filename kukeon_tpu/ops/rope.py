"""Rotary position embeddings (RoPE).

Split-half convention (as in the Llama reference implementations): the head
dimension is split into two halves that form the (real, imaginary) pair.
Frequencies are computed in float32; the rotation is applied in float32 and
cast back to the input dtype.
"""

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies [head_dim // 2] for a RoPE of base ``theta``."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponents)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotate q or k by position.

    Args:
      x: [batch, seq, heads, head_dim].
      positions: [batch, seq] absolute token positions (int32).
      theta: RoPE base frequency.

    Returns:
      Rotated array, same shape and dtype as ``x``.
    """
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta)           # [D/2]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]                   # [B, S, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]

    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)
