"""Attention ops: XLA-fused reference path and Pallas flash dispatch.

Grouped-query attention (GQA) with a position-based mask, which uniformly
covers:
  - full causal self-attention (prefill / training),
  - decode-against-cache (each query attends to cache slots with
    key_position <= query_position and slot < used length).

The reference path is plain einsum + softmax: XLA fuses this well on TPU and
keeps the matmuls on the MXU. The Pallas flash kernel
(:mod:`kukeon_tpu.ops.flash_attention`) is used for long-sequence prefill and
training on TPU, where materializing the [S, S] score matrix would blow HBM
bandwidth.
"""

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Expand KV heads for GQA: [B, S, KV, D] -> [B, S, KV * n_rep, D]."""
    if n_rep == 1:
        return x
    b, s, kv, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, kv, n_rep, d))
    return x.reshape(b, s, kv * n_rep, d)


def attention_mask(
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    kv_length: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Boolean mask [B, 1, Sq, Skv]: True = attend.

    Args:
      q_positions: [B, Sq] absolute positions of the queries.
      kv_positions: [B, Skv] absolute positions of the keys.
      kv_length: optional [B] number of valid cache slots; slots at index >=
        kv_length are masked out (used when attending to a fixed-size cache).
    """
    causal = kv_positions[:, None, :] <= q_positions[:, :, None]  # [B, Sq, Skv]
    if kv_length is not None:
        skv = kv_positions.shape[-1]
        valid = jnp.arange(skv)[None, None, :] < kv_length[:, None, None]
        causal = jnp.logical_and(causal, valid)
    return causal[:, None, :, :]


def attention_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,
) -> jnp.ndarray:
    """Masked multi-head attention via einsum (GQA-expanded inputs).

    Args:
      q: [B, Sq, H, D]; k, v: [B, Skv, H, D]; mask: [B, 1, Sq, Skv] bool.

    Returns:
      [B, Sq, H, D] in q's dtype. Softmax is computed in float32.
    """
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def attention_grouped(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,
) -> jnp.ndarray:
    """GQA attention WITHOUT materializing repeated KV heads.

    ``repeat_kv`` + reference attention reads (and copies) the KV tensors
    ``n_heads/n_kv`` times — for a decode step against a large cache that
    multiplies the dominant HBM stream by the group factor. Grouping the
    query heads instead ([B, Sq, KV, G, D]) keeps every KV byte read exactly
    once; same math, same mask semantics.

    Args:
      q: [B, Sq, H, D]; k, v: [B, Skv, KV, D] (H % KV == 0);
      mask: [B, 1, Sq, Skv] bool.
    """
    B, Sq, H, D = q.shape
    KV = k.shape[2]
    G = H // KV
    scale = 1.0 / jnp.sqrt(D).astype(jnp.float32)
    qg = q.reshape(B, Sq, KV, G, D)
    scores = jnp.einsum(
        "bqkgd,bTkd->bkgqT", qg, k, preferred_element_type=jnp.float32
    ) * scale
    # mask [B, 1, Sq, Skv] -> broadcast over (KV, G).
    scores = jnp.where(mask[:, :, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgqT,bTkd->bqkgd", probs.astype(v.dtype), v)
    return out.reshape(B, Sq, H, D).astype(q.dtype)


def decode_gqa_attention(
    q: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    cache_k: jnp.ndarray,
    cache_v: jnp.ndarray,
    lengths: jnp.ndarray,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Single-token decode attention against a cache, append-free.

    The new token's K/V are NOT written into the cache first (that write
    pattern forces a full-cache copy per layer inside a scan); instead the
    cache contributes `lengths` masked slots and the current token
    contributes one extra score, softmaxed together. The caller inserts the
    new K/V into the cache once per step, outside the layer scan.

    Quantized cache: cache_k/cache_v int8 with per-token per-head scales
    k_scale/v_scale [B, S, KV]. Dequant is fused: the score dot runs on the
    int8 keys (convert folds into the einsum, so int8 is the HBM stream) and
    the per-token key scale multiplies the f32 scores; the value scale folds
    into the probabilities before the value dot. Exact same math as
    dequantize-then-attend, at half the cache bytes.

    Args:
      q: [B, 1, H, D]; k_new, v_new: [B, 1, KV, D] (always full precision);
      cache_k, cache_v: [B, S, KV, D]; lengths: [B] valid cache slots.

    Returns: [B, 1, H, D].
    """
    B, _, H, D = q.shape
    S = cache_k.shape[1]
    KV = cache_k.shape[2]
    G = H // KV
    scale = 1.0 / jnp.sqrt(D).astype(jnp.float32)
    dt = q.dtype

    qg = q.reshape(B, KV, G, D)
    # Only the quantized path converts (int8 -> activation dtype folds into
    # the dot); a full-precision cache keeps its own dtype so callers with a
    # wider-than-activations cache lose nothing.
    ck = cache_k.astype(dt) if k_scale is not None else cache_k
    s_cache = jnp.einsum(
        "bkgd,bTkd->bkgT", qg, ck, preferred_element_type=jnp.float32
    ) * scale
    if k_scale is not None:
        s_cache = s_cache * k_scale.transpose(0, 2, 1)[:, :, None, :]
    valid = jnp.arange(S)[None, None, None, :] < lengths[:, None, None, None]
    s_cache = jnp.where(valid, s_cache, NEG_INF)
    s_self = jnp.einsum(
        "bkgd,bkd->bkg", qg, k_new.reshape(B, KV, D),
        preferred_element_type=jnp.float32,
    )[..., None] * scale

    probs = jax.nn.softmax(jnp.concatenate([s_cache, s_self], axis=-1), axis=-1)
    p_cache = probs[..., :S]
    if v_scale is not None:
        p_cache = p_cache * v_scale.transpose(0, 2, 1)[:, :, None, :]
        cv = cache_v.astype(dt)
        p_cache = p_cache.astype(dt)
    else:
        cv = cache_v
        p_cache = p_cache.astype(cache_v.dtype)
    p_self = probs[..., S:].astype(v_new.dtype)
    out = (
        jnp.einsum("bkgT,bTkd->bkgd", p_cache, cv)
        + p_self * v_new.reshape(B, KV, 1, D)
    )
    return out.reshape(B, 1, H, D).astype(q.dtype)


def gqa_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    kv_length: jnp.ndarray | None = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """GQA attention entry point used by the model.

    q: [B, Sq, NH, D]; k, v: [B, Skv, NKV, D] with NH % NKV == 0.

    ``impl``: "auto" picks flash on TPU for long-enough sequences, else the
    XLA reference; "reference" / "flash" / "ring" / "ulysses" force a path.
    "ring" (shard_map + ppermute) and "ulysses" (all-to-all seq<->heads) are
    the sequence-parallel paths over the ``seq`` mesh axis and require an
    ambient mesh (``jax.set_mesh``) with one.
    """
    if impl in ("ring", "ulysses"):
        if kv_length is not None or q.shape[1] != k.shape[1]:
            raise ValueError(
                f"impl={impl!r} requires full self-attention (Sq == Skv, no "
                f"kv_length); got Sq={q.shape[1]}, Skv={k.shape[1]}, "
                f"kv_length={'set' if kv_length is not None else 'None'}. "
                "Use 'reference' or 'auto' for cached decode."
            )
        if impl == "ulysses":
            from kukeon_tpu.parallel.ulysses import ulysses_attention

            return ulysses_attention(
                q, k, v, q_positions=q_positions, kv_positions=kv_positions
            )
        from kukeon_tpu.parallel.ring_attention import ring_attention

        return ring_attention(
            q, k, v, q_positions=q_positions, kv_positions=kv_positions
        )

    n_heads = q.shape[2]
    n_kv = k.shape[2]

    from kukeon_tpu.ops import flash_attention as fa

    use_flash = False
    if impl == "flash":
        if kv_length is not None or not fa.supports(q.shape[1], k.shape[1]):
            raise ValueError(
                "impl='flash' requires full self-attention with Sq == Skv, "
                "Sq >= 128, Sq a multiple of the 256 block, and no kv_length; "
                f"got Sq={q.shape[1]}, Skv={k.shape[1]}, "
                f"kv_length={'set' if kv_length is not None else 'None'}. "
                "Use 'reference' or 'auto'."
            )
        use_flash = True
    elif impl == "auto":
        # Flash pays off when the score matrix is big; decode (Sq==1), tiny
        # prefills, cache attention, and non-TPU backends stay on the fused
        # XLA path.
        # Measured on v5e: parity at S=2048, 27x at S=8192 (the XLA path
        # materializes the [S, S] scores); flash also saves the O(S^2) HBM.
        use_flash = (
            kv_length is None
            and q.shape[1] >= 1024
            and fa.supports(q.shape[1], k.shape[1])
            and jax.default_backend() == "tpu"
        )

    if use_flash:
        k = repeat_kv(k, n_heads // n_kv)
        v = repeat_kv(v, n_heads // n_kv)
        return fa.flash_attention(q, k, v, q_positions, kv_positions)

    # XLA path: grouped-query einsum — KV is never head-repeated, so cache
    # bytes stream through HBM exactly once.
    mask = attention_mask(q_positions, kv_positions, kv_length)
    return attention_grouped(q, k, v, mask)
