"""Pallas TPU flash attention (causal, forward).

Online-softmax tiled attention: grid (batch*heads, q_blocks, kv_blocks) with
the kv dimension innermost/sequential; running max/sum/accumulator live in
VMEM scratch across kv steps, so the [S, S] score matrix never touches HBM.
Fully-masked kv blocks (kv_start > q_end) are predicated out with ``pl.when``.

Scope: self-attention with row/column positions equal to ``arange(S)``
(training and uncached prefill — exactly where the dispatcher uses it; the
decode path attends against a cache and stays on the fused XLA path). For
the backward pass the caller wraps attention in ``jax.checkpoint`` and this
kernel is used for the recomputed forward; gradients flow through the XLA
reference path via ``jax.custom_vjp`` fallback (see ``flash_attention``'s
``@jax.custom_vjp`` definition).

Block sizes default to 256x256 tiles over f32/bf16 inputs, clamped to the
sequence length; sequences must divide by the block size (the dispatcher
guarantees this by falling back to the reference path otherwise).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    kv_start = ki * block_k

    # A kv block is live unless every (q, kv) pair in it is masked.
    @pl.when(kv_start <= q_start + block_q - 1)
    def _compute():
        q = q_ref[0]                       # [bq, D]
        k = k_ref[0]                       # [bk, D]
        v = v_ref[0]                       # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                          # [bq, bk]

        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + q_start
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + kv_start
        s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_scr[:, :1]              # [bq, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)             # [bq, bk] f32
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)

        acc = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, block_q: int, block_k: int, interpret: bool = False):
    """q, k, v: [BH, S, D] (GQA-expanded, heads folded into batch)."""
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    grid = (BH, S // block_q, S // block_k)

    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running sum
            pltpu.VMEM((block_q, D), jnp.float32),       # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)


def supports(q_len: int, kv_len: int, block: int = 256) -> bool:
    """Whether the kernel covers this shape (dispatcher guard)."""
    if q_len != kv_len:
        return False
    b = min(block, q_len)
    return q_len % b == 0 and q_len >= 128


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_q: int = 256,
    block_k: int = 256,
) -> jnp.ndarray:
    """Causal flash attention. q, k, v: [B, S, H, D] (same head counts).

    Positions are implicitly arange(S) per batch row — the dispatcher only
    routes here for uncached self-attention.
    """
    B, S, H, D = q.shape
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    out = _flash_forward(fold(q), fold(k), fold(v), block_q=block_q, block_k=block_k)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, block_q, block_k):
    return flash_attention(q, k, v, block_q, block_k), (q, k, v)


def _flash_bwd(block_q, block_k, res, g):
    """Backward via the XLA reference path (flash backward kernel: future
    work; jax.checkpoint around layers keeps peak memory bounded anyway)."""
    q, k, v = res

    def ref(q, k, v):
        from kukeon_tpu.ops.attention import attention_mask, attention_reference

        B, S = q.shape[0], q.shape[1]
        pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        return attention_reference(q, k, v, attention_mask(pos, pos))

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
