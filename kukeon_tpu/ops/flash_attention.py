"""Pallas TPU flash attention (causal-by-position, forward).

Online-softmax tiled attention: grid (batch*heads, q_blocks, kv_blocks) with
the kv dimension innermost/sequential; running max/sum/accumulator live in
VMEM scratch across kv steps, so the [S, S] score matrix never touches HBM.

Masking uses the caller's absolute position tensors (attend where
kv_position <= q_position), so arbitrary position layouts — offset
continuations, per-batch starts — are exact, matching
:func:`kukeon_tpu.ops.attention.attention_mask` semantics (without
kv_length, which only the cached-decode path needs). KV blocks that can
prove themselves fully masked via the arange fast path are predicated out.

The backward pass runs the XLA reference attention under ``jax.vjp``
(a fused flash backward kernel is future work; ``jax.checkpoint`` around
layers keeps peak memory bounded anyway).

Measured on v5e (bf16, H=8, D=64): parity with the fused XLA path at
S=2048, 27x faster at S=8192.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _flash_kernel(q_pos_ref, kv_pos_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Position rows arrive as full-length [1, 1, S] blocks (TPU block-shape
    # rules constrain the trailing two dims; a full row satisfies them and
    # costs ~S*4 bytes of VMEM); slice this tile's window.
    q_pos = q_pos_ref[0, 0, pl.ds(qi * block_q, block_q)]     # [bq] int32
    kv_pos = kv_pos_ref[0, 0, pl.ds(ki * block_k, block_k)]   # [bk] int32

    # Skip blocks that are provably fully masked (every kv position exceeds
    # every q position).
    @pl.when(jnp.min(kv_pos) <= jnp.max(q_pos))
    def _compute():
        q = q_ref[0]                       # [bq, D]
        k = k_ref[0]                       # [bk, D]
        v = v_ref[0]                       # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                          # [bq, bk]

        mask = kv_pos[None, :] <= q_pos[:, None]
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]              # [bq, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)             # [bq, bk] f32
        l_new = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)

        acc = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, q_positions, kv_positions, n_heads: int,
                   *, block_q: int, block_k: int, interpret: bool = False):
    """q, k, v: [BH, S, D] (GQA-expanded, heads folded into batch);
    q_positions / kv_positions: [B, S] int32 (per batch, shared by heads)."""
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    grid = (BH, S // block_q, S // block_k)
    H = n_heads

    kernel = functools.partial(
        _flash_kernel, scale=scale, block_q=block_q, block_k=block_k
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, S), lambda b, i, j: (b // H, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, i, j: (b // H, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),   # running sum
            pltpu.VMEM((block_q, D), jnp.float32),       # output accumulator
        ],
        interpret=interpret,
    )(q_positions[:, None, :], kv_positions[:, None, :], q, k, v)


def supports(q_len: int, kv_len: int, block: int = 256) -> bool:
    """Whether the kernel covers this shape (dispatcher guard)."""
    if q_len != kv_len:
        return False
    b = min(block, q_len)
    return q_len % b == 0 and q_len >= 128


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    block_q: int = 256,
    block_k: int = 256,
) -> jnp.ndarray:
    """Position-masked flash attention. q, k, v: [B, S, H, D] (equal head
    counts — GQA expansion happens in the dispatcher); positions: [B, S]."""
    B, S, H, D = q.shape
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, S, D)
    out = _flash_forward(
        fold(q), fold(k), fold(v),
        q_positions.astype(jnp.int32), kv_positions.astype(jnp.int32),
        H, block_q=block_q, block_k=block_k,
    )
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, q_positions, kv_positions, block_q, block_k):
    out = flash_attention(q, k, v, q_positions, kv_positions, block_q, block_k)
    return out, (q, k, v, q_positions, kv_positions)


def _flash_bwd(block_q, block_k, res, g):
    del block_q, block_k
    q, k, v, q_pos, kv_pos = res

    def ref(q, k, v):
        from kukeon_tpu.ops.attention import attention_mask, attention_reference

        return attention_reference(q, k, v, attention_mask(q_pos, kv_pos))

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    # Integer position inputs take float0 cotangents.
    zq = np.zeros(q_pos.shape, jax.dtypes.float0)
    zk = np.zeros(kv_pos.shape, jax.dtypes.float0)
    return dq, dk, dv, zq, zk


flash_attention.defvjp(_flash_fwd, _flash_bwd)
