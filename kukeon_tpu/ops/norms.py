"""Normalization ops.

RMSNorm as used by the Llama family. Computed in float32 regardless of input
dtype (the usual TPU-stable recipe: bf16 activations, f32 reductions), then
cast back so the surrounding matmuls stay bf16 on the MXU.
"""

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm over the trailing dimension.

    Args:
      x: [..., hidden] activations (any float dtype).
      scale: [hidden] learned gain.
      eps: numerical-stability epsilon.
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * scale.astype(jnp.float32)).astype(orig_dtype)
