from kukeon_tpu.ops.attention import gqa_attention, attention_reference  # noqa: F401
from kukeon_tpu.ops.norms import rms_norm  # noqa: F401
from kukeon_tpu.ops.rope import apply_rope  # noqa: F401
