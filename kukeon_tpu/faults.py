"""Fault-injection harness: named failure points, armed via environment.

The runtime's resilience behaviors (load shedding, deadline expiry, engine
recovery, watchdog trip, crash-atomic checkpointing) all respond to failures
that are hard to *time* in a test — a wedged chip, a kill mid-save, a dying
HTTP handler. This module turns each of them into a named seam:

    from kukeon_tpu import faults
    faults.maybe_fail("engine.decode")          # raises iff armed

Arming syntax (``KUKEON_FAULTS`` env var)::

    KUKEON_FAULTS=point:prob[:count][,point2:prob2[:count2]]

- ``point``  — the seam name passed to :func:`maybe_fail` (exact match).
- ``prob``   — firing probability per hit, ``1`` meaning always.
- ``count``  — optional cap on total fires for this point (e.g.
  ``engine.decode:1:2`` fails the first two decode dispatches, then
  passes). Without it the point fires forever.

Contract:

- **Unarmed is free.** With ``KUKEON_FAULTS`` unset/empty, :func:`maybe_fail`
  is a single dict lookup and returns immediately — no parsing, no locking,
  no allocation. Production code can leave the calls in hot-ish paths
  (engine dispatch, host transfers) without a measurable tax; the guard
  test in tests/test_faults.py pins this.
- **Env changes take effect immediately.** The parsed table is cached
  keyed on the raw env string, so tests may flip ``KUKEON_FAULTS`` between
  (or within) tests without touching module state; the conftest fixture
  clears the env and calls :func:`reset` around every test.
- Fires are counted in :data:`stats` so tests can assert a point actually
  triggered (a fault test whose seam was renamed must fail, not silently
  pass).
"""

from __future__ import annotations

import os
import random
import threading

ENV = "KUKEON_FAULTS"

# Every fault point threaded through the codebase, declared here so the
# observability layer can expose a ``kukeon_faults_fired_total{point=...}``
# sample for each one (zero when never fired) and the guard test in
# tests/test_obs.py can grep call sites against this list — a new
# ``maybe_fail("x.y")`` that is not declared here fails CI, so fault
# points can't ship unobservable.
POINTS = (
    "engine.prefill",
    "engine.decode",
    "engine.fetch",
    "engine.upload",
    "kv.alloc",
    "kv.handoff",
    "cell.http",
    "gateway.spill",
    "scaler.tick",
    "alerts.webhook",
    "checkpoint.save",
    "checkpoint.load",
    "checkpoint.stream",
    "devices.probe_wedged",
    "profile.capture",
    "profile.layers",
)


class FaultInjected(RuntimeError):
    """Raised by an armed fault point (the injected failure)."""


class _Point:
    __slots__ = ("prob", "remaining")

    def __init__(self, prob: float, remaining: int | None):
        self.prob = prob
        self.remaining = remaining   # None = unlimited


_lock = threading.Lock()
_cached_spec: str | None = None          # raw env value the table came from
_points: dict[str, _Point] = {}

# point -> number of times it fired (survives re-parses; reset() clears it).
stats: dict[str, int] = {}


def _parse(spec: str) -> dict[str, _Point]:
    points: dict[str, _Point] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if not bits[0]:
            raise ValueError(f"{ENV}: empty fault point in {part!r}")
        prob = float(bits[1]) if len(bits) > 1 and bits[1] else 1.0
        count = int(bits[2]) if len(bits) > 2 and bits[2] else None
        points[bits[0]] = _Point(prob, count)
    return points


def active() -> bool:
    """True when any fault spec is armed."""
    return bool(os.environ.get(ENV))


def fired(point: str) -> int:
    """How many times ``point`` has fired since the last :func:`reset`."""
    return stats.get(point, 0)


def reset() -> None:
    """Drop the parsed table and fire counts (test isolation seam)."""
    global _cached_spec
    with _lock:
        _cached_spec = None
        _points.clear()
        stats.clear()


def maybe_fail(point: str, exc: type[BaseException] = FaultInjected,
               msg: str | None = None) -> None:
    """Raise ``exc`` iff ``point`` is armed via ``KUKEON_FAULTS`` and fires.

    The unarmed path is a single env lookup; see module docstring.
    """
    spec = os.environ.get(ENV)
    if not spec:
        return
    global _cached_spec
    with _lock:
        if spec != _cached_spec:
            _points.clear()
            _points.update(_parse(spec))
            _cached_spec = spec
        p = _points.get(point)
        if p is None:
            return
        if p.remaining is not None and p.remaining <= 0:
            return
        if p.prob < 1.0 and random.random() >= p.prob:
            return
        if p.remaining is not None:
            p.remaining -= 1
        stats[point] = stats.get(point, 0) + 1
    raise exc(msg or f"injected fault at {point!r} ({ENV}={spec})")
