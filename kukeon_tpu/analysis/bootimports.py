"""KUKE013 — heavy module-scope imports in control-plane runtime modules.

`kuke get` answering in 40ms and the daemon booting instantly both depend
on one invariant: the control plane (CLI, daemon, runner, scaler, store —
everything under ``kukeon_tpu/runtime/`` EXCEPT the serving cell process
itself) never imports jax or the model/serving stack at module scope. A
single ``import jax`` at the top of a runtime module drags multi-second
framework initialization into every CLI invocation and every daemon
restart, and it silently survives review because the module still works —
just slowly. The streamed-boot work (PR 14) makes this worse to get wrong:
the cold-start budget is now max(disk, transfer, compile), and a control
plane that pays jax import tax adds a serial prefix no pipeline can hide.

Detection: an ``import``/``from ... import`` statement at module or class
scope (anything that executes at import time — function bodies are fine,
that is exactly the lazy-import idiom the codebase uses) whose target
module is ``jax``/``jax.*``, ``kukeon_tpu.models``/``.models.*``, or
``kukeon_tpu.serving``/``.serving.*``, in a file under
``kukeon_tpu/runtime/`` other than ``serving_cell.py`` (the serving
process is the data plane; its heavy imports are deliberate and measured
as the ``boot_imports`` cold-start phase).
"""

from __future__ import annotations

import ast
from typing import Sequence

from kukeon_tpu.analysis.core import Finding, SourceFile, register_pass

# Import prefixes that pull the accelerator/model stack in transitively.
HEAVY_PREFIXES = ("jax", "kukeon_tpu.models", "kukeon_tpu.serving")

# The data-plane process: execs as `python -m ...serving_cell`, measures
# its own import cost as the boot_imports phase — exempt by design.
EXEMPT_SUFFIXES = ("runtime/serving_cell.py",)

CONTROL_PLANE_DIR = "kukeon_tpu/runtime/"


def _is_heavy(module: str) -> bool:
    return any(module == p or module.startswith(p + ".")
               for p in HEAVY_PREFIXES)


def _heavy_targets(node: ast.stmt) -> list[str]:
    """Heavy module names an import statement binds, if any."""
    out: list[str] = []
    if isinstance(node, ast.Import):
        out.extend(a.name for a in node.names if _is_heavy(a.name))
    elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
        if _is_heavy(node.module):
            out.append(node.module)
        else:
            # `from kukeon_tpu import models` binds the heavy package too.
            out.extend(f"{node.module}.{a.name}" for a in node.names
                       if _is_heavy(f"{node.module}.{a.name}"))
    return out


@register_pass(("KUKE013",))
def check_boot_imports(sources: Sequence[SourceFile],
                       package_root: str) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        if CONTROL_PLANE_DIR not in src.rel:
            continue
        if src.rel.endswith(EXEMPT_SUFFIXES):
            continue

        def visit(node: ast.AST, scope: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # function bodies import lazily — the fix, not a bug
            if isinstance(node, ast.ClassDef):
                for child in ast.iter_child_nodes(node):
                    visit(child, node.name)  # class bodies run at import
                return
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for mod in _heavy_targets(node):
                    findings.append(Finding(
                        "KUKE013", src.rel, node.lineno,
                        f"module-scope import of {mod} in a control-plane "
                        f"runtime module pays framework init on every CLI "
                        f"call and daemon boot — move it inside the "
                        f"function that needs it",
                        scope=scope, detail=f"import:{mod}"))
                return
            for child in ast.iter_child_nodes(node):
                visit(child, scope)

        for stmt in src.tree.body:
            visit(stmt, "<module>")
    return findings
