"""KUKE005/KUKE006 — lock discipline across the threaded modules.

The runtime is full of small, single-purpose locks (engine admission,
cell lifecycle/stats, registry, tracer, runner per-cell locks…). Two
properties keep them honest, both checkable from the AST:

- **KUKE005 — consistent guarding.** Per class: an attribute that is
  written under ``self.<lock>`` *anywhere* must never be written outside
  it. Half-guarded state is the classic latent race — the locked site
  documents the intent, the unlocked one silently breaks it. Constructor
  writes (``__init__``/``__post_init__``/``_init*`` helpers) are exempt:
  the object is not shared yet. A private method whose every intra-class
  call site sits inside a region of the same lock is treated as running
  under that lock (one level of call-mediated context, computed to a
  fixed point), so ``call()``-holds-the-lock-then-calls-``_ensure_conn``
  patterns do not false-positive. Inference can be supplemented with an
  explicit ``# guarded-by: <lock>`` comment on any ``self.attr = …``
  statement (normally the constructor's): the attribute joins the
  guarded set even when no locked write exists yet for inference to
  learn from.
- **KUKE006 — acquisition-order cycles.** A directed graph over every
  lock in the package: edge A→B when code acquires B while holding A,
  either lexically (nested ``with``) or through a call made inside A's
  region that resolves to a method acquiring B (resolution: same-class
  ``self.m()``; ``self.attr.m()`` where ``self.attr`` is assigned a
  constructor of a package class — imports followed one re-export hop).
  Any cycle is a potential deadlock and is reported once per cycle with
  the participating edges. Resolution is deliberately under-approximate
  (unknown callees add no edge): a reported cycle is real evidence, not
  name-collision noise.

Lock identification: an attribute assigned ``threading.Lock()`` /
``RLock()`` (instance or class level) or the sanitize factory's
``sanitize.lock()`` / ``sanitize.rlock()``, a module-level name so
assigned, or — for classes that receive a lock by injection — any
``with self.X:`` where ``X`` contains ``lock`` or ``mu`` (the obs
registry hands its lock to the metrics it creates; the convention is
load-bearing and cheap to honor).

The per-class guarded-attribute sets this pass infers are also the
**guarded-by contract** the dynamic sanitizer (kukeon_tpu/sanitize,
"kukesan") enforces at runtime: :func:`guarded_contracts` exports them,
``python -m kukeon_tpu.analysis --write-contracts`` persists them to
``analysis/guarded_by.json``, and kukesan's ``__setattr__`` hooks check
every write against that file while the suite runs under
``KUKEON_SANITIZE=1``. Likewise :func:`build_lock_graph` exposes the
KUKE006 edge set so kukesan can diff the runtime-observed acquisition
graph against the static one (sanitize/report.py).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Sequence

from kukeon_tpu.analysis.core import (
    Finding, SourceFile, is_self_attr, register_pass,
)

INIT_EXEMPT_PREFIXES = ("__init__", "__post_init__", "_init")

_LOCKY = ("lock", "mu", "mutex")

# ``self.attr = …  # guarded-by: _lock`` (comma-separated lock names).
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z0-9_,\s]+)")


def _is_lock_ctor(node: ast.AST) -> bool:
    """``threading.Lock()`` / ``threading.RLock()`` / bare ``Lock()`` /
    the sanitize factory's ``sanitize.lock()`` / ``sanitize.rlock()``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and f.value.id in ("sanitize", "san")
            and f.attr in ("lock", "rlock")):
        return True
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    return name in ("Lock", "RLock")


def _locky_name(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in _LOCKY)


@dataclasses.dataclass
class _Write:
    attr: str
    method: str
    line: int
    locks: frozenset[str]     # lock names held lexically at the write


@dataclasses.dataclass
class _ClassInfo:
    module: str               # rel path of the defining file
    name: str
    node: ast.ClassDef
    lock_attrs: set[str] = dataclasses.field(default_factory=set)
    writes: list[_Write] = dataclasses.field(default_factory=list)
    # method -> [(locks-held-at-call, callee-expr)]
    calls: dict[str, list[tuple[frozenset, ast.Call]]] = (
        dataclasses.field(default_factory=dict))
    # method -> locks it acquires anywhere in its body
    acquires: dict[str, set[str]] = dataclasses.field(default_factory=dict)
    # self.attr -> class name assigned via ``self.attr = ClassName(...)``
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)
    # attr -> lock names from explicit ``# guarded-by:`` annotations.
    declared: dict[str, set[str]] = dataclasses.field(default_factory=dict)

    def lock_id(self, lock_name: str) -> str:
        return f"{self.module}:{self.name}.{lock_name}"

    def guarded_attrs(self) -> dict[str, set[str]]:
        """attr -> self-attr lock names guarding it: the union of inference
        (written under a lock anywhere outside init) and explicit
        ``# guarded-by:`` declarations. Lock attributes themselves and
        module-level lock guards are excluded — the contract consumer
        (kukesan's ``__setattr__`` hook) can only resolve ``self.<lock>``."""
        ctx = _locked_context_methods(self)
        out: dict[str, set[str]] = {}
        for w in self.writes:
            held = w.locks | ctx.get(w.method, frozenset())
            held = {h for h in held if not h.startswith("<module>:")}
            if held and w.attr not in self.lock_attrs:
                out.setdefault(w.attr, set()).update(held)
        for attr, locks in self.declared.items():
            if attr not in self.lock_attrs:
                out.setdefault(attr, set()).update(locks)
        return out


def _with_lock_items(node: ast.With, cls: "_ClassInfo | None",
                     module_locks: set[str]) -> list[str]:
    """Names of locks acquired by this ``with`` (empty for non-lock withs)."""
    out: list[str] = []
    for item in node.items:
        ctx = item.context_expr
        if is_self_attr(ctx):
            if cls is not None and (ctx.attr in cls.lock_attrs
                                    or _locky_name(ctx.attr)):
                if cls is not None:
                    cls.lock_attrs.add(ctx.attr)
                out.append(ctx.attr)
        elif isinstance(ctx, ast.Name) and ctx.id in module_locks:
            out.append(f"<module>:{ctx.id}")
    return out


def _scan_function(fn: ast.FunctionDef, cls: _ClassInfo | None,
                   module_locks: set[str]) -> None:
    """Record writes, lock regions, and in-region calls for one function."""
    acquires: set[str] = set()

    def visit(node: ast.AST, held: frozenset) -> None:
        if isinstance(node, ast.With):
            got = _with_lock_items(node, cls, module_locks)
            acquires.update(got)
            inner = frozenset(held | set(got))
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return   # nested defs run later, under unknown locks
        if cls is not None:
            target_attrs: list[tuple[str, int]] = []
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    target_attrs.extend(_attr_writes(t))
                # Track ``self.attr = ClassName(...)`` for call resolution.
                if (len(node.targets) == 1
                        and is_self_attr(node.targets[0])
                        and isinstance(node.value, ast.Call)):
                    c = _ctor_name(node.value)
                    if c:
                        cls.attr_types[node.targets[0].attr] = c
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                target_attrs.extend(_attr_writes(node.target))
            for attr, line in target_attrs:
                cls.writes.append(_Write(attr, fn.name, line, held))
            if isinstance(node, ast.Call):
                cls.calls.setdefault(fn.name, []).append((held, node))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, frozenset())
    if cls is not None:
        cls.acquires.setdefault(fn.name, set()).update(acquires)


def _attr_writes(target: ast.AST) -> list[tuple[str, int]]:
    """self-attribute names written by an assignment target, including
    through a subscript (``self.x[k] = v`` mutates ``self.x``)."""
    out: list[tuple[str, int]] = []
    if is_self_attr(target):
        out.append((target.attr, target.lineno))
    elif isinstance(target, ast.Subscript) and is_self_attr(target.value):
        out.append((target.value.attr, target.lineno))
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            out.extend(_attr_writes(elt))
    return out


def _ctor_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _marker_lines(text: str) -> dict[int, set[str]]:
    """lineno -> lock names for every ``# guarded-by: A, B`` comment."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = _GUARDED_BY_RE.search(line)
        if m:
            names = {n.strip() for n in m.group(1).split(",") if n.strip()}
            if names:
                out[i] = names
    return out


def _collect_class(src: SourceFile, node: ast.ClassDef,
                   module_locks: set[str],
                   markers: dict[int, set[str]]) -> _ClassInfo:
    info = _ClassInfo(module=src.rel, name=node.name, node=node)
    # Pre-pass: find declared lock attributes (instance + class level).
    for sub in ast.walk(node):
        if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
            for t in sub.targets:
                if is_self_attr(t):
                    info.lock_attrs.add(t.attr)
                elif isinstance(t, ast.Name):
                    info.lock_attrs.add(t.id)     # class-level lock
    for meth in node.body:
        if isinstance(meth, ast.FunctionDef):
            _scan_function(meth, info, module_locks)
    # Explicit guard declarations: a write line carrying a guarded-by
    # comment binds the written attribute(s) to the named lock(s).
    for w in info.writes:
        names = markers.get(w.line)
        if names:
            info.declared.setdefault(w.attr, set()).update(names)
    return info


def _locked_context_methods(info: _ClassInfo) -> dict[str, frozenset]:
    """Private methods that only ever run with a known lock held: every
    intra-class ``self.m()`` call site is inside a region of the same
    lock(s). Fixed point so chains (A locks, calls _b, _b calls _c)
    resolve."""
    # method -> set of (held) frozensets at each intra-class call site
    sites: dict[str, list[frozenset]] = {}
    for caller, calls in info.calls.items():
        for held, call in calls:
            f = call.func
            if is_self_attr(f) and f.attr != caller:
                sites.setdefault(f.attr, []).append(held)
    ctx: dict[str, frozenset] = {}
    for _ in range(len(sites) + 1):
        changed = False
        for meth, helds in sites.items():
            if not meth.startswith("_") or meth.startswith("__"):
                continue
            eff = []
            for caller, calls in info.calls.items():
                for held, call in calls:
                    f = call.func
                    if is_self_attr(f, meth):
                        eff.append(held | ctx.get(caller, frozenset()))
            if not eff:
                continue
            common = frozenset.intersection(*[frozenset(e) for e in eff])
            if common and ctx.get(meth) != common:
                ctx[meth] = common
                changed = True
        if not changed:
            break
    return ctx


def _collect_model(sources: Sequence[SourceFile], package_root: str
                   ) -> tuple[list[_ClassInfo], dict[str, list[_ClassInfo]]]:
    """Parse every class's lock model once (shared by the KUKE005/006
    checks, the guarded-by contract export, and the lock-graph export)."""
    classes: list[_ClassInfo] = []
    classes_by_name: dict[str, list[_ClassInfo]] = {}
    for src in sources:
        module_locks = {
            t.id
            for stmt in src.tree.body if isinstance(stmt, ast.Assign)
            and _is_lock_ctor(stmt.value)
            for t in stmt.targets if isinstance(t, ast.Name)
        }
        markers = _marker_lines(src.text)
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                info = _collect_class(src, node, module_locks, markers)
                classes.append(info)
                classes_by_name.setdefault(node.name, []).append(info)
            elif isinstance(node, ast.FunctionDef):
                _scan_function(node, None, module_locks)
    return classes, classes_by_name


def build_lock_graph(sources: Sequence[SourceFile], package_root: str
                     ) -> dict[tuple[str, str], tuple[str, int]]:
    """The KUKE006 acquisition-order graph: ``(held, acquired) -> (module,
    line)`` over lock ids of the form ``path/to/file.py:Class.lock``.
    Exposed so kukesan can merge the runtime-observed graph with this one
    and report the edges the static pass could not see."""
    classes, classes_by_name = _collect_model(sources, package_root)
    return _build_edges(classes, classes_by_name)


def _build_edges(classes: list[_ClassInfo],
                 classes_by_name: dict[str, list[_ClassInfo]]
                 ) -> dict[tuple[str, str], tuple[str, int]]:
    # Locks a method of a class acquires (for call-mediated edges).
    acquires_of: dict[tuple[str, str], set[str]] = {}
    for info in classes:
        for meth, locks in info.acquires.items():
            ids = {
                info.lock_id(n) if not n.startswith("<module>:")
                else f"{info.module}:{n[9:]}"
                for n in locks
            }
            if ids:
                acquires_of[(info.name, meth)] = ids

    edges: dict[tuple[str, str], tuple[str, int]] = {}

    def add_edge(a: str, b: str, module: str, line: int) -> None:
        if a != b and (a, b) not in edges:
            edges[(a, b)] = (module, line)

    for info in classes:
        for caller, calls in info.calls.items():
            for held, call in calls:
                if not held:
                    continue
                held_ids = [
                    info.lock_id(n) if not n.startswith("<module>:")
                    else f"{info.module}:{n[9:]}"
                    for n in held
                ]
                f = call.func
                callee_acquires: set[str] = set()
                if is_self_attr(f):
                    callee_acquires = acquires_of.get(
                        (info.name, f.attr), set())
                elif (isinstance(f, ast.Attribute)
                      and is_self_attr(f.value)):
                    tname = info.attr_types.get(f.value.attr)
                    if tname:
                        for target in classes_by_name.get(tname, ()):
                            callee_acquires |= acquires_of.get(
                                (target.name, f.attr), set())
                for a in held_ids:
                    for b in callee_acquires:
                        add_edge(a, b, info.module, call.lineno)
        # Lexical nesting inside one class: a with-region acquiring a
        # second lock shows up as acquires during a held region — catch it
        # by rescanning withs with held context.
        for meth in info.node.body:
            if not isinstance(meth, ast.FunctionDef):
                continue
            _nested_with_edges(meth, info, add_edge)
    return edges


def guarded_contracts(sources: Sequence[SourceFile], package_root: str
                      ) -> dict[str, dict[str, list[str]]]:
    """``dotted.module.Class -> attr -> sorted lock names``: the KUKE005
    guarded-attribute sets (inferred + ``# guarded-by:`` declared) in the
    machine-readable shape both kukelint and kukesan consume. Persisted by
    ``--write-contracts`` as ``analysis/guarded_by.json``; kukesan's
    ``__setattr__`` hooks enforce it at runtime."""
    classes, _ = _collect_model(sources, package_root)
    out: dict[str, dict[str, list[str]]] = {}
    rel_to_dotted = {
        src.rel: _modname(src, package_root) for src in sources}
    for info in classes:
        guarded = info.guarded_attrs()
        if not guarded:
            continue
        key = f"{rel_to_dotted[info.module]}.{info.name}"
        out[key] = {attr: sorted(locks)
                    for attr, locks in sorted(guarded.items())}
    return dict(sorted(out.items()))


def default_contracts_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "guarded_by.json")


def render_contracts(contracts: dict[str, dict[str, list[str]]]) -> str:
    import json

    return json.dumps(
        {"version": 1,
         "comment": "KUKE005 guarded-by contract, generated by "
                    "`python -m kukeon_tpu.analysis --write-contracts`. "
                    "Consumed by kukeon_tpu/sanitize (kukesan) __setattr__ "
                    "hooks under KUKEON_SANITIZE=1. Do not edit by hand: "
                    "add `# guarded-by:` annotations or locked writes in "
                    "the source and regenerate.",
         "classes": contracts},
        indent=2, sort_keys=True) + "\n"


@register_pass(("KUKE005", "KUKE006"))
def check_locks(sources: Sequence[SourceFile],
                package_root: str) -> list[Finding]:
    findings: list[Finding] = []
    classes, classes_by_name = _collect_model(sources, package_root)

    # --- KUKE005: locked-somewhere means locked-everywhere ---------------
    for info in classes:
        ctx = _locked_context_methods(info)
        locked_attrs: dict[str, set[str]] = {}
        for w in info.writes:
            held = w.locks | ctx.get(w.method, frozenset())
            if held:
                locked_attrs.setdefault(w.attr, set()).update(held)
        for attr, locks in info.declared.items():
            locked_attrs.setdefault(attr, set()).update(locks)
        for w in info.writes:
            if w.attr not in locked_attrs:
                continue
            if w.attr in info.lock_attrs:
                continue
            if any(w.method.startswith(p) for p in INIT_EXEMPT_PREFIXES):
                continue
            held = w.locks | ctx.get(w.method, frozenset())
            if not held:
                declared = w.attr in info.declared
                guards = ", ".join(sorted(
                    f"self.{g}" for g in locked_attrs[w.attr]))
                why = ("declared `# guarded-by` " if declared
                       else f"written under {guards} elsewhere ")
                findings.append(Finding(
                    "KUKE005", info.module, w.line,
                    f"self.{w.attr} is {why}"
                    f"in {info.name} but written without the lock here "
                    f"({info.name}.{w.method}) — guard this write or "
                    f"document why the attribute needs no lock at all",
                    scope=f"{info.name}.{w.method}",
                    detail=w.attr))

    # --- KUKE006: acquisition-order cycle detection ----------------------
    findings.extend(_find_cycles(_build_edges(classes, classes_by_name)))
    return findings


def _nested_with_edges(fn: ast.FunctionDef, info: _ClassInfo,
                       add_edge) -> None:
    def visit(node: ast.AST, held: list[str]) -> None:
        if isinstance(node, ast.With):
            got = [n for n in _with_lock_items(node, info, set())]
            ids = [info.lock_id(n) for n in got]
            for a in held:
                for b in ids:
                    add_edge(a, b, info.module, node.lineno)
            for child in node.body:
                visit(child, held + ids)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, [])


def _find_cycles(edges: dict[tuple[str, str], tuple[str, int]]
                 ) -> list[Finding]:
    """Report each elementary cycle once (smallest node first)."""
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    seen_cycles: set[tuple[str, ...]] = set()
    findings: list[Finding] = []

    def dfs(start: str, node: str, path: list[str],
            on_path: set[str]) -> None:
        for nxt in adj.get(node, ()):  # noqa: B007
            if nxt == start and len(path) >= 1:
                cyc = path + [start]
                anchor = min(cyc[:-1])
                i = cyc.index(anchor)
                canon = tuple(cyc[i:-1] + cyc[:i])
                if canon in seen_cycles:
                    continue
                seen_cycles.add(canon)
                module, line = edges[(path[-1], start)]
                chain = " -> ".join(list(canon) + [canon[0]])
                findings.append(Finding(
                    "KUKE006", module, line,
                    f"lock acquisition-order cycle (potential deadlock): "
                    f"{chain}",
                    scope="lock-graph", detail=chain))
            elif nxt not in on_path:
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return findings


def _modname(src: SourceFile, package_root: str) -> str:
    rel = os.path.relpath(src.path, os.path.dirname(package_root))
    return rel[:-3].replace(os.sep, ".")
