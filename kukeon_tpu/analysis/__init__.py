"""kukelint — the in-tree static analyzer that enforces the runtime's own
invariants (host-sync discipline, jit stability, lock discipline, the
fault-point and metric registries) as lint errors with stable rule ids.

Run it::

    python -m kukeon_tpu.analysis            # whole package, baseline applied
    python -m kukeon_tpu.analysis --select KUKE005,KUKE006
    python -m kukeon_tpu.analysis --update-baseline

Rules:

======== =====================================================================
KUKE001  device→host transfer in an engine hot-path method outside ``_fetch``
KUKE002  host→device upload in an engine hot-path method outside ``_upload``
KUKE003  Python container literal in a traced position of a jitted program
KUKE004  jitted program closes over mutable engine state
KUKE005  attribute written under a lock somewhere, written unlocked elsewhere
KUKE006  lock acquisition-order cycle (potential deadlock)
KUKE007  fault point not declared in faults.POINTS (or stale declaration)
KUKE008  ``kukeon_*`` metric family missing from the README reference table
KUKE009  sub-10ms ``time.sleep`` polling loop (busy-wait in disguise)
KUKE010  span phase/mark literal not declared in ``obs/trace.py`` PHASES
         (or stale declaration, or a dynamic phase name)
KUKE011  built-in alert rule references a metric family no module declares
KUKE012  raw device transfer in KV export/import (handoff) code outside the
         counted ``_fetch``/``_upload``/``sanitize.blocking`` seams
KUKE013  heavy module-scope import in a control-plane runtime module
KUKE014  jitted program compiled without explicit ``in_shardings`` /
         ``out_shardings`` (implicit GSPMD placement on a mesh engine)
======== =====================================================================

Zero-dependency by design (stdlib ``ast`` only): importable and runnable
without jax, so it can gate commits anywhere the repo checks out. The
checked-in baseline (``analysis/baseline.json``) suppresses accepted
pre-existing findings — a new violation fails the run and the tier-1
test in tests/test_static_analysis.py.

kukelint is the *static* half of a pair: the KUKE005 guarded-by sets it
infers are exported as a machine-readable contract
(``--write-contracts`` → ``analysis/guarded_by.json``) that the dynamic
concurrency sanitizer — kukesan, ``kukeon_tpu/sanitize``, armed by
``KUKEON_SANITIZE=1`` — enforces while the test suite actually runs,
and kukesan merges its runtime-observed lock-acquisition graph back
into the KUKE006 static graph to report the edges (callback-reached
locks, dynamically started threads) the AST pass cannot see.
"""

from kukeon_tpu.analysis.core import (
    Baseline,
    BaselineEntry,
    Finding,
    default_baseline_path,
    load_sources,
    registered_rules,
    run_analysis,
)
from kukeon_tpu.analysis.locks import (
    build_lock_graph,
    default_contracts_path,
    guarded_contracts,
    render_contracts,
)

__all__ = [
    "Baseline",
    "BaselineEntry",
    "Finding",
    "build_lock_graph",
    "default_baseline_path",
    "default_contracts_path",
    "guarded_contracts",
    "load_sources",
    "registered_rules",
    "render_contracts",
    "run_analysis",
]
