"""kukelint — the in-tree static analyzer that enforces the runtime's own
invariants (host-sync discipline, jit stability, lock discipline, the
fault-point and metric registries) as lint errors with stable rule ids.

Run it::

    python -m kukeon_tpu.analysis            # whole package, baseline applied
    python -m kukeon_tpu.analysis --select KUKE005,KUKE006
    python -m kukeon_tpu.analysis --update-baseline

Rules:

======== =====================================================================
KUKE001  device→host transfer in an engine hot-path method outside ``_fetch``
KUKE002  host→device upload in an engine hot-path method outside ``_upload``
KUKE003  Python container literal in a traced position of a jitted program
KUKE004  jitted program closes over mutable engine state
KUKE005  attribute written under a lock somewhere, written unlocked elsewhere
KUKE006  lock acquisition-order cycle (potential deadlock)
KUKE007  fault point not declared in faults.POINTS (or stale declaration)
KUKE008  ``kukeon_*`` metric family missing from the README reference table
======== =====================================================================

Zero-dependency by design (stdlib ``ast`` only): importable and runnable
without jax, so it can gate commits anywhere the repo checks out. The
checked-in baseline (``analysis/baseline.json``) suppresses accepted
pre-existing findings — a new violation fails the run and the tier-1
test in tests/test_static_analysis.py.
"""

from kukeon_tpu.analysis.core import (
    Baseline,
    BaselineEntry,
    Finding,
    default_baseline_path,
    load_sources,
    registered_rules,
    run_analysis,
)

__all__ = [
    "Baseline",
    "BaselineEntry",
    "Finding",
    "default_baseline_path",
    "load_sources",
    "registered_rules",
    "run_analysis",
]
