"""KUKE009 — sub-10ms sleep-polling loops in package hot paths.

A loop whose body sleeps for less than 10ms is a busy-wait in disguise: it
burns a core, wakes the scheduler ~1000×/s, and adds up to a full sleep
quantum of latency to the event it is polling for — all to emulate what a
``threading.Condition``/``Event`` signal does for free. PR 8 replaced the
engine loop's ``time.sleep(0.001)`` with a condition-variable work signal;
this rule keeps the pattern from silently returning anywhere in the
package.

Detection: a ``time.sleep(X)`` call lexically inside a ``while``/``for``
loop where ``X`` is a numeric literal (or a module-level constant assigned
one) below :data:`THRESHOLD_S`. Sleeps at or above 10ms are judged
acceptable poll intervals (drain/rollout polling); nested function bodies
are skipped (they run on someone else's schedule, not the loop's).
"""

from __future__ import annotations

import ast
from typing import Sequence

from kukeon_tpu.analysis.core import (
    Finding, SourceFile, qualname, register_pass,
)

THRESHOLD_S = 0.01


def _module_consts(tree: ast.Module) -> dict[str, float]:
    out: dict[str, float] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, (int, float))
                and not isinstance(stmt.value.value, bool)):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = float(stmt.value.value)
    return out


def _sleep_seconds(call: ast.Call,
                   consts: dict[str, float]) -> float | None:
    """The literal/constant duration of a ``time.sleep(X)`` call, else
    None (dynamic durations are not judged — they may be long)."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "sleep"
            and isinstance(f.value, ast.Name) and f.value.id == "time"):
        return None
    if not call.args:
        return None
    arg = call.args[0]
    if (isinstance(arg, ast.Constant)
            and isinstance(arg.value, (int, float))
            and not isinstance(arg.value, bool)):
        return float(arg.value)
    if isinstance(arg, ast.Name):
        return consts.get(arg.id)
    return None


@register_pass(("KUKE009",))
def check_busywait(sources: Sequence[SourceFile],
                   package_root: str) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        consts = _module_consts(src.tree)

        def visit(node: ast.AST, stack: list[ast.AST],
                  loop_depth: int) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # A nested scope's body does not run inside the enclosing
                # loop's iterations: reset the loop context.
                for child in ast.iter_child_nodes(node):
                    visit(child, stack + [node], 0)
                return
            if isinstance(node, ast.Lambda):
                for child in ast.iter_child_nodes(node):
                    visit(child, stack, 0)
                return
            if isinstance(node, (ast.While, ast.For)):
                loop_depth += 1
            if isinstance(node, ast.Call) and loop_depth > 0:
                s = _sleep_seconds(node, consts)
                if s is not None and s < THRESHOLD_S:
                    scope = qualname(stack) or "<module>"
                    findings.append(Finding(
                        "KUKE009", src.rel, node.lineno,
                        f"time.sleep({s:g}) inside a loop is a sub-10ms "
                        f"busy-wait — signal the loop with a "
                        f"threading.Condition/Event (notify on the state "
                        f"change it polls for) instead of spin-sleeping",
                        scope=scope, detail=f"sleep:{s:g}"))
            for child in ast.iter_child_nodes(node):
                visit(child, stack, loop_depth)

        for stmt in src.tree.body:
            visit(stmt, [], 0)
    return findings
