"""CLI: ``python -m kukeon_tpu.analysis [options] [package_root]``.

Exit codes: 0 = clean (all findings baseline-suppressed), 1 = new
findings, 2 = bad usage. Stale baseline entries are reported but do not
fail the run (they fail ``--strict-baseline``, which tools/check.sh and
the tier-1 self-check use so the baseline cannot rot).
"""

from __future__ import annotations

import argparse
import os
import sys

from kukeon_tpu.analysis.core import (
    Baseline, BaselineEntry, default_baseline_path, registered_rules,
    run_analysis,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kukeon_tpu.analysis",
        description="kukelint: enforce the runtime's own invariants",
    )
    parser.add_argument(
        "package_root", nargs="?",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="package directory to analyze (default: the installed "
             "kukeon_tpu package)")
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids to run (default: all)")
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline file (default: kukeon_tpu/analysis/baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, suppressing nothing")
    parser.add_argument(
        "--strict-baseline", action="store_true",
        help="also fail on stale baseline entries (pre-PR gate mode)")
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings, keeping "
             "existing justifications; new entries get a TODO marker")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the registered rule ids and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in registered_rules():
            print(rule)
        return 0

    select = args.select.split(",") if args.select else None
    try:
        findings = run_analysis(args.package_root, select=select)
    except (OSError, SyntaxError) as e:
        print(f"kukelint: cannot analyze {args.package_root}: {e}",
              file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path()
    baseline = (Baseline() if args.no_baseline
                else Baseline.load(baseline_path))

    if args.update_baseline:
        kept = {e.fingerprint: e for e in baseline.entries}
        baseline.entries = [
            kept.get(f.fingerprint,
                     BaselineEntry(f.fingerprint, "TODO: justify"))
            for f in {f.fingerprint: f for f in findings}.values()
        ]
        baseline.save(baseline_path)
        print(f"kukelint: baseline rewritten with "
              f"{len(baseline.entries)} suppression(s) at {baseline_path}")
        return 0

    new, suppressed, stale = baseline.apply(findings)
    for f in new:
        print(f.render())
    for e in stale:
        print(f"kukelint: stale baseline entry (matches nothing): "
              f"{e.fingerprint}")
    print(f"kukelint: {len(new)} finding(s), {len(suppressed)} suppressed "
          f"by baseline, {len(stale)} stale baseline entr(ies)")
    if new:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
