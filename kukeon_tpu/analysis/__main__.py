"""CLI: ``python -m kukeon_tpu.analysis [options] [package_root]``.

Exit codes: 0 = clean (all findings baseline-suppressed), 1 = new
findings, 2 = bad usage. Stale baseline entries are reported but do not
fail the run (they fail ``--strict-baseline``, which tools/check.sh and
the tier-1 self-check use so the baseline cannot rot).

``--format json`` emits one machine-readable document (stable finding
ids = baseline fingerprints, file:line, stale entries) for tooling;
``--format github`` emits ``::error file=…,line=…`` workflow commands so
CI annotates findings inline on the PR diff. ``--write-contracts``
regenerates the KUKE005 guarded-by contract file
(``analysis/guarded_by.json``) that the dynamic sanitizer (kukesan)
enforces at runtime.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kukeon_tpu.analysis.core import (
    Baseline, BaselineEntry, default_baseline_path, load_sources,
    registered_rules, run_analysis,
)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kukeon_tpu.analysis",
        description="kukelint: enforce the runtime's own invariants",
    )
    parser.add_argument(
        "package_root", nargs="?",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="package directory to analyze (default: the installed "
             "kukeon_tpu package)")
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids to run (default: all)")
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline file (default: kukeon_tpu/analysis/baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, suppressing nothing")
    parser.add_argument(
        "--strict-baseline", action="store_true",
        help="also fail on stale baseline entries (pre-PR gate mode)")
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings, keeping "
             "existing justifications; new entries get a TODO marker")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the registered rule ids and exit")
    parser.add_argument(
        "--format", default="text", choices=("text", "json", "github"),
        dest="fmt",
        help="finding output format: human text (default), one JSON "
             "document for tooling, or GitHub workflow commands for "
             "inline CI annotations")
    parser.add_argument(
        "--write-contracts", nargs="?", const="", default=None,
        metavar="PATH",
        help="regenerate the KUKE005 guarded-by contract file consumed "
             "by the kukesan runtime sanitizer (default path: "
             "kukeon_tpu/analysis/guarded_by.json) and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in registered_rules():
            print(rule)
        return 0

    if args.write_contracts is not None:
        from kukeon_tpu.analysis import locks

        path = args.write_contracts or locks.default_contracts_path()
        contracts = locks.guarded_contracts(
            load_sources(args.package_root), args.package_root)
        with open(path, "w", encoding="utf-8") as f:
            f.write(locks.render_contracts(contracts))
        print(f"kukelint: guarded-by contract for {len(contracts)} "
              f"class(es) written to {path}")
        return 0

    select = args.select.split(",") if args.select else None
    try:
        findings = run_analysis(args.package_root, select=select)
    except (OSError, SyntaxError) as e:
        print(f"kukelint: cannot analyze {args.package_root}: {e}",
              file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path()
    baseline = (Baseline() if args.no_baseline
                else Baseline.load(baseline_path))

    if args.update_baseline:
        kept = {e.fingerprint: e for e in baseline.entries}
        baseline.entries = [
            kept.get(f.fingerprint,
                     BaselineEntry(f.fingerprint, "TODO: justify"))
            for f in {f.fingerprint: f for f in findings}.values()
        ]
        baseline.save(baseline_path)
        print(f"kukelint: baseline rewritten with "
              f"{len(baseline.entries)} suppression(s) at {baseline_path}")
        return 0

    new, suppressed, stale = baseline.apply(findings)
    if args.fmt == "json":
        # One machine-readable document: stable ids (the baseline
        # fingerprint doubles as the finding id — line-independent, so
        # tooling can track a finding across unrelated edits), file:line
        # for annotation placement, and the stale entries CI should nag
        # about. kukesan findings serialize to the same shape
        # (sanitize/runtime.py SanFinding.to_dict), so one consumer
        # handles both analyzers' reports.
        print(json.dumps({
            "version": 1,
            "tool": "kukelint",
            "findings": [
                {"id": f.fingerprint, "rule": f.rule, "file": f.file,
                 "line": f.line, "scope": f.scope, "detail": f.detail,
                 "message": f.message}
                for f in new
            ],
            "suppressed": len(suppressed),
            "stale_baseline_entries": [e.fingerprint for e in stale],
        }, indent=2))
    elif args.fmt == "github":
        for f in new:
            # Workflow-command escaping: newlines/%/CR would truncate the
            # annotation message.
            msg = (f.message.replace("%", "%25").replace("\r", "%0D")
                   .replace("\n", "%0A"))
            print(f"::error file={f.file},line={f.line},"
                  f"title={f.rule}::{msg}")
        for e in stale:
            print(f"::warning title=kukelint stale baseline::"
                  f"baseline entry matches nothing: {e.fingerprint}")
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"kukelint: stale baseline entry (matches nothing): "
                  f"{e.fingerprint}")
        print(f"kukelint: {len(new)} finding(s), {len(suppressed)} "
              f"suppressed by baseline, {len(stale)} stale baseline "
              f"entr(ies)")
    if new:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
