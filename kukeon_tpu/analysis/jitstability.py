"""KUKE003/KUKE004/KUKE014/KUKE015 — jit-stability, placement, and
observability of the engine's compiled programs.

The engine's performance story rests on "decode never recompiles": its
jitted programs are built once in ``_build_programs`` and every dispatch
must hit the tracing cache. Two statically-checkable ways to break that:

- **KUKE003 — container literals in traced positions.** A Python
  list/tuple/dict/set literal (or comprehension) passed where the program
  expects an array becomes part of the *pytree structure* of the call, so
  its length/keys are baked into the cache key — a per-request-sized list
  mints a fresh compile per length. Arrays (numpy or device) are the only
  safe payload in a traced position. Positions declared ``static_argnums``
  are exempt (their values are legitimately part of the cache key; the
  engine bounds them separately, e.g. chunk sizes rounded to powers of 4).
- **KUKE004 — closing over mutable engine state.** The program bodies are
  closures; a read of ``self.X`` inside one is evaluated at *trace* time
  and frozen into every cached executable. For init-frozen configuration
  that is fine (and used: ``self.max_seq_len``, ``self._bucket``); for
  mutable scheduler state (``self.state``, ``self._slot_len``, the pool…)
  it is a silent staleness bug — the compiled program keeps the value the
  first trace saw. Only the declared frozen allowlist may appear.

A third statically-checkable property guards the multi-chip story:

- **KUKE014 — implicit placement on a mesh-enabled engine.** The engine
  serves on an explicit mesh (1..N chips); a ``jax.jit`` without
  ``in_shardings``/``out_shardings`` leaves placement to GSPMD inference,
  which can silently replicate a sharded KV pool (N× HBM) or insert a
  resharding transfer on the decode path. Every jitted-program definition
  in ``_build_programs`` must pass BOTH keywords — replication is fine,
  but it must be spelled (``NamedSharding(mesh, PartitionSpec())``), never
  defaulted.

A fourth guards the roofline instrumentation:

- **KUKE015 — programs must register with the program-timer seam.** Every
  jitted program wrapped in ``_build_programs`` must pass a ``timer=``
  keyword to ``CompileTracker.wrap`` (``timer=tm.track("<program>")``).
  A program wrapped without one dispatches invisibly to the per-program
  wall-time/MFU gauges (``kukeon_program_seconds``,
  ``kukeon_program_mfu``) — the flight recorder and the bench's
  ``program_costs`` section would silently under-report where device
  time goes.

All rules are scoped to ``serving/engine.py``'s ``ServingEngine``: the
pass reads ``_build_programs`` to learn which inner functions are jitted
(and their ``static_argnums``), then checks every call site of the seven
``self._<program>`` attributes across the class (including the
``.lower(...)`` AOT path in ``precompile``).
"""

from __future__ import annotations

import ast
from typing import Sequence

from kukeon_tpu.analysis.core import (
    Finding, SourceFile, is_self_attr, register_pass,
)
from kukeon_tpu.analysis.hostsync import (
    ENGINE_CLASS, ENGINE_FILE_SUFFIX, JITTED_PROGRAMS,
)

# self attributes a jitted program body may read: frozen at __init__ and
# never reassigned while the engine serves (the lint that keeps this list
# honest is KUKE005 — none of these may gain a locked writer).
FROZEN_SELF_ATTRS = frozenset({
    "cfg", "mesh", "max_seq_len", "prefill_buckets", "page_tokens",
    "paged", "num_slots", "kv_cache_int8", "max_pages_per_slot",
    "kv_pool_pages", "eos_ids", "decode_chunk", "_bucket",
    "_fwd_logit_positions", "_forward",
})

CONTAINER_NODES = (ast.List, ast.Tuple, ast.Dict, ast.Set,
                   ast.ListComp, ast.DictComp, ast.SetComp,
                   ast.GeneratorExp)


def _static_argnums(jit_call: ast.Call) -> tuple[int, ...]:
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            if isinstance(kw.value, ast.Tuple):
                return tuple(
                    n.value for n in kw.value.elts
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, int))
            if (isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, int)):
                return (kw.value.value,)
    return ()


def _find_jit_call(node: ast.AST) -> ast.Call | None:
    """The ``jax.jit(fn, ...)`` call inside an expression like
    ``ct.wrap(jax.jit(fn, ...), "name")`` or a bare ``jax.jit(fn)``."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if (isinstance(f, ast.Attribute) and f.attr == "jit"
                and isinstance(f.value, ast.Name) and f.value.id == "jax"):
            return sub
        if isinstance(f, ast.Name) and f.id == "jit":
            return sub
    return None


def _collect_programs(build: ast.FunctionDef) -> tuple[
        dict[str, str], dict[str, tuple[int, ...]]]:
    """(program attr -> inner function name, program attr -> static nums)
    from ``_build_programs``'s ``self._X = ...jax.jit(fn, ...)...``."""
    fn_of: dict[str, str] = {}
    statics: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(build):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not (is_self_attr(target) and target.attr in JITTED_PROGRAMS):
            continue
        jit_call = _find_jit_call(node.value)
        if jit_call is None or not jit_call.args:
            continue
        inner = jit_call.args[0]
        if isinstance(inner, ast.Name):
            fn_of[target.attr] = inner.id
        statics[target.attr] = _static_argnums(jit_call)
    return fn_of, statics


@register_pass(("KUKE003", "KUKE004"))
def check_jit_stability(sources: Sequence[SourceFile],
                        package_root: str) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        if not src.rel.endswith(ENGINE_FILE_SUFFIX):
            continue
        for cls in src.tree.body:
            if not (isinstance(cls, ast.ClassDef)
                    and cls.name == ENGINE_CLASS):
                continue
            build = next(
                (m for m in cls.body if isinstance(m, ast.FunctionDef)
                 and m.name == "_build_programs"), None)
            if build is None:
                continue
            fn_of, statics = _collect_programs(build)

            # KUKE004: traced bodies may only read frozen self attrs. Every
            # function defined directly in _build_programs is traced — the
            # jitted programs plus helpers they call (walking each one also
            # covers its nested scan bodies).
            prog_of_fn = {v: k for k, v in fn_of.items()}
            inner_defs = {
                n.name: n for n in build.body
                if isinstance(n, ast.FunctionDef)}
            for fname, body in inner_defs.items():
                prog = prog_of_fn.get(fname, fname)
                for node in ast.walk(body):
                    if (is_self_attr(node)
                            and isinstance(node.ctx, ast.Load)
                            and node.attr not in FROZEN_SELF_ATTRS):
                        findings.append(Finding(
                            "KUKE004", src.rel, node.lineno,
                            f"jitted program {prog} ({fname}) closes over "
                            f"mutable engine state self.{node.attr}; its "
                            f"value is frozen at trace time — pass it as "
                            f"an argument or add it to the frozen "
                            f"allowlist if it is init-immutable",
                            scope=f"{cls.name}.{fname}",
                            detail=f"self.{node.attr}"))

            # KUKE003: container literals in traced call-site positions.
            for meth in cls.body:
                if (not isinstance(meth, ast.FunctionDef)
                        or meth.name == "_build_programs"):
                    continue
                for node in ast.walk(meth):
                    if not isinstance(node, ast.Call):
                        continue
                    prog = _called_program(node)
                    if prog is None or prog not in fn_of:
                        continue
                    static = set(statics.get(prog, ()))
                    for i, arg in enumerate(node.args):
                        if i in static:
                            continue
                        if isinstance(arg, CONTAINER_NODES):
                            findings.append(Finding(
                                "KUKE003", src.rel, arg.lineno,
                                f"Python container literal passed in "
                                f"traced position {i} of jitted program "
                                f"{prog}: its structure becomes part of "
                                f"the compile cache key (recompile per "
                                f"length) — pass an array",
                                scope=f"{cls.name}.{meth.name}",
                                detail=f"{prog}[{i}]"))
    return findings


@register_pass(("KUKE014",))
def check_jit_shardings(sources: Sequence[SourceFile],
                        package_root: str) -> list[Finding]:
    """Every jitted-program definition must place its data explicitly."""
    findings: list[Finding] = []
    for src in sources:
        if not src.rel.endswith(ENGINE_FILE_SUFFIX):
            continue
        for cls in src.tree.body:
            if not (isinstance(cls, ast.ClassDef)
                    and cls.name == ENGINE_CLASS):
                continue
            build = next(
                (m for m in cls.body if isinstance(m, ast.FunctionDef)
                 and m.name == "_build_programs"), None)
            if build is None:
                continue
            for node in ast.walk(build):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                target = node.targets[0]
                if not (is_self_attr(target)
                        and target.attr in JITTED_PROGRAMS):
                    continue
                jit_call = _find_jit_call(node.value)
                if jit_call is None:
                    continue
                present = {kw.arg for kw in jit_call.keywords}
                missing = [k for k in ("in_shardings", "out_shardings")
                           if k not in present]
                if missing:
                    findings.append(Finding(
                        "KUKE014", src.rel, jit_call.lineno,
                        f"jitted program {target.attr} is compiled without "
                        f"explicit {' / '.join(missing)}: on a multi-chip "
                        f"mesh GSPMD would infer placement (silent KV-pool "
                        f"replication or decode-path resharding) — spell "
                        f"the sharding, using NamedSharding(mesh, "
                        f"PartitionSpec()) for intentional replication",
                        scope=f"{cls.name}._build_programs",
                        detail=target.attr))
    return findings


def _find_wrap_call(node: ast.AST) -> ast.Call | None:
    """The ``<tracker>.wrap(...)`` call inside an expression like
    ``ct.wrap(jax.jit(fn), "name", timer=...)``."""
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "wrap"):
            return sub
    return None


@register_pass(("KUKE015",))
def check_program_timers(sources: Sequence[SourceFile],
                         package_root: str) -> list[Finding]:
    """Every jitted program must register with the program-timer seam."""
    findings: list[Finding] = []
    for src in sources:
        if not src.rel.endswith(ENGINE_FILE_SUFFIX):
            continue
        for cls in src.tree.body:
            if not (isinstance(cls, ast.ClassDef)
                    and cls.name == ENGINE_CLASS):
                continue
            build = next(
                (m for m in cls.body if isinstance(m, ast.FunctionDef)
                 and m.name == "_build_programs"), None)
            if build is None:
                continue
            for node in ast.walk(build):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                target = node.targets[0]
                if not (is_self_attr(target)
                        and target.attr in JITTED_PROGRAMS):
                    continue
                wrap_call = _find_wrap_call(node.value)
                if wrap_call is None or not any(
                        kw.arg == "timer" for kw in wrap_call.keywords):
                    findings.append(Finding(
                        "KUKE015", src.rel, node.lineno,
                        f"jitted program {target.attr} is built without a "
                        f"timer= registration on its CompileTracker.wrap: "
                        f"its dispatches are invisible to the per-program "
                        f"wall-time/MFU gauges and the flight recorder — "
                        f"wrap it with timer=tm.track(\"<program>\")",
                        scope=f"{cls.name}._build_programs",
                        detail=target.attr))
    return findings


def _called_program(node: ast.Call) -> str | None:
    """``self._prog(...)`` or ``self._prog.lower(...)`` -> ``_prog``."""
    f = node.func
    if is_self_attr(f) and f.attr in JITTED_PROGRAMS:
        return f.attr
    if (isinstance(f, ast.Attribute) and f.attr == "lower"
            and is_self_attr(f.value) and f.value.attr in JITTED_PROGRAMS):
        return f.value.attr
    return None
