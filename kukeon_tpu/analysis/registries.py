"""KUKE007/KUKE008/KUKE010 — declaration registries kept honest,
AST-accurately.

These replace the two grep guards that previously lived in the test suite
(PR 3's fault-point grep, PR 4's README metric-table regex): the AST
versions see only *code* (no docstring/comment false hits), report
file:line for every violation, and run both under ``python -m
kukeon_tpu.analysis`` and inside tier-1 via tests/test_static_analysis.py.

- **KUKE007 — fault-point registry.** Every ``faults.maybe_fail("p")``
  call site in the package must name a point declared in
  ``faults.POINTS`` (else it is invisible to the
  ``kukeon_faults_fired_total`` exposition), and every declared point
  must have a call site (else the declaration is stale). Dynamic point
  names (non-literal first argument) are themselves a violation — the
  registry can only be checked when the name is a literal.
- **KUKE008 — metric doc-drift.** Every ``kukeon_*`` metric-family
  literal in the package must appear in the README's metric reference
  table. The scan is exact string constants (including f-string constant
  parts would hide a dynamic name, so JoinedStr pieces are ignored —
  dynamic family names are not used in this codebase and should stay
  that way).
- **KUKE010 — trace phase registry.** Every ``<span>.event("phase")``
  mark literal in the package must be declared in ``obs/trace.py``'s
  ``PHASES`` tuple (the vocabulary ``kuke trace`` renders and the tail
  sampler keys off), every declared phase must have a call site, and
  phase names must be literals — same contract shape as KUKE007.
  ``sanitize.event(...)`` (the named-threading.Event factory) is the one
  same-named API and is excluded by its receiver.
- **KUKE011 — alert rules vs the metric registry.** Every metric family
  a built-in alert rule (``obs/alerts.py`` ``Rule(...)`` expressions)
  references must exist as a declared metric family elsewhere in the
  package — a renamed metric would otherwise leave a silently dead
  alert that never fires. Dynamic (non-literal) rule expressions are
  themselves findings: the registry can only be checked against
  literals.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Sequence

from kukeon_tpu.analysis.core import (
    Finding, SourceFile, const_str, register_pass,
)

FAULTS_MODULE = "faults.py"
METRIC_RE = re.compile(r"kukeon_[a-z0-9_]+\Z")
# Package-y literals that match the metric shape but are not families.
METRIC_IGNORE = frozenset({"kukeon_tpu", "kukeon_faults"})


def collect_fault_call_sites(sources: Sequence[SourceFile]) -> list[
        tuple[str, str | None, int]]:
    """(file, point-or-None-if-dynamic, line) for each maybe_fail call
    outside faults.py itself."""
    out: list[tuple[str, str | None, int]] = []
    for src in sources:
        if os.path.basename(src.path) == FAULTS_MODULE:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name != "maybe_fail":
                continue
            point = const_str(node.args[0]) if node.args else None
            out.append((src.rel, point, node.lineno))
    return out


def declared_points(sources: Sequence[SourceFile]) -> tuple[
        dict[str, int], str, int]:
    """(point -> line, faults.py rel path, POINTS line) parsed from the
    ``POINTS = (...)`` assignment."""
    for src in sources:
        if os.path.basename(src.path) != FAULTS_MODULE:
            continue
        for node in src.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == "POINTS"
                       for t in node.targets):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                pts = {}
                for elt in node.value.elts:
                    s = const_str(elt)
                    if s is not None:
                        pts[s] = elt.lineno
                return pts, src.rel, node.lineno
    return {}, "", 0


@register_pass(("KUKE007",))
def check_fault_registry(sources: Sequence[SourceFile],
                         package_root: str) -> list[Finding]:
    declared, faults_rel, points_line = declared_points(sources)
    if not faults_rel:
        return []    # no faults module in this tree (fixture packages)
    findings: list[Finding] = []
    seen: set[str] = set()
    for rel, point, line in collect_fault_call_sites(sources):
        if point is None:
            findings.append(Finding(
                "KUKE007", rel, line,
                "maybe_fail with a non-literal point name: the fault "
                "registry (faults.POINTS) can only be checked against "
                "literals — name the point inline",
                scope="", detail="<dynamic>"))
            continue
        seen.add(point)
        if point not in declared:
            findings.append(Finding(
                "KUKE007", rel, line,
                f"fault point \"{point}\" is not declared in "
                f"faults.POINTS; undeclared points never appear in the "
                f"kukeon_faults_fired_total exposition",
                scope="", detail=point))
    for point, line in declared.items():
        if point not in seen:
            findings.append(Finding(
                "KUKE007", faults_rel, line,
                f"faults.POINTS declares \"{point}\" but no "
                f"maybe_fail(\"{point}\") call site exists — remove the "
                f"stale declaration",
                scope="POINTS", detail=point))
    return findings


def collect_metric_literals(sources: Sequence[SourceFile]) -> dict[
        str, tuple[str, int]]:
    """metric family -> (file, first line) for every kukeon_* string
    constant in the package."""
    out: dict[str, tuple[str, int]] = {}
    for src in sources:
        for node in ast.walk(src.tree):
            s = const_str(node)
            if s is None or not METRIC_RE.match(s) or s in METRIC_IGNORE:
                continue
            if s not in out or (src.rel, node.lineno) < out[s]:
                out[s] = (src.rel, node.lineno)
    return out


TRACE_MODULE_SUFFIX = "obs/trace.py"


def collect_span_event_sites(sources: Sequence[SourceFile]) -> list[
        tuple[str, str | None, int]]:
    """(file, phase-or-None-if-dynamic, line) for each span ``.event()``
    mark in the package. ``sanitize.event(...)`` — the named
    threading.Event factory — shares the attribute name and is excluded
    by its receiver; everything else dotted ``.event(`` is a span mark
    in this codebase (Span.event, req.trace.event, span.event)."""
    out: list[tuple[str, str | None, int]] = []
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute) or f.attr != "event":
                continue
            if isinstance(f.value, ast.Name) and f.value.id == "sanitize":
                continue
            phase = const_str(node.args[0]) if node.args else None
            out.append((src.rel, phase, node.lineno))
    return out


def declared_phases(sources: Sequence[SourceFile]) -> tuple[
        dict[str, int], str]:
    """(phase -> line, trace.py rel path) parsed from the
    ``PHASES = (...)`` assignment in obs/trace.py."""
    for src in sources:
        if not src.rel.endswith(TRACE_MODULE_SUFFIX):
            continue
        for node in src.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not any(isinstance(t, ast.Name) and t.id == "PHASES"
                       for t in node.targets):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                phases = {}
                for elt in node.value.elts:
                    s = const_str(elt)
                    if s is not None:
                        phases[s] = elt.lineno
                return phases, src.rel
    return {}, ""


@register_pass(("KUKE010",))
def check_phase_registry(sources: Sequence[SourceFile],
                         package_root: str) -> list[Finding]:
    declared, trace_rel = declared_phases(sources)
    if not trace_rel:
        return []    # no trace module in this tree (fixture packages)
    findings: list[Finding] = []
    seen: set[str] = set()
    for rel, phase, line in collect_span_event_sites(sources):
        if phase is None:
            findings.append(Finding(
                "KUKE010", rel, line,
                "span event with a non-literal phase name: the phase "
                "registry (obs/trace.py PHASES) can only be checked "
                "against literals — name the phase inline and carry "
                "dynamic data as event attrs",
                scope="", detail="<dynamic>"))
            continue
        seen.add(phase)
        if phase not in declared:
            findings.append(Finding(
                "KUKE010", rel, line,
                f"span phase \"{phase}\" is not declared in the "
                f"obs/trace.py PHASES registry; undeclared phases are "
                f"invisible to `kuke trace` consumers and the tail "
                f"sampler's keep rules",
                scope="", detail=phase))
    for phase, line in declared.items():
        if phase not in seen:
            findings.append(Finding(
                "KUKE010", trace_rel, line,
                f"PHASES declares \"{phase}\" but no span "
                f".event(\"{phase}\") call site exists — remove the "
                f"stale declaration",
                scope="PHASES", detail=phase))
    return findings


ALERTS_MODULE_SUFFIX = "obs/alerts.py"
# One rule expression is a selector, or `selector / selector`; a family
# name is the identifier each selector leads with.
_EXPR_FAMILY_RE = re.compile(r"^\s*([a-zA-Z_:][a-zA-Z0-9_:]*)")


def expr_families(expr: str) -> list[str]:
    """Metric family names an alert-rule expression references: the
    leading identifier of each top-level '/'-separated selector."""
    out: list[str] = []
    depth = 0
    part_start = 0
    parts: list[str] = []
    for i, ch in enumerate(expr):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        elif ch == "/" and depth == 0:
            parts.append(expr[part_start:i])
            part_start = i + 1
    parts.append(expr[part_start:])
    for part in parts:
        m = _EXPR_FAMILY_RE.match(part)
        if m:
            out.append(m.group(1))
    return out


def collect_alert_rule_exprs(sources: Sequence[SourceFile]) -> list[
        tuple[str, str | None, str | None, int]]:
    """(file, rule name, expr-or-None-if-dynamic, line) for every
    ``Rule(...)`` construction in the alerts module (the built-in rule
    set lives there; user rules are validated at load time instead)."""
    out: list[tuple[str, str | None, str | None, int]] = []
    for src in sources:
        if not src.rel.endswith(ALERTS_MODULE_SUFFIX):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name != "Rule":
                continue
            expr = rule_name = None
            for kw in node.keywords:
                if kw.arg == "expr":
                    expr = const_str(kw.value)
                    if expr is None:
                        expr = "<dynamic>"
                elif kw.arg == "name":
                    rule_name = const_str(kw.value)
            if len(node.args) > 1 and expr is None:
                expr = const_str(node.args[1]) or "<dynamic>"
            if node.args and rule_name is None:
                rule_name = const_str(node.args[0])
            if expr is not None:
                out.append((src.rel,
                            rule_name,
                            None if expr == "<dynamic>" else expr,
                            node.lineno))
    return out


@register_pass(("KUKE011",))
def check_alert_rule_families(sources: Sequence[SourceFile],
                              package_root: str) -> list[Finding]:
    exprs = collect_alert_rule_exprs(sources)
    if not exprs:
        return []    # no alerts module in this tree (fixture packages)
    # The declared registry: every metric-family literal OUTSIDE the
    # alerts module (a rule's own expr string must not satisfy itself).
    declared = set(collect_metric_literals(
        [s for s in sources if not s.rel.endswith(ALERTS_MODULE_SUFFIX)]))
    findings: list[Finding] = []
    for rel, rule_name, expr, line in exprs:
        scope = rule_name or "?"
        if expr is None:
            findings.append(Finding(
                "KUKE011", rel, line,
                f"alert rule {scope!r} has a non-literal expression: the "
                f"metric registry can only be checked against literal "
                f"family names — inline the expression",
                scope=scope, detail="<dynamic>"))
            continue
        for fam in expr_families(expr):
            if fam not in declared:
                findings.append(Finding(
                    "KUKE011", rel, line,
                    f"alert rule {scope!r} references metric family "
                    f"\"{fam}\" which no module in the package declares "
                    f"— the rule can never fire",
                    scope=scope, detail=fam))
    return findings


@register_pass(("KUKE008",))
def check_metric_docs(sources: Sequence[SourceFile],
                      package_root: str) -> list[Finding]:
    readme = os.path.join(os.path.dirname(os.path.abspath(package_root)),
                          "README.md")
    if not os.path.exists(readme):
        return []
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    findings: list[Finding] = []
    for name, (rel, line) in sorted(collect_metric_literals(sources).items()):
        if name not in text:
            findings.append(Finding(
                "KUKE008", rel, line,
                f"metric family \"{name}\" is not documented in the "
                f"README metric reference table",
                scope="", detail=name))
    return findings
