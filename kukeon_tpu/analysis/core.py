"""kukelint core: findings, baseline suppression, file loading, pass registry.

The analyzer is a zero-dependency ``ast``-module tool: every pass receives
the parsed module trees and returns :class:`Finding` objects. Nothing here
imports jax (or anything else heavy) — ``python -m kukeon_tpu.analysis``
must be runnable in a bare interpreter and cheap enough for a pre-commit
gate.

Baselines: a finding's identity for suppression purposes is its
:meth:`Finding.fingerprint` — rule + file + enclosing scope + a
rule-chosen detail key, deliberately WITHOUT the line number, so editing
an unrelated part of a file does not orphan the suppression. The checked-in
baseline (``kukeon_tpu/analysis/baseline.json``) lists accepted
pre-existing findings with a one-line justification each; anything not in
it fails the run, and baseline entries matching nothing are reported as
stale so they get cleaned up rather than rotting.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Callable, Iterable, Sequence

BASELINE_FILENAME = "baseline.json"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str          # stable rule id, e.g. "KUKE001"
    file: str          # path relative to the repo root (posix separators)
    line: int
    message: str       # human sentence, shown with file:line
    scope: str = ""    # enclosing qualname (Class.method) — part of identity
    detail: str = ""   # rule-chosen stable key (attr name, point name, ...)

    @property
    def fingerprint(self) -> str:
        """Line-independent identity used by baseline suppression."""
        return f"{self.rule}:{self.file}:{self.scope}:{self.detail}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}"


@dataclasses.dataclass
class SourceFile:
    """A parsed package module handed to every pass."""

    path: str          # absolute
    rel: str           # relative to the repo root, posix separators
    tree: ast.Module
    text: str


@dataclasses.dataclass
class BaselineEntry:
    fingerprint: str
    justification: str


class Baseline:
    """Accepted pre-existing findings; everything else is a failure."""

    def __init__(self, entries: Sequence[BaselineEntry] = ()):
        self.entries = list(entries)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = [
            BaselineEntry(e["fingerprint"], e.get("justification", ""))
            for e in data.get("suppressions", ())
        ]
        return cls(entries)

    def save(self, path: str) -> None:
        data = {
            "version": 1,
            "suppressions": [
                {"fingerprint": e.fingerprint,
                 "justification": e.justification}
                for e in sorted(self.entries, key=lambda e: e.fingerprint)
            ],
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2)
            f.write("\n")

    def apply(self, findings: Iterable[Finding]) -> tuple[
            list[Finding], list[Finding], list[BaselineEntry]]:
        """(new, suppressed, stale-entries) split of ``findings``."""
        by_fp: dict[str, BaselineEntry] = {
            e.fingerprint: e for e in self.entries}
        new: list[Finding] = []
        suppressed: list[Finding] = []
        matched: set[str] = set()
        for f in findings:
            if f.fingerprint in by_fp:
                suppressed.append(f)
                matched.add(f.fingerprint)
            else:
                new.append(f)
        stale = [e for e in self.entries if e.fingerprint not in matched]
        return new, suppressed, stale


def load_sources(package_root: str) -> list[SourceFile]:
    """Parse every ``*.py`` under ``package_root`` (skipping caches)."""
    repo_root = os.path.dirname(os.path.abspath(package_root))
    out: list[SourceFile] = []
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
            out.append(SourceFile(
                path=path, rel=rel, tree=ast.parse(text, filename=path),
                text=text,
            ))
    return out


# A pass: (sources, package_root) -> findings. Registered with the rule ids
# it can emit so --select can skip whole passes.
Pass = Callable[[Sequence[SourceFile], str], list[Finding]]

_PASSES: list[tuple[tuple[str, ...], Pass]] = []


def register_pass(rule_ids: tuple[str, ...]) -> Callable[[Pass], Pass]:
    def deco(fn: Pass) -> Pass:
        _PASSES.append((rule_ids, fn))
        return fn
    return deco


def _ensure_passes_loaded() -> None:
    # Import the passes for their registration side effect; deferred so
    # core stays importable without the pass modules (fixture tests).
    from kukeon_tpu.analysis import (  # noqa: F401
        bootimports, busywait, hostsync, jitstability, locks, registries,
    )


def registered_rules() -> tuple[str, ...]:
    _ensure_passes_loaded()
    out: list[str] = []
    for ids, _fn in _PASSES:
        out.extend(ids)
    return tuple(sorted(out))


def run_analysis(package_root: str,
                 select: Sequence[str] | None = None) -> list[Finding]:
    """Run every registered pass (or the ``select``-ed rule ids) over the
    package; findings come back sorted by file, line, rule."""
    _ensure_passes_loaded()
    sources = load_sources(package_root)
    wanted = set(select) if select else None
    findings: list[Finding] = []
    for rule_ids, fn in _PASSES:
        if wanted is not None and not (wanted & set(rule_ids)):
            continue
        got = fn(sources, package_root)
        if wanted is not None:
            got = [f for f in got if f.rule in wanted]
        findings.extend(got)
    return sorted(findings, key=lambda f: (f.file, f.line, f.rule, f.detail))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        BASELINE_FILENAME)


# --- small shared AST helpers -------------------------------------------------


def qualname(stack: Sequence[ast.AST]) -> str:
    """Dotted Class.method name from an enclosing-scope stack."""
    parts = [n.name for n in stack
             if isinstance(n, (ast.ClassDef, ast.FunctionDef,
                               ast.AsyncFunctionDef))]
    return ".".join(parts)


def is_self_attr(node: ast.AST, attr: str | None = None) -> bool:
    """``self.X`` (any X, or a specific one)."""
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and (attr is None or node.attr == attr)
    )


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
