"""KUKE001/KUKE002 — host-sync discipline in the serving engine hot path.

The decode roofline contract (PR 1, enforced dynamically by
``test_decode_host_sync_budget``): every blocking device→host readback in
the engine goes through ``ServingEngine._fetch`` and every host→device
array upload through ``_upload``, so the ≤1-blocking-transfer-per-chunk
budget is *countable*. This pass makes the routing itself a lint error —
a raw transfer in a hot-path method is flagged at review time instead of
showing up as a budget-test failure (or worse, a latency regression the
budget test's snapshot happens to miss).

- **KUKE001** (device→host): ``np.asarray``/``np.array`` on a
  device-tainted value, ``jax.device_get(...)``, ``.item()``,
  ``.block_until_ready()``, and ``int()``/``float()``/``bool()`` coercion
  of a device-tainted value, inside a hot-path method, outside ``_fetch``.
- **KUKE002** (host→device): ``jnp.asarray``/``jnp.array``/
  ``jax.device_put`` inside a hot-path method, outside ``_upload`` —
  uploads must route through the counting seam even when cheap, or the
  budget tests undercount and the dirty-flag discipline silently erodes.

Device taint is a per-method forward propagation: results of the engine's
jitted programs (and ``self.state``/``self.params``/device caches, and
``jnp.*`` array results) are device values; ``self._fetch(...)`` results
and ``np.*`` results are host values; unknown stays unflagged — the pass
prefers false negatives over noise, with the runtime budget test as the
dynamic backstop. Metadata access (``x.shape``/``x.dtype``/``x.size``…)
never counts as a transfer.
"""

from __future__ import annotations

import ast
from typing import Sequence

from kukeon_tpu.analysis.core import (
    Finding, SourceFile, is_self_attr, register_pass,
)

ENGINE_FILE_SUFFIX = "serving/engine.py"
ENGINE_CLASS = "ServingEngine"

# The transfer seams themselves: raw transfer primitives are their job.
SEAM_METHODS = ("_fetch", "_upload")

# Methods on the submit->prefill->decode->emit path (plus warmup, which
# dispatches real chunks): the scope where a stray transfer costs a link
# round trip per request or per chunk.
HOT_PATH_METHODS = frozenset({
    "submit", "step", "warmup", "generate", "_loop",
    "_dispatch_prefill", "_dispatch_prefill_paged", "_dispatch_decode_chunk",
    "_flush_inflight", "_emit", "_release_slot", "_preempt_slot",
    "_sampling_dev_arrays", "_bt_dev_array", "_ensure_decode_pages",
    "_prefix_lookup", "_prefix_store", "_prefix_lookup_paged",
    "_prefix_store_paged", "_reclaim_prefix_pages", "_chunk_size",
    "_pop_waiting", "_sweep_cancelled",
})

# The engine's jitted programs: their results are device values.
JITTED_PROGRAMS = frozenset({
    "_prefill", "_prefill_ext", "_insert", "_decode_chunk",
    "_gather_block", "_insert_paged", "_decode_chunk_paged",
})

# Always-device engine attributes.
DEVICE_SELF_ATTRS = frozenset({
    "state", "params", "_bt_dev", "_sampling_dev",
})

# Attribute reads that are static metadata, never a transfer.
METADATA_ATTRS = frozenset({
    "shape", "ndim", "size", "dtype", "nbytes", "itemsize", "sharding",
})

# jnp names that are dtype constructors / free functions on device values,
# not transfers.
JNP_UPLOADS = frozenset({"asarray", "array"})


def _is_metadata(node: ast.AST) -> bool:
    while isinstance(node, ast.Subscript):
        node = node.value
    return isinstance(node, ast.Attribute) and node.attr in METADATA_ATTRS


class _Taint:
    """Per-method device-taint set over local names."""

    def __init__(self) -> None:
        self.device: set[str] = set()

    def expr_is_device(self, node: ast.AST) -> bool:
        if _is_metadata(node):
            return False
        if is_self_attr(node) and node.attr in DEVICE_SELF_ATTRS:
            return True
        if isinstance(node, ast.Call):
            # Results of the counting seams have known sides regardless of
            # their argument taint: _fetch returns host numpy, _upload a
            # device array. np.* construct host arrays; jnp.* device ones.
            if is_self_attr(node.func, "_fetch"):
                return False
            if is_self_attr(node.func, "_upload"):
                return True
            base, _attr = _call_name(node)
            if base == "np":
                return False
            if base == "jnp":
                return True
        if _jitted_call(node) is not None:
            return True
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.device:
                if not _is_metadata_path(node, sub):
                    return True
            if is_self_attr(sub) and sub.attr in DEVICE_SELF_ATTRS:
                if not _is_metadata_path(node, sub):
                    return True
        return False


def _is_metadata_path(root: ast.AST, target: ast.AST) -> bool:
    """True when ``target`` is only reached through a metadata attribute
    access within ``root`` (e.g. the ``x`` of ``x.shape[0]``)."""
    for sub in ast.walk(root):
        if isinstance(sub, ast.Attribute) and sub.attr in METADATA_ATTRS:
            for inner in ast.walk(sub.value):
                if inner is target:
                    return True
    return False


def _jitted_call(node: ast.AST) -> str | None:
    """Name of the jitted program when ``node`` is ``self._prog(...)``."""
    if (isinstance(node, ast.Call)
            and is_self_attr(node.func)
            and node.func.attr in JITTED_PROGRAMS):
        return node.func.attr
    return None


def _call_name(node: ast.Call) -> tuple[str | None, str | None]:
    """(module-ish base, attr) for ``base.attr(...)`` / (None, name)."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id, f.attr
    if isinstance(f, ast.Name):
        return None, f.id
    return None, None


def _seed_and_check(method: ast.FunctionDef, cls_name: str,
                    rel: str) -> list[Finding]:
    """Two passes over the statements: propagate taint, then flag. A single
    sweep in statement order is enough for straight-line dataflow; the
    second sweep catches names tainted later in a loop body."""
    taint = _Taint()
    findings: list[Finding] = []
    scope = f"{cls_name}.{method.name}"

    def assign_taint(target: ast.AST, value_is_device: bool) -> None:
        if not value_is_device:
            return
        if isinstance(target, ast.Name):
            taint.device.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                assign_taint(elt, True)

    def propagate(node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                dev = taint.expr_is_device(sub.value)
                for t in sub.targets:
                    assign_taint(t, dev)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                if sub.value is not None and taint.expr_is_device(sub.value):
                    assign_taint(sub.target, True)
            elif isinstance(sub, ast.For):
                if taint.expr_is_device(sub.iter):
                    assign_taint(sub.target, True)
            elif isinstance(sub, ast.withitem) and sub.optional_vars:
                if taint.expr_is_device(sub.context_expr):
                    assign_taint(sub.optional_vars, True)

    def flag(node: ast.Call) -> None:
        base, attr = _call_name(node)
        args = node.args
        # --- device→host (KUKE001) ------------------------------------
        if attr == "item" and not args and isinstance(node.func,
                                                      ast.Attribute):
            findings.append(Finding(
                "KUKE001", rel, node.lineno,
                f"raw device→host transfer `.item()` in hot-path "
                f"{scope}; route the readback through self._fetch",
                scope=scope, detail="item"))
            return
        if attr == "block_until_ready" and isinstance(node.func,
                                                      ast.Attribute):
            findings.append(Finding(
                "KUKE001", rel, node.lineno,
                f"`.block_until_ready()` in hot-path {scope} blocks the "
                f"driver on the device; route through self._fetch",
                scope=scope, detail="block_until_ready"))
            return
        if base == "jax" and attr == "device_get":
            findings.append(Finding(
                "KUKE001", rel, node.lineno,
                f"raw `jax.device_get` in hot-path {scope}; route the "
                f"readback through self._fetch",
                scope=scope, detail="device_get"))
            return
        if (base == "np" and attr in ("asarray", "array") and args
                and taint.expr_is_device(args[0])):
            findings.append(Finding(
                "KUKE001", rel, node.lineno,
                f"`np.{attr}` on a device value in hot-path {scope} is a "
                f"blocking uncounted readback; route through self._fetch",
                scope=scope, detail=f"np.{attr}"))
            return
        if (base is None and attr in ("int", "float", "bool") and args
                and taint.expr_is_device(args[0])):
            findings.append(Finding(
                "KUKE001", rel, node.lineno,
                f"`{attr}()` coercion of a device value in hot-path "
                f"{scope} is a blocking uncounted readback; fetch the "
                f"array through self._fetch first",
                scope=scope, detail=f"coerce.{attr}"))
            return
        # --- host→device (KUKE002) ------------------------------------
        if base == "jnp" and attr in JNP_UPLOADS:
            findings.append(Finding(
                "KUKE002", rel, node.lineno,
                f"raw `jnp.{attr}` upload in hot-path {scope}; route the "
                f"upload through self._upload so the transfer budget "
                f"counts it",
                scope=scope, detail=f"jnp.{attr}"))
            return
        if base == "jax" and attr == "device_put":
            findings.append(Finding(
                "KUKE002", rel, node.lineno,
                f"raw `jax.device_put` upload in hot-path {scope}; route "
                f"through self._upload",
                scope=scope, detail="device_put"))

    propagate(method)
    propagate(method)   # second sweep: loop-carried taint
    for sub in ast.walk(method):
        if isinstance(sub, ast.Call):
            flag(sub)
    return findings


@register_pass(("KUKE001", "KUKE002"))
def check_host_sync(sources: Sequence[SourceFile],
                    package_root: str) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        if not src.rel.endswith(ENGINE_FILE_SUFFIX):
            continue
        for node in src.tree.body:
            if not (isinstance(node, ast.ClassDef)
                    and node.name == ENGINE_CLASS):
                continue
            for meth in node.body:
                if not isinstance(meth, ast.FunctionDef):
                    continue
                if meth.name in SEAM_METHODS:
                    continue
                if meth.name not in HOT_PATH_METHODS:
                    continue
                findings.extend(_seed_and_check(meth, node.name, src.rel))
    return findings


# --- KUKE012: KV handoff transfer discipline ---------------------------------
#
# The disaggregated prefill/decode handoff moves whole KV blocks between
# cells — by far the largest per-request transfers in the tree. Every byte
# must cross the device boundary through the counted seams
# (``self._fetch`` / ``self._upload``, or an explicit
# ``sanitize.blocking(...)``-marked section), or the handoff's cost is
# invisible to ``sync_stats``, the ``kukeon_engine_host_sync_*``
# exposition, AND the kukesan blocking-under-hot-lock checks. This pass
# scopes to export/import-named methods in the serving engine and cell —
# the code that owns handoff bytes — and flags raw transfer primitives
# there; the generic hot-path discipline stays KUKE001/002's job.

import re as _re

HANDOFF_FILE_SUFFIXES = (ENGINE_FILE_SUFFIX, "runtime/serving_cell.py")
# Methods/functions owning handoff bytes: anything whose name carries an
# export/import marker (``kv_export``, ``_dispatch_prefill_export``,
# ``_finish_export``, ``_dispatch_import``, ``kv_import_stream``...).
# ``pack_kv``/``unpack_kv`` (pure host serialization) are covered too —
# a device transfer has no business appearing in them at all.
HANDOFF_NAME_RE = _re.compile(
    r"(^|_)(export|import)(ed)?(_|$)|(^|_)kv(_|$)")


def _handoff_findings(fn: ast.FunctionDef, scope: str,
                      rel: str) -> list[Finding]:
    taint = _Taint()
    findings: list[Finding] = []

    def flag(node: ast.Call) -> None:
        base, attr = _call_name(node)
        if base == "jax" and attr in ("device_get", "device_put"):
            findings.append(Finding(
                "KUKE012", rel, node.lineno,
                f"raw `jax.{attr}` in KV handoff code ({scope}); handoff "
                f"bytes must move through the counted transfer seams "
                f"(self._fetch / self._upload / sanitize.blocking)",
                scope=scope, detail=f"jax.{attr}"))
            return
        if base == "jnp" and attr in JNP_UPLOADS:
            findings.append(Finding(
                "KUKE012", rel, node.lineno,
                f"raw `jnp.{attr}` upload in KV handoff code ({scope}); "
                f"route the block through self._upload so the handoff's "
                f"transfer cost is counted",
                scope=scope, detail=f"jnp.{attr}"))
            return
        if (base == "np" and attr in ("asarray", "array") and node.args
                and taint.expr_is_device(node.args[0])):
            findings.append(Finding(
                "KUKE012", rel, node.lineno,
                f"`np.{attr}` on a device value in KV handoff code "
                f"({scope}) is a blocking uncounted readback; route the "
                f"block through self._fetch",
                scope=scope, detail=f"np.{attr}"))

    # Reuse the host-sync taint model (device values = jitted program
    # results, device self attrs, jnp results) with a minimal assignment
    # propagation — handoff methods are straight-line.
    def propagate(node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                dev = taint.expr_is_device(sub.value)
                for tgt in sub.targets:
                    if dev and isinstance(tgt, ast.Name):
                        taint.device.add(tgt.id)
                    elif dev and isinstance(tgt, (ast.Tuple, ast.List)):
                        for elt in tgt.elts:
                            if isinstance(elt, ast.Name):
                                taint.device.add(elt.id)

    propagate(fn)
    propagate(fn)   # second sweep: loop-carried taint
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            flag(sub)
    return findings


@register_pass(("KUKE012",))
def check_handoff_transfers(sources: Sequence[SourceFile],
                            package_root: str) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        if not any(src.rel.endswith(sfx) for sfx in HANDOFF_FILE_SUFFIXES):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for meth in node.body:
                if not isinstance(meth, ast.FunctionDef):
                    continue
                if meth.name in SEAM_METHODS:
                    continue
                if not HANDOFF_NAME_RE.search(meth.name):
                    continue
                findings.extend(_handoff_findings(
                    meth, f"{node.name}.{meth.name}", src.rel))
        for node in src.tree.body:
            if (isinstance(node, ast.FunctionDef)
                    and HANDOFF_NAME_RE.search(node.name)):
                findings.extend(_handoff_findings(node, node.name, src.rel))
    return findings
