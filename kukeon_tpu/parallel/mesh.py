"""Device-mesh construction.

Canonical axis names for the whole framework (the scaling-book convention):

  - ``data``:    pure data parallelism (gradients all-reduced).
  - ``fsdp``:    data parallelism with sharded params/optimizer state
                 (params all-gathered per layer, grads reduce-scattered).
  - ``tensor``:  tensor (megatron-style) parallelism inside a layer.
  - ``seq``:     sequence/context parallelism (ring attention).
  - ``expert``:  expert parallelism (MoE: experts sharded over chips, token
                 dispatch/combine become all-to-alls inserted by GSPMD from
                 the einsum shardings — models/moe.py).
  - ``pipe``:    pipeline parallelism (layer stages over chips; GPipe
                 microbatch schedule with ppermute activation transfer —
                 parallel/pipeline.py). Outermost axis: stage hops are the
                 lowest-frequency, most latency-tolerant traffic, so they
                 map to the outer interconnect dimension (DCN on multi-host).

Serving uses (data, tensor); training adds fsdp/seq; MoE models add expert.
On a TPU slice the mesh should be laid out so that ``tensor`` (highest-
bandwidth collectives) maps to the innermost ICI dimension —
``jax.make_mesh`` handles device ordering.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_PIPE = "pipe"


def make_mesh(
    data: int = 1,
    fsdp: int = 1,
    tensor: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Build a mesh with the canonical axes; sizes must multiply to #devices."""
    devices = devices if devices is not None else jax.devices()
    want = data * fsdp * tensor * seq * expert * pipe
    if want != len(devices):
        raise ValueError(
            f"mesh {pipe}x{data}x{fsdp}x{expert}x{seq}x{tensor}={want} != "
            f"{len(devices)} devices"
        )
    # Auto axis types: GSPMD propagates shardings from the annotations we set
    # at jit boundaries (jax 0.9 defaults to Explicit mode, which turns
    # with_sharding_constraint into an assert — not what this codebase wants).
    # Older runtimes (<= 0.5) have no AxisType and are Auto-only; the kwarg
    # must be omitted there, not passed as None.
    kwargs = {}
    if hasattr(jax.sharding, "AxisType"):
        kwargs["axis_types"] = (jax.sharding.AxisType.Auto,) * 6
    return jax.make_mesh(
        (pipe, data, fsdp, expert, seq, tensor),
        (AXIS_PIPE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR),
        devices=devices,
        **kwargs,
    )


def set_mesh(mesh: Mesh):
    """Context manager activating ``mesh`` as the ambient mesh.

    jax >= 0.6 exposes ``jax.set_mesh``; on older runtimes the Mesh object
    itself is the context manager. Every call site goes through this one
    shim so the framework runs on both."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def ambient_mesh():
    """The mesh activated by :func:`set_mesh` (abstract on jax >= 0.6,
    physical on older runtimes — both carry the axis names shard_map
    needs)."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return jax.sharding.get_abstract_mesh()
    from jax._src import mesh as _mesh_lib

    return _mesh_lib.thread_resources.env.physical_mesh


def shard_map(fn, *, mesh, in_specs, out_specs, axis_names=None):
    """``jax.shard_map`` (>= 0.6) / ``jax.experimental.shard_map`` (older),
    one call-site-stable spelling.

    ``axis_names`` (manual over only those axes) is the new partial-manual
    spelling; old shard_map expresses the same thing inversely via
    ``auto=<the other axes>``."""
    if hasattr(jax, "shard_map"):
        kwargs = {} if axis_names is None else {"axis_names": axis_names}
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    kwargs = {}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - set(axis_names)
        if auto:
            kwargs["auto"] = auto
    # The old replication checker miscounts scan carries (its own error
    # message says to disable it); correctness is covered by the real
    # numeric tests, and the new-jax path above keeps full checking.
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False, **kwargs)


def serving_mesh(n_devices: int | None = None) -> Mesh:
    """All chips on ``tensor`` — the latency-optimal layout for one model.

    ``n_devices`` is a hard request, not a hint: asking for more chips than
    the process can see fails loudly here (a ``chips: N`` grant that cannot
    be honored must die at boot, never silently serve on fewer chips)."""
    visible = len(jax.devices())
    n = n_devices if n_devices is not None else visible
    if n < 1:
        raise ValueError(f"serving mesh needs >= 1 device, got {n}")
    if n > visible:
        raise ValueError(
            f"serving mesh wants {n} chips but only {visible} visible "
            "(check the cell's chip grant / TPU_VISIBLE_DEVICES)")
    return make_mesh(tensor=n, devices=jax.devices()[:n])


def training_mesh(n_devices: int | None = None, tensor: int = 1, seq: int = 1) -> Mesh:
    """FSDP over whatever is left after tensor/seq axes."""
    n = n_devices if n_devices is not None else len(jax.devices())
    if n % (tensor * seq):
        raise ValueError(f"{n} devices not divisible by tensor*seq={tensor * seq}")
    return make_mesh(fsdp=n // (tensor * seq), tensor=tensor, seq=seq,
                     devices=jax.devices()[:n])


def largest_pow2_leq(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n > 0 else 1


def auto_mesh_shape(n_devices: int) -> dict[str, int]:
    """Heuristic serving layout: tensor up to 8 (one ICI ring), data beyond.

    ``data * tensor == n_devices`` always — a non-power-of-two count picks
    its largest divisor <= 8 for the tensor axis (6 chips -> tensor=6,
    12 -> tensor=6 x data=2) instead of truncating to a power of two and
    dropping chips. A prime count degenerates to tensor=n_devices, which
    is still every chip; callers that need a specific slice size say so
    via :func:`serving_mesh` and get a loud error instead."""
    if n_devices < 1:
        raise ValueError(f"auto_mesh_shape needs >= 1 device, got {n_devices}")
    tensor = max(d for d in range(1, min(8, n_devices) + 1)
                 if n_devices % d == 0)
    return {"data": n_devices // tensor, "tensor": tensor}


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` (>= 0.6); older runtimes count via psum(1)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x, axes, to="varying"):
    """``jax.lax.pcast`` (>= 0.6 varying-type system); a no-op on older
    runtimes, whose shard_map has no replication typing to satisfy."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to=to)
    return x
