"""Device-mesh construction.

Canonical axis names for the whole framework (the scaling-book convention):

  - ``data``:    pure data parallelism (gradients all-reduced).
  - ``fsdp``:    data parallelism with sharded params/optimizer state
                 (params all-gathered per layer, grads reduce-scattered).
  - ``tensor``:  tensor (megatron-style) parallelism inside a layer.
  - ``seq``:     sequence/context parallelism (ring attention).
  - ``expert``:  expert parallelism (MoE: experts sharded over chips, token
                 dispatch/combine become all-to-alls inserted by GSPMD from
                 the einsum shardings — models/moe.py).
  - ``pipe``:    pipeline parallelism (layer stages over chips; GPipe
                 microbatch schedule with ppermute activation transfer —
                 parallel/pipeline.py). Outermost axis: stage hops are the
                 lowest-frequency, most latency-tolerant traffic, so they
                 map to the outer interconnect dimension (DCN on multi-host).

Serving uses (data, tensor); training adds fsdp/seq; MoE models add expert.
On a TPU slice the mesh should be laid out so that ``tensor`` (highest-
bandwidth collectives) maps to the innermost ICI dimension —
``jax.make_mesh`` handles device ordering.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_PIPE = "pipe"


def make_mesh(
    data: int = 1,
    fsdp: int = 1,
    tensor: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Build a mesh with the canonical axes; sizes must multiply to #devices."""
    devices = devices if devices is not None else jax.devices()
    want = data * fsdp * tensor * seq * expert * pipe
    if want != len(devices):
        raise ValueError(
            f"mesh {pipe}x{data}x{fsdp}x{expert}x{seq}x{tensor}={want} != "
            f"{len(devices)} devices"
        )
    # Auto axis types: GSPMD propagates shardings from the annotations we set
    # at jit boundaries (jax 0.9 defaults to Explicit mode, which turns
    # with_sharding_constraint into an assert — not what this codebase wants).
    return jax.make_mesh(
        (pipe, data, fsdp, expert, seq, tensor),
        (AXIS_PIPE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR),
        devices=devices,
        axis_types=(jax.sharding.AxisType.Auto,) * 6,
    )


def serving_mesh(n_devices: int | None = None) -> Mesh:
    """All chips on ``tensor`` — the latency-optimal layout for one model."""
    n = n_devices if n_devices is not None else len(jax.devices())
    return make_mesh(tensor=n, devices=jax.devices()[:n])


def training_mesh(n_devices: int | None = None, tensor: int = 1, seq: int = 1) -> Mesh:
    """FSDP over whatever is left after tensor/seq axes."""
    n = n_devices if n_devices is not None else len(jax.devices())
    if n % (tensor * seq):
        raise ValueError(f"{n} devices not divisible by tensor*seq={tensor * seq}")
    return make_mesh(fsdp=n // (tensor * seq), tensor=tensor, seq=seq,
                     devices=jax.devices()[:n])


def largest_pow2_leq(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n > 0 else 1


def auto_mesh_shape(n_devices: int) -> dict[str, int]:
    """Heuristic serving layout: tensor up to 8 (one ICI ring), data beyond."""
    tensor = min(8, largest_pow2_leq(n_devices))
    data = n_devices // tensor
    if tensor * data != n_devices:
        tensor, data = n_devices, 1
    return {"data": data, "tensor": tensor}
