"""Pipeline parallelism: GPipe microbatch schedule over the ``pipe`` axis.

The last of the canonical parallelism dimensions (tp/dp/fsdp/sp/ep live in
sharding.py / ring_attention.py / moe.py). Layer-stacked weights ([L, ...]
leading axis) shard their L axis over ``pipe`` — stage s owns layers
[s*L/P, (s+1)*L/P) with no weight re-layout — and activations hop stage to
stage via ``lax.ppermute`` under a ``shard_map`` that is manual over *only*
the pipe axis (``axis_names={'pipe'}``): tensor/fsdp/data sharding inside a
stage stays GSPMD-automatic, so pp composes with tp/dp.

Schedule: plain GPipe. M microbatches flow through P stages in M+P-1 ticks;
each tick every stage runs its local layer scan, the last stage banks its
finished microbatch, and the ring rotates. Bubble fraction is (P-1)/(M+P-1)
— pick M >= 4*P for ~80%+ utilization. The tick loop is a static-bound
``fori_loop`` (reverse-differentiable), so the same forward drives training.

Design notes, TPU-first:
- ``pipe`` is the OUTERMOST mesh axis: stage hops are low-frequency,
  latency-tolerant point-to-point transfers — exactly what DCN (multi-host)
  or the outer ICI dimension should carry, while tensor collectives stay on
  the inner ring.
- Activations are [Bm, S, H] per tick — the only cross-stage traffic.
  Weights never move.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kukeon_tpu.models import llama
from kukeon_tpu.parallel.mesh import (
    AXIS_PIPE,
    ambient_mesh,
    pcast,
    shard_map,
)
from kukeon_tpu.parallel import sharding as shd


def pp_param_specs(fsdp: bool = False) -> dict:
    """Llama param specs with the stacked-layer axis sharded over ``pipe``.

    Embedding / final norm / lm_head are replicated across stages (first and
    last stage use them; they are small next to the layer stack)."""
    specs = shd.llama_param_specs(fsdp)
    layers = {}
    for name, spec in specs["layers"].items():
        layers[name] = P(AXIS_PIPE, *spec[1:])
    specs["layers"] = layers
    return specs


def pp_specs_for_params(params, fsdp: bool = False) -> dict:
    full = pp_param_specs(fsdp)
    return {k: full[k] for k in params}


def pipeline_forward(
    params: dict,
    cfg: llama.LlamaConfig,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    mesh: Mesh | None = None,
    num_microbatches: int | None = None,
    attn_impl: str = "auto",
) -> jnp.ndarray:
    """Pipeline-parallel forward: logits [B, S, V] f32.

    ``tokens``/``positions`` are [B, S] with B divisible by
    ``num_microbatches`` (default: 2 * pipe size). The layer weights must be
    sharded with :func:`pp_param_specs`. No KV-cache path: pipelining is the
    training/prefill layout; decode serving uses the tensor-parallel engine.
    """
    if mesh is None:
        mesh = ambient_mesh()
    P_ = mesh.shape.get(AXIS_PIPE, 1)
    c = cfg
    B, S = tokens.shape
    if c.num_layers % P_:
        raise ValueError(f"num_layers {c.num_layers} % pipe {P_} != 0")
    M = num_microbatches or max(2 * P_, 1)
    if B % M:
        raise ValueError(f"batch {B} % microbatches {M} != 0")
    Bm = B // M

    x = llama._embed(params, tokens, c.dtype)          # [B, S, H]
    H = x.shape[-1]
    xm = x.reshape(M, Bm, S, H)
    pos_m = positions.reshape(M, Bm, S)

    def stages(layer_ws, xm, pos_m):
        """Manual over ``pipe`` only: layer_ws leaves arrive [L/P, ...]."""
        stage = jax.lax.axis_index(AXIS_PIPE)
        perm = [(i, (i + 1) % P_) for i in range(P_)]

        def run_local(state, pstate):
            def body(carry, w):
                return llama.transformer_block(
                    carry, w, c, pstate, attn_impl=attn_impl
                ), None

            out, _ = jax.lax.scan(body, state, layer_ws)
            return out

        state = jnp.zeros((Bm, S, H), c.dtype)
        pstate = jnp.zeros((Bm, S), jnp.int32)
        out = jnp.zeros((M, Bm, S, H), c.dtype)
        # Mark device-dependent so the loop carry type is stable.
        state = pcast(state, (AXIS_PIPE,), to="varying")
        pstate = pcast(pstate, (AXIS_PIPE,), to="varying")
        out = pcast(out, (AXIS_PIPE,), to="varying")

        def tick(t, carry):
            state, pstate, out = carry
            feed_idx = jnp.minimum(t, M - 1)
            inject = jax.lax.dynamic_index_in_dim(xm, feed_idx, 0, keepdims=False)
            pinject = jax.lax.dynamic_index_in_dim(pos_m, feed_idx, 0, keepdims=False)
            feeding = jnp.logical_and(stage == 0, t < M)
            state = jnp.where(feeding[..., None, None, None], inject, state)
            pstate = jnp.where(feeding[..., None, None], pinject, pstate)

            state = run_local(state, pstate)

            # Last stage banks microbatch t-(P-1) once the pipe is full.
            emit_idx = t - (P_ - 1)
            banked = jax.lax.dynamic_update_slice(
                out, state[None].astype(out.dtype),
                (jnp.maximum(emit_idx, 0), 0, 0, 0),
            )
            emit = jnp.logical_and(stage == P_ - 1, emit_idx >= 0)
            out = jnp.where(emit[..., None, None, None, None], banked, out)

            state = jax.lax.ppermute(state, AXIS_PIPE, perm)
            pstate = jax.lax.ppermute(pstate, AXIS_PIPE, perm)
            return state, pstate, out

        _, _, out = jax.lax.fori_loop(0, M + P_ - 1, tick, (state, pstate, out))
        # Only the last stage holds real outputs; psum replicates them
        # (every other stage contributes zeros).
        mask = (stage == P_ - 1).astype(out.dtype)
        return jax.lax.psum(out * mask, AXIS_PIPE)

    layer_in_specs = jax.tree.map(
        lambda _: P(AXIS_PIPE), params["layers"],
        is_leaf=lambda v: isinstance(v, (jnp.ndarray, jax.Array)) or hasattr(v, "shape"),
    )
    out_m = shard_map(
        stages,
        mesh=mesh,
        in_specs=(layer_in_specs, P(), P()),
        out_specs=P(),
        axis_names={AXIS_PIPE},
    )(params["layers"], xm, pos_m)

    x = out_m.reshape(B, S, H)
    x = llama.rms_norm(x, params["final_norm"], c.rms_norm_eps)
    return llama._logits(params, c, x)


def make_pp_train_step(cfg, mesh: Mesh, optimizer, *,
                       num_microbatches: int | None = None):
    """Jitted, donated pipeline-parallel train step (GPipe forward; reverse
    AD runs the schedule backwards — ppermute transposes to the reverse
    ring). Composes with tensor/data sharding via the auto axes."""
    import optax

    from kukeon_tpu.training.train_step import TrainState, cross_entropy_loss

    def loss_fn(p, tokens, targets, mask, positions):
        logits = pipeline_forward(
            p, cfg, tokens, positions,
            mesh=mesh, num_microbatches=num_microbatches,
        )
        return cross_entropy_loss(logits, targets, mask)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, tokens, targets, mask):
        B, S = tokens.shape
        positions = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32)[None, :], (B, S)
        )
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, targets, mask, positions
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=new_params, opt_state=new_opt,
                       step=state.step + 1),
            loss,
        )

    return train_step
