"""Ulysses sequence parallelism: all-to-all seq<->heads reshard.

The second of the framework's two sequence/context-parallel strategies
(DeepSpeed-Ulysses, Jacobs et al. 2023 — arXiv:2309.14509, public
algorithm; the first is :mod:`kukeon_tpu.parallel.ring_attention`).

Activations arrive sequence-sharded [B, S/n, H, D]. One ``all_to_all``
re-shards them so each device holds ALL positions for H/n of the heads,
full-sequence attention runs locally per head group (any local kernel —
here the XLA reference path), and a second ``all_to_all`` swaps back.

Trade-off vs ring: two all-to-alls per attention instead of an n-step
ppermute pipeline — lower latency when the interconnect does all-to-all
well (ICI does) and when n divides the head counts; ring has no head-count
constraint and overlaps transfer with compute. Both are exact.

Constraints: the per-device head counts (num_heads and num_kv_heads after
any tensor sharding) must be divisible by the ``seq`` axis size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kukeon_tpu.ops.attention import (
    attention_mask,
    attention_reference,
    repeat_kv,
)
from kukeon_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
    ambient_mesh,
    axis_size,
    shard_map,
)


def _ulysses_local(q, k, v, q_pos, kv_pos, axis_name: str):
    """Per-device body under shard_map: local arrays are [B, S/n, h, D]."""
    n = axis_size(axis_name)
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(
            f"ulysses needs seq axis ({n}) to divide the local head counts "
            f"(q heads {q.shape[2]}, kv heads {k.shape[2]}); use ring "
            "attention for odd head layouts"
        )
    # seq-sharded -> head-sharded: split the head axis n ways, gather the
    # full sequence for the local head group.
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name,
        split_axis=2, concat_axis=1, tiled=True,
    )
    qf, kf, vf = a2a(q), a2a(k), a2a(v)              # [B, S, h/n, D]
    q_pos_f = jax.lax.all_gather(q_pos, axis_name, axis=1, tiled=True)
    kv_pos_f = jax.lax.all_gather(kv_pos, axis_name, axis=1, tiled=True)

    mask = attention_mask(q_pos_f, kv_pos_f)
    n_rep = qf.shape[2] // kf.shape[2]
    out = attention_reference(qf, repeat_kv(kf, n_rep), repeat_kv(vf, n_rep),
                              mask)
    # head-sharded -> seq-sharded.
    return jax.lax.all_to_all(out, axis_name=axis_name,
                              split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    mesh: Mesh | None = None,
    axis_name: str = AXIS_SEQ,
) -> jnp.ndarray:
    """Sequence-parallel causal GQA attention via all-to-all.

    Same contract as :func:`kukeon_tpu.parallel.ring_attention`: S is the
    global sequence length, arrays are (or will be constrained) seq-sharded
    over ``axis_name``; returns [B, S, NH, D] with q's sharding.
    """
    if mesh is None:
        mesh = ambient_mesh()
    mesh_axes = set(mesh.axis_names)
    batch_axes = tuple(a for a in (AXIS_DATA, AXIS_FSDP) if a in mesh_axes) or None
    head_axis = AXIS_TENSOR if AXIS_TENSOR in mesh_axes else None

    qkv_spec = P(batch_axes, axis_name, head_axis, None)
    pos_spec = P(batch_axes, axis_name)
    return shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, pos_spec, pos_spec),
        out_specs=qkv_spec,
    )(q, k, v, q_positions, kv_positions)
