"""Ring attention: sequence-parallel exact attention over the ``seq`` mesh axis.

Long-context path (RingAttention, Liu et al. 2023 — arXiv:2310.01889, public
algorithm). Each device holds one sequence shard of Q/K/V; K/V blocks rotate
around the ring via ``lax.ppermute`` (ICI neighbor exchange) while each device
accumulates its queries' attention with an online-softmax (flash-style)
running max/sum, so the full [S, S] score matrix never materializes and
sequence length scales linearly with the number of devices.

Causality is handled by absolute positions: the position vector rotates with
its K/V block, so masking is exact regardless of ring step — no special-cased
block skipping (XLA overlaps the permute with the block compute; skipping
blocks would create load imbalance anyway).

This is an exact drop-in for :func:`kukeon_tpu.ops.attention.gqa_attention`
on seq-sharded activations.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kukeon_tpu.ops.attention import NEG_INF, repeat_kv
from kukeon_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
    ambient_mesh,
    axis_size,
    pcast,
    shard_map,
)


def _block_update(o, m, l, q, k, v, q_pos, kv_pos, scale, n_rep):
    """One online-softmax accumulation step against a K/V block.

    o: [B, Sq, H, D] f32 running (unnormalized) output
    m: [B, H, Sq] f32 running max;  l: [B, H, Sq] f32 running sum
    k/v arrive compact ([B, Sk, NKV, D]) and are GQA-expanded here, after the
    ring transfer, so ppermute traffic stays 1/n_rep of the expanded size.
    """
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = (kv_pos[:, None, :] <= q_pos[:, :, None])[:, None, :, :]  # [B,1,Sq,Sk]
    scores = jnp.where(mask, scores, NEG_INF)

    m_block = jnp.max(scores, axis=-1)                 # [B, H, Sq]
    m_new = jnp.maximum(m, m_block)
    # Renormalize previous accumulators to the new max.
    correction = jnp.exp(m - m_new)                    # [B, H, Sq]
    p = jnp.exp(scores - m_new[..., None])             # [B, H, Sq, Sk]
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    )
    return o_new, m_new, l_new


def _ring_attention_local(q, k, v, q_pos, kv_pos, axis_name: str, all_axes: tuple):
    """Per-device body; runs under shard_map over ``axis_name``."""
    n = axis_size(axis_name)
    n_rep = q.shape[2] // k.shape[2]
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    B, Sq, H, D = q.shape
    # Fresh accumulators are device-invariant; mark them varying over every
    # manual axis so the fori_loop carry type stays fixed across iterations.
    def vary(x):
        return pcast(x, all_axes, to="varying")

    o = vary(jnp.zeros((B, Sq, H, D), jnp.float32))
    m = vary(jnp.full((B, H, Sq), NEG_INF, jnp.float32))
    l = vary(jnp.zeros((B, H, Sq), jnp.float32))

    def step(i, carry):
        o, m, l, k, v, kv_pos = carry
        o, m, l = _block_update(o, m, l, q, k, v, q_pos, kv_pos, scale, n_rep)
        # Rotate K/V (and their positions) to the next ring neighbor.
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        kv_pos = jax.lax.ppermute(kv_pos, axis_name, perm)
        return o, m, l, k, v, kv_pos

    o, m, l, _, _, _ = jax.lax.fori_loop(0, n, step, (o, m, l, k, v, kv_pos))
    l = jnp.maximum(l, 1e-30)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    mesh: Mesh | None = None,
    axis_name: str = AXIS_SEQ,
) -> jnp.ndarray:
    """Sequence-parallel causal GQA attention.

    Args:
      q: [B, S, NH, D]; k/v: [B, S, NKV, D] — S is the *global* sequence
        length; arrays must be (or will be constrained) seq-sharded over
        ``axis_name``.
      q_positions / kv_positions: [B, S] absolute positions.
      mesh: mesh to shard_map over; defaults to the ambient abstract mesh.

    Returns: [B, S, NH, D], same sharding as q.
    """
    if mesh is None:
        mesh = ambient_mesh()

    mesh_axes = set(mesh.axis_names)
    batch_axes = tuple(a for a in (AXIS_DATA, AXIS_FSDP) if a in mesh_axes) or None
    head_axis = AXIS_TENSOR if AXIS_TENSOR in mesh_axes else None

    qkv_spec = P(batch_axes, axis_name, head_axis, None)
    pos_spec = P(batch_axes, axis_name)
    # Accumulators become varying ONLY over axes the inputs are sharded on;
    # axes this op never touches (e.g. ``expert``) must stay invariant or
    # shard_map's replication check rejects the out_specs.
    used = {*(batch_axes or ()), axis_name}
    if head_axis:
        used.add(head_axis)
    fn = functools.partial(
        _ring_attention_local,
        axis_name=axis_name,
        all_axes=tuple(a for a in mesh.axis_names if a in used),
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, pos_spec, pos_spec),
        out_specs=qkv_spec,
    )(q, k, v, q_positions, kv_positions)
