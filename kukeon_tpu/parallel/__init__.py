from kukeon_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_PIPE,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
    auto_mesh_shape,
    make_mesh,
    serving_mesh,
    set_mesh,
    training_mesh,
)
from kukeon_tpu.parallel.pipeline import (  # noqa: F401
    make_pp_train_step,
    pipeline_forward,
    pp_param_specs,
    pp_specs_for_params,
)
from kukeon_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from kukeon_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from kukeon_tpu.parallel.sharding import (  # noqa: F401
    batch_spec,
    kv_cache_spec,
    llama_param_specs,
    moe_param_specs,
    moe_specs_for_params,
    shard_params,
    specs_for_params,
)
