"""Sharding rules: param/activation PartitionSpecs for the Llama family.

GSPMD-style: we annotate shardings on the pytrees and jit boundaries and let
XLA insert the collectives (all-gather / reduce-scatter / all-reduce over
ICI). The megatron pattern for one transformer block needs exactly one
all-reduce per attention block and one per MLP block in forward:

  - wq/wk/wv and w_gate/w_up are sharded on their *output* dim ('tensor'),
  - wo and w_down are sharded on their *input* dim ('tensor'),

so the pair (column-parallel -> row-parallel) keeps activations sharded by
head/intermediate between them, with a single psum at the end of each block.
The embedding is vocab-sharded; the final projection gathers logits.

FSDP shards every weight's largest remaining dim over 'fsdp'; XLA turns that
into per-layer all-gathers (forward) and reduce-scatters (backward), which
overlap with compute on TPU.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kukeon_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
)


def llama_param_specs(fsdp: bool = False) -> dict:
    """PartitionSpec pytree matching the layout of models.llama.init_params.

    Stacked-layer weights have a leading [L] axis that is always replicated
    (the scan iterates over it).
    """
    f = AXIS_FSDP if fsdp else None
    t = AXIS_TENSOR
    specs = {
        "embed": P(t, f),                       # vocab-sharded
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, f, t),                # column-parallel (heads)
            "wk": P(None, f, t),
            "wv": P(None, f, t),
            "wo": P(None, t, f),                # row-parallel
            "mlp_norm": P(None, None),
            "w_gate": P(None, f, t),            # column-parallel (intermediate)
            "w_up": P(None, f, t),
            "w_down": P(None, t, f),            # row-parallel
        },
        "final_norm": P(None),
    }
    # lm_head present only for untied configs; caller prunes to the actual tree.
    specs["lm_head"] = P(f, t)
    return specs


def specs_for_params(params, fsdp: bool = False) -> dict:
    """Prune the full spec tree to the keys present in ``params``."""
    full = llama_param_specs(fsdp)
    return {k: full[k] for k in params}


def _quant_scale_spec(spec: P, q, s) -> P:
    """Spec for an int8 scale vector: the matrix spec minus the contracted
    axis (scale spans the non-contracted axis/axes)."""
    if q.ndim == 4:                      # experts [L, E, in, out] -> s [L, E, out]
        return P(spec[0], spec[1], spec[3])
    if q.ndim == 3:                      # stacked [L, in, out] -> s [L, out]
        return P(spec[0], spec[2])
    # 2-D: s aligns with whichever matrix axis it matches in size.
    return P(spec[0] if s.shape[0] == q.shape[0] else spec[1])


def param_shardings(params, mesh: Mesh, fsdp: bool = False, specs=None):
    """NamedSharding pytree matching ``params``' structure (quantized
    {"q","s"} leaves expanded), without touching any device. ``specs``
    overrides the Llama defaults (e.g. moe_specs_for_params)."""
    if specs is None:
        specs = specs_for_params(params, fsdp)

    def expand(spec, leaf):
        if isinstance(leaf, dict) and "q" in leaf:
            return {
                "q": NamedSharding(mesh, spec),
                "s": NamedSharding(
                    mesh, _quant_scale_spec(spec, leaf["q"], leaf["s"])
                ),
            }
        return NamedSharding(mesh, spec)

    return jax.tree.map(
        expand, specs, params,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params, mesh: Mesh, fsdp: bool = False, threads: int = 4,
                 specs=None):
    """Device-put a param pytree with the canonical shardings.

    Quantized leaves ({"q": int8 matrix, "s": scale}) inherit the matrix
    spec for q; the scale shards with the matrix's surviving axes.

    Transfers are issued from a small thread pool: on a direct PCIe link
    this changes nothing measurable, but on a tunneled/remote chip the
    per-transfer RPC latency dominates and concurrent streams pipeline it
    (an 8B int8 tree is ~300 leaves; serial puts pay ~300 round trips)."""
    shardings = param_shardings(params, mesh, fsdp, specs=specs)
    flat_s, treedef = jax.tree.flatten(shardings)
    flat_p, _ = jax.tree.flatten(params)

    if threads <= 1 or len(flat_p) < 8:
        out = [jax.device_put(x, s) for x, s in zip(flat_p, flat_s)]
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=threads) as pool:
            out = list(pool.map(
                lambda xs: jax.device_put(xs[0], xs[1]),
                zip(flat_p, flat_s),
            ))
    return jax.tree.unflatten(treedef, out)


def moe_param_specs(fsdp: bool = False) -> dict:
    """PartitionSpec pytree matching models.moe.init_params.

    The attention trunk shards exactly like Llama; the expert weights put
    their E axis on ``expert`` (each chip owns E/ep experts — GSPMD turns
    the dispatch/combine einsums into all-to-alls) and keep the megatron
    column->row pairing on ``tensor`` within each expert. The router is
    tiny and replicated."""
    f = AXIS_FSDP if fsdp else None
    t = AXIS_TENSOR
    e = AXIS_EXPERT
    specs = {
        "embed": P(t, f),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, f, t),
            "wk": P(None, f, t),
            "wv": P(None, f, t),
            "wo": P(None, t, f),
            "mlp_norm": P(None, None),
            "router": P(None, None, None),
            "w_gate": P(None, e, f, t),          # [L, E, H, I]
            "w_up": P(None, e, f, t),
            "w_down": P(None, e, t, f),          # [L, E, I, H]
        },
        "final_norm": P(None),
    }
    specs["lm_head"] = P(f, t)
    return specs


def moe_specs_for_params(params, fsdp: bool = False) -> dict:
    full = moe_param_specs(fsdp)
    return {k: full[k] for k in params}


def bert_param_specs(fsdp: bool = False) -> dict:
    """PartitionSpec pytree matching models.bert.init_params — the same
    megatron column->row pairing as the decoder: qkv/in projections shard
    their output dim on 'tensor', wo/out their input dim, one psum per
    block. Biases follow their matmul's output sharding."""
    f = AXIS_FSDP if fsdp else None
    t = AXIS_TENSOR
    return {
        "embed": {
            "word": P(t, f),                    # vocab-sharded
            "position": P(None, f),
            "type": P(None, f),
            "norm_scale": P(None),
            "norm_bias": P(None),
        },
        "layers": {
            "wq": P(None, f, t), "bq": P(None, t),
            "wk": P(None, f, t), "bk": P(None, t),
            "wv": P(None, f, t), "bv": P(None, t),
            "wo": P(None, t, f), "bo": P(None, None),
            "attn_norm_scale": P(None, None), "attn_norm_bias": P(None, None),
            "w_in": P(None, f, t), "b_in": P(None, t),
            "w_out": P(None, t, f), "b_out": P(None, None),
            "mlp_norm_scale": P(None, None), "mlp_norm_bias": P(None, None),
        },
    }


def shard_bert_params(params, mesh: Mesh, fsdp: bool = False):
    specs = bert_param_specs(fsdp)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(params, shardings)


def batch_spec() -> P:
    """Tokens/positions: batch over (data, fsdp), sequence over seq axis."""
    return P((AXIS_DATA, AXIS_FSDP), AXIS_SEQ)


def kv_cache_spec(shard_batch: bool = False) -> "P":
    """KVCache k/v [L, B, S, KV, D]: kv-heads on tensor; optionally batch on
    data/fsdp (training-style). A serving engine is one model replica, so its
    decode slots stay replicated — data parallelism means multiple engines."""
    batch = (AXIS_DATA, AXIS_FSDP) if shard_batch else None
    return P(None, batch, None, AXIS_TENSOR, None)


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
