"""Roofline profiling: per-program timers, per-layer cost profiles, and
the engine step flight recorder.

The device/ layer (PR 4) answers "did it compile again"; this module
answers "where does the device time go" — three instruments deep:

- :class:`ProgramTimers` — dispatch counts, wall-time histograms, and
  token rates for every jitted engine program, plus static
  ``cost_analysis()`` FLOPs/bytes pulled at compile time. Scrape-time
  collectors derive roofline gauges from them: per-program MFU
  (``kukeon_program_mfu``) and HBM bandwidth utilization
  (``kukeon_program_membw_util``). Timing is settled inside the engine's
  counted ``_fetch`` seam only — a dispatch leaves a pending mark, and
  the next blocking readback (which the decode budget already pays for)
  retires every mark whose output is ready. Zero new device→host syncs:
  the host-sync budget tests pass unchanged with timers armed.
- :func:`profile_layers` — lowers each transformer layer's forward
  individually at prefill and decode shapes, recording cost-analysis
  FLOPs/bytes and measured wall time per layer. The persisted artifact
  (serving/tuning.py) is the direct input to pipeline-split placement:
  segmenting on measured per-layer cost instead of "layers are equal".
- :class:`FlightRecorder` — a bounded lock-disciplined ring of
  engine-loop step records (occupancy, chunk size, tokens, per-program
  wall times, transfer counts, preemptions, seated trace ids) behind
  ``GET /v1/timeline`` — "what was the engine doing in the 5s before
  the alert fired", reconstructable after the fact.

jax is imported lazily (function scope) throughout: the obs package
stays importable — and the timers/recorder fully testable — without an
accelerator runtime.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Iterable

from kukeon_tpu import sanitize

# The engine's seven jitted programs (ServingEngine._build_programs).
# kukelint KUKE015 requires every wrap() there to register with this
# seam; the names here are the timer-label vocabulary — distinct from
# the coarse prefill|insert|decode compile labels, which bench.py and
# the compile-flat tests consume and which must not change.
PROGRAMS = (
    "prefill",
    "prefill_ext",
    "insert",
    "decode_chunk",
    "gather_block",
    "insert_paged",
    "decode_chunk_paged",
)

PEAK_FLOPS_ENV = "KUKEON_PEAK_FLOPS"
PEAK_HBM_BPS_ENV = "KUKEON_PEAK_HBM_BPS"

# device_kind substring -> (peak FLOP/s, peak HBM bytes/s), bf16 dense.
# Matched longest-substring-first so "TPU v5p" never hits the "v5" of a
# litespec. Unknown backends (CPU smoke) fall back to a deliberately
# generous default: MFU then reads LOW, never a fabricated 90%.
_PEAK_SPECS: tuple[tuple[str, float, float], ...] = (
    ("v6e", 918e12, 1.64e12),
    ("v5p", 459e12, 2.76e12),
    ("v5e", 197e12, 0.82e12),
    ("v4", 275e12, 1.2e12),
)
_DEFAULT_PEAKS = (1e12, 100e9)


def device_peaks() -> tuple[float, float]:
    """(peak FLOP/s, peak HBM bytes/s) for device 0 — env overrides
    (``KUKEON_PEAK_FLOPS`` / ``KUKEON_PEAK_HBM_BPS``) beat the built-in
    table, the table beats the conservative unknown-backend default."""
    flops, bw = _DEFAULT_PEAKS
    try:
        import jax

        kind = str(jax.devices()[0].device_kind).lower()
        for sub, f, b in _PEAK_SPECS:
            if sub in kind:
                flops, bw = f, b
                break
    except Exception:  # noqa: BLE001 — no backend is not an error here
        pass
    try:
        flops = float(os.environ.get(PEAK_FLOPS_ENV) or flops)
        bw = float(os.environ.get(PEAK_HBM_BPS_ENV) or bw)
    except ValueError:
        pass
    return max(flops, 1.0), max(bw, 1.0)


def cost_summary(compiled) -> tuple[float, float] | None:
    """(flops, bytes accessed) from a compiled executable's
    ``cost_analysis()``; None when the backend reports nothing usable.
    Handles both return shapes jax has shipped (dict and [dict])."""
    try:
        d = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — optional analysis, never a failure
        return None
    if isinstance(d, (list, tuple)):
        d = d[0] if d else None
    if not isinstance(d, dict):
        return None
    try:
        flops = float(d.get("flops", 0.0))
        nbytes = float(d.get("bytes accessed", 0.0))
    except (TypeError, ValueError):
        return None
    if flops <= 0.0 and nbytes <= 0.0:
        return None
    return flops, nbytes


def _first_device_leaf(out: Any) -> Any | None:
    """First leaf in a (possibly nested) program output that looks like a
    device array — the readiness probe target for deferred timing."""
    stack = [out]
    while stack:
        x = stack.pop()
        if hasattr(x, "block_until_ready"):
            return x
        if isinstance(x, (tuple, list)):
            stack.extend(x)
        elif isinstance(x, dict):
            stack.extend(x.values())
    return None


class _ProgramTimer:
    """Per-program dispatch marks. ``dispatched`` and ``settle`` both run
    on the engine driver thread only (dispatch sites and the ``_fetch``
    seam), so the pending deque needs no lock; the shared accumulators
    the scrape thread reads live in the parent under its lock."""

    # Marks outliving this many newer dispatches were lost to a dropped
    # readiness probe; cap the deque so they can never accumulate.
    MAX_PENDING = 8

    def __init__(self, owner: "ProgramTimers", program: str):
        self._owner = owner
        self.program = program
        self._pending: deque[tuple[float, Any]] = deque(maxlen=self.MAX_PENDING)

    def dispatched(self, t0: float, out: Any) -> None:
        """Record a dispatch that started at ``t0`` whose result is
        ``out`` — counted now, timed when a later ``settle`` finds the
        output ready."""
        self._owner._note_dispatch(self.program)
        leaf = _first_device_leaf(out)
        if leaf is not None:
            self._pending.append((t0, leaf))

    def settle(self, now: float) -> None:
        while self._pending:
            t0, leaf = self._pending[0]
            try:
                ready = bool(leaf.is_ready()) if hasattr(leaf, "is_ready") \
                    else True
            except Exception:  # noqa: BLE001 — donated buffers raise: consumed == done
                ready = True
            if not ready:
                break
            self._pending.popleft()
            self._owner._note_settled(self.program, max(0.0, now - t0))


class ProgramTimers:
    """Per-jitted-program roofline telemetry.

    Families (all labelled ``program=`` from :data:`PROGRAMS`):

    - ``kukeon_program_dispatch_total`` — dispatches.
    - ``kukeon_program_seconds`` — wall time per settled dispatch.
    - ``kukeon_program_tokens_total`` — tokens the program processed.
    - ``kukeon_program_flops`` / ``kukeon_program_hbm_bytes`` — static
      per-dispatch cost from ``cost_analysis()`` at compile time.
    - ``kukeon_program_mfu`` / ``kukeon_program_membw_util`` — derived
      at scrape time: achieved FLOP/s (bytes/s) over the device peak,
      clamped to 1.0.

    Timing protocol: the engine's ``_TrackedJit`` wrapper calls
    ``track(program).dispatched(t0, out)`` after each dispatch (async —
    nothing has executed yet), and the engine's ``_fetch`` calls
    :meth:`settle` right after its blocking readback. Device execution
    is in dispatch order, so everything enqueued before the fetched
    array is complete by then; readiness is probed non-blockingly and
    unready marks simply wait for the next fetch. The measured wall
    time therefore includes device queue wait — an overestimate that
    can only LOWER the derived MFU, never inflate it.
    """

    def __init__(self, registry, peaks: tuple[float, float] | None = None):
        self._registry = registry
        self._peaks = peaks
        self._lock = sanitize.lock("ProgramTimers._lock", hot=True)
        self._dispatches: dict[str, int] = {}     # guarded-by: _lock
        self._settled: dict[str, int] = {}        # guarded-by: _lock
        self._busy_s: dict[str, float] = {}       # guarded-by: _lock
        self._tokens: dict[str, int] = {}         # guarded-by: _lock
        self._costs: dict[str, tuple[float, float]] = {}  # guarded-by: _lock
        self._timers: dict[str, _ProgramTimer] = {}
        self._m_dispatch = registry.counter(
            "kukeon_program_dispatch_total",
            "Jitted program dispatches, by engine program.",
            labels=("program",))
        self._m_seconds = registry.histogram(
            "kukeon_program_seconds",
            "Wall time per settled program dispatch (includes device "
            "queue wait), by program.",
            labels=("program",))
        self._m_tokens = registry.counter(
            "kukeon_program_tokens_total",
            "Tokens processed (prompt rows prefetched, batch*k decoded), "
            "by program.",
            labels=("program",))
        self._m_flops = registry.gauge(
            "kukeon_program_flops",
            "Static per-dispatch FLOPs from compile-time cost_analysis "
            "(0 until the program compiles on a reporting backend).",
            labels=("program",))
        self._m_bytes = registry.gauge(
            "kukeon_program_hbm_bytes",
            "Static per-dispatch bytes accessed from compile-time "
            "cost_analysis.",
            labels=("program",))
        registry.register_collector(self._collect)

    # --- engine-facing seam ------------------------------------------------

    def track(self, program: str) -> _ProgramTimer:
        """The (engine-driver-thread) timer handle for one program; the
        ``timer=`` argument CompileTracker.wrap threads into _TrackedJit
        (kukelint KUKE015 requires every _build_programs wrap to pass
        one)."""
        t = self._timers.get(program)
        if t is None:
            t = self._timers[program] = _ProgramTimer(self, program)
        return t

    def settle(self) -> None:
        """Retire pending dispatch marks whose outputs are ready. Called
        from the engine's counted ``_fetch`` seam ONLY — right after a
        blocking readback the budget already paid for."""
        now = time.monotonic()
        for t in self._timers.values():
            t.settle(now)

    def set_cost(self, program: str, flops: float, nbytes: float) -> None:
        """Record a program's static per-dispatch cost (compile time)."""
        with self._lock:
            self._costs[program] = (float(flops), float(nbytes))
        self._m_flops.set(float(flops), program=program)
        self._m_bytes.set(float(nbytes), program=program)

    def note_cost(self, program: str, compiled) -> None:
        """``set_cost`` from a compiled executable's cost_analysis; a
        backend that reports nothing leaves the gauges at zero."""
        got = cost_summary(compiled)
        if got is not None:
            self.set_cost(program, got[0], got[1])

    def note_tokens(self, program: str, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._tokens[program] = self._tokens.get(program, 0) + int(n)
        self._m_tokens.inc(int(n), program=program)

    # --- accumulators (driver thread writes, scrape thread reads) ----------

    def _note_dispatch(self, program: str) -> None:
        with self._lock:
            self._dispatches[program] = self._dispatches.get(program, 0) + 1
        self._m_dispatch.inc(program=program)

    def _note_settled(self, program: str, dt: float) -> None:
        with self._lock:
            self._settled[program] = self._settled.get(program, 0) + 1
            self._busy_s[program] = self._busy_s.get(program, 0.0) + dt
        self._m_seconds.observe(dt, program=program)

    # --- derived views -----------------------------------------------------

    def _utilization(self) -> dict[str, tuple[float, float]]:
        """{program: (mfu, membw_util)} over settled dispatches, clamped
        to [0, 1]: achieved = static per-dispatch cost x settled count /
        measured busy seconds; peak from :func:`device_peaks`."""
        peak_flops, peak_bw = self._peaks or device_peaks()
        out = {}
        with self._lock:
            for program, (flops, nbytes) in self._costs.items():
                n = self._settled.get(program, 0)
                busy = self._busy_s.get(program, 0.0)
                if n <= 0 or busy <= 0.0:
                    continue
                out[program] = (
                    min(1.0, (flops * n) / (busy * peak_flops)),
                    min(1.0, (nbytes * n) / (busy * peak_bw)),
                )
        return out

    def _collect(self) -> Iterable[object]:
        util = self._utilization()
        yield ("kukeon_program_mfu", "gauge",
               "Model FLOPs utilization per program: static FLOPs x "
               "settled dispatches / (measured busy seconds x device "
               "peak FLOP/s), clamped to 1.",
               [({"program": p}, mfu) for p, (mfu, _bw) in
                sorted(util.items())])
        yield ("kukeon_program_membw_util", "gauge",
               "HBM bandwidth utilization per program: bytes accessed x "
               "settled dispatches / (busy seconds x peak bytes/s), "
               "clamped to 1.",
               [({"program": p}, bw) for p, (_mfu, bw) in
                sorted(util.items())])

    def snapshot(self) -> dict[str, dict[str, float]]:
        """Per-program roofline summary for bench artifacts and step
        records: dispatches, settled count, busy seconds, tokens, static
        cost, and derived MFU/bandwidth utilization."""
        util = self._utilization()
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            programs = (set(self._dispatches) | set(self._costs)
                        | set(self._tokens))
            for p in sorted(programs):
                flops, nbytes = self._costs.get(p, (0.0, 0.0))
                mfu, bw = util.get(p, (0.0, 0.0))
                out[p] = {
                    "dispatches": self._dispatches.get(p, 0),
                    "settled": self._settled.get(p, 0),
                    "busy_s": round(self._busy_s.get(p, 0.0), 6),
                    "tokens": self._tokens.get(p, 0),
                    "flops": flops,
                    "hbm_bytes": nbytes,
                    "mfu": round(mfu, 6),
                    "membw_util": round(bw, 6),
                }
        return out

    def busy_seconds(self) -> dict[str, float]:
        with self._lock:
            return dict(self._busy_s)


class FlightRecorder:
    """Bounded ring of engine-loop step records — the step timeline.

    The engine driver appends one small dict per working step
    (:meth:`record`); HTTP readers snapshot the newest N
    (:meth:`snapshot`). The ring is a preallocated circular list: memory
    is bounded at ``capacity`` records forever, overwritten (dropped)
    records are counted on ``kukeon_timeline_dropped_total``, and both
    sides take one short lock — green under KUKEON_SANITIZE=1 with
    ingest and readers hammering concurrently.
    """

    DEFAULT_CAPACITY = 512

    def __init__(self, capacity: int = DEFAULT_CAPACITY, registry=None):
        self.capacity = max(1, int(capacity))
        self._lock = sanitize.lock("FlightRecorder._lock", hot=True)
        self._ring: list[dict | None] = [None] * self.capacity  # guarded-by: _lock
        self._next_seq = 0   # guarded-by: _lock
        self._dropped = 0    # guarded-by: _lock
        self._m_dropped = None
        if registry is not None:
            self._m_dropped = registry.counter(
                "kukeon_timeline_dropped_total",
                "Step records overwritten in the flight-recorder ring "
                "before any reader saw the window slide past them.")
            registry.gauge(
                "kukeon_timeline_depth",
                "Step records currently held in the flight-recorder "
                "ring (caps at its capacity).").set_function(
                lambda: float(len(self)))

    def record(self, rec: dict) -> int:
        """Append one step record; returns its sequence number. The
        record is stamped with ``seq`` and ``t`` (wall-clock seconds)
        here so every producer shares one schema spine."""
        rec = dict(rec)
        rec.setdefault("t", time.time())
        with self._lock:
            seq = self._next_seq
            self._next_seq = seq + 1
            rec["seq"] = seq
            idx = seq % self.capacity
            if self._ring[idx] is not None:
                self._dropped += 1
            self._ring[idx] = rec
        if self._m_dropped is not None and seq >= self.capacity:
            self._m_dropped.inc()
        return seq

    def snapshot(self, n: int | None = None) -> list[dict]:
        """The newest ``n`` (default: all held) step records, oldest
        first — the shape `kuke timeline` renders top-to-bottom."""
        with self._lock:
            end = self._next_seq
            held = min(end, self.capacity)
            want = held if n is None else max(0, min(int(n), held))
            out = [self._ring[s % self.capacity]
                   for s in range(end - want, end)]
        return [dict(r) for r in out if r is not None]

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return min(self._next_seq, self.capacity)


# --- per-layer cost profiler -------------------------------------------------

LAYER_PROFILE_SCHEMA = "kukeon-layer-profile/v1"


def _time_compiled(fn, args, reps: int = 3) -> float:
    """Best-of-``reps`` wall seconds for one executed call (post-warmup,
    blocked to completion) — the cheapest honest point measurement."""
    best = None
    for _ in range(max(1, reps)):
        t0 = time.monotonic()
        out = fn(*args)
        leaf = _first_device_leaf(out)
        if leaf is not None:
            leaf.block_until_ready()
        dt = time.monotonic() - t0
        best = dt if best is None else min(best, dt)
    return float(best or 0.0)


def profile_layers(params, cfg, mesh=None, *, prefill_len: int = 64,
                   decode_batch: int = 8, measure: bool = True,
                   reps: int = 3) -> dict:
    """Per-component roofline profile of a llama model: embed, each
    transformer layer, and the LM head, each lowered INDIVIDUALLY at a
    prefill shape ``[1, prefill_len]`` and a decode shape
    ``[decode_batch, 1]``, recording cost-analysis FLOPs/bytes and (with
    ``measure=True``) executed wall time.

    The whole-model reference cost is taken from a scan-free composition
    of the same components (XLA's cost analysis cannot see a while
    loop's trip count, so scanning would under-count the stack) — the
    per-layer FLOPs sum matches it within the 5% acceptance bound by
    construction of the lowering, not by luck.

    Failures degrade, never crash: a component whose lowering (or the
    armed ``profile.layers`` fault point) raises contributes an
    ``error`` entry and profiling continues. The caller decides whether
    a partial profile is worth persisting (``result["errors"]``).
    """
    import jax
    import jax.numpy as jnp

    from kukeon_tpu import faults
    from kukeon_tpu.models import llama

    n_layers = int(cfg.num_layers)
    hidden = int(cfg.hidden_size)
    prefill_len = max(1, int(prefill_len))
    decode_batch = max(1, int(decode_batch))

    shapes = (
        ("prefill", (1, prefill_len)),
        ("decode", (decode_batch, 1)),
    )

    def _embed_fn(tokens):
        return llama._embed(params, tokens, cfg.dtype)

    def _head_fn(x):
        h = llama.rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
        return llama._logits(params, cfg, h)

    def _layer_fn(i):
        w = jax.tree.map(lambda a: a[i], params["layers"])

        def fn(x, positions):
            return llama.transformer_block(x, w, cfg, positions)
        return fn

    def _whole_fn(tokens, positions):
        x = llama._embed(params, tokens, cfg.dtype)
        for i in range(n_layers):
            w = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            x = llama.transformer_block(x, w, cfg, positions)
        return _head_fn(x)

    def _args_for(name: str, B: int, S: int):
        tokens = jnp.zeros((B, S), jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        x = jnp.zeros((B, S, hidden), cfg.dtype)
        if name == "embed":
            return (tokens,)
        if name == "head":
            return (x,)
        if name == "model":
            return (tokens, positions)
        return (x, positions)

    def _profile_one(name: str, fn) -> dict:
        entry: dict[str, Any] = {"name": name}
        for shape_name, (B, S) in shapes:
            faults.maybe_fail("profile.layers")
            jitted = jax.jit(fn)
            args = _args_for(name, B, S)
            compiled = jitted.lower(*args).compile()
            got = cost_summary(compiled)
            rec = {"flops": got[0] if got else 0.0,
                   "bytes": got[1] if got else 0.0}
            if measure:
                _time_compiled(jitted, args, reps=1)   # warmup / cache prime
                rec["wall_s"] = round(_time_compiled(jitted, args, reps), 6)
            entry[shape_name] = rec
        return entry

    components: list[dict] = []
    errors = 0
    plan = [("embed", _embed_fn)]
    plan += [(f"layer{i}", _layer_fn(i)) for i in range(n_layers)]
    plan += [("head", _head_fn)]
    for name, fn in plan:
        try:
            components.append(_profile_one(name, fn))
        except Exception as e:  # noqa: BLE001 — a partial profile beats a dead cell
            errors += 1
            components.append(
                {"name": name, "error": f"{type(e).__name__}: {e}"})

    model_flops = model_bytes = 0.0
    try:
        compiled = jax.jit(_whole_fn).lower(
            *_args_for("model", 1, prefill_len)).compile()
        got = cost_summary(compiled)
        if got is not None:
            model_flops, model_bytes = got
    except Exception as e:  # noqa: BLE001 — reference cost is advisory
        errors += 1
        components.append({"name": "model", "error":
                           f"{type(e).__name__}: {e}"})

    return {
        "schema": LAYER_PROFILE_SCHEMA,
        "num_layers": n_layers,
        "prefill_len": prefill_len,
        "decode_batch": decode_batch,
        "model_flops": model_flops,
        "model_bytes": model_bytes,
        "components": components,
        "errors": errors,
    }
