"""SLO evaluation: availability + TTFT-latency burn rates at scrape time.

Objectives are declared on the ModelSpec (``sloAvailability``,
``sloTtftP95Ms``) and evaluated against the instruments the engine already
maintains — ``kukeon_engine_requests_total{outcome}`` and the
``kukeon_engine_ttft_seconds`` histogram — so the SLO layer adds ZERO work
to the serving hot path. Each scrape records a counter snapshot; burn rates
are computed from the delta between "now" and the snapshot nearest each
window's start (5m, 1h). With one scraper at a typical 15–60s interval the
windows resolve fine; with no scraper the cell simply reports
since-boot numbers.

Exposed families:

- ``kukeon_slo_objective{slo=}`` — the declared objectives (availability as
  a fraction, ttft_p95 in seconds), so dashboards need no config.
- ``kukeon_slo_burn_rate{slo=,window=5m|1h}`` — observed bad-event rate
  divided by the allowed rate; 1.0 = burning budget exactly at the
  objective, >1 = violating, 0 = clean.
- ``kukeon_slo_error_budget_remaining{slo=}`` — fraction of the budget left
  over the long window: ``max(0, 1 - burn_1h)``.

"Bad" for availability = outcomes ``error`` and ``timeout`` (sheds are
load-management, not failures — they answer 429 with Retry-After). "Bad"
for latency = requests whose TTFT exceeded the objective, estimated from
the histogram's cumulative buckets with interpolation in the landing
bucket; the objective is a p95, so the allowed bad fraction is 5%.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

from kukeon_tpu import sanitize

_BAD_OUTCOMES = ("error", "timeout")
# The ttft objective is a p95: up to 5% of requests may exceed it.
_TTFT_QUANTILE_SLACK = 0.05

WINDOWS = ((300.0, "5m"), (3600.0, "1h"))


@dataclasses.dataclass(frozen=True)
class SloObjectives:
    """Serving objectives; defaults are deliberately loose so a cell with
    no declared SLO still exposes the families without alarming anyone."""

    availability: float = 0.99       # fraction of requests that must succeed
    ttft_p95_ms: float = 2000.0      # 95th-percentile TTFT bound


@dataclasses.dataclass
class _Snapshot:
    at: float
    total: float                     # requests reaching a terminal event
    bad: float                       # of those, error/timeout outcomes
    ttft_counts: list[int]           # per-bucket TTFT counts (+ overflow)


def _count_leq(buckets: tuple[float, ...], counts: list[int],
               threshold: float) -> float:
    """Estimated observations <= threshold from per-bucket counts, linear
    inside the landing bucket (same estimator family as percentile)."""
    good = 0.0
    lo = 0.0
    for b, c in zip(buckets, counts[:-1]):
        if threshold >= b:
            good += c
        else:
            if threshold > lo and b > lo:
                good += c * (threshold - lo) / (b - lo)
            break
        lo = b
    return good


class SloTracker:
    """Windowed burn-rate evaluation over an obs Registry's counters.

    Registered as a scrape-time collector; every ``collect()`` call records
    one snapshot and prunes those older than the longest window. Thread-safe
    (scrapes can overlap), injectable clock for tests.
    """

    def __init__(self, registry, objectives: SloObjectives | None = None, *,
                 requests_counter: str = "kukeon_engine_requests_total",
                 ttft_histogram: str = "kukeon_engine_ttft_seconds",
                 windows=WINDOWS, clock=time.monotonic):
        self._reg = registry
        self.objectives = objectives or SloObjectives()
        self._requests_name = requests_counter
        self._ttft_name = ttft_histogram
        self._windows = tuple(windows)
        self._clock = clock
        self._lock = sanitize.lock("SloTracker._lock")
        self._snaps: deque[_Snapshot] = deque()
        registry.register_collector(self.collect)

    # --- snapshotting -------------------------------------------------------

    def _take_snapshot(self) -> _Snapshot:
        total = bad = 0.0
        c = self._reg.get(self._requests_name)
        if c is not None:
            for labels, v in c.samples():
                total += v
                if labels.get("outcome") in _BAD_OUTCOMES:
                    bad += v
        h = self._reg.get(self._ttft_name)
        counts = list(h.snapshot()[0]) if h is not None else []
        return _Snapshot(at=self._clock(), total=total, bad=bad,
                         ttft_counts=counts)

    def _baseline(self, now: float, window_s: float) -> _Snapshot | None:
        """Latest snapshot at or before the window start; the oldest one we
        have when history is still shorter than the window."""
        base = None
        for s in self._snaps:
            if s.at <= now - window_s:
                base = s
            else:
                break
        if base is None and self._snaps:
            base = self._snaps[0]
        return base

    # --- burn math ----------------------------------------------------------

    def _burns(self, cur: _Snapshot, base: _Snapshot | None
               ) -> dict[str, float]:
        if base is None:
            base = _Snapshot(at=cur.at, total=0.0, bad=0.0,
                             ttft_counts=[0] * len(cur.ttft_counts))
        d_total = max(0.0, cur.total - base.total)
        d_bad = max(0.0, cur.bad - base.bad)
        out = {"availability": 0.0, "ttft_p95": 0.0}
        allowed_bad = max(1e-9, 1.0 - self.objectives.availability)
        if d_total > 0:
            out["availability"] = (d_bad / d_total) / allowed_bad
        h = self._reg.get(self._ttft_name)
        if h is not None and cur.ttft_counts:
            base_counts = base.ttft_counts or [0] * len(cur.ttft_counts)
            d_counts = [c - b for c, b in zip(cur.ttft_counts, base_counts)]
            n = sum(d_counts)
            if n > 0:
                thr = self.objectives.ttft_p95_ms / 1000.0
                slow = max(0.0, n - _count_leq(h.buckets, d_counts, thr))
                out["ttft_p95"] = (slow / n) / _TTFT_QUANTILE_SLACK
        return out

    # --- collector ----------------------------------------------------------

    def collect(self):
        cur = self._take_snapshot()
        with self._lock:
            self._snaps.append(cur)
            horizon = cur.at - max(w for w, _ in self._windows) - 120.0
            while self._snaps and self._snaps[0].at < horizon:
                self._snaps.popleft()
            per_window = {
                label: self._burns(cur, self._baseline(cur.at, w))
                for w, label in self._windows
            }
        long_label = max(self._windows)[1]
        yield ("kukeon_slo_objective", "gauge",
               "Declared serving objectives (availability fraction, "
               "ttft_p95 seconds).",
               [({"slo": "availability"}, self.objectives.availability),
                ({"slo": "ttft_p95"}, self.objectives.ttft_p95_ms / 1000.0)])
        yield ("kukeon_slo_burn_rate", "gauge",
               "Observed bad-event rate over allowed rate, per window "
               "(1.0 = exactly at objective).",
               [({"slo": slo, "window": label}, rate)
                for label, burns in per_window.items()
                for slo, rate in sorted(burns.items())])
        yield ("kukeon_slo_error_budget_remaining", "gauge",
               "Fraction of error budget left over the long window.",
               [({"slo": slo}, max(0.0, 1.0 - rate))
                for slo, rate in sorted(per_window[long_label].items())])
