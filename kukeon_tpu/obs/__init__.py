"""Unified observability layer: metrics registry, Prometheus exposition,
and per-request trace spans.

Zero-dependency by design (the container bakes no prometheus_client): the
registry is a few hundred lines of locked dicts, the exposition is the
Prometheus text format 0.0.4 by hand, and traces are dataclasses in a ring
buffer. Everything the serving engine, the cells, the runner, and the
daemon report flows through here; ``bench.py`` scores itself from the same
histograms a production scrape would read.

Naming convention: ``kukeon_<subsystem>_<name>`` with ``_total`` for
counters and ``_seconds`` for latency histograms — e.g.
``kukeon_engine_ttft_seconds``, ``kukeon_runner_cell_restarts_total``,
``kukeon_faults_fired_total{point="engine.decode"}``.
"""

from kukeon_tpu.obs.registry import (  # noqa: F401
    LATENCY_BUCKETS_S,
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_default,
    percentile_from_counts,
)
from kukeon_tpu.obs.expo import faults_collector, render  # noqa: F401
from kukeon_tpu.obs.trace import (  # noqa: F401
    PHASES,
    TRACEPARENT_HEADER,
    Span,
    TraceContext,
    Tracer,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)
from kukeon_tpu.obs.device import (  # noqa: F401
    CompileTracker,
    ProfileBusy,
    ProfileSpool,
    device_memory_collector,
)
from kukeon_tpu.obs.profile import (  # noqa: F401
    LAYER_PROFILE_SCHEMA,
    PROGRAMS,
    FlightRecorder,
    ProgramTimers,
    cost_summary,
    device_peaks,
    profile_layers,
)
from kukeon_tpu.obs.slo import SloObjectives, SloTracker  # noqa: F401
from kukeon_tpu.obs.tsdb import (  # noqa: F401
    AGGS,
    TSDB,
    parse_expr,
    parse_selector,
    parse_window,
    sparkline,
)
from kukeon_tpu.obs.alerts import (  # noqa: F401
    BUILTIN_RULES,
    AlertEngine,
    Rule,
    load_user_rules,
    validate_rule,
)
