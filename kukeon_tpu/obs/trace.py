"""Per-request trace spans with W3C-style distributed trace context, a
tail-sampled bounded ring buffer, and the declared phase registry.

A request's life inside one engine is a chain of monotonic timestamps::

    submitted -> admitted -> prefill_dispatched -> first_token -> finished

and the exported span derives phase durations from CONSECUTIVE event
pairs, so the phases partition the request's wall time exactly:
``queued + prefill + decode == e2e`` (the acceptance tolerance exists only
for float rounding). Requests that die early (shed at submit, deadline
expiry while queued, cancel) simply stop the chain where they stopped —
their later phases read 0 and the recorded outcome names why.

**Distributed context.** Request identity used to be an engine-local
integer, so a request flowing gateway -> replica -> engine (retried onto a
second replica, preempted and resumed on the paged KV path) left span
fragments that could not be joined. Every span now carries a
``trace_id``/``span_id``/``parent_span_id`` triple minted at the first hop
(the gateway, or the engine for direct submissions) and propagated over
HTTP via a W3C-``traceparent``-shaped header
(``00-<32 hex trace id>-<16 hex span id>-01``). The daemon's ``Traces``
RPC unions every cell's ring by trace id and ``kuke trace <trace-id>``
renders the reconstructed cross-component timeline.

**Tail sampling.** The ring is bounded, so under flood the interesting
traces (slow, errored, preempted, retried) must not be evicted by a wall
of boring fast ones. :meth:`Tracer.finish` therefore decides keep/drop at
completion time — when the outcome is known — instead of head-sampling at
submit: error/timeout/cancelled/shed outcomes, preempted or retried
spans, and spans slower than the tracer's own running p95 are ALWAYS
kept; the rest are kept with ``KUKEON_TRACE_SAMPLE`` probability
(default 1.0 — sampling is an operator opt-in) decided deterministically
from the trace id, so every component of one trace reaches the same
verdict. Verdict counts surface as
``kukeon_trace_tail_sampled_total{decision=}``.

The buffer is a ``deque(maxlen=capacity)``: O(1) append, oldest spans
evicted, bounded memory no matter the traffic. ``GET /v1/trace?n=K``
returns the newest K spans; ``?trace_id=`` pulls one trace's spans, and
JSON log lines carry the same ``trace_id``/``request_id`` pair so logs
and traces join on one key.
"""

from __future__ import annotations

import dataclasses
import os
import time
import uuid
from collections import deque

from kukeon_tpu import sanitize

# Event-chain order; phase N is the gap between event N and event N+1.
EVENTS = ("submitted", "admitted", "prefill_dispatched", "first_token",
          "finished")
# Human phase names for the exported span, keyed by the gap's start event.
# Applied to engine-component spans only: gateway/boot spans keep their
# raw event names as phase keys.
_PHASE_OF = {
    "submitted": "queued",            # submit -> dequeued for a slot
    "admitted": "prefill_dispatch",   # dequeue -> prefill program dispatched
    "prefill_dispatched": "prefill_wait",  # dispatch -> first token emitted
    "first_token": "decode",          # first token -> terminal event
}

OUTCOMES = ("ok", "shed", "timeout", "cancelled", "error")

# Every span phase/mark literal used anywhere in the package. kukelint
# KUKE010 (analysis/registries.py) enforces this registry both ways: an
# ``<span>.event("x")`` call site whose literal is missing here fails the
# lint, and an entry here with no call site is a stale declaration. Keep
# the groups in hop order — the registry doubles as the vocabulary
# ``kuke trace`` renders.
PHASES = (
    # engine request lifecycle (serving/engine.py)
    "submitted", "admitted", "prefill_dispatched", "first_token",
    "finished", "preempted",
    # disaggregated KV handoff (serving/engine.py export/import,
    # gateway/cell.py handoff driver + local-decode fallback)
    "kv_exported", "kv_imported", "kv_handoff", "handoff_fallback",
    # gateway proxy hops (gateway/cell.py)
    "proxy_attempt", "proxy_retry", "proxy_shed",
    # gateway spillover (gateway/cell.py): an all-shed request parking in
    # the bounded deadline-aware queue, and its later retry winning a
    # replica — a brief storm rendered as latency, not an error.
    "spill_park", "spill_resume",
    # cell boot phases (runtime/serving_cell.py finish_boot)
    "boot_imports", "boot_init", "boot_compile", "boot_warmup",
)

# The propagation header. Shaped like W3C traceparent (version-00):
# ``00-<trace_id:32 hex>-<span_id:16 hex>-01``.
TRACEPARENT_HEADER = "traceparent"

# Tail-sampling keep probability for boring fast-path traces; interesting
# traces (non-ok outcome, preempted, retried, slower than the running p95)
# are always kept regardless.
TRACE_SAMPLE_ENV = "KUKEON_TRACE_SAMPLE"

# Shared latency ladder for the tracer's own e2e distribution (slow-trace
# detection); importing from registry would be circular only in spirit —
# obs.registry does not import trace — but a local import keeps this
# module dependency-light for the analyzer.
from kukeon_tpu.obs.registry import LATENCY_BUCKETS_S  # noqa: E402


def new_trace_id() -> str:
    """Globally unique 32-hex-char trace id (uuid4 randomness)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """16-hex-char span id."""
    return uuid.uuid4().hex[:16]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """A parsed propagation header: the trace to join and the parent span
    to hang this hop's span under."""

    trace_id: str
    span_id: str


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: str | None) -> TraceContext | None:
    """Strictly parse a traceparent header; None on absence or anything
    malformed (a garbled header must degrade to a fresh root trace, never
    to a crashed request)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _ver, trace_id, span_id, _flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id=trace_id.lower(), span_id=span_id.lower())


@dataclasses.dataclass
class Span:
    """One hop's lifecycle record (mutated only by its owning driver
    thread until finish; read-only afterwards).

    ``events`` entries are ``(name, monotonic_t)`` tuples, or
    ``(name, monotonic_t, attrs)`` when the mark carries attributes (a
    gateway attempt records which replica it dialed). Consumers must
    index, not unpack, unless they know the producer."""

    request_id: int
    prompt_tokens: int
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str | None = None
    component: str = "engine"
    started_wall: float = dataclasses.field(default_factory=time.time)
    events: list[tuple] = dataclasses.field(default_factory=list)
    outcome: str | None = None
    error: str | None = None
    tokens: int = 0
    decode_chunks: int = 0
    attrs: dict = dataclasses.field(default_factory=dict)
    # Back-date the root event (boot spans start at process t0, not at
    # span construction).
    start_mono: float | None = None

    def __post_init__(self):
        if not self.trace_id:
            self.trace_id = new_trace_id()
        if not self.span_id:
            self.span_id = new_span_id()
        self.event("submitted", at=self.start_mono)

    def event(self, name: str, at: float | None = None, **attrs) -> None:
        t = time.monotonic() if at is None else at
        if attrs:
            self.events.append((name, t, attrs))
        else:
            self.events.append((name, t))

    @property
    def finished(self) -> bool:
        return self.outcome is not None

    @property
    def e2e_s(self) -> float:
        return self.events[-1][1] - self.events[0][1]

    def to_dict(self) -> dict:
        first = self.events[0][1]
        last = self.events[-1][1]
        phases: dict[str, float] = {}
        alias = self.component == "engine"
        for ev, nxt in zip(self.events, self.events[1:]):
            name = ev[0]
            phase = _PHASE_OF.get(name, name) if alias else name
            phases[phase] = phases.get(phase, 0.0) + (nxt[1] - ev[1])
        out_events = []
        for ev in self.events:
            d = {"event": ev[0], "atS": round(ev[1] - first, 6)}
            if len(ev) > 2 and ev[2]:
                d["attrs"] = ev[2]
            out_events.append(d)
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            **({"parentSpanId": self.parent_span_id}
               if self.parent_span_id else {}),
            "component": self.component,
            "requestId": self.request_id,
            "startedAt": self.started_wall,
            "outcome": self.outcome,
            **({"error": self.error} if self.error else {}),
            "promptTokens": self.prompt_tokens,
            "tokens": self.tokens,
            "decodeChunks": self.decode_chunks,
            **({"attrs": dict(self.attrs)} if self.attrs else {}),
            "events": out_events,
            "phasesS": {k: round(v, 6) for k, v in phases.items()},
            "e2eS": round(last - first, 6),
        }


def _hash01(trace_id: str) -> float:
    """Deterministic uniform-[0,1) value from a trace id: every component
    of one trace reaches the same probabilistic verdict."""
    try:
        return int(trace_id[:8], 16) / float(16 ** 8)
    except ValueError:
        return 0.0


class Tracer:
    """Span factory + tail-sampled bounded completed-span buffer
    (thread-safe)."""

    def __init__(self, capacity: int = 512,
                 keep_probability: float | None = None):
        self._lock = sanitize.lock("Tracer._lock")
        self._done: deque[Span] = deque(maxlen=max(1, capacity))
        if keep_probability is None:
            try:
                keep_probability = float(
                    os.environ.get(TRACE_SAMPLE_ENV, "") or 1.0)
            except ValueError:
                keep_probability = 1.0
        self.keep_probability = min(1.0, max(0.0, keep_probability))
        # Running e2e distribution over the shared latency ladder: the
        # slow-trace criterion ("always keep p95+") is computed from the
        # tracer's OWN population, so it needs no engine histogram handle.
        self._e2e_counts = [0] * (len(LATENCY_BUCKETS_S) + 1)
        # Tail-sampler verdicts, exposed as
        # kukeon_trace_tail_sampled_total{decision=} by the owning
        # component's collector.
        self.sample_stats = {"kept": 0, "dropped": 0}

    def begin(self, request_id: int, prompt_tokens: int, *,
              trace_ctx: TraceContext | None = None,
              component: str = "engine",
              start_mono: float | None = None) -> Span:
        """New span — joining ``trace_ctx``'s trace as a child when given,
        else rooting a fresh trace (direct engine submissions still get
        globally unique trace ids)."""
        return Span(
            request_id=request_id, prompt_tokens=prompt_tokens,
            trace_id=trace_ctx.trace_id if trace_ctx is not None else "",
            parent_span_id=(trace_ctx.span_id
                            if trace_ctx is not None else None),
            component=component, start_mono=start_mono,
        )

    # --- tail sampling -----------------------------------------------------

    def _p95_bound_locked(self) -> float:
        """Upper bound of the bucket holding the running p95 (callers hold
        ``_lock``). A span must land in a strictly HIGHER bucket to count
        as slow — with a uniform population nothing outruns its own
        bucket, so uniform fast traffic is all 'boring'."""
        n = sum(self._e2e_counts)
        if n == 0:
            return float("inf")
        rank = 0.95 * n
        seen = 0
        for i, c in enumerate(self._e2e_counts):
            seen += c
            if seen >= rank:
                return LATENCY_BUCKETS_S[min(i, len(LATENCY_BUCKETS_S) - 1)]
        return LATENCY_BUCKETS_S[-1]

    def _interesting(self, span: Span) -> bool:
        """Unconditionally-kept traces: anything that went wrong, anything
        the scheduler disturbed (preemption), anything the gateway had to
        retry. These are exactly what an operator pulls up post-hoc."""
        if span.outcome != "ok":
            return True
        if span.attrs.get("retries"):
            return True
        return any(ev[0] in ("preempted", "proxy_retry")
                   for ev in span.events)

    def finish(self, span: Span, outcome: str, *, tokens: int | None = None,
               error: str | None = None) -> Span:
        """Terminal transition: stamps the ``finished`` event, records the
        outcome, and tail-samples the span into the ring. Idempotent — a
        request failed twice (sweep + fail_all racing) keeps its FIRST
        verdict."""
        if span.finished:
            return span
        span.event("finished")
        span.outcome = outcome
        if tokens is not None:
            span.tokens = tokens
        if error is not None:
            span.error = error
        e2e = span.e2e_s
        with self._lock:
            # Record into the running distribution first so the very first
            # span compares against a population that includes itself.
            for i, b in enumerate(LATENCY_BUCKETS_S):
                if e2e <= b:
                    self._e2e_counts[i] += 1
                    break
            else:
                self._e2e_counts[-1] += 1
            keep = (
                self._interesting(span)
                or e2e > self._p95_bound_locked()
                or _hash01(span.trace_id) < self.keep_probability
            )
            self.sample_stats["kept" if keep else "dropped"] += 1
            if keep:
                self._done.append(span)
        return span

    # --- queries -----------------------------------------------------------

    def recent(self, n: int = 50) -> list[dict]:
        """Newest-first completed spans, at most ``n``."""
        with self._lock:
            spans = list(self._done)
        return [s.to_dict() for s in reversed(spans[-max(0, n):])]

    def for_request(self, request_id: int) -> list[dict]:
        """Exact-match lookup by request id (``GET /v1/trace?request_id=``):
        a slow request found in the logs can be pulled directly instead of
        paging the tail and eyeballing. Newest-first; normally one span,
        but shed spans share id -1."""
        with self._lock:
            spans = [s for s in self._done if s.request_id == request_id]
        return [s.to_dict() for s in reversed(spans)]

    def for_trace(self, trace_id: str) -> list[dict]:
        """All completed spans of one trace (``GET /v1/trace?trace_id=``),
        oldest-first — the order a timeline renders them."""
        with self._lock:
            spans = [s for s in self._done if s.trace_id == trace_id]
        return [s.to_dict() for s in spans]

    def __len__(self) -> int:
        with self._lock:
            return len(self._done)
