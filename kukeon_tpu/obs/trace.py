"""Per-request trace spans: the full lifecycle of every generation request
in a bounded ring buffer.

A request's life is a chain of monotonic timestamps::

    submitted -> admitted -> prefill_dispatched -> first_token -> finished

and the exported span derives phase durations from CONSECUTIVE event
pairs, so the phases partition the request's wall time exactly:
``queued + prefill + decode == e2e`` (the acceptance tolerance exists only
for float rounding). Requests that die early (shed at submit, deadline
expiry while queued, cancel) simply stop the chain where they stopped —
their later phases read 0 and the recorded outcome names why.

The buffer is a ``deque(maxlen=capacity)``: O(1) append, oldest spans
evicted, bounded memory no matter the traffic. ``GET /v1/trace?n=K``
returns the newest K spans; log lines carry the same ``request_id`` so a
span and its log records correlate.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

from kukeon_tpu import sanitize

# Event-chain order; phase N is the gap between event N and event N+1.
EVENTS = ("submitted", "admitted", "prefill_dispatched", "first_token",
          "finished")
# Human phase names for the exported span, keyed by the gap's start event.
_PHASE_OF = {
    "submitted": "queued",            # submit -> dequeued for a slot
    "admitted": "prefill_dispatch",   # dequeue -> prefill program dispatched
    "prefill_dispatched": "prefill_wait",  # dispatch -> first token emitted
    "first_token": "decode",          # first token -> terminal event
}

OUTCOMES = ("ok", "shed", "timeout", "cancelled", "error")


@dataclasses.dataclass
class Span:
    """One request's lifecycle record (mutated only by the engine driver
    thread until finish; read-only afterwards)."""

    request_id: int
    prompt_tokens: int
    started_wall: float = dataclasses.field(default_factory=time.time)
    events: list[tuple[str, float]] = dataclasses.field(default_factory=list)
    outcome: str | None = None
    error: str | None = None
    tokens: int = 0
    decode_chunks: int = 0

    def __post_init__(self):
        self.events.append(("submitted", time.monotonic()))

    def event(self, name: str) -> None:
        self.events.append((name, time.monotonic()))

    @property
    def finished(self) -> bool:
        return self.outcome is not None

    def to_dict(self) -> dict:
        first = self.events[0][1]
        last = self.events[-1][1]
        phases: dict[str, float] = {}
        for (name, t0), (_n, t1) in zip(self.events, self.events[1:]):
            phase = _PHASE_OF.get(name, name)
            phases[phase] = phases.get(phase, 0.0) + (t1 - t0)
        return {
            "requestId": self.request_id,
            "startedAt": self.started_wall,
            "outcome": self.outcome,
            **({"error": self.error} if self.error else {}),
            "promptTokens": self.prompt_tokens,
            "tokens": self.tokens,
            "decodeChunks": self.decode_chunks,
            "events": [{"event": n, "atS": round(t - first, 6)}
                       for n, t in self.events],
            "phasesS": {k: round(v, 6) for k, v in phases.items()},
            "e2eS": round(last - first, 6),
        }


class Tracer:
    """Span factory + bounded completed-span buffer (thread-safe)."""

    def __init__(self, capacity: int = 512):
        self._lock = sanitize.lock("Tracer._lock")
        self._done: deque[Span] = deque(maxlen=max(1, capacity))

    def begin(self, request_id: int, prompt_tokens: int) -> Span:
        return Span(request_id=request_id, prompt_tokens=prompt_tokens)

    def finish(self, span: Span, outcome: str, *, tokens: int | None = None,
               error: str | None = None) -> Span:
        """Terminal transition: stamps the ``finished`` event, records the
        outcome, and moves the span into the ring. Idempotent — a request
        failed twice (sweep + fail_all racing) keeps its FIRST verdict."""
        if span.finished:
            return span
        span.event("finished")
        span.outcome = outcome
        if tokens is not None:
            span.tokens = tokens
        if error is not None:
            span.error = error
        with self._lock:
            self._done.append(span)
        return span

    def recent(self, n: int = 50) -> list[dict]:
        """Newest-first completed spans, at most ``n``."""
        with self._lock:
            spans = list(self._done)
        return [s.to_dict() for s in reversed(spans[-max(0, n):])]

    def for_request(self, request_id: int) -> list[dict]:
        """Exact-match lookup by request id (``GET /v1/trace?request_id=``):
        a slow request found in the logs can be pulled directly instead of
        paging the tail and eyeballing. Newest-first; normally one span,
        but shed spans share id -1."""
        with self._lock:
            spans = [s for s in self._done if s.request_id == request_id]
        return [s.to_dict() for s in reversed(spans)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._done)
