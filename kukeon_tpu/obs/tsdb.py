"""In-daemon time-series store: bounded history for the federated scrape.

PR 4's federation made one scrape see the whole fleet — and forget it the
moment `kuke top` rendered. This module is the memory: the daemon's
telemetry loop ingests every cell's parsed /metrics exposition into
per-series rings so windowed questions ("TTFT p95 over the last 5
minutes", "is this replica crash-looping") have an answer without any
external Prometheus. The alert engine (obs/alerts.py) and the autoscaler's
future reconcile loop read the same store.

Design constraints:

- **Zero dependencies, bounded memory.** A series is a deque of
  ``(unix_ts, value)`` pairs trimmed to ``KUKEON_TSDB_RETENTION_S``
  (default 1h) on every append; series that stop updating are GC'd after
  one retention window; the series *count* is hard-capped
  (``KUKEON_TSDB_MAX_SERIES``) — past the cap new series are dropped and
  counted, never silently absorbed into unbounded growth.
- **Thread-safe, never blocking under the lock.** Ingest builds its rows
  from the parsed families entirely outside the store lock and only
  appends under it; queries snapshot the matching rings under the lock
  and do all math outside. The whole suite runs clean under
  ``KUKEON_SANITIZE=1``.
- **Counter-reset aware.** A cell restart mid-window drops its cumulative
  counters to ~0; a reset-oblivious delta would go negative and a rate
  would dip below zero. Monotonic series (counters and histogram
  ``_bucket``/``_sum``/``_count`` children) accumulate increase as
  ``v1 - v0`` when monotone and ``v1`` after a drop (the post-reset value
  IS the increase since the reset).
- **Histogram aware.** ``p50/p95/p99`` aggregations reconstruct windowed
  per-bucket deltas from the cumulative ``_bucket`` series (per-``le``
  reset detection, negatives clamped) and feed the exact estimator the
  live registry uses (:func:`obs.percentile_from_counts`) — same
  log-spaced ladder, same interpolation, so a windowed p95 and the cell's
  own since-boot p95 agree to within a bucket.

Query language: ``family{label=value,label2="value 2"}`` with optional
aggregations ``rate | delta | avg | max | min | latest | p50 | p95 |
p99``, plus a single top-level ``/`` for label-joined ratios
(``kukeon_hbm_bytes_in_use / kukeon_hbm_bytes_limit``). Deliberately not
PromQL — just enough for `kuke query`, the alert rules, and sparklines.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from collections import deque
from typing import Callable, Iterable

from kukeon_tpu import sanitize
from kukeon_tpu.obs.registry import percentile_from_counts

RETENTION_ENV = "KUKEON_TSDB_RETENTION_S"
DEFAULT_RETENTION_S = 3600.0
MAX_SERIES_ENV = "KUKEON_TSDB_MAX_SERIES"
DEFAULT_MAX_SERIES = 8192

#: Supported aggregations, in the order `kuke query --help` lists them.
AGGS = ("rate", "delta", "avg", "max", "min", "latest", "p50", "p95", "p99")

_QUANTILES = {"p50": 0.50, "p95": 0.95, "p99": 0.99}

_SELECTOR_RE = re.compile(
    r"^\s*([a-zA-Z_:][a-zA-Z0-9_:]*)\s*(\{(.*)\})?\s*$")
_LABEL_PAIR_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*(?:"((?:[^"\\]|\\.)*)"|([^,{}"\s]+))\s*(?:,|$)')
_WINDOW_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(ms|s|m|h|d)?\s*$")
_SUFFIX_RE = re.compile(r"_(bucket|sum|count)$")

_WINDOW_MULT = {"ms": 0.001, "s": 1.0, None: 1.0, "m": 60.0, "h": 3600.0,
                "d": 86400.0}

_LabelItems = tuple[tuple[str, str], ...]


def parse_window(text: "str | float | int") -> float:
    """``"30s" | "5m" | "1h" | "250ms" | 300`` -> seconds (float > 0)."""
    if isinstance(text, (int, float)):
        if text <= 0:
            raise ValueError(f"window must be positive, got {text!r}")
        return float(text)
    m = _WINDOW_RE.match(str(text))
    if not m:
        raise ValueError(
            f"bad window {text!r} (want a duration like 30s, 5m, 1h)")
    out = float(m.group(1)) * _WINDOW_MULT[m.group(2)]
    if out <= 0:
        raise ValueError(f"window must be positive, got {text!r}")
    return out


@dataclasses.dataclass(frozen=True)
class Selector:
    """One parsed ``family{label=value,...}`` term."""

    family: str
    matchers: _LabelItems = ()

    def matches(self, labels: dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in self.matchers)


def parse_selector(text: str) -> Selector:
    m = _SELECTOR_RE.match(text)
    if not m:
        raise ValueError(
            f"bad selector {text!r} (want family or family{{label=value}})")
    inner = m.group(3)
    matchers: list[tuple[str, str]] = []
    if inner is not None and inner.strip():
        pos = 0
        while pos < len(inner):
            pm = _LABEL_PAIR_RE.match(inner, pos)
            if pm is None:
                raise ValueError(
                    f"bad label matcher in {text!r} at {inner[pos:]!r} "
                    f'(want label=value or label="value")')
            matchers.append((pm.group(1),
                             pm.group(2) if pm.group(2) is not None
                             else pm.group(3)))
            pos = pm.end()
    return Selector(m.group(1), tuple(sorted(matchers)))


def parse_expr(text: str) -> tuple[Selector, Selector | None]:
    """An expression is one selector, or ``selector / selector`` (the
    label-joined ratio). The split is on a top-level ``/`` only — never
    inside ``{...}``."""
    depth = 0
    split_at = None
    for i, ch in enumerate(text):
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        elif ch == "/" and depth == 0:
            if split_at is not None:
                raise ValueError(
                    f"at most one '/' in a query expression: {text!r}")
            split_at = i
    if split_at is None:
        return parse_selector(text), None
    return (parse_selector(text[:split_at]),
            parse_selector(text[split_at + 1:]))


class _Series:
    __slots__ = ("monotonic", "points", "last_at")

    def __init__(self, monotonic: bool):
        self.monotonic = monotonic
        self.points: deque[tuple[float, float]] = deque()
        self.last_at = 0.0


def _increase(points: list[tuple[float, float]], monotonic: bool,
              start: float, end: float) -> float | None:
    """Reset-aware increase over ``(start, end]``: consecutive-pair sums
    with the last at-or-before-``start`` point as the baseline. ``None``
    when the series has no point inside the range (stale series)."""
    baseline = None
    seq: list[tuple[float, float]] = []
    for t, v in points:
        if t <= start:
            baseline = (t, v)
        elif t <= end:
            seq.append((t, v))
    if not seq:
        return None
    if baseline is not None:
        seq.insert(0, baseline)
    inc = 0.0
    for (_, v0), (_, v1) in zip(seq, seq[1:]):
        if not monotonic:
            inc += v1 - v0
        elif v1 >= v0:
            inc += v1 - v0
        else:
            # Counter reset (cell restart): the post-reset cumulative
            # value is itself the increase since the reset.
            inc += v1
    return inc


def _agg_window(points: list[tuple[float, float]], monotonic: bool,
                agg: str, start: float, end: float) -> float | None:
    if agg in ("rate", "delta"):
        inc = _increase(points, monotonic, start, end)
        if inc is None:
            return None
        return inc / max(end - start, 1e-9) if agg == "rate" else inc
    vals = [v for t, v in points if start < t <= end]
    if not vals:
        return None
    if agg == "avg":
        return sum(vals) / len(vals)
    if agg == "max":
        return max(vals)
    if agg == "min":
        return min(vals)
    if agg == "latest":
        return vals[-1]
    raise ValueError(f"unknown aggregation {agg!r} (want one of {AGGS})")


class TSDB:
    """The bounded in-daemon store: per-series rings keyed by
    (sample name, sorted labels), fed by the telemetry loop, read by
    `kuke query`, the alert engine, and `kuke top --watch` sparklines."""

    def __init__(self, retention_s: float | None = None,
                 max_series: int | None = None,
                 clock: Callable[[], float] = time.time):
        if retention_s is None:
            retention_s = float(
                os.environ.get(RETENTION_ENV, "") or DEFAULT_RETENTION_S)
        if max_series is None:
            max_series = int(
                os.environ.get(MAX_SERIES_ENV, "") or DEFAULT_MAX_SERIES)
        if retention_s <= 0:
            raise ValueError("retention must be positive")
        self.retention_s = float(retention_s)
        self.max_series = int(max_series)
        self._clock = clock
        self._lock = sanitize.lock("TSDB._lock")
        self._series: dict[tuple[str, _LabelItems], _Series] = {}
        # (family, labels-without-le) -> (trace_id, value, at): the last
        # exemplar seen per histogram labelset, so an alert transition can
        # name a reconstructable trace for its cell.
        self._exemplars: dict[tuple[str, _LabelItems],
                              tuple[str, float, float]] = {}
        self._dropped = 0
        self._ingested = 0

    # --- ingest ---------------------------------------------------------------

    def ingest(self, families: dict, at: float | None = None) -> None:
        """Append one scrape's parsed families (``federate.parse`` output,
        already relabelled with ``cell=``). Rows are built entirely outside
        the store lock; the lock covers only the appends and the eviction
        sweep."""
        if at is None:
            at = self._clock()
        rows: list[tuple[str, _LabelItems, bool, float]] = []
        exemplars: list[tuple[str, _LabelItems, str, float]] = []
        for fam in families.values():
            kind = getattr(fam, "kind", "untyped")
            for name, labels, value in fam.samples:
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                monotonic = kind == "counter" or (
                    kind == "histogram" and bool(_SUFFIX_RE.search(name)))
                rows.append(
                    (name, tuple(sorted(labels.items())), monotonic, v))
            for name, labels, trace_id, value in getattr(
                    fam, "exemplars", ()):
                if not trace_id:
                    continue
                base = _SUFFIX_RE.sub("", name)
                lab = {k: v for k, v in labels.items() if k != "le"}
                try:
                    exemplars.append((base, tuple(sorted(lab.items())),
                                      trace_id, float(value)))
                except (TypeError, ValueError):
                    continue
        horizon = at - self.retention_s
        with self._lock:
            for name, key, monotonic, v in rows:
                s = self._series.get((name, key))
                if s is None:
                    if len(self._series) >= self.max_series:
                        self._dropped += 1
                        continue
                    s = self._series[(name, key)] = _Series(monotonic)
                s.points.append((at, v))
                s.last_at = max(s.last_at, at)
                while s.points and s.points[0][0] < horizon:
                    s.points.popleft()
            for base, key, trace_id, v in exemplars:
                self._exemplars[(base, key)] = (trace_id, v, at)
            # GC: series (and exemplars) nothing has updated for a full
            # retention window — a deleted cell must not pin memory.
            for k in [k for k, s in self._series.items()
                      if s.last_at < horizon]:
                del self._series[k]
            for k in [k for k, (_t, _v, ex_at) in self._exemplars.items()
                      if ex_at < horizon]:
                del self._exemplars[k]
            self._ingested += 1

    # --- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "points": sum(len(s.points)
                              for s in self._series.values()),
                "droppedSeries": self._dropped,
                "ingests": self._ingested,
            }

    def latest_exemplar(self, family: str,
                        **match: str) -> tuple[str, float, float] | None:
        """Most recent (trace_id, value, at) exemplar for a histogram
        family whose labels include ``match``."""
        want = {k: str(v) for k, v in match.items()}
        best: tuple[str, float, float] | None = None
        with self._lock:
            for (fam, key), rec in self._exemplars.items():
                if fam != family:
                    continue
                labels = dict(key)
                if any(labels.get(k) != v for k, v in want.items()):
                    continue
                if best is None or rec[2] > best[2]:
                    best = rec
        return best

    # --- queries --------------------------------------------------------------

    def _snapshot(self, name: str, matchers: _LabelItems
                  ) -> list[tuple[_LabelItems, bool, list[tuple[float, float]]]]:
        want = dict(matchers)
        out = []
        with self._lock:
            for (n, key), s in self._series.items():
                if n != name:
                    continue
                labels = dict(key)
                if any(labels.get(k) != v for k, v in want.items()):
                    continue
                out.append((key, s.monotonic, list(s.points)))
        return out

    def _eval_quantile(self, sel: Selector, q: float, start: float,
                       end: float) -> list[tuple[dict[str, str], float]]:
        groups: dict[_LabelItems, dict[str, float]] = {}
        for key, _mono, pts in self._snapshot(sel.family + "_bucket",
                                              sel.matchers):
            labels = dict(key)
            le = labels.pop("le", None)
            if le is None:
                continue
            inc = _increase(pts, True, start, end)
            if inc is None:
                continue
            groups.setdefault(tuple(sorted(labels.items())), {})[le] = inc
        out: list[tuple[dict[str, str], float]] = []
        for key, les in groups.items():
            finite = sorted((float(le), inc) for le, inc in les.items()
                            if le != "+Inf")
            if not finite:
                continue
            bounds = tuple(le for le, _ in finite)
            counts: list[int] = []
            prev = 0.0
            for _le, cum in finite:
                # Clamp: per-le reset adjustment can leave a cumulative
                # sequence locally non-monotone; a negative bucket count
                # would poison the estimator.
                counts.append(max(0, int(round(cum - prev))))
                prev = max(prev, cum)
            counts.append(max(0, int(round(les.get("+Inf", prev) - prev))))
            v = percentile_from_counts(bounds, counts, q)
            if v is not None:
                out.append((dict(key), v))
        return out

    def _eval(self, sel: Selector, agg: str, start: float,
              end: float) -> list[tuple[dict[str, str], float]]:
        if agg in _QUANTILES:
            return self._eval_quantile(sel, _QUANTILES[agg], start, end)
        if agg not in AGGS:
            raise ValueError(f"unknown aggregation {agg!r} "
                             f"(want one of {', '.join(AGGS)})")
        out: list[tuple[dict[str, str], float]] = []
        for key, monotonic, pts in self._snapshot(sel.family, sel.matchers):
            v = _agg_window(pts, monotonic, agg, start, end)
            if v is not None:
                out.append((dict(key), v))
        return out

    @staticmethod
    def _join_div(left: list[tuple[dict[str, str], float]],
                  right: list[tuple[dict[str, str], float]]
                  ) -> list[tuple[dict[str, str], float]]:
        """Label-joined division: each left series pairs with the unique
        right series agreeing on every shared label key; ambiguous or
        zero-denominator pairs are dropped (an alert must never fire off
        a nonsense join)."""
        out = []
        for llab, lv in left:
            cands = []
            for rlab, rv in right:
                shared = set(llab) & set(rlab)
                if all(llab[k] == rlab[k] for k in shared):
                    cands.append(rv)
            if len(cands) == 1 and cands[0] != 0:
                out.append((llab, lv / cands[0]))
        return out

    def query(self, expr: str, window_s: float, agg: str,
              at: float | None = None
              ) -> list[tuple[dict[str, str], float]]:
        """One aggregated value per matching series over the trailing
        window. Ratio expressions aggregate both sides with the same
        ``agg`` and join on shared labels."""
        if at is None:
            at = self._clock()
        window_s = parse_window(window_s)
        left, right = parse_expr(expr)
        lres = self._eval(left, agg, at - window_s, at)
        if right is None:
            return lres
        return self._join_div(lres, self._eval(right, agg, at - window_s, at))

    def query_range(self, expr: str, window_s: float, step_s: float,
                    agg: str, at: float | None = None
                    ) -> list[tuple[dict[str, str], list[float | None]]]:
        """Per-series value lists over ``window_s`` split into ``step_s``
        buckets (the sparkline shape). Buckets with no samples are
        ``None`` so a gap renders as a gap, not a zero."""
        if at is None:
            at = self._clock()
        window_s = parse_window(window_s)
        step_s = parse_window(step_s)
        n = max(1, int(round(window_s / step_s)))
        left, right = parse_expr(expr)

        def eval_steps(sel: Selector) -> dict[_LabelItems,
                                              list[float | None]]:
            out: dict[_LabelItems, list[float | None]] = {}
            for i in range(n):
                start = at - window_s + i * step_s
                for labels, v in self._eval(sel, agg, start,
                                            start + step_s):
                    key = tuple(sorted(labels.items()))
                    out.setdefault(key, [None] * n)[i] = v
            return out

        lres = eval_steps(left)
        if right is None:
            return [(dict(k), vals) for k, vals in sorted(lres.items())]
        rres = eval_steps(right)
        out: list[tuple[dict[str, str], list[float | None]]] = []
        for k, lvals in sorted(lres.items()):
            llab = dict(k)
            cands = []
            for rk, rvals in rres.items():
                rlab = dict(rk)
                shared = set(llab) & set(rlab)
                if all(llab[x] == rlab[x] for x in shared):
                    cands.append(rvals)
            if len(cands) != 1:
                continue
            out.append((llab, [
                (lv / rv) if (lv is not None and rv not in (None, 0))
                else None
                for lv, rv in zip(lvals, cands[0])]))
        return out


def sparkline(values: Iterable[float | None], width: int | None = None
              ) -> str:
    """Unicode block sparkline; ``None`` gaps render as spaces. Scaled to
    the series' own max (sparklines show shape, not magnitude — the table
    column next to it shows the number)."""
    blocks = "▁▂▃▄▅▆▇█"
    vals = list(values)
    if width is not None:
        vals = vals[-width:]
    present = [v for v in vals if v is not None]
    if not present:
        return " " * len(vals)
    top = max(present)
    lo = min(present)
    span = top - lo
    out = []
    for v in vals:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(blocks[0] if top <= 0 else blocks[3])
        else:
            out.append(blocks[min(len(blocks) - 1,
                                  int((v - lo) / span * (len(blocks) - 1)
                                      + 0.5))])
    return "".join(out)
