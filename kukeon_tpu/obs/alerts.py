"""Declarative alerting over the in-daemon TSDB (obs/tsdb.py).

A rule is a windowed query plus a threshold and a ``for:`` duration; the
daemon's telemetry loop evaluates every rule each scrape tick and runs a
pending -> firing -> resolved state machine **per result labelset** (an
alert on ``kukeon_slo_burn_rate`` fires per cell, not once for the fleet).

Semantics, pinned by tests:

- A breach first moves the labelset to **pending**; it becomes **firing**
  only once the breach has held for ``for_s`` (``for_s=0`` fires on the
  first breaching tick). Pending never fires early, and a breach that
  clears while pending cancels silently — that near-miss is visible in
  `kuke alerts` state but produces no transition noise.
- A firing labelset whose condition clears (or whose series ages out of
  the window entirely — a deleted cell resolves its own alerts) emits a
  **resolved** transition.
- Transitions are structured events: JSON-logged (``alert``, ``severity``,
  ``cell``, and the cell's latest TTFT exemplar ``trace_id`` when the rule
  declares an exemplar family — an SLO page links straight to a
  reconstructable `kuke trace`), appended to a bounded ring for
  `kuke alerts`, optionally POSTed to ``KUKEON_ALERT_WEBHOOK``, and the
  firing census is exported as ``kukeon_alerts_firing{alert,severity}``
  (every known rule declared at 0 so "nothing firing" is an observable 0,
  not an absent family).

Built-in rules cover the failure modes the runtime already measures:
SLO burn (fast + slow window), container restart loops, HBM pressure,
queue saturation, cell scrape-down, and cold-start regression against the
ROADMAP 90s target. ``KUKEON_ALERT_RULES`` adds operator rules (a JSON/
YAML file path or an inline document), validated field-by-field — a typo'd
rule is a loud error, never a silently dead alert. kukelint's KUKE011
keeps every built-in rule's metric families honest against the declared
registry.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable

from kukeon_tpu import faults, sanitize
from kukeon_tpu.obs import tsdb as tsdb_mod

RULES_ENV = "KUKEON_ALERT_RULES"
WEBHOOK_ENV = "KUKEON_ALERT_WEBHOOK"
WEBHOOK_TIMEOUT_S = 2.0
# One bounded retry after a failed delivery POST: a page lost to a single
# dropped connection is the worst kind of silent failure, but an alert
# webhook is not a durable queue either — one backoff'd re-send, then the
# error is counted and logged.
WEBHOOK_RETRY_BACKOFF_S = 0.5

SEVERITIES = ("info", "warning", "critical")
OPS = (">", "<")

log = logging.getLogger("kukeon.alerts")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One declarative alert: fire when ``agg(expr)`` over ``window_s``
    compares ``op`` against ``threshold`` for at least ``for_s``."""

    name: str
    expr: str
    agg: str
    window_s: float
    op: str
    threshold: float
    for_s: float = 0.0
    severity: str = "warning"
    description: str = ""
    # Histogram family whose latest exemplar (per cell) decorates this
    # rule's transitions with a reconstructable trace id.
    exemplar_family: str | None = None


# The failure modes the runtime already measures, alerted on by default.
# KUKE011 (kukelint) checks every family referenced here against the
# package's declared metric registry, so a renamed metric cannot leave a
# silently dead rule behind.
BUILTIN_RULES: tuple[Rule, ...] = (
    Rule(name="SloBurnFast",
         expr="kukeon_slo_burn_rate{window=5m}",
         agg="max", window_s=60.0, op=">", threshold=10.0, for_s=0.0,
         severity="critical",
         description="short-window SLO burn: the error budget is burning "
                     ">=10x faster than allowed (deadline storm, crash "
                     "loop, or latency collapse)",
         exemplar_family="kukeon_engine_ttft_seconds"),
    Rule(name="SloBurnSlow",
         expr="kukeon_slo_burn_rate{window=1h}",
         agg="avg", window_s=300.0, op=">", threshold=1.0, for_s=120.0,
         severity="warning",
         description="sustained SLO burn: the long-window budget is "
                     "burning faster than allowed",
         exemplar_family="kukeon_engine_ttft_seconds"),
    Rule(name="ContainerRestartLoop",
         expr="kukeon_runner_container_restarts_total",
         agg="delta", window_s=600.0, op=">", threshold=3.0, for_s=0.0,
         severity="critical",
         description="a container restarted >3 times in 10m — crash loop "
                     "(exit 86 = watchdog-declared wedge)"),
    Rule(name="HbmPressure",
         expr="kukeon_hbm_bytes_in_use / kukeon_hbm_bytes_limit",
         agg="max", window_s=120.0, op=">", threshold=0.92, for_s=60.0,
         severity="warning",
         description="device HBM above 92% of capacity — next admission "
                     "may OOM or force preemptions"),
    Rule(name="QueueSaturation",
         expr="kukeon_engine_queue_depth / kukeon_engine_max_pending",
         agg="avg", window_s=120.0, op=">", threshold=0.9, for_s=60.0,
         severity="warning",
         description="admission queue above 90% of max_pending — sheds "
                     "are imminent"),
    Rule(name="CellScrapeDown",
         expr="kukeon_cell_scrape_ok",
         agg="max", window_s=60.0, op="<", threshold=0.5, for_s=30.0,
         severity="critical",
         description="the federated scrape has not reached this cell for "
                     "a full window — down, not merely flapping"),
    Rule(name="ColdStartRegression",
         expr="kukeon_cold_start_seconds",
         agg="max", window_s=3600.0, op=">", threshold=90.0, for_s=0.0,
         severity="warning",
         description="a cell boot exceeded the 90s cold-start target "
                     "(rolling restarts and autoscaling assume it)"),
)

_RULE_FIELDS = {f.name for f in dataclasses.fields(Rule)}
# Spelling used in user-facing JSON/YAML documents.
_USER_KEYS = {"for": "for_s", "window": "window_s"}


def validate_rule(obj: object) -> Rule:
    """One user rule document -> Rule, with every problem named."""
    if not isinstance(obj, dict):
        raise ValueError(f"alert rule must be a mapping, got {type(obj).__name__}")
    raw = {}
    for k, v in obj.items():
        key = _USER_KEYS.get(k, k)
        if key not in _RULE_FIELDS:
            raise ValueError(f"alert rule has unknown field {k!r}")
        raw[key] = v
    for req in ("name", "expr", "agg", "window_s", "op", "threshold"):
        if req not in raw:
            raise ValueError(
                f"alert rule {raw.get('name', '?')!r} is missing "
                f"required field {req!r}")
    if not isinstance(raw["name"], str) or not raw["name"]:
        raise ValueError("alert rule name must be a non-empty string")
    name = raw["name"]
    if raw["agg"] not in tsdb_mod.AGGS:
        raise ValueError(
            f"alert rule {name!r}: agg {raw['agg']!r} not in "
            f"{', '.join(tsdb_mod.AGGS)}")
    if raw["op"] not in OPS:
        raise ValueError(f"alert rule {name!r}: op must be one of {OPS}")
    if raw.get("severity", "warning") not in SEVERITIES:
        raise ValueError(
            f"alert rule {name!r}: severity must be one of {SEVERITIES}")
    try:
        raw["window_s"] = tsdb_mod.parse_window(raw["window_s"])
    except ValueError as e:
        raise ValueError(f"alert rule {name!r}: {e}") from None
    if "for_s" in raw:
        try:
            raw["for_s"] = (0.0 if raw["for_s"] in (0, "0")
                            else tsdb_mod.parse_window(raw["for_s"]))
        except ValueError as e:
            raise ValueError(f"alert rule {name!r}: {e}") from None
    try:
        raw["threshold"] = float(raw["threshold"])
    except (TypeError, ValueError):
        raise ValueError(
            f"alert rule {name!r}: threshold must be a number") from None
    try:
        tsdb_mod.parse_expr(raw["expr"])
    except ValueError as e:
        raise ValueError(f"alert rule {name!r}: {e}") from None
    return Rule(**raw)


def load_user_rules(spec: str | None = None) -> tuple[Rule, ...]:
    """``KUKEON_ALERT_RULES`` (or an explicit spec) -> validated rules.

    The spec is an inline JSON/YAML document when it starts with ``[`` or
    ``{``, else a path to a file holding one. The document is a list of
    rule mappings (a single mapping is accepted as a list of one)."""
    if spec is None:
        spec = os.environ.get(RULES_ENV, "")
    spec = spec.strip()
    if not spec:
        return ()
    if spec.startswith("[") or spec.startswith("{"):
        text, origin = spec, "inline " + RULES_ENV
    else:
        try:
            with open(spec, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            raise ValueError(f"cannot read {RULES_ENV} file {spec!r}: {e}"
                             ) from None
        origin = spec
    try:
        doc = json.loads(text)
    except ValueError:
        try:
            import yaml
        except ImportError:
            raise ValueError(
                f"{origin} is not valid JSON and no yaml module is "
                f"available") from None
        try:
            doc = yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise ValueError(f"{origin} is not valid JSON or YAML: {e}"
                             ) from None
    if isinstance(doc, dict):
        doc = [doc]
    if not isinstance(doc, list):
        raise ValueError(
            f"{origin} must hold a list of alert rules, got "
            f"{type(doc).__name__}")
    rules = tuple(validate_rule(obj) for obj in doc)
    seen: set[str] = set()
    for r in rules:
        if r.name in seen or any(r.name == b.name for b in BUILTIN_RULES):
            raise ValueError(f"duplicate alert rule name {r.name!r}")
        seen.add(r.name)
    return rules


class _Active:
    __slots__ = ("state", "since", "firing_since", "value", "labels")

    def __init__(self, since: float, labels: dict[str, str], value: float):
        self.state = "pending"
        self.since = since
        self.firing_since: float | None = None
        self.value = value
        self.labels = labels


class AlertEngine:
    """Evaluates rules against the TSDB each telemetry tick and keeps the
    per-labelset state machines, the transition ring, and the firing
    gauge. Thread-safe: evaluation runs on the daemon's telemetry thread
    while `kuke alerts` reads state from RPC handler threads."""

    def __init__(self, tsdb: tsdb_mod.TSDB,
                 rules: tuple[Rule, ...] = BUILTIN_RULES,
                 registry=None,
                 clock: Callable[[], float] = time.time,
                 webhook_url: str | None = None,
                 max_transitions: int = 256):
        self._tsdb = tsdb
        self._rules = tuple(rules)
        self._clock = clock
        self._webhook_url = (webhook_url if webhook_url is not None
                             else os.environ.get(WEBHOOK_ENV) or None)
        self._lock = sanitize.lock("AlertEngine._lock")
        self._active: dict[tuple[str, tuple[tuple[str, str], ...]],
                           _Active] = {}
        self._transitions: deque[dict] = deque(maxlen=max_transitions)
        self._m_firing = None
        self._m_webhook = None
        if registry is not None:
            self._m_firing = registry.gauge(
                "kukeon_alerts_firing",
                "Labelsets currently firing per alert rule (0 = healthy).",
                labels=("alert", "severity"))
            for r in self._rules:
                self._m_firing.set(0, alert=r.name, severity=r.severity)
            self._m_webhook = registry.counter(
                "kukeon_alerts_webhook_total",
                "Alert-transition webhook POSTs by result.",
                labels=("result",))

    @property
    def rules(self) -> tuple[Rule, ...]:
        return self._rules

    # --- evaluation -----------------------------------------------------------

    def evaluate(self, at: float | None = None) -> list[dict]:
        """One tick: query every rule, advance the state machines, emit
        transitions. Queries run before the engine lock is taken (the
        TSDB has its own lock; holding both across the query would nest
        them for no reason), and side effects (log/webhook/gauge) run
        after it is released."""
        now = self._clock() if at is None else at
        results: list[tuple[Rule, list[tuple[dict[str, str], float]]]] = []
        for rule in self._rules:
            try:
                results.append((rule, self._tsdb.query(
                    rule.expr, rule.window_s, rule.agg, at=now)))
            except ValueError as e:  # a bad rule must not kill the loop
                log.warning("alert rule %s query failed: %s", rule.name, e)
                results.append((rule, []))
        transitions: list[dict] = []
        with self._lock:
            for rule, series in results:
                breached = {
                    tuple(sorted(labels.items())): (labels, value)
                    for labels, value in series
                    if (value > rule.threshold if rule.op == ">"
                        else value < rule.threshold)
                }
                for key, (labels, value) in breached.items():
                    st = self._active.get((rule.name, key))
                    if st is None:
                        st = self._active[(rule.name, key)] = _Active(
                            now, labels, value)
                    st.value = value
                    st.labels = labels
                    if (st.state == "pending"
                            and now - st.since >= rule.for_s):
                        st.state = "firing"
                        st.firing_since = now
                        transitions.append(self._transition(
                            rule, "firing", now, labels, value))
                for (rname, key) in [
                        k for k in self._active if k[0] == rule.name]:
                    if key in breached:
                        continue
                    st = self._active.pop((rname, key))
                    if st.state == "firing":
                        transitions.append(self._transition(
                            rule, "resolved", now, st.labels, st.value))
                    # A pending labelset that clears cancels silently.
            for tr in transitions:
                self._transitions.append(tr)
            firing_counts: dict[tuple[str, str], int] = {}
            for (rname, _key), st in self._active.items():
                if st.state != "firing":
                    continue
                rule = next(r for r in self._rules if r.name == rname)
                firing_counts[(rname, rule.severity)] = firing_counts.get(
                    (rname, rule.severity), 0) + 1
        if self._m_firing is not None:
            for r in self._rules:
                self._m_firing.set(
                    firing_counts.get((r.name, r.severity), 0),
                    alert=r.name, severity=r.severity)
        for tr in transitions:
            self._emit(tr)
        return transitions

    def _transition(self, rule: Rule, state: str, at: float,
                    labels: dict[str, str], value: float) -> dict:
        tr = {
            "alert": rule.name,
            "severity": rule.severity,
            "state": state,
            "at": at,
            "labels": dict(labels),
            "value": value,
            "expr": rule.expr,
            "threshold": rule.threshold,
            "description": rule.description,
        }
        cell = labels.get("cell")
        if cell:
            tr["cell"] = cell
            if rule.exemplar_family:
                ex = self._tsdb.latest_exemplar(rule.exemplar_family,
                                                cell=cell)
                if ex is not None:
                    tr["trace_id"] = ex[0]
        return tr

    def _emit(self, tr: dict) -> None:
        level = (logging.WARNING if tr["state"] == "firing"
                 else logging.INFO)
        log.log(level, "alert %s %s (value %.4g %s %.4g)%s",
                tr["alert"], tr["state"], tr["value"],
                "breaching" if tr["state"] == "firing" else "vs",
                tr["threshold"],
                f" cell={tr['cell']}" if tr.get("cell") else "",
                extra={"alert": tr["alert"], "severity": tr["severity"],
                       "cell": tr.get("cell"),
                       "trace_id": tr.get("trace_id"),
                       "outcome": tr["state"]})
        if self._webhook_url:
            threading.Thread(target=self._post_webhook, args=(tr,),
                             daemon=True, name="alert-webhook").start()

    def _post_webhook(self, tr: dict) -> None:
        import urllib.request
        for attempt in (0, 1):
            try:
                # The chaos seam: `alerts.webhook` armed fails the POST
                # before the socket, so the retry/backoff path is testable
                # without a flaky endpoint.
                faults.maybe_fail("alerts.webhook")
                req = urllib.request.Request(
                    self._webhook_url, data=json.dumps(tr).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=WEBHOOK_TIMEOUT_S):
                    pass
                if self._m_webhook is not None:
                    self._m_webhook.inc(
                        result="retried" if attempt else "ok")
                return
            except Exception as e:  # noqa: BLE001 — a dead webhook must not matter
                if attempt:
                    log.warning("alert webhook POST failed after retry: %s",
                                e)
                    if self._m_webhook is not None:
                        self._m_webhook.inc(result="error")
                    return
                log.warning("alert webhook POST failed (%s); retrying in "
                            "%.1fs", e, WEBHOOK_RETRY_BACKOFF_S)
                time.sleep(WEBHOOK_RETRY_BACKOFF_S)

    # --- views ----------------------------------------------------------------

    def states(self) -> list[dict]:
        """One row per rule (state ``ok`` when nothing is active) plus one
        per active labelset — the `kuke alerts` table."""
        with self._lock:
            active = [
                {"alert": rname, "labels": dict(st.labels),
                 "state": st.state, "since": st.since,
                 "firingSince": st.firing_since, "value": st.value}
                for (rname, _key), st in sorted(
                    self._active.items(), key=lambda kv: kv[0])
            ]
        by_rule: dict[str, list[dict]] = {}
        for row in active:
            by_rule.setdefault(row["alert"], []).append(row)
        out: list[dict] = []
        for rule in self._rules:
            rows = by_rule.get(rule.name)
            if not rows:
                out.append({"alert": rule.name, "severity": rule.severity,
                            "state": "ok", "expr": rule.expr,
                            "threshold": rule.threshold,
                            "description": rule.description})
                continue
            for row in rows:
                out.append({**row, "severity": rule.severity,
                            "expr": rule.expr,
                            "threshold": rule.threshold,
                            "description": rule.description})
        return out

    def transitions(self, n: int = 50) -> list[dict]:
        with self._lock:
            return list(self._transitions)[-int(n):]
