"""Prometheus text exposition (format 0.0.4) over an obs Registry.

Hand-rolled because the container bakes no prometheus_client; the golden
test in tests/test_obs.py parses this output with its own strict parser,
so the format here is pinned by test, not by hope. Histograms emit the
conventional cumulative ``_bucket{le=...}`` series (always ending in
``le="+Inf"``) plus ``_sum``/``_count``.
"""

from __future__ import annotations

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_str(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(b: float) -> str:
    return ("%.10g" % b)


def render(registry) -> str:
    """The full exposition for one registry: declared metrics first
    (sorted by name), then every registered collector's families. The
    scrape-error counter renders LAST so a callable that fails during THIS
    scrape is already visible in it (ordering by name would render the
    counter before most gauges evaluate)."""
    out: list[str] = []
    err_counter = getattr(registry, "scrape_errors", None)
    deferred = None
    for m in registry.metrics():
        if m is err_counter:
            deferred = m
            continue
        out.append(f"# HELP {m.name} {m.help}".rstrip())
        out.append(f"# TYPE {m.name} {m.kind}")
        if m.kind == "histogram":
            _render_histogram(out, m)
            continue
        for labels, value in sorted(
            m.samples(), key=lambda s: sorted(s[0].items())
        ):
            out.append(f"{m.name}{_labels_str(labels)} {_fmt(value)}")
    for fn in registry.collectors():
        # One raising collector skips only its own families: the rest of
        # the exposition still renders and the failure is counted on
        # kukeon_scrape_errors_total (same scrape-robustness contract the
        # Gauge callables follow).
        lines: list[str] = []
        try:
            for name, kind, help, samples in fn():
                lines.append(f"# HELP {name} {help}".rstrip())
                lines.append(f"# TYPE {name} {kind}")
                for labels, value in samples:
                    lines.append(f"{name}{_labels_str(labels)} {_fmt(value)}")
        except Exception:  # noqa: BLE001 — a dead collector must not kill the scrape
            err = getattr(registry, "scrape_errors", None)
            if err is not None:
                err.inc(metric=getattr(fn, "__qualname__", "collector"))
            continue
        out.extend(lines)
    if deferred is not None:
        out.append(f"# HELP {deferred.name} {deferred.help}".rstrip())
        out.append(f"# TYPE {deferred.name} {deferred.kind}")
        for labels, value in sorted(
            deferred.samples(), key=lambda s: sorted(s[0].items())
        ):
            out.append(f"{deferred.name}{_labels_str(labels)} {_fmt(value)}")
    return "\n".join(out) + "\n"


def _render_histogram(out: list[str], h) -> None:
    with h._lock:
        series = {k: (list(c), s, n) for k, (c, s, n) in h._series.items()}
        exemplars = {k: dict(v) for k, v in h._exemplars.items()}
    if not series:
        # An empty histogram still exposes a zero-count labelless series
        # only when it has no label dimensions (a scraper then sees the
        # family exists); labelled families stay silent until observed.
        if not h.label_names:
            series[()] = ([0] * (len(h.buckets) + 1), 0.0, 0)
    for key in sorted(series):
        counts, total, n = series[key]
        labels = dict(zip(h.label_names, key))
        cum = 0
        for b, c in zip(h.buckets, counts[:-1]):
            cum += c
            le = dict(labels)
            le["le"] = _fmt_le(b)
            out.append(f"{h.name}_bucket{_labels_str(le)} {cum}")
        le = dict(labels)
        le["le"] = "+Inf"
        out.append(f"{h.name}_bucket{_labels_str(le)} {n}")
        out.append(f"{h.name}_sum{_labels_str(labels)} {_fmt(total)}")
        out.append(f"{h.name}_count{_labels_str(labels)} {n}")
        # Exemplar comment lines: one per bucket that has a trace id
        # attached. Comments, so any 0.0.4 scraper ignores them; the
        # in-repo federation parser extracts them (so `kuke top`'s p95
        # row can name a reconstructable trace) and the golden-format
        # test pins the syntax.
        for idx in sorted(exemplars.get(key, {})):
            v, ex = exemplars[key][idx]
            exl = dict(labels)
            exl["le"] = (_fmt_le(h.buckets[idx])
                         if idx < len(h.buckets) else "+Inf")
            out.append(
                f"# EXEMPLAR {h.name}_bucket{_labels_str(exl)} "
                f'trace_id="{ex}" value={_fmt(v)}')


def faults_collector():
    """Scrape-time family for the fault-injection harness: one
    ``kukeon_faults_fired_total{point=...}`` sample per declared fault
    point (zero when never fired), plus any extra point that fired without
    being declared — the conftest guard test turns that situation into a
    failure, but the scrape itself must never hide a fire count."""
    from kukeon_tpu import faults

    points = dict.fromkeys(faults.POINTS, 0)
    points.update(faults.stats)
    yield (
        "kukeon_faults_fired_total", "counter",
        "Fault-injection fires by point (kukeon_tpu.faults).",
        [({"point": p}, float(v)) for p, v in sorted(points.items())],
    )
