"""Thread-safe metrics registry: Counter, Gauge, Histogram.

Design constraints (the serving hot path runs through these):

- **One lock per registry**, taken only for the few dict/float operations
  of an update. The decode loop's per-chunk instrumentation is a handful
  of counter bumps; a contended mutex would still be nanoseconds next to a
  device dispatch, and the hammer test in tests/test_obs.py pins exactness
  (no torn reads, no lost increments).
- **Labels are kwargs**, values stringified, keyed by a tuple in declared
  order. Metric identity is (name); re-asking the registry for an existing
  name returns the same object (and raises on a type/label mismatch — two
  subsystems silently sharing a name with different schemas is a bug).
- **Scrape-time values**: a Gauge can be backed by a callable
  (``set_function``) so live values like queue depth cost nothing between
  scrapes; whole families can be produced at collect time via
  :meth:`Registry.register_collector` (how fault-injection fire counts
  surface without the faults module importing obs).

Latency histograms share one fixed log-spaced bucket ladder
(:data:`LATENCY_BUCKETS_S`, 250µs → ~131s, powers of two) so every
latency metric is cross-comparable and the exposition stays compact.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, TypeVar, cast

from kukeon_tpu import sanitize

# Fixed log-spaced latency ladder: 0.25ms * 2^i, i in [0, 19) -> ~0.25ms,
# 0.5ms, 1ms, ... 65.5s, 131s. Wide enough for TTFT on a tunneled chip and
# tight enough at the bottom for inter-token latency.
LATENCY_BUCKETS_S: tuple[float, ...] = tuple(
    0.00025 * (2 ** i) for i in range(19)
)

_LabelKey = tuple[str, ...]
_M = TypeVar("_M", bound="_Metric")


def _label_key(label_names: tuple[str, ...],
               labels: dict[str, object]) -> _LabelKey:
    if set(labels) != set(label_names):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(label_names)}"
        )
    return tuple(str(labels[k]) for k in label_names)


class _Metric:
    kind: str = "untyped"

    def __init__(self, name: str, help: str, label_names: tuple[str, ...],
                 lock: threading.Lock) -> None:
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = lock
        # Wired by the owning Registry: the scrape-error counter a failing
        # scrape-time callable reports to (None for the counter itself).
        self._scrape_errors: "Counter | None" = None

    def samples(self) -> list[tuple[dict[str, str], float]]:
        """(labels, value) pairs for exposition (flat metrics only)."""
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing float, optionally labelled."""

    kind = "counter"

    def __init__(self, name: str, help: str, label_names: tuple[str, ...],
                 lock: threading.Lock) -> None:
        super().__init__(name, help, label_names, lock)
        self._values: dict[_LabelKey, float] = {}
        if not self.label_names:
            self._values[()] = 0.0

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: object) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self) -> list[tuple[dict[str, str], float]]:
        with self._lock:
            items = list(self._values.items())
        return [(dict(zip(self.label_names, k)), v) for k, v in items]


class Gauge(_Metric):
    """Point-in-time float; settable, incrementable, or callable-backed."""

    kind = "gauge"

    def __init__(self, name: str, help: str, label_names: tuple[str, ...],
                 lock: threading.Lock) -> None:
        super().__init__(name, help, label_names, lock)
        self._values: dict[_LabelKey, float] = {}
        self._fns: dict[_LabelKey, Callable[[], float]] = {}

    def set(self, value: float, **labels: object) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: object) -> None:
        self.inc(-amount, **labels)

    def set_function(self, fn: Callable[[], float],
                     **labels: object) -> None:
        """Back this labelset with a callable evaluated at scrape time —
        live values (queue depth, uptime) cost nothing between scrapes."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._fns[key] = fn

    def value(self, **labels: object) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            fn = self._fns.get(key)
        if fn is not None:
            return float(fn())
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self) -> list[tuple[dict[str, str], float]]:
        with self._lock:
            items = dict(self._values)
            fns = list(self._fns.items())
        for key, fn in fns:
            try:
                items[key] = float(fn())
            except Exception:  # noqa: BLE001 — a dead callback must not kill the scrape
                # Skip the sample but make the failure visible: a silently
                # vanishing gauge looks identical to "never set".
                items.pop(key, None)
                if self._scrape_errors is not None:
                    self._scrape_errors.inc(metric=self.name)
        return [(dict(zip(self.label_names, k)), v)
                for k, v in items.items()]


class Histogram(_Metric):
    """Fixed-bucket histogram with cumulative exposition and quantile
    estimation (linear interpolation inside the landing bucket)."""

    kind = "histogram"

    def __init__(self, name: str, help: str, label_names: tuple[str, ...],
                 lock: threading.Lock,
                 buckets: tuple[float, ...] = LATENCY_BUCKETS_S) -> None:
        super().__init__(name, help, label_names, lock)
        b = tuple(sorted(float(x) for x in buckets))
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError("buckets must be non-empty and increasing")
        self.buckets = b
        # per labelset: ([count per finite bucket] + [overflow], sum, count)
        self._series: dict[_LabelKey, tuple[list[int], float, int]] = {}
        # per labelset: {bucket index: (value, exemplar id)} — the last
        # observation per bucket that carried an exemplar. Exemplars link
        # a histogram bucket to a reconstructable trace: the TTFT p95 row
        # in `kuke top` resolves to a real `kuke trace <id>` timeline.
        self._exemplars: dict[_LabelKey, dict[int, tuple[float, str]]] = {}

    def observe(self, value: float, exemplar: str | None = None,
                **labels: object) -> None:
        key = _label_key(self.label_names, labels)
        v = float(value)
        with self._lock:
            counts, total, n = self._series.get(
                key, ([0] * (len(self.buckets) + 1), 0.0, 0))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    idx = i
                    counts[i] += 1
                    break
            else:
                idx = len(self.buckets)
                counts[-1] += 1
            self._series[key] = (counts, total + v, n + 1)
            if exemplar is not None:
                self._exemplars.setdefault(key, {})[idx] = (v, str(exemplar))

    def exemplars(self, **labels: object) -> dict[int, tuple[float, str]]:
        """{bucket index: (value, exemplar id)} for one labelset; the
        index ``len(buckets)`` is the overflow (+Inf) slot."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            return dict(self._exemplars.get(key, {}))

    def snapshot(self, **labels: object) -> tuple[list[int], float, int]:
        """(per-bucket counts + overflow, sum, count) for one labelset."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            counts, total, n = self._series.get(
                key, ([0] * (len(self.buckets) + 1), 0.0, 0))
            return list(counts), total, n

    def percentile(self, q: float, **labels: object) -> float | None:
        """Estimated q-quantile (q in [0,1]) from the bucket counts; None
        with no observations. Overflow observations clamp to the top
        bucket bound (the honest answer a fixed ladder can give)."""
        counts, _total, _n = self.snapshot(**labels)
        return percentile_from_counts(self.buckets, counts, q)

    def samples(self) -> list[tuple[dict[str, str], float]]:
        # Exposition is histogram-shaped; see expo.render.
        raise TypeError("histograms expose via expo.render, not samples()")


def percentile_from_counts(buckets: tuple[float, ...],
                           counts: "list[int] | tuple[int, ...]",
                           q: float) -> float | None:
    """q-quantile from per-bucket counts (finite buckets + overflow slot).

    Module-level so callers holding a count DELTA (bench.py subtracts a
    pre-measurement snapshot to keep warmup compiles out of the reported
    percentiles) share the exact estimator the live histogram uses.

    Edge contracts (unit-tested): an empty histogram returns the None
    sentinel — never a fabricated 0.0 that would read as "instant" on a
    dashboard; q is clamped into [0, 1]; observations past the top finite
    bucket clamp to that bound instead of extrapolating."""
    n = sum(counts)
    if n == 0:
        return None
    q = min(1.0, max(0.0, float(q)))
    rank = q * n
    seen = 0
    for i, c in enumerate(counts[:-1]):
        if seen + c >= rank and c > 0:
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += c
    return buckets[-1]


class Registry:
    """A named set of metrics plus scrape-time collectors."""

    def __init__(self) -> None:
        # One lock per registry, shared with every metric it creates
        # (kukesan proxy under KUKEON_SANITIZE=1 — metric updates inside
        # other subsystems' critical sections then become lock-graph
        # edges the static pass cannot see).
        self._lock: threading.Lock = sanitize.lock("Registry._lock")
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], Iterable[object]]] = []
        # Scrape-robustness accounting: a gauge callable or collector that
        # raises at scrape time is skipped — and counted here — instead of
        # 500ing the whole exposition (one bad callback must not blind the
        # operator to every other metric).
        self.scrape_errors = self.counter(
            "kukeon_scrape_errors_total",
            "Scrape-time callables (gauge functions, collectors) that "
            "raised; their samples were skipped.", labels=("metric",))

    def _get_or_create(self, cls: "type[_M]", name: str, help: str,
                       label_names: Iterable[str], **kw: object) -> _M:
        label_names = tuple(label_names)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.label_names != label_names:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.label_names}"
                    )
                return cast("_M", m)
            new = cls(name, help, label_names, self._lock, **kw)
            new._scrape_errors = getattr(self, "scrape_errors", None)
            self._metrics[name] = new
            return new

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: tuple[float, ...] = LATENCY_BUCKETS_S) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def register_collector(self, fn: Callable[[], Iterable[object]]) -> None:
        """``fn() -> iterable of (name, kind, help, [(labels, value), ...])``
        evaluated at every scrape — for families whose source of truth
        lives elsewhere (fault fire counts, cgroup stats)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def collectors(self) -> list[Callable[[], Iterable[object]]]:
        with self._lock:
            return list(self._collectors)


_default = Registry()


def get_default() -> Registry:
    """The process-global registry (runner/daemon side: one process, one
    scrape). Serving engines take a per-instance registry instead so tests
    and multi-engine processes never cross-pollute."""
    return _default
