"""Fleet federation: parse, relabel, and merge Prometheus text expositions.

The daemon scrapes every running model cell's ``GET /metrics`` and
re-exposes the union with a ``cell="realm/space/stack/name"`` label on every
sample, so one scrape of the daemon sees the whole host's serving fleet.
This module is the text machinery: a strict line parser for the subset of
the format ``expo.render`` emits (it IS the in-repo format, pinned by the
golden test), label injection, and family-grouped re-rendering (samples of
one family from many cells must land under a single TYPE declaration).

Parsing is strict — a cell emitting garbage is treated as a failed scrape
(``kukeon_cell_scrape_ok 0``) rather than corrupting the merged exposition.
"""

from __future__ import annotations

import dataclasses
import re

from kukeon_tpu.obs import expo

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{.*\})?'
    r' (-?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|\+Inf|-Inf|NaN))$'
)
_LABEL_RE = re.compile(r'([a-zA-Z0-9_]+)="((?:[^"\\]|\\.)*)"')
_SUFFIX_RE = re.compile(r"_(bucket|sum|count)$")
# Histogram exemplar comment lines (expo._render_histogram): carried
# through federation so the daemon's merged exposition (and `kuke top`)
# can link a latency bucket to a reconstructable trace id.
_EXEMPLAR_RE = re.compile(
    r'^# EXEMPLAR ([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{.*\})?'
    r' trace_id="([0-9a-fA-F]*)" value=(\S+)$'
)


@dataclasses.dataclass
class Family:
    name: str
    kind: str = "untyped"
    help: str = ""
    # (sample name incl. _bucket/_sum/_count suffix, labels, value string)
    samples: list[tuple[str, dict[str, str], str]] = dataclasses.field(
        default_factory=list)
    # (sample name, labels incl. le, trace id, value string)
    exemplars: list[tuple[str, dict[str, str], str, str]] = dataclasses.field(
        default_factory=list)


def parse(text: str) -> dict[str, Family]:
    """Exposition text -> ordered {family name: Family}. Raises ValueError
    on any line the in-repo renderer could not have produced."""
    families: dict[str, Family] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            name = parts[2]
            fam = families.setdefault(name, Family(name))
            fam.help = parts[3] if len(parts) > 3 else ""
        elif line.startswith("# TYPE "):
            _h, _t, name, kind = line.split(None, 3)
            if kind not in ("counter", "gauge", "histogram", "untyped"):
                raise ValueError(f"unknown metric type in {line!r}")
            families.setdefault(name, Family(name)).kind = kind
        elif line.startswith("# EXEMPLAR "):
            m = _EXEMPLAR_RE.match(line)
            if not m:
                raise ValueError(f"malformed exemplar line {line!r}")
            sample_name = m.group(1)
            fam = families.get(sample_name) or families.get(
                _SUFFIX_RE.sub("", sample_name))
            if fam is None:
                raise ValueError(
                    f"exemplar before family declaration: {line!r}")
            labels = ({k: v for k, v in _LABEL_RE.findall(m.group(2))}
                      if m.group(2) else {})
            fam.exemplars.append((sample_name, labels, m.group(3),
                                  m.group(4)))
        elif line.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(line)
            if not m:
                raise ValueError(f"malformed sample line {line!r}")
            sample_name = m.group(1)
            fam = families.get(sample_name) or families.get(
                _SUFFIX_RE.sub("", sample_name))
            if fam is None:
                raise ValueError(
                    f"sample before family declaration: {line!r}")
            labels: dict[str, str] = {}
            if m.group(2):
                labels = {k: v for k, v in _LABEL_RE.findall(m.group(2))}
            fam.samples.append((sample_name, labels, m.group(3)))
    return families


def inject_label(families: dict[str, Family], **labels: str) -> None:
    """Add label(s) to every sample in place (the ``cell=`` relabel)."""
    for fam in families.values():
        fam.samples = [
            (name, {**lab, **{k: str(v) for k, v in labels.items()}}, value)
            for name, lab, value in fam.samples
        ]
        fam.exemplars = [
            (name, {**lab, **{k: str(v) for k, v in labels.items()}},
             trace_id, value)
            for name, lab, trace_id, value in fam.exemplars
        ]


def render(families: dict[str, Family]) -> str:
    """Families -> exposition text (one HELP/TYPE per family, samples
    grouped under it; the inverse of :func:`parse`)."""
    out: list[str] = []
    for fam in families.values():
        out.append(f"# HELP {fam.name} {fam.help}".rstrip())
        out.append(f"# TYPE {fam.name} {fam.kind}")
        for name, labels, value in fam.samples:
            out.append(f"{name}{expo._labels_str(labels)} {value}")
        for name, labels, trace_id, value in fam.exemplars:
            out.append(f"# EXEMPLAR {name}{expo._labels_str(labels)} "
                       f'trace_id="{trace_id}" value={value}')
    return "\n".join(out) + "\n"


def merge(parts: list[dict[str, Family]]) -> dict[str, Family]:
    """Union of several parsed expositions, first-seen HELP/TYPE winning,
    samples concatenated in part order."""
    merged: dict[str, Family] = {}
    for families in parts:
        for name, fam in families.items():
            tgt = merged.get(name)
            if tgt is None:
                merged[name] = Family(name, fam.kind, fam.help,
                                      list(fam.samples),
                                      list(fam.exemplars))
            else:
                tgt.samples.extend(fam.samples)
                tgt.exemplars.extend(fam.exemplars)
    return merged


def scrape_age_family(ages: dict[str, float]) -> Family:
    """The ``kukeon_cell_scrape_age_seconds{cell=}`` staleness family:
    seconds since each cell's last GOOD scrape. A failing cell's age
    keeps growing while ``kukeon_cell_scrape_ok`` sits at 0 — the two
    together distinguish "stale but last known good" from "never seen".
    Cells with no good scrape yet contribute no sample."""
    fam = Family(
        "kukeon_cell_scrape_age_seconds", "gauge",
        "Seconds since the last successful scrape of each cell "
        "(grows while a cell is down; kuke top dims rows past 2 "
        "scrape intervals).")
    for cell, age in sorted(ages.items()):
        fam.samples.append(("kukeon_cell_scrape_age_seconds",
                            {"cell": str(cell)}, f"{max(0.0, age):.3f}"))
    return fam


def histogram_counts(fam: Family, **match: str
                     ) -> tuple[tuple[float, ...], list[int]]:
    """(finite bucket bounds, per-bucket counts + overflow slot) recovered
    from a parsed histogram family's cumulative ``_bucket`` samples,
    restricted to samples whose labels include ``match``. The return shape
    feeds ``obs.percentile_from_counts`` directly."""
    rows: list[tuple[float, float]] = []
    inf = 0.0
    for name, labels, value in fam.samples:
        if not name.endswith("_bucket"):
            continue
        if any(labels.get(k) != v for k, v in match.items()):
            continue
        le = labels.get("le", "")
        if le == "+Inf":
            inf = float(value)
        else:
            rows.append((float(le), float(value)))
    rows.sort()
    bounds = tuple(le for le, _ in rows)
    counts: list[int] = []
    prev = 0.0
    for _le, cum in rows:
        counts.append(int(cum - prev))
        prev = cum
    counts.append(int(inf - prev))
    return bounds, counts
