"""Device-level telemetry: HBM memory gauges, compile tracking, and the
on-demand profiler spool.

The request-level layer (registry/trace) answers "how slow"; this module
answers "why": is a bad p95 a recompile (``kukeon_compiles_total`` moving in
steady state), HBM pressure (``kukeon_hbm_bytes_in_use`` near the limit), or
a queue problem (neither)? Everything here imports jax lazily — the obs
package stays importable (and testable) without an accelerator runtime.

Three pieces:

- :func:`device_memory_collector` — a scrape-time collector over
  ``jax.Device.memory_stats()`` producing ``kukeon_hbm_bytes_in_use`` /
  ``_limit`` / ``_peak{device=}``. Backends without memory stats (CPU)
  declare the families with no samples, so dashboards and the golden parser
  see a stable schema everywhere.
- :class:`CompileTracker` — wraps the engine's jitted programs and detects
  tracing-cache growth around each dispatch, so every compile is counted
  (``kukeon_compiles_total{program=}``) and timed
  (``kukeon_compile_seconds{program=}``). The engine's "occupancy changes
  never recompile" docstring promise becomes a measurable invariant: after
  warmup the decode counter must stay flat, and a tier-1 test asserts it.
- :class:`ProfileSpool` — single-flight ``jax.profiler.trace`` captures into
  a bounded keep-last-K spool dir (``KUKEON_PROFILE_DIR``), driving the
  cells' ``POST /v1/profile`` endpoint.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
from collections import deque

from kukeon_tpu import sanitize

# memory_stats() key -> exposed family. Every backend that reports memory
# uses these PJRT names (TPU, GPU); absent keys are simply skipped.
_HBM_FAMILIES = (
    ("bytes_in_use", "kukeon_hbm_bytes_in_use",
     "Device memory currently allocated, per device."),
    ("bytes_limit", "kukeon_hbm_bytes_limit",
     "Device memory capacity visible to the runtime, per device."),
    ("peak_bytes_in_use", "kukeon_hbm_bytes_peak",
     "High-water-mark device memory allocation, per device."),
)


def device_memory_collector():
    """Scrape-time HBM families from ``jax.Device.memory_stats()``.

    One sample per device per family; a device (or backend) without memory
    stats contributes no samples but the families are still declared — the
    scrape schema must not depend on which backend happens to be up.
    """
    import jax

    stats = []
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:  # noqa: BLE001 — a dead device must not kill the scrape
            ms = None
        if ms:
            stats.append((str(d.id), ms))
    for key, name, help in _HBM_FAMILIES:
        yield (name, "gauge", help,
               [({"device": dev}, float(ms[key]))
                for dev, ms in stats if key in ms])


def _cache_size(fn) -> int | None:
    """The jit tracing-cache entry count, or None when the runtime doesn't
    expose it (compile detection then degrades to 'unknown', never wrong)."""
    try:
        return int(fn._cache_size())
    except Exception:  # noqa: BLE001 — private API; absence must not break dispatch
        return None


class _TrackedJit:
    """A jitted callable whose dispatches are watched for cache growth.

    Attribute access (``.lower``, ``.compile``) forwards to the underlying
    jit function so AOT precompilation paths keep working unchanged.

    ``timer`` (an ``obs.profile._ProgramTimer``) additionally marks every
    dispatch for deferred roofline timing: the mark is settled later
    inside the engine's counted ``_fetch`` seam, so timing adds zero
    blocking work here — dispatch stays async.
    """

    def __init__(self, fn, program: str, counter, seconds, timer=None):
        self._fn = fn
        self._program = program
        self._m_compiles = counter
        self._m_seconds = seconds
        self._timer = timer

    def __call__(self, *args, **kwargs):
        before = _cache_size(self._fn)
        t0 = time.monotonic()
        out = self._fn(*args, **kwargs)
        if before is not None:
            after = _cache_size(self._fn)
            if after is not None and after > before:
                self._m_compiles.inc(after - before, program=self._program)
                self._m_seconds.observe(time.monotonic() - t0,
                                        program=self._program)
        if self._timer is not None:
            self._timer.dispatched(t0, out)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


class CompileTracker:
    """Registers the compile families and wraps jitted programs.

    A dispatch that grows the jit tracing cache was a (re)trace+compile:
    count it by program and record its wall time. Warmup compiles land here
    too (they are real compiles); the invariant under test is that the
    counters go FLAT afterwards — an unexpected steady-state retrace is the
    exact failure this makes visible.
    """

    def __init__(self, registry):
        self._m_compiles = registry.counter(
            "kukeon_compiles_total",
            "jit compiles observed at dispatch, by engine program "
            "(prefill|insert|decode). Flat in steady state.",
            labels=("program",))
        self._m_seconds = registry.histogram(
            "kukeon_compile_seconds",
            "Wall time of dispatches that compiled, by program.",
            labels=("program",))

    def wrap(self, fn, program: str, timer=None) -> _TrackedJit:
        """Wrap one jitted program. ``timer`` registers the program with
        the roofline seam (obs/profile.ProgramTimers.track) — kukelint
        KUKE015 requires every engine program to pass one."""
        return _TrackedJit(fn, program, self._m_compiles, self._m_seconds,
                           timer=timer)

    def count(self, program: str) -> int:
        return int(self._m_compiles.value(program=program))


class ProfileBusy(RuntimeError):
    """A capture is already running (single-flight; HTTP maps this to 409)."""


PROFILE_DIR_ENV = "KUKEON_PROFILE_DIR"
PROFILE_KEEP_ENV = "KUKEON_PROFILE_KEEP"
MAX_CAPTURE_MS = 600_000


class ProfileSpool:
    """Single-flight on-demand ``jax.profiler.trace`` captures.

    ``start(duration_ms)`` kicks a background thread that traces the live
    process for the requested window and writes the capture under the spool
    dir; only the newest K completed captures are kept (bounded disk, K from
    ``KUKEON_PROFILE_KEEP``). One capture at a time: profiling is itself a
    perturbation, and two overlapping jax traces would corrupt each other —
    a second start raises :class:`ProfileBusy`. Backends without a usable
    profiler produce a clear error record instead of a wedged endpoint.
    """

    def __init__(self, base_dir: str | None = None, keep: int | None = None,
                 registry=None):
        self.base_dir = (base_dir or os.environ.get(PROFILE_DIR_ENV)
                         or os.path.join(tempfile.gettempdir(),
                                         "kukeon-profiles"))
        self.keep = max(1, keep if keep is not None
                        else int(os.environ.get(PROFILE_KEEP_ENV, "4") or 4))
        self._lock = sanitize.lock("ProfileSpool._lock")
        self._active: dict | None = None   # guarded-by: _lock
        # Failed captures leave nothing on disk; keep their records so
        # GET /v1/profile can answer "why did my capture vanish".
        self._failed: deque[dict] = deque(maxlen=8)
        self._m_captures = None
        if registry is not None:
            self._m_captures = registry.counter(
                "kukeon_profile_captures_total",
                "On-demand profiler captures by outcome.",
                labels=("outcome",))

    def start(self, duration_ms: float) -> dict:
        """Begin a capture; returns its record immediately (the trace runs
        in the background for ``duration_ms``). Raises ProfileBusy while a
        capture is in flight and ValueError on a bad duration."""
        from kukeon_tpu import faults

        duration_ms = float(duration_ms)
        if not (0 < duration_ms <= MAX_CAPTURE_MS):
            raise ValueError(
                f"durationMs must be in (0, {MAX_CAPTURE_MS}]")
        faults.maybe_fail("profile.capture")
        name = f"capture-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
        rec = {
            "name": name,
            "path": os.path.join(self.base_dir, name),
            "state": "running",
            "startedAt": time.time(),
            "durationMs": duration_ms,
        }
        with self._lock:
            if self._active is not None:
                raise ProfileBusy(
                    f"capture {self._active['name']} is already running")
            self._active = rec
        threading.Thread(target=self._capture, args=(rec,), daemon=True,
                         name="profile-capture").start()
        return dict(rec)

    def _capture(self, rec: dict) -> None:
        try:
            import jax

            if not hasattr(jax, "profiler") or not hasattr(
                    jax.profiler, "start_trace"):
                raise RuntimeError(
                    "jax.profiler.start_trace is unavailable on this "
                    "backend; no capture possible")
            os.makedirs(rec["path"], exist_ok=True)
            jax.profiler.start_trace(rec["path"])
            try:
                time.sleep(rec["durationMs"] / 1000.0)
            finally:
                jax.profiler.stop_trace()
            rec["state"] = "done"
            rec["sizeBytes"] = _tree_size(rec["path"])
            if self._m_captures is not None:
                self._m_captures.inc(outcome="ok")
        except Exception as e:  # noqa: BLE001 — the spool must never wedge closed
            rec["state"] = "error"
            rec["error"] = f"{type(e).__name__}: {e}"
            shutil.rmtree(rec["path"], ignore_errors=True)
            if self._m_captures is not None:
                self._m_captures.inc(outcome="error")
        finally:
            with self._lock:
                self._active = None
                if rec["state"] == "error":
                    self._failed.append(rec)
            self._prune()

    def _prune(self) -> None:
        """Keep only the newest K completed captures on disk."""
        try:
            entries = sorted(
                (e for e in os.scandir(self.base_dir) if e.is_dir()),
                key=lambda e: e.stat().st_mtime, reverse=True,
            )
        except OSError:
            return
        for stale in entries[self.keep:]:
            shutil.rmtree(stale.path, ignore_errors=True)

    def list(self) -> list[dict]:
        """Newest-first capture records: the running one (if any), recent
        failures, then completed captures read from the spool dir."""
        with self._lock:
            out = [dict(self._active)] if self._active is not None else []
            out.extend(dict(r) for r in reversed(self._failed))
        try:
            entries = sorted(
                (e for e in os.scandir(self.base_dir) if e.is_dir()),
                key=lambda e: e.stat().st_mtime, reverse=True,
            )
        except OSError:
            entries = []
        active_name = out[0]["name"] if out and out[0]["state"] == "running" \
            else None
        for e in entries:
            if e.name == active_name:
                continue
            out.append({
                "name": e.name,
                "path": e.path,
                "state": "done",
                "startedAt": e.stat().st_mtime,
                "sizeBytes": _tree_size(e.path),
            })
        return out


def _tree_size(path: str) -> int:
    total = 0
    for dirpath, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, f))
            except OSError:
                pass
    return total
