"""Fault-injection harness: arming syntax, counts, probability, and the
zero-overhead guarantee when KUKEON_FAULTS is unset."""

import os

import pytest

from kukeon_tpu import faults


def test_unarmed_is_a_noop():
    """The guard contract: with KUKEON_FAULTS unset, maybe_fail builds no
    table, takes no lock-protected slow path, and never raises — the seams
    threaded through engine dispatch/transfers stay free in production."""
    assert os.environ.get(faults.ENV) is None
    assert not faults.active()
    for _ in range(1000):
        faults.maybe_fail("engine.decode")
    # Nothing parsed, nothing counted: the armed-path state stays empty.
    assert faults._cached_spec is None
    assert faults._points == {}
    assert faults.stats == {}


def test_unarmed_is_cheap_relative_to_armed_miss():
    """The unset path must be a bare env lookup — meaningfully cheaper than
    even an armed-but-different-point lookup (which pays the lock)."""
    import timeit

    # Both paths share the os.environ lookup that dominates their cost, so
    # the real gap is only ~10% — one scheduler hiccup can invert a single
    # sample.  Take the min of several repeats and allow a bounded retry:
    # the unarmed path is deterministically cheaper, so three consecutive
    # inversions would mean the guard is broken, not the clock.
    for _ in range(3):
        unarmed = min(timeit.repeat(
            lambda: faults.maybe_fail("p"), number=50000, repeat=3))
        os.environ[faults.ENV] = "other.point:1"
        try:
            armed_miss = min(timeit.repeat(
                lambda: faults.maybe_fail("p"), number=50000, repeat=3))
        finally:
            del os.environ[faults.ENV]
        if unarmed < armed_miss:
            break
    assert unarmed < armed_miss


@pytest.mark.faults
def test_always_fires_and_counts():
    os.environ[faults.ENV] = "engine.decode:1"
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("engine.decode")
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("engine.decode")
    faults.maybe_fail("engine.prefill")   # unarmed point passes
    assert faults.fired("engine.decode") == 2
    assert faults.fired("engine.prefill") == 0


@pytest.mark.faults
def test_count_cap_exhausts():
    os.environ[faults.ENV] = "cell.http:1:2"
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("cell.http")
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("cell.http")
    faults.maybe_fail("cell.http")        # cap reached: passes forever after
    faults.maybe_fail("cell.http")
    assert faults.fired("cell.http") == 2


@pytest.mark.faults
def test_multiple_points_and_env_reparse():
    os.environ[faults.ENV] = "a:1, b:1:1"
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("a")
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("b")
    faults.maybe_fail("b")                # b exhausted
    # Re-arming with a different spec takes effect immediately (no reset).
    os.environ[faults.ENV] = "c:1"
    faults.maybe_fail("a")
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("c")


@pytest.mark.faults
def test_probability_zero_never_fires():
    os.environ[faults.ENV] = "p:0"
    for _ in range(200):
        faults.maybe_fail("p")
    assert faults.fired("p") == 0


@pytest.mark.faults
def test_custom_exception_and_message():
    os.environ[faults.ENV] = "io:1"
    with pytest.raises(OSError, match="disk gone"):
        faults.maybe_fail("io", exc=OSError, msg="disk gone")


@pytest.mark.faults
def test_bad_spec_fails_loudly():
    os.environ[faults.ENV] = "point:not-a-prob"
    with pytest.raises(ValueError):
        faults.maybe_fail("point")
