"""Observability layer (kukeon_tpu/obs): registry semantics, Prometheus
exposition golden format, trace-span lifecycle (including the PR-2 shed and
deadline-expiry paths), cell /metrics + /v1/trace endpoints under load, and
the fault-point/counter guard."""

from __future__ import annotations

import http.client
import json
import os
import re
import threading
import time
from http.server import ThreadingHTTPServer

import jax
import numpy as np
import pytest

from kukeon_tpu import faults
from kukeon_tpu.models import llama
from kukeon_tpu.obs import (
    LATENCY_BUCKETS_S,
    Registry,
    Tracer,
    expo,
    render,
)
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import RejectedError, SamplingParams, ServingEngine

PROMPT = np.arange(1, 9, dtype=np.int32)


def _tiny_engine(**kw):
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    kw.setdefault("num_slots", 1)
    return ServingEngine(cfg, params, mesh, max_seq_len=96,
                        decode_chunk=4, **kw)


# --- registry semantics ------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = Registry()
    c = reg.counter("kukeon_t_total", "help", labels=("kind",))
    c.inc(kind="a")
    c.inc(2, kind="a")
    c.inc(kind="b")
    assert c.value(kind="a") == 3
    assert c.value(kind="b") == 1
    with pytest.raises(ValueError):
        c.inc(-1, kind="a")            # counters only go up
    g = reg.gauge("kukeon_t_gauge", "g")
    g.set(5)
    g.dec(2)
    assert g.value() == 3
    g.set_function(lambda: 42)
    assert g.value() == 42             # callable wins over stored value
    h = reg.histogram("kukeon_t_seconds", "h")
    h.observe(0.001)
    counts, total, n = h.snapshot()
    assert n == 1 and abs(total - 0.001) < 1e-9
    assert sum(counts) == 1


def test_registry_get_or_create_is_idempotent_and_typed():
    reg = Registry()
    a = reg.counter("kukeon_same_total", "x")
    b = reg.counter("kukeon_same_total", "different help ignored")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("kukeon_same_total", "now a gauge?")
    with pytest.raises(ValueError):
        reg.counter("kukeon_same_total", "x", labels=("k",))


def test_histogram_percentiles():
    reg = Registry()
    h = reg.histogram("kukeon_p_seconds", "p")
    assert h.percentile(0.5) is None   # no observations yet
    for v in (0.001, 0.002, 0.004, 0.008, 0.016, 0.032):
        h.observe(v)
    p50 = h.percentile(0.5)
    assert 0.001 <= p50 <= 0.008
    # Overflow clamps to the top finite bound rather than inventing data.
    h.observe(10_000.0)
    assert h.percentile(1.0) == h.buckets[-1]
    assert LATENCY_BUCKETS_S[0] <= 0.001   # ladder reaches ITL scale


def test_registry_hammer_counts_are_exact():
    """Multi-threaded registry hammer: no torn reads, no lost increments —
    counters and histogram counts land exactly."""
    reg = Registry()
    c = reg.counter("kukeon_hammer_total", "h", labels=("t",))
    h = reg.histogram("kukeon_hammer_seconds", "h")
    g = reg.gauge("kukeon_hammer_gauge", "h")
    N_THREADS, N_ITER = 8, 2000

    def worker(tid: int):
        for i in range(N_ITER):
            c.inc(t=str(tid % 2))
            h.observe(0.0001 * (i % 50))
            g.inc()

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)
    assert c.value(t="0") + c.value(t="1") == N_THREADS * N_ITER
    counts, _total, n = h.snapshot()
    assert n == N_THREADS * N_ITER
    assert sum(counts) == n
    assert g.value() == N_THREADS * N_ITER


# --- exposition golden format ------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(\{[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*"(?:,[a-zA-Z0-9_]+="(?:[^"\\]|\\.)*")*\})?'
    r' (-?(?:\d+\.?\d*(?:e-?\d+)?|\+Inf|-Inf|NaN))$'
)


def _parse_expo(text: str) -> dict[str, dict]:
    """Strict parser for the subset of the Prometheus text format expo.py
    emits: families {name: {"type", "help", "samples": [(labels, value)]}}.
    Raises on any malformed line — this IS the golden assertion."""
    families: dict[str, dict] = {}
    declared: str | None = None
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if line.startswith("# HELP "):
            name = line.split()[2]
            families.setdefault(name, {"samples": []})["help"] = line
            declared = name
        elif line.startswith("# EXEMPLAR "):
            # Histogram trace exemplars ride as comment lines (any 0.0.4
            # scraper ignores them); the golden parser pins their syntax.
            m = re.match(
                r'^# EXEMPLAR ([a-zA-Z_:][a-zA-Z0-9_:]*_bucket)(\{.*\})? '
                r'trace_id="[0-9a-fA-F]*" value=\S+$', line)
            assert m, f"malformed exemplar line: {line!r}"
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            assert name == declared, f"TYPE without preceding HELP: {line}"
            assert kind in ("counter", "gauge", "histogram"), line
            families[name]["type"] = kind
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"malformed sample line: {line!r}"
            name = m.group(1)
            base = re.sub(r"_(bucket|sum|count)$", "", name)
            fam = families.get(name) or families.get(base)
            assert fam is not None, f"sample before family declaration: {line}"
            labels = {}
            if m.group(2):
                for kv in re.findall(r'([a-zA-Z0-9_]+)="((?:[^"\\]|\\.)*)"',
                                     m.group(2)):
                    labels[kv[0]] = kv[1]
            fam["samples"].append((name, labels, m.group(3)))
    return families


def test_exposition_golden_format():
    reg = Registry()
    c = reg.counter("kukeon_g_total", "a counter", labels=("kind",))
    c.inc(kind='weird "value"\nwith escapes')
    reg.gauge("kukeon_g_gauge", "a gauge").set(1.5)
    h = reg.histogram("kukeon_g_seconds", "a histogram")
    for v in (0.0001, 0.01, 1.0, 500.0):
        h.observe(v)
    text = render(reg)
    families = _parse_expo(text)
    assert families["kukeon_g_total"]["type"] == "counter"
    assert families["kukeon_g_gauge"]["type"] == "gauge"
    assert families["kukeon_g_seconds"]["type"] == "histogram"
    # Label values survive escaping and round-trip through the parser.
    (_n, labels, v), = families["kukeon_g_total"]["samples"]
    assert labels["kind"] == 'weird \\"value\\"\\nwith escapes'
    assert v == "1"
    # Histogram invariants: cumulative bucket counts are monotone, the
    # +Inf bucket equals _count, and _sum matches the observations.
    hs = families["kukeon_g_seconds"]["samples"]
    buckets = [(lab["le"], float(val)) for n, lab, val in hs
               if n.endswith("_bucket")]
    assert buckets[-1][0] == "+Inf"
    values = [v for _le, v in buckets]
    assert values == sorted(values), "bucket counts must be cumulative"
    count = next(float(v) for n, _l, v in hs if n.endswith("_count"))
    total = next(float(v) for n, _l, v in hs if n.endswith("_sum"))
    assert values[-1] == count == 4
    assert abs(total - 501.0101) < 1e-6
    # le bounds are strictly increasing (bucket monotonicity by bound too).
    finite = [float(le) for le, _v in buckets[:-1]]
    assert finite == sorted(finite) and len(set(finite)) == len(finite)


def test_collector_families_render():
    reg = Registry()
    reg.register_collector(lambda: iter([
        ("kukeon_extra_total", "counter", "from a collector",
         [({"k": "v"}, 3.0)]),
    ]))
    text = render(reg)
    fams = _parse_expo(text)
    assert ("kukeon_extra_total", {"k": "v"}, "3") in \
        fams["kukeon_extra_total"]["samples"]


# --- trace spans -------------------------------------------------------------


def test_tracer_ring_buffer_bounded():
    t = Tracer(capacity=3)
    for i in range(10):
        t.finish(t.begin(i, 1), "ok")
    spans = t.recent(100)
    assert len(spans) == 3
    assert [s["requestId"] for s in spans] == [9, 8, 7]   # newest first


def test_span_phases_partition_e2e():
    t = Tracer()
    s = t.begin(7, 16)
    s.event("admitted")
    time.sleep(0.01)
    s.event("prefill_dispatched")
    s.event("first_token")
    time.sleep(0.005)
    t.finish(s, "ok", tokens=3)
    d = t.recent(1)[0]
    assert d["outcome"] == "ok" and d["tokens"] == 3
    assert set(d["phasesS"]) == {"queued", "prefill_dispatch",
                                 "prefill_wait", "decode"}
    assert abs(sum(d["phasesS"].values()) - d["e2eS"]) < 1e-3


def test_engine_trace_lifecycle_ok_path():
    eng = _tiny_engine()
    got = eng.generate(PROMPT, SamplingParams(max_new_tokens=6))
    assert len(got) == 6
    span = eng.tracer.recent(1)[0]
    assert span["outcome"] == "ok"
    assert span["tokens"] == 6
    assert span["promptTokens"] == PROMPT.size
    assert span["decodeChunks"] >= 1
    events = [e["event"] for e in span["events"]]
    assert events == ["submitted", "admitted", "prefill_dispatched",
                      "first_token", "finished"]
    # Acceptance: phase durations sum (within tolerance) to e2e latency.
    assert abs(sum(span["phasesS"].values()) - span["e2eS"]) < 1e-3


def test_engine_trace_shed_path():
    """The PR-2 admission-shed path records both the counter and a span."""
    eng = _tiny_engine(max_pending=1)
    held = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    with pytest.raises(RejectedError):
        eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    assert eng.shed_stats["rejected"] == 1
    assert eng._m_requests.value(outcome="shed") == 1
    span = eng.tracer.recent(1)[0]
    assert span["outcome"] == "shed"
    assert span["requestId"] == -1     # never admitted, never got an id
    assert span["tokens"] == 0
    held.cancel()
    while not held.done.is_set():
        eng.step()


def test_engine_trace_deadline_expiry_paths():
    """Deadline expiry while QUEUED and while ACTIVE both finish their
    spans with outcome=timeout, and the phases still partition e2e."""
    eng = _tiny_engine()
    hog = eng.submit(PROMPT, SamplingParams(max_new_tokens=64))
    eng.step()                          # hog takes THE slot
    queued_victim = eng.submit(PROMPT, SamplingParams(max_new_tokens=4),
                               deadline_s=0.01)
    time.sleep(0.03)
    eng.step()
    assert queued_victim.timed_out
    span = eng.tracer.recent(1)[0]
    assert span["outcome"] == "timeout"
    assert span["requestId"] == queued_victim.id
    assert list(span["phasesS"]) == ["queued"]   # never left the queue
    assert abs(sum(span["phasesS"].values()) - span["e2eS"]) < 1e-3
    assert eng._m_requests.value(outcome="timeout") == 1

    hog.cancel()
    while not hog.done.is_set():
        eng.step()
    active_victim = eng.submit(PROMPT, SamplingParams(max_new_tokens=500),
                               deadline_s=0.3)
    while not active_victim.done.is_set():
        eng.step()
    assert active_victim.timed_out
    span = next(s for s in eng.tracer.recent(4)
                if s["requestId"] == active_victim.id)
    assert span["outcome"] == "timeout"
    assert span["decodeChunks"] >= 1 and span["tokens"] >= 1
    assert "decode" in span["phasesS"]
    assert abs(sum(span["phasesS"].values()) - span["e2eS"]) < 1e-3
    assert eng.shed_stats["timed_out"] == 2
    # The cancelled hog got its own terminal span too.
    assert eng._m_requests.value(outcome="cancelled") == 1


def test_engine_metrics_families_after_traffic():
    eng = _tiny_engine(max_pending=4)
    eng.generate(PROMPT, SamplingParams(max_new_tokens=5))
    text = render(eng.registry)
    fams = _parse_expo(text)
    for name, kind in (
        ("kukeon_engine_queue_wait_seconds", "histogram"),
        ("kukeon_engine_prefill_seconds", "histogram"),
        ("kukeon_engine_ttft_seconds", "histogram"),
        ("kukeon_engine_inter_token_seconds", "histogram"),
        ("kukeon_engine_e2e_seconds", "histogram"),
        ("kukeon_engine_tokens_total", "counter"),
        ("kukeon_engine_requests_total", "counter"),
        ("kukeon_engine_shed_total", "counter"),
        ("kukeon_engine_slots_total", "gauge"),
        ("kukeon_engine_slots_free", "gauge"),
        ("kukeon_engine_queue_depth", "gauge"),
        ("kukeon_engine_max_pending", "gauge"),
        ("kukeon_engine_host_sync_total", "counter"),
        ("kukeon_engine_decode_chunks_total", "counter"),
        ("kukeon_faults_fired_total", "counter"),
    ):
        assert fams.get(name, {}).get("type") == kind, name
    # Prefill histogram is labelled by padded bucket; 8 tokens pad to 64.
    pre = fams["kukeon_engine_prefill_seconds"]["samples"]
    assert any(lab.get("bucket") == "64" for _n, lab, _v in pre)
    # Transfer counters mirror the sync_stats seam exactly.
    hs = {lab["kind"]: float(v)
          for n, lab, v in fams["kukeon_engine_host_sync_total"]["samples"]}
    assert hs["fetch"] == eng.sync_stats["fetches"]
    assert hs["upload"] == eng.sync_stats["uploads"]


# --- fault-point guard -------------------------------------------------------


def test_every_fault_point_call_site_is_declared():
    """Guard (conftest-level contract): every ``maybe_fail("<point>")``
    call site in the package appears in faults.POINTS, and every declared
    point has a call site — a new fault point can't ship unobservable,
    and a stale declaration can't linger after a seam is removed.

    Since PR 7 this rides kukelint's AST-accurate KUKE007 registry pass
    (kukeon_tpu/analysis/registries.py) instead of a regex over source
    text: dynamic point names are themselves a violation, and failures
    carry file:line."""
    from kukeon_tpu.analysis import load_sources, run_analysis
    from kukeon_tpu.analysis.registries import collect_fault_call_sites

    pkg_root = os.path.dirname(os.path.abspath(faults.__file__))
    findings = run_analysis(pkg_root, select=["KUKE007"])
    assert findings == [], "\n".join(f.render() for f in findings)
    # Vacuity guard: the pass really saw the package's call sites (a scan
    # rooted in the wrong directory would pass trivially).
    sites = {p for _f, p, _l in collect_fault_call_sites(
        load_sources(pkg_root))}
    assert sites == set(faults.POINTS)


@pytest.mark.faults
def test_every_fault_point_has_a_fired_counter():
    """Every declared point exposes kukeon_faults_fired_total{point=...}
    (zero unfired), and a fired point's count lands on the scrape."""
    reg = Registry()
    reg.register_collector(expo.faults_collector)
    fams = _parse_expo(render(reg))
    seen = {lab["point"]: float(v) for _n, lab, v
            in fams["kukeon_faults_fired_total"]["samples"]}
    assert set(faults.POINTS) <= set(seen)
    assert all(v == 0 for v in seen.values())
    os.environ[faults.ENV] = "engine.decode:1:2"
    for _ in range(2):
        with pytest.raises(faults.FaultInjected):
            faults.maybe_fail("engine.decode")
    fams = _parse_expo(render(reg))
    seen = {lab["point"]: float(v) for _n, lab, v
            in fams["kukeon_faults_fired_total"]["samples"]}
    assert seen["engine.decode"] == 2


# --- cell endpoints under load (tier-1 acceptance) ---------------------------


@pytest.fixture(scope="module")
def obs_cell():
    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    cell = ServingCell("tiny", num_slots=2, max_seq_len=96, checkpoint=None,
                       dtype=None, max_pending=8)
    cell.engine.start()
    cell.mark_ready()
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield cell, server.server_address[1]
    server.shutdown()
    server.server_close()
    cell.engine.stop()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    raw = resp.read()
    ctype = resp.getheader("Content-Type")
    conn.close()
    return resp.status, raw, ctype


def test_metrics_scrape_is_valid_while_flooded(obs_cell):
    """Acceptance: /metrics parses as Prometheus text — with the required
    histogram/counter/gauge families — WHILE a flood of requests is in
    flight, and /v1/trace spans' phases sum to their e2e latency."""
    cell, port = obs_cell
    eng = cell.engine
    sp = SamplingParams(max_new_tokens=3)
    flood: list = []
    rejected = 0
    for _ in range(24):
        try:
            flood.append(eng.submit(PROMPT, sp))
        except RejectedError:
            rejected += 1
    # Scrape repeatedly mid-flight: every scrape must parse cleanly.
    for _ in range(5):
        status, raw, ctype = _get(port, "/metrics")
        assert status == 200
        assert ctype.startswith("text/plain")
        fams = _parse_expo(raw.decode())
        for name in ("kukeon_engine_ttft_seconds",
                     "kukeon_engine_inter_token_seconds",
                     "kukeon_engine_e2e_seconds",
                     "kukeon_engine_queue_wait_seconds",
                     "kukeon_engine_prefill_seconds",
                     "kukeon_engine_shed_total",
                     "kukeon_engine_slots_free",
                     "kukeon_engine_queue_depth",
                     "kukeon_watchdog_probes_total",
                     "kukeon_watchdog_trips_total",
                     "kukeon_faults_fired_total",
                     "kukeon_cell_ready",
                     "kukeon_cell_uptime_seconds"):
            assert name in fams, name
    deadline = time.monotonic() + 120
    for r in flood:
        assert r.done.wait(timeout=max(0.0, deadline - time.monotonic()))
    # Settle: the terminal emit races the span append by design.
    deadline = time.monotonic() + 10
    while len(eng.tracer) < len(flood) and time.monotonic() < deadline:
        time.sleep(0.02)
    status, raw, _ = _get(port, f"/v1/trace?n={len(flood) + 8}")
    assert status == 200
    spans = json.loads(raw)["spans"]
    ok_spans = [s for s in spans if s["outcome"] == "ok"]
    assert len(ok_spans) >= len(flood)
    for s in ok_spans:
        assert abs(sum(s["phasesS"].values()) - s["e2eS"]) < 1e-3
    # The scrape agrees with the JSON stats view (same registry).
    status, raw, _ = _get(port, "/v1/stats")
    stats = json.loads(raw)
    fams = _parse_expo(_get(port, "/metrics")[1].decode())
    shed = {lab["reason"]: float(v) for _n, lab, v
            in fams["kukeon_engine_shed_total"]["samples"]}
    assert shed.get("rejected", 0) == stats["rejected"] == rejected


def test_trace_endpoint_bounds_and_validates(obs_cell):
    _cell, port = obs_cell
    status, raw, _ = _get(port, "/v1/trace?n=1")
    assert status == 200
    assert len(json.loads(raw)["spans"]) <= 1
    status, _raw, _ = _get(port, "/v1/trace?n=bogus")
    assert status == 400


def test_watchdog_counters_land_on_registry():
    from kukeon_tpu.runtime.serving_cell import EngineWatchdog

    class _Stalled:
        last_progress = 0.0

        def stalled_s(self):
            return 1e9

    reg = Registry()
    wd = EngineWatchdog(_Stalled(), stall_budget_s=0.01, interval_s=0.01,
                        probe=lambda timeout_s: ("wedged", "injected"),
                        on_wedged=lambda d: None, registry=reg)
    wd.start()
    wd.join(timeout=10)
    assert wd.tripped
    assert reg.get("kukeon_watchdog_trips_total").value() == 1
    assert reg.get("kukeon_watchdog_probes_total").value(verdict="wedged") == 1


def test_embedding_cell_stats_parity():
    """EmbeddingCell.stats() reports the same ready/draining/uptime fields
    the decoder cell does, so scrapers treat both flavors uniformly."""
    from kukeon_tpu.runtime.serving_cell import EmbeddingCell, ServingCell

    ec = EmbeddingCell("bge-tiny", batch_size=4)
    dc = ServingCell("tiny", num_slots=1, max_seq_len=96, checkpoint=None,
                     dtype=None)
    try:
        for key in ("ready", "draining", "uptimeSeconds", "unreadyReason"):
            assert key in ec.stats(), key
            assert key in dc.stats(), key
        ec.mark_ready()
        s = ec.stats()
        assert s["ready"] is True and "unreadyReason" not in s
        # Both flavors expose a registry the handler can scrape.
        for cell, kind in ((ec, "embedding"), (dc, "decoder")):
            fams = _parse_expo(render(cell.registry))
            assert "kukeon_cell_ready" in fams
            info = fams["kukeon_cell_info"]["samples"]
            assert any(lab.get("kind") == kind for _n, lab, _v in info)
        assert "kukeon_embed_sequences_total" in _parse_expo(
            render(ec.registry))
    finally:
        dc.engine.stop()
