"""Fleet federation (PR 4): the daemon scrapes every running model cell's
/metrics, re-exposes the union with cell= labels (unreachable cells marked
via kukeon_cell_scrape_ok 0), summarizes the fleet for `kuke top`, and the
federate text machinery round-trips the in-repo exposition format."""

from __future__ import annotations

import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kukeon_tpu import obs
from kukeon_tpu.obs import Registry, expo
from kukeon_tpu.obs import federate as fed
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.cells import FakeBackend
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.daemon import RPCService, summarize_cell_scrape
from kukeon_tpu.runtime.devices import TPUDeviceManager
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import Runner, RunnerOptions
from kukeon_tpu.runtime.store import ResourceStore

from test_obs import _parse_expo


# --- federate text machinery -------------------------------------------------


def _cell_registry(*, ready=1.0, uptime=100.0, ok_requests=50,
                   queue=3, ttft=(0.01, 0.02, 0.04, 0.08)) -> Registry:
    reg = Registry()
    reg.gauge("kukeon_cell_ready", "ready").set(ready)
    reg.gauge("kukeon_cell_uptime_seconds", "uptime").set(uptime)
    reg.gauge("kukeon_cell_info", "info", labels=("model", "kind")).set(
        1, model="tiny", kind="decoder")
    c = reg.counter("kukeon_engine_requests_total", "req",
                    labels=("outcome",))
    c.inc(ok_requests, outcome="ok")
    reg.gauge("kukeon_engine_queue_depth", "q").set(queue)
    h = reg.histogram("kukeon_engine_ttft_seconds", "ttft")
    for v in ttft:
        h.observe(v)
    return reg


def test_federate_parse_inject_render_roundtrip():
    reg = _cell_registry()
    text = expo.render(reg)
    fams = fed.parse(text)
    assert fams["kukeon_engine_requests_total"].kind == "counter"
    assert fams["kukeon_engine_ttft_seconds"].kind == "histogram"
    fed.inject_label(fams, cell="r/s/st/c1")
    out = fed.render(fams)
    parsed = _parse_expo(out)            # strict golden parser accepts it
    for _n, labels, _v in parsed["kukeon_engine_requests_total"]["samples"]:
        assert labels["cell"] == "r/s/st/c1"
    # Histogram child samples (_bucket/_sum/_count) are relabelled too.
    bucket_rows = [s for s in parsed["kukeon_engine_ttft_seconds"]["samples"]
                   if s[0].endswith("_bucket")]
    assert bucket_rows and all(
        lab["cell"] == "r/s/st/c1" for _n, lab, _v in bucket_rows)


def test_federate_parse_rejects_garbage():
    with pytest.raises(ValueError):
        fed.parse("this is not prometheus text\n")
    with pytest.raises(ValueError):
        fed.parse("kukeon_orphan_total 3\n")   # sample before declaration


def test_federate_histogram_counts_roundtrip():
    reg = Registry()
    h = reg.histogram("kukeon_t_fed_seconds", "x")
    for v in (0.001, 0.001, 0.01, 5.0, 1e9):
        h.observe(v)
    fams = fed.parse(expo.render(reg))
    bounds, counts = fed.histogram_counts(fams["kukeon_t_fed_seconds"])
    assert bounds == h.buckets
    assert counts == h.snapshot()[0]
    p95 = obs.percentile_from_counts(bounds, counts, 0.95)
    assert p95 == h.percentile(0.95)


def test_merge_groups_families_across_cells():
    a = fed.parse(expo.render(_cell_registry(queue=1)))
    b = fed.parse(expo.render(_cell_registry(queue=9)))
    fed.inject_label(a, cell="a")
    fed.inject_label(b, cell="b")
    merged = fed.merge([a, b])
    text = fed.render(merged)
    # One TYPE declaration per family, samples from both cells beneath it.
    assert text.count("# TYPE kukeon_engine_queue_depth gauge") == 1
    parsed = _parse_expo(text)
    depths = {lab["cell"]: v for _n, lab, v
              in parsed["kukeon_engine_queue_depth"]["samples"]}
    assert depths == {"a": "1", "b": "9"}


def test_summarize_cell_scrape_fields():
    fams = fed.parse(expo.render(_cell_registry()))
    row = summarize_cell_scrape(fams)
    assert row["ready"] is True
    assert row["model"] == "tiny"
    assert row["qps"] == 0.5             # 50 requests / 100s uptime
    assert row["queueDepth"] == 3
    assert 0 < row["ttftP50S"] <= row["ttftP95S"] < 0.2


# --- daemon federation over live endpoints -----------------------------------


class _MetricsHandler(BaseHTTPRequestHandler):
    registry: Registry = None  # type: ignore[assignment]

    def log_message(self, fmt, *a):  # noqa: D102 — quiet test server
        pass

    def do_GET(self):
        if self.path != "/metrics":
            self.send_response(404)
            self.end_headers()
            return
        body = expo.render(self.registry).encode()
        self.send_response(200)
        self.send_header("Content-Type", expo.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _serve_registry(reg: Registry) -> tuple[ThreadingHTTPServer, int]:
    handler = type("H", (_MetricsHandler,), {"registry": reg})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def fleet(tmp_path):
    """A controller (fake backend) running two reachable model cells backed
    by real /metrics HTTP endpoints, plus one whose port is dead."""
    store = ResourceStore(MetadataStore(str(tmp_path)))
    runner = Runner(store, FakeBackend(), cgroups=None,
                    devices=TPUDeviceManager(store.ms, chips=[0, 1, 2, 3]),
                    options=RunnerOptions(stop_grace_s=0.2),
                    registry=obs.Registry())
    ctl = Controller(store, runner)
    ctl.bootstrap()
    servers = []
    ports = {}
    for name, queue in (("llm-a", 1), ("llm-b", 7)):
        srv, port = _serve_registry(_cell_registry(queue=queue))
        servers.append(srv)
        ports[name] = port
    ports["llm-dead"] = _free_port()
    for name, port in ports.items():
        doc = t.Document(
            kind=t.KIND_CELL, metadata=t.Metadata(name=name),
            spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                              port=port)),
        )
        ctl.create_cell(doc)
    yield RPCService(ctl), ports
    for srv in servers:
        srv.shutdown()
        srv.server_close()


def test_daemon_metrics_federates_cells(fleet):
    """Acceptance: daemon metrics union >=2 running cells with cell=
    labels; the unreachable cell is marked kukeon_cell_scrape_ok 0 and the
    scrape still succeeds and golden-parses."""
    service, _ports = fleet
    out = service.Metrics()
    fams = _parse_expo(out["text"])
    # Daemon-side families survive, unlabelled.
    assert "kukeon_daemon_uptime_seconds" in fams
    # Cell families carry cell= labels for both reachable cells.
    depths = {lab["cell"]: v for _n, lab, v
              in fams["kukeon_engine_queue_depth"]["samples"]}
    assert depths == {"default/default/default/llm-a": "1",
                      "default/default/default/llm-b": "7"}
    ok = {lab["cell"]: float(v) for _n, lab, v
          in fams["kukeon_cell_scrape_ok"]["samples"]}
    assert ok["default/default/default/llm-a"] == 1
    assert ok["default/default/default/llm-b"] == 1
    assert ok["default/default/default/llm-dead"] == 0
    # Non-federated view still works (the old scrape shape).
    bare = service.Metrics(federate=False)
    assert "kukeon_cell_scrape_ok" not in bare["text"]


def test_scrape_cells_summary_rows(fleet):
    service, _ports = fleet
    rows = {r["cell"]: r for r in service.ScrapeCells()["cells"]}
    a = rows["default/default/default/llm-a"]
    assert a["ok"] and a["ready"] and a["qps"] == 0.5 and a["queueDepth"] == 1
    assert a["phase"] == "ready" and a["restarts"] == 0
    dead = rows["default/default/default/llm-dead"]
    assert dead["ok"] is False and "error" in dead


@pytest.mark.slow
def test_fleet_federation_e2e():
    """Full-stack variant (excluded from tier-1 by the slow marker): a real
    daemon supervises two real tiny model cells; `kuke daemon metrics`
    federates both with cell= labels and `kuke top` renders the fleet."""
    import json
    import time
    import urllib.request

    from test_runtime_e2e import Daemon

    d = Daemon(chips="0,1")
    try:
        manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: fed-a}
spec:
  model: {model: tiny, chips: 1, port: 9481, numSlots: 2, maxSeqLen: 128,
          hostNetwork: true}
---
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: fed-b}
spec:
  model: {model: tiny, chips: 1, port: 9482, numSlots: 2, maxSeqLen: 128,
          hostNetwork: true, sloTtftP95Ms: 500, sloAvailability: 0.999}
"""
        d.kuke("apply", "-f", "-", stdin_data=manifest)
        deadline = time.monotonic() + 180
        pending = {9481, 9482}
        while pending and time.monotonic() < deadline:
            for port in list(pending):
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/v1/health",
                            timeout=1) as r:
                        if json.loads(r.read())["status"] == "ok":
                            pending.discard(port)
                except OSError:
                    pass
            time.sleep(1.0)
        assert not pending, f"model cells on ports {pending} never healthy"

        metrics = d.kuke("daemon", "metrics").stdout
        fams = _parse_expo(metrics)
        cells = {lab["cell"]: float(v) for _n, lab, v
                 in fams["kukeon_cell_scrape_ok"]["samples"]}
        assert cells == {"default/default/default/fed-a": 1.0,
                         "default/default/default/fed-b": 1.0}
        labelled = {lab["cell"] for _n, lab, _v
                    in fams["kukeon_engine_queue_depth"]["samples"]}
        assert labelled == set(cells)
        # The declared SLO objective federates through with the cell label.
        objectives = {(lab["cell"], lab["slo"]): float(v) for _n, lab, v
                      in fams["kukeon_slo_objective"]["samples"]}
        assert objectives[("default/default/default/fed-b",
                           "availability")] == 0.999

        top = d.kuke("top").stdout
        assert "default/default/default/fed-a" in top
        assert "default/default/default/fed-b" in top
        assert "P95TTFT" in top
    finally:
        d.stop()


def test_kuke_top_renders_from_federated_scrape(fleet, capsys, monkeypatch):
    import argparse

    from kukeon_tpu.runtime import cli

    service, _ports = fleet

    class _Client:
        def call(self, method, **params):
            return getattr(service, method)(**params)

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    args = argparse.Namespace(json=False)
    assert cli.cmd_top(args) == 0
    out = capsys.readouterr().out
    assert "CELL" in out and "P95TTFT" in out and "QUEUE" in out
    assert "default/default/default/llm-a" in out
    assert "down" in out                 # the dead cell row is visible
    # JSON mode emits the raw rows.
    args = argparse.Namespace(json=True)
    assert cli.cmd_top(args) == 0
    assert '"qps": 0.5' in capsys.readouterr().out
