"""Serving resilience: admission control, deadlines, lifecycle endpoints,
TPU watchdog, and engine recovery — each failure *injected* via the fault
harness (kukeon_tpu.faults), never timed.

Engine-level tests drive step() manually for determinism; the HTTP class
runs one cell through the full lifecycle story in definition order."""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from http.server import ThreadingHTTPServer

import jax
import numpy as np
import pytest

from kukeon_tpu import faults
from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import (
    DeadlineExceeded,
    RejectedError,
    SamplingParams,
    ServingEngine,
)


def _tiny_engine(**kw):
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    kw.setdefault("num_slots", 1)
    return ServingEngine(cfg, params, mesh, max_seq_len=96,
                         decode_chunk=4, **kw)


PROMPT = np.arange(1, 9, dtype=np.int32)


# --- admission control ------------------------------------------------------


def test_queue_full_sheds_with_rejected_error():
    eng = _tiny_engine(max_pending=2)
    a = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    b = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    assert eng.queue_depth == 2
    with pytest.raises(RejectedError) as ei:
        eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    assert ei.value.retry_after_s > 0
    assert eng.shed_stats["rejected"] == 1
    # Shedding is not sticky: drain the queue and submits are admitted again.
    while not (a.done.is_set() and b.done.is_set()):
        eng.step()
    assert eng.queue_depth == 0
    c = eng.generate(PROMPT, SamplingParams(max_new_tokens=2))
    assert len(c) == 2
    assert eng.shed_stats["rejected"] == 1


def test_slotted_requests_do_not_count_against_max_pending():
    """max_pending bounds the QUEUE, not concurrency: once a request is
    slotted it stops counting, so num_slots + max_pending requests coexist."""
    eng = _tiny_engine(num_slots=2, max_pending=1)
    a = eng.submit(PROMPT, SamplingParams(max_new_tokens=32))
    eng.step()                      # a takes a slot; queue is empty again
    assert eng.queue_depth == 0
    b = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    assert eng.queue_depth == 1
    a.cancel()
    while not (a.done.is_set() and b.done.is_set()):
        eng.step()


# --- deadlines --------------------------------------------------------------


def test_queued_request_past_deadline_times_out_in_band():
    eng = _tiny_engine()
    hog = eng.submit(PROMPT, SamplingParams(max_new_tokens=64))
    eng.step()                      # hog occupies THE slot
    events: list[tuple[int, bool]] = []
    victim = eng.submit(PROMPT, SamplingParams(max_new_tokens=4),
                        emit=lambda t, d: events.append((t, d)),
                        deadline_s=0.01)
    time.sleep(0.03)
    eng.step()
    assert victim.done.is_set()
    assert victim.timed_out
    assert isinstance(victim.error, DeadlineExceeded)
    assert events == [(-1, True)]   # in-band terminal event, no token
    assert eng.shed_stats["timed_out"] == 1
    hog.cancel()
    while not hog.done.is_set():
        eng.step()


def test_active_request_deadline_frees_slot_and_keeps_partial_output():
    eng = _tiny_engine()
    victim = eng.submit(PROMPT, SamplingParams(max_new_tokens=64),
                        deadline_s=0.2)
    waiter = eng.submit(PROMPT, SamplingParams(max_new_tokens=3))
    deadline = time.monotonic() + 60
    while not (victim.done.is_set() and waiter.done.is_set()):
        assert time.monotonic() < deadline, "deadline expiry left a hang"
        eng.step()
    assert victim.timed_out
    assert len(victim.generated) < 64       # stopped at the deadline...
    assert waiter.generated and len(waiter.generated) == 3  # ...slot reused
    assert len(eng._free_slots()) == eng.num_slots
    assert not eng._requests
    assert eng.shed_stats["timed_out"] == 1


def test_generate_surfaces_deadline_error():
    eng = _tiny_engine()
    req = eng.submit(PROMPT, SamplingParams(max_new_tokens=500),
                     deadline_s=0.05)
    while not req.done.is_set():
        eng.step()
    assert req.timed_out and isinstance(req.error, DeadlineExceeded)


def test_submit_rejects_nonpositive_deadline():
    eng = _tiny_engine()
    with pytest.raises(ValueError, match="deadline_s"):
        eng.submit(PROMPT, SamplingParams(max_new_tokens=1), deadline_s=0.0)


# --- fault-injected engine failures ----------------------------------------


@pytest.mark.faults
def test_engine_thread_recovers_from_injected_decode_fault():
    """One poisoned decode chunk fails the in-flight request but the engine
    loop rebuilds state and keeps serving (the _fail_all + re-init path,
    exercised by injection instead of hoping for a real XLA error)."""
    eng = _tiny_engine()
    os.environ[faults.ENV] = "engine.decode:1:1"
    eng.start()
    try:
        r1 = eng.submit(PROMPT, SamplingParams(max_new_tokens=4))
        assert r1.done.wait(60)
        assert isinstance(r1.error, faults.FaultInjected)
        assert faults.fired("engine.decode") == 1
        # The injected fault is exhausted (count=1): service continues.
        r2 = eng.submit(PROMPT, SamplingParams(max_new_tokens=4))
        assert r2.done.wait(60)
        assert r2.error is None
        assert len(r2.generated) == 4
        assert isinstance(eng.error, faults.FaultInjected)
    finally:
        eng.stop()


@pytest.mark.faults
def test_manual_step_prefill_fault_fails_only_that_request():
    eng = _tiny_engine()
    os.environ[faults.ENV] = "engine.prefill:1:1"
    r = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    with pytest.raises(faults.FaultInjected):
        eng.step()
    # The popped-but-never-slotted request was failed, not leaked.
    assert r.done.is_set()
    assert isinstance(r.error, faults.FaultInjected)
    assert eng.queue_depth == 0
    # Engine state is untouched (the fault fired before any dispatch).
    ok = eng.generate(PROMPT, SamplingParams(max_new_tokens=2))
    assert len(ok) == 2


# --- TPU watchdog -----------------------------------------------------------


class _StalledEngine:
    """Engine stand-in with a controllable progress heartbeat."""

    def __init__(self, busy=True):
        self.busy = busy
        # Same guarded heartbeat shape as the real engine: the watchdog
        # re-arms the heartbeat under this lock on a healthy probe.
        self._lock = threading.Lock()
        self.last_progress = time.monotonic()

    def stalled_s(self) -> float:
        if not self.busy:
            return 0.0
        return time.monotonic() - self.last_progress


def _watchdog(eng, probe, budget=0.05, **kw):
    from kukeon_tpu.runtime.serving_cell import EngineWatchdog

    return EngineWatchdog(eng, stall_budget_s=budget, probe=probe,
                          interval_s=0.01, **kw)


def test_watchdog_trips_on_wedged_probe():
    eng = _StalledEngine()
    eng.last_progress -= 10          # already stalled way past the budget
    hits: list[str] = []
    wd = _watchdog(eng, probe=lambda timeout_s: ("wedged", "probe hung"),
                   on_wedged=hits.append)
    wd.start()
    wd.join(timeout=5)
    assert not wd.is_alive()         # trip terminates the watchdog thread
    assert wd.tripped
    assert hits == ["probe hung"]
    assert wd.last_verdict == ("wedged", "probe hung")


def test_watchdog_rearms_on_healthy_probe():
    """A slow-but-alive runtime (long compile, giant prefill) must NOT get
    the cell killed: an ok probe re-arms the budget instead of tripping."""
    eng = _StalledEngine()
    eng.last_progress -= 10
    wd = _watchdog(eng, probe=lambda timeout_s: ("ok", "backend=cpu"))
    wd.start()
    try:
        deadline = time.monotonic() + 5
        while wd.probes == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert wd.probes >= 1
        assert not wd.tripped
        # The probe bumped the heartbeat: the stall clock restarted.
        assert eng.stalled_s() < 5
    finally:
        wd.stop()
        wd.join(timeout=5)


def test_watchdog_never_probes_an_idle_engine():
    eng = _StalledEngine(busy=False)
    wd = _watchdog(eng, probe=lambda timeout_s: ("wedged", "must not run"))
    wd.start()
    try:
        time.sleep(0.1)
        assert wd.probes == 0
        assert not wd.tripped
    finally:
        wd.stop()
        wd.join(timeout=5)


@pytest.mark.faults
def test_probe_reports_wedged_under_fault_injection():
    """devices.probe_tpu_runtime's fault seam: the wedged verdict (and so
    the whole watchdog->exit->restart chain) is reachable without a chip."""
    from kukeon_tpu.runtime.devices import probe_tpu_runtime

    os.environ[faults.ENV] = "devices.probe_wedged:1"
    status, detail = probe_tpu_runtime(timeout_s=5)
    assert status == "wedged"
    assert "fault-injected" in detail


@pytest.mark.faults
def test_watchdog_default_probe_uses_devices_seam():
    """EngineWatchdog with no probe override consults the real
    probe_tpu_runtime — wired shut by the fault seam, no subprocess."""
    eng = _StalledEngine()
    eng.last_progress -= 10
    hits: list[str] = []
    os.environ[faults.ENV] = "devices.probe_wedged:1"
    wd = _watchdog(eng, probe=None, on_wedged=hits.append)
    wd.start()
    wd.join(timeout=10)
    assert wd.tripped
    assert hits and "fault-injected" in hits[0]


@pytest.mark.faults
def test_wedged_cell_exits_nonzero_end_to_end(tmp_path):
    """Full chain in a real cell process: KUKEON_FAULTS makes the runtime
    probe report wedged; a request stalls the engine past the (tiny)
    watchdog budget (its first step sits in jit compilation — a genuine
    multi-second device-side stall); the watchdog trips and the process
    exits WEDGED_EXIT_CODE — the exit the runner's restart policy turns
    into a restart on the same chip grant
    (test_runner_restart_edges.test_crash_looping_model_cell_keeps_its_chip_grant)."""
    import socket as _socket
    import subprocess
    import sys
    import urllib.request

    from kukeon_tpu.runtime.serving_cell import WEDGED_EXIT_CODE

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "KUKEON_WATCHDOG_S": "0.3",
        "KUKEON_WATCHDOG_PROBE_TIMEOUT_S": "5",
        "KUKEON_FAULTS": "devices.probe_wedged:1",
        # A fresh compilation cache: the stall under test IS the compile.
        "KUKEON_JAX_CACHE_DIR": str(tmp_path / "jax-cache"),
    })
    log = open(tmp_path / "cell.log", "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "kukeon_tpu.runtime.serving_cell",
         "--model", "tiny", "--port", str(port), "--no-warmup",
         "--max-seq-len", "64", "--num-slots", "2"],
        env=env, stdout=log, stderr=log,
    )
    try:
        deadline = time.monotonic() + 240
        while True:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2).read()
                break
            except Exception:  # noqa: BLE001 — still booting
                if proc.poll() is not None:
                    raise AssertionError(
                        f"cell died before serving: rc={proc.returncode}, "
                        f"log:\n{(tmp_path / 'cell.log').read_bytes().decode(errors='replace')[-2000:]}"
                    ) from None
                assert time.monotonic() < deadline, "cell never came up"
                time.sleep(0.2)

        def fire():
            try:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/generate",
                    data=json.dumps({"prompt": "hi",
                                     "maxNewTokens": 32}).encode(),
                    headers={"Content-Type": "application/json"}),
                    timeout=120).read()
            except Exception:  # noqa: BLE001 — the cell dies under us; expected
                pass

        threading.Thread(target=fire, daemon=True).start()
        rc = proc.wait(timeout=120)
        assert rc == WEDGED_EXIT_CODE
        tail = (tmp_path / "cell.log").read_bytes().decode(errors="replace")
        assert "watchdog tripped" in tail
    finally:
        if proc.poll() is None:
            proc.kill()
        log.close()


# --- HTTP lifecycle ---------------------------------------------------------


@pytest.fixture(scope="module")
def http_cell():
    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    cell = ServingCell("tiny", num_slots=1, max_seq_len=96, checkpoint=None,
                       dtype=None, max_pending=2)
    cell.engine.start()
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield cell, server.server_address[1]
    server.shutdown()
    server.server_close()
    cell.engine.stop()


def _req(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, (json.loads(raw) if raw else {}), headers


class TestHTTPLifecycle:
    """One cell through its whole life: unready -> ready -> shedding ->
    timing out -> draining. Ordered; later tests depend on earlier state."""

    def test_unready_until_marked(self, http_cell):
        cell, port = http_cell
        status, body, _ = _req(port, "GET", "/healthz")
        assert status == 200                       # alive even while warming
        status, body, _ = _req(port, "GET", "/readyz")
        assert status == 503 and body["ready"] is False
        assert "warming" in body["reason"]
        # Admission is lifecycle-gated: 503 + Retry-After, not a hang.
        status, body, headers = _req(port, "POST", "/v1/generate",
                                     {"prompt": "hi", "maxNewTokens": 2})
        assert status == 503
        assert int(headers["Retry-After"]) >= 1

    def test_ready_serves(self, http_cell):
        cell, port = http_cell
        cell.mark_ready()
        status, body, _ = _req(port, "GET", "/readyz")
        assert status == 200 and body["ready"] is True
        status, body, _ = _req(port, "POST", "/v1/generate",
                               {"prompt": "hi", "maxNewTokens": 3,
                                "deadlineS": 60})
        assert status == 200
        assert body["numTokens"] == 3

    def test_queue_full_returns_429_with_retry_after(self, http_cell):
        cell, port = http_cell
        eng = cell.engine
        eng.stop()                                 # freeze the driver
        try:
            held = [eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
                    for _ in range(2)]             # fill max_pending=2
            status, body, headers = _req(port, "POST", "/v1/generate",
                                         {"prompt": "hi", "maxNewTokens": 2})
            assert status == 429
            assert "Retry-After" in headers
            assert "queue full" in body["error"]
            status, stats, _ = _req(port, "GET", "/v1/stats")
            assert stats["rejected"] >= 1
            assert stats["queueDepth"] == 2
            assert stats["maxPending"] == 2
        finally:
            eng.start()                            # thaw; held reqs drain
        for r in held:
            assert r.done.wait(60)

    def test_deadline_timeout_is_in_band(self, http_cell):
        cell, port = http_cell
        hog = cell.engine.submit(PROMPT, SamplingParams(max_new_tokens=80))
        try:
            # Non-streaming: the timeout surfaces as 504 Gateway Timeout.
            status, body, _ = _req(port, "POST", "/v1/generate",
                                   {"prompt": "hi", "maxNewTokens": 4,
                                    "deadlineS": 0.01})
            assert status == 504
            assert body["timedOut"] is True
            # Streaming: headers are long gone when a mid-stream deadline
            # hits, so the timeout is an in-band terminal ndjson record.
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            conn.request("POST", "/v1/generate", body=json.dumps(
                {"prompt": "hi", "maxNewTokens": 4, "deadlineS": 0.01,
                 "stream": True}), headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            lines = [json.loads(x) for x in resp.read().decode().splitlines()]
            conn.close()
            assert lines[-1].get("timedOut") is True
            assert "deadline" in lines[-1]["error"]
            status, stats, _ = _req(port, "GET", "/v1/stats")
            assert stats["timedOut"] >= 2
        finally:
            hog.cancel()

    def test_drain_finishes_inflight_then_unready(self, http_cell):
        cell, port = http_cell
        inflight = cell.engine.submit(PROMPT,
                                      SamplingParams(max_new_tokens=24))
        status, body, _ = _req(port, "POST", "/drain")
        assert status == 200 and body["draining"] is True
        status, body, _ = _req(port, "GET", "/readyz")
        assert status == 503 and body["reason"] == "draining"
        # New work is refused while draining...
        status, body, headers = _req(port, "POST", "/v1/generate",
                                     {"prompt": "hi", "maxNewTokens": 2})
        assert status == 503 and "Retry-After" in headers
        # ...but the in-flight request FINISHES (never killed mid-decode).
        assert cell.drained.wait(30)
        assert inflight.done.is_set()
        assert len(inflight.generated) == 24
        assert not inflight.cancelled and inflight.error is None
        assert not cell.engine._running            # engine shut down
        # Drain is idempotent.
        assert cell.begin_drain() is False
