"""Serving tune profiles: persistence round-trip, stale-key hygiene, and
the autotune → engine/cell boot seam (ISSUE 1 tentpole)."""

import json

import jax
import numpy as np
import pytest

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine
from kukeon_tpu.serving import tuning


@pytest.fixture()
def tune_path(tmp_path, monkeypatch):
    p = str(tmp_path / "serving_tune.json")
    monkeypatch.setenv("KUKEON_TUNE_PATH", p)
    return p


class TestProfileFile:
    def test_round_trip(self, tune_path):
        t = tuning.ServingTune(decode_chunk=64, kv_cache_int8=True,
                               prefill_buckets=(128, 32), tok_per_s=261.2)
        assert tuning.save("llama3-8b", "tpu", 1, t) == tune_path
        got = tuning.load("llama3-8b", "tpu", 1)
        assert got.decode_chunk == 64
        assert got.kv_cache_int8 is True
        assert got.prefill_buckets == (32, 128)   # normalized sorted
        assert got.tok_per_s == 261.2
        assert got.tuned_at            # stamped at save time

    def test_keys_coexist(self, tune_path):
        tuning.save("llama3-8b", "tpu", 1, tuning.ServingTune(decode_chunk=64))
        tuning.save("tiny", "cpu", 1, tuning.ServingTune(decode_chunk=4))
        tuning.save("llama3-8b", "tpu", 8, tuning.ServingTune(decode_chunk=16))
        assert tuning.load("llama3-8b", "tpu", 1).decode_chunk == 64
        assert tuning.load("tiny", "cpu", 1).decode_chunk == 4
        assert tuning.load("llama3-8b", "tpu", 8).decode_chunk == 16

    def test_stale_key_is_a_miss(self, tune_path):
        """A profile tuned for another model, backend, or chip-count must
        never be applied."""
        tuning.save("llama3-8b", "tpu", 1, tuning.ServingTune(decode_chunk=64))
        assert tuning.load("llama3-1b", "tpu", 1) is None
        assert tuning.load("llama3-8b", "cpu", 1) is None
        assert tuning.load("llama3-8b", "tpu", 8) is None
        assert tuning.load(None, "tpu", 1) is None

    def test_corrupt_or_missing_file_degrades(self, tune_path):
        assert tuning.load("tiny", "cpu", 1) is None     # missing
        with open(tune_path, "w") as f:
            f.write("{ not json")
        assert tuning.load("tiny", "cpu", 1) is None     # corrupt
        with open(tune_path, "w") as f:
            json.dump({"tiny|cpu|1": {"kv_cache_int8": True}}, f)
        assert tuning.load("tiny", "cpu", 1) is None     # malformed entry
        # And save repairs the file rather than crashing on it.
        tuning.save("tiny", "cpu", 1, tuning.ServingTune(decode_chunk=4))
        assert tuning.load("tiny", "cpu", 1).decode_chunk == 4


class TestEngineBootPickup:
    def _build(self, **kw):
        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
        return ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                             **kw)

    def test_fresh_engine_loads_profile(self, tune_path):
        """The acceptance seam: a fresh ServingEngine boot picks up every
        persisted lever — chunk size, int8 KV (visible in the allocated
        cache), bucket ladder — and still generates correctly."""
        tuning.save("tiny", jax.default_backend(), 1, tuning.ServingTune(
            decode_chunk=64, kv_cache_int8=True, prefill_buckets=(32, 128)))
        eng = self._build(model_name="tiny")
        assert eng.tune is not None
        assert eng.decode_chunk == 64
        assert eng.kv_cache_int8 and eng.state.cache.quantized
        assert eng.prefill_buckets == (32, 128)
        toks = eng.generate(np.arange(1, 9, dtype=np.int32),
                            SamplingParams(max_new_tokens=4))
        assert len(toks) == 4

    def test_explicit_args_beat_profile(self, tune_path):
        tuning.save("tiny", jax.default_backend(), 1, tuning.ServingTune(
            decode_chunk=64, kv_cache_int8=True))
        eng = self._build(model_name="tiny", decode_chunk=8,
                          kv_cache_int8=False)
        assert eng.decode_chunk == 8
        assert not eng.kv_cache_int8 and not eng.state.cache.quantized

    def test_stale_profile_boots_defaults(self, tune_path):
        tuning.save("llama3-8b", jax.default_backend(), 1,
                    tuning.ServingTune(decode_chunk=64, kv_cache_int8=True))
        eng = self._build(model_name="tiny")
        assert eng.tune is None
        assert eng.decode_chunk == 16          # default
        assert not eng.kv_cache_int8

    def test_no_model_name_never_reads_profile(self, tune_path):
        with open(tune_path, "w") as f:
            f.write("{ not json")   # would explode if read un-defensively
        eng = self._build()
        assert eng.tune is None and eng.decode_chunk == 16


def test_serving_cell_boots_from_profile(tune_path, monkeypatch):
    """ServingCell passes its model name through, so the HTTP cell boots at
    the swept winner and reports it in /v1/stats."""
    from kukeon_tpu.runtime.serving_cell import ServingCell

    n_chips = len(jax.devices())
    tuning.save("tiny", jax.default_backend(), n_chips,
                tuning.ServingTune(decode_chunk=4, tok_per_s=99.0))
    cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                       checkpoint=None, dtype=None)
    assert cell.engine.decode_chunk == 4
    t = cell.stats()["tuning"]
    assert t == {"decodeChunk": 4, "kvCacheInt8": False, "kvPageTokens": 0,
                 "fromProfile": True}
    out = cell.generate({"prompt": "hello", "maxNewTokens": 4})
    assert out["numTokens"] == 4
