"""Ulysses all-to-all sequence parallelism: exact parity with reference
attention, composition with data+tensor axes, and the train-step hookup."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kukeon_tpu.ops.attention import attention_mask, attention_reference, repeat_kv
from kukeon_tpu.parallel import make_mesh, set_mesh, ulysses_attention


def _ref(q, k, v, positions):
    n_rep = q.shape[2] // k.shape[2]
    mask = attention_mask(positions, positions)
    return attention_reference(q, repeat_kv(k, n_rep), repeat_kv(v, n_rep), mask)


def test_ulysses_matches_reference():
    B, S, NH, NKV, D = 2, 32, 8, 4, 16
    kq, kk, kv_ = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (B, S, NH, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, NKV, D), jnp.float32)
    v = jax.random.normal(kv_, (B, S, NKV, D), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    ref = _ref(q, k, v, positions)

    mesh = make_mesh(seq=4, data=2)
    with set_mesh(mesh):
        out = jax.jit(
            lambda *a: ulysses_attention(
                a[0], a[1], a[2], q_positions=a[3], kv_positions=a[3], mesh=mesh
            )
        )(q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_composes_with_tensor_axis():
    """seq=2 x tensor=2: heads shard over tensor AND re-shard over seq."""
    B, S, NH, NKV, D = 2, 16, 8, 4, 8
    kq, kk, kv_ = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, (B, S, NH, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, NKV, D), jnp.float32)
    v = jax.random.normal(kv_, (B, S, NKV, D), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    ref = _ref(q, k, v, positions)

    mesh = make_mesh(seq=2, tensor=2, data=2)
    with set_mesh(mesh):
        out = jax.jit(
            lambda *a: ulysses_attention(
                a[0], a[1], a[2], q_positions=a[3], kv_positions=a[3], mesh=mesh
            )
        )(q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_head_divisibility_rejected():
    """kv heads not divisible by the seq axis -> clear error naming ring."""
    B, S, NH, NKV, D = 2, 16, 8, 2, 8
    q = jnp.zeros((B, S, NH, D), jnp.float32)
    k = jnp.zeros((B, S, NKV, D), jnp.float32)
    v = jnp.zeros((B, S, NKV, D), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    mesh = make_mesh(seq=4, data=2)
    with set_mesh(mesh):
        with pytest.raises(ValueError, match="ring"):
            jax.jit(
                lambda *a: ulysses_attention(
                    a[0], a[1], a[2], q_positions=a[3], kv_positions=a[3],
                    mesh=mesh,
                )
            )(q, k, v, positions)


def test_train_step_with_ulysses_attention():
    """A llama train step with attn_impl='ulysses' over a seq-sharded mesh
    produces the same loss as the ring and plain paths."""
    import dataclasses

    from kukeon_tpu.models import llama
    from kukeon_tpu.training import create_train_state
    from kukeon_tpu.training.train_step import make_optimizer, make_train_step

    cfg = dataclasses.replace(llama.llama_tiny(), num_heads=8, num_kv_heads=4)
    losses = {}
    for impl, seq in (("ulysses", 2), ("ring", 2), ("auto", 1)):
        mesh = make_mesh(seq=seq, data=8 // seq // 2, tensor=2)
        with set_mesh(mesh):
            opt = make_optimizer(warmup_steps=1, total_steps=10)
            state, opt = create_train_state(cfg, mesh, jax.random.key(0), opt)
            # use_ring_attention=False so we control attn_impl directly
            import functools

            from kukeon_tpu.training.train_step import cross_entropy_loss

            B, S = 4, 32
            tokens = jax.random.randint(jax.random.key(1), (B, S), 0,
                                        cfg.vocab_size)
            targets = jnp.roll(tokens, -1, axis=1)
            mask = jnp.ones((B, S), jnp.float32)
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :],
                                         (B, S))

            @jax.jit
            def loss_fn(params, tokens, targets, mask, positions, impl=impl):
                logits, _ = llama.forward(params, cfg, tokens, positions,
                                          attn_impl=impl)
                return cross_entropy_loss(logits, targets, mask)

            losses[impl] = float(loss_fn(state.params, tokens, targets, mask,
                                         positions))
    assert losses["ulysses"] == pytest.approx(losses["auto"], rel=1e-5)
    assert losses["ring"] == pytest.approx(losses["auto"], rel=1e-5)
