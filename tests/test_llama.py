"""Model correctness: shapes, cache-vs-full equivalence, causality."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kukeon_tpu.models import llama


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, params


def test_forward_shapes(tiny):
    cfg, params = tiny
    B, S = 2, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    logits, cache = llama.forward(params, cfg, tokens, positions)
    assert logits.shape == (B, S, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache is None
    assert np.isfinite(np.asarray(logits)).all()


def test_param_count_matches(tiny):
    cfg, params = tiny
    total = sum(x.size for x in jax.tree.leaves(params))
    assert total == cfg.param_count()


def test_cached_decode_matches_full_forward(tiny):
    """Prefill + token-by-token decode must equal one full forward pass."""
    cfg, params = tiny
    B, S = 2, 12
    prefill_len = 8
    tokens = jax.random.randint(jax.random.key(2), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))

    full_logits, _ = llama.forward(params, cfg, tokens, positions)

    cache = llama.KVCache.create(cfg, B, max_len=32)
    logits_p, cache = llama.forward(
        params, cfg, tokens[:, :prefill_len], positions[:, :prefill_len], cache
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(full_logits[:, :prefill_len]),
        rtol=2e-4, atol=2e-4,
    )

    for t in range(prefill_len, S):
        logits_t, cache = llama.forward(
            params, cfg, tokens[:, t : t + 1], positions[:, t : t + 1], cache
        )
        np.testing.assert_allclose(
            np.asarray(logits_t[:, 0]), np.asarray(full_logits[:, t]),
            rtol=2e-4, atol=2e-4,
        )
    assert int(cache.lengths[0]) == S


def test_causality(tiny):
    """Changing a future token must not change past logits."""
    cfg, params = tiny
    B, S = 1, 10
    tokens = jax.random.randint(jax.random.key(3), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    logits_a, _ = llama.forward(params, cfg, tokens, positions)

    tokens_b = tokens.at[0, -1].set((tokens[0, -1] + 1) % cfg.vocab_size)
    logits_b, _ = llama.forward(params, cfg, tokens_b, positions)

    np.testing.assert_allclose(
        np.asarray(logits_a[:, :-1]), np.asarray(logits_b[:, :-1]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(logits_a[:, -1]), np.asarray(logits_b[:, -1]))


def test_int8_kv_cache_decode_tracks_full_forward(tiny):
    """Quantized-cache prefill + decode must track the exact full forward
    within int8 quantization noise (per-token per-head scales keep the
    relative error ~0.4% per element)."""
    cfg, params = tiny
    B, S = 2, 12
    prefill_len = 8
    tokens = jax.random.randint(jax.random.key(7), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))

    full_logits, _ = llama.forward(params, cfg, tokens, positions)

    cache = llama.KVCache.create(cfg, B, max_len=32, quantized=True)
    assert cache.quantized and cache.k.dtype == jnp.int8
    logits_p, cache = llama.forward(
        params, cfg, tokens[:, :prefill_len], positions[:, :prefill_len], cache
    )
    assert cache.k.dtype == jnp.int8 and cache.k_scale.dtype == jnp.float32

    got = [np.asarray(logits_p[:, t]) for t in range(prefill_len)]
    for t in range(prefill_len, S):
        logits_t, cache = llama.forward(
            params, cfg, tokens[:, t : t + 1], positions[:, t : t + 1], cache
        )
        got.append(np.asarray(logits_t[:, 0]))
    assert int(cache.lengths[0]) == S

    want = np.asarray(full_logits)
    for t in range(S):
        a, b = got[t].ravel(), want[:, t].ravel()
        cos = np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b))
        assert cos > 0.999, f"step {t}: cosine {cos}"
        np.testing.assert_allclose(a, b, rtol=0.08, atol=0.08)


def test_int8_kv_roundtrip_error_bounded(tiny):
    cfg, _ = tiny
    x = jax.random.normal(jax.random.key(9), (2, 16, cfg.num_kv_heads, 32))
    q, s = llama.quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    back = np.asarray(q, np.float32) * np.asarray(s)[..., None]
    err = np.abs(back - np.asarray(x))
    # Symmetric int8 rounding error <= scale/2 per element.
    assert (err <= np.asarray(s)[..., None] / 2 + 1e-6).all()


def test_int8_pallas_decode_parity(tiny):
    """cfg.int8_pallas routes the fused decode's quantized matmuls through
    ops/int8_matmul (XLA fallback off-TPU); the decode logits must match
    the dequant-in-dot path (ISSUE 1 parity criterion)."""
    import dataclasses

    cfg, params = tiny
    qp = llama.quantize_params(params)
    cfg_pl = dataclasses.replace(cfg, int8_pallas=True)
    B, S = 2, 8
    tokens = jax.random.randint(jax.random.key(3), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    cache = llama.KVCache.create(cfg, B, 32)
    _, cache = llama.forward(qp, cfg, tokens, positions, cache)

    step = jax.random.randint(jax.random.key(4), (B, 1), 0, cfg.vocab_size)
    step_pos = cache.lengths[:, None]
    want, _ = llama.forward(qp, cfg, step, step_pos, cache)
    got, _ = llama.forward(qp, cfg_pl, step, step_pos, cache)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_logit_positions_matches_full_head(tiny):
    """logit_positions computes the LM head at one position per sequence;
    the row must equal the same row of the full-head logits (the prefill
    fast path must not change sampled tokens)."""
    cfg, params = tiny
    B, S = 2, 12
    tokens = jax.random.randint(jax.random.key(5), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    full, _ = llama.forward(params, cfg, tokens, positions)
    idx = jnp.asarray([3, S - 1], jnp.int32)
    one, _ = llama.forward(params, cfg, tokens, positions,
                           logit_positions=idx)
    assert one.shape == (B, 1, cfg.vocab_size)
    for b in range(B):
        np.testing.assert_array_equal(np.asarray(one[b, 0]),
                                      np.asarray(full[b, int(idx[b])]))
