"""The alerting engine (obs/alerts.py) and the daemon telemetry loop
(FleetTelemetry): rule validation, the pending->firing->resolved state
machine (pending never fires early; resolved clears), per-cell scrape
health, webhook/exemplar decoration, the Query/Alerts RPCs + CLI, and the
acceptance spine — a fake-backend fleet scraped for 30+ ticks whose
deadline storm flips the SLO-burn alert within two scrape intervals and
resolves after the storm."""

from __future__ import annotations

import argparse
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kukeon_tpu import obs
from kukeon_tpu.obs import Registry, SloTracker, expo
from kukeon_tpu.obs import alerts as alerts_mod
from kukeon_tpu.obs import federate as fed
from kukeon_tpu.obs.alerts import (
    BUILTIN_RULES,
    AlertEngine,
    Rule,
    load_user_rules,
    validate_rule,
)
from kukeon_tpu.obs.tsdb import TSDB, parse_expr
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.cells import FakeBackend
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.daemon import FleetTelemetry, RPCService
from kukeon_tpu.runtime.devices import TPUDeviceManager
from kukeon_tpu.runtime.errors import InvalidArgument
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import Runner, RunnerOptions
from kukeon_tpu.runtime.store import ResourceStore

from test_federation import _free_port
from test_obs import _parse_expo


def _fam(name: str, kind: str, *samples) -> dict:
    return {name: fed.Family(name, kind, "", [
        (name, dict(labels), str(value)) for labels, value in samples])}


# --- rule validation ---------------------------------------------------------


def test_validate_rule_names_every_problem():
    ok = {"name": "r", "expr": "kukeon_g", "agg": "max", "window": "1m",
          "op": ">", "threshold": 5}
    r = validate_rule(ok)
    assert r.window_s == 60.0 and r.threshold == 5.0 and r.for_s == 0.0
    cases = (
        ({**ok, "agg": "median"}, "agg"),
        ({**ok, "op": ">="}, "op"),
        ({**ok, "severity": "sev1"}, "severity"),
        ({**ok, "window": "nope"}, "window"),
        ({**ok, "threshold": "high"}, "threshold"),
        ({**ok, "expr": "a / b / c"}, "'/'"),
        ({**ok, "bogus": 1}, "bogus"),
        ({k: v for k, v in ok.items() if k != "expr"}, "expr"),
        ("not a mapping", "mapping"),
    )
    for doc, needle in cases:
        with pytest.raises(ValueError, match=needle):
            validate_rule(doc)


def test_load_user_rules_file_inline_and_yaml(tmp_path, monkeypatch):
    doc = [{"name": "QueueDeep", "expr": "kukeon_engine_queue_depth",
            "agg": "avg", "window": "2m", "op": ">", "threshold": 5,
            "for": "30s", "severity": "info"}]
    # Inline JSON via the env var.
    monkeypatch.setenv(alerts_mod.RULES_ENV, json.dumps(doc))
    (rule,) = load_user_rules()
    assert rule.name == "QueueDeep" and rule.for_s == 30.0
    # JSON file path.
    p = tmp_path / "rules.json"
    p.write_text(json.dumps(doc))
    assert load_user_rules(str(p)) == (rule,)
    # YAML file path.
    y = tmp_path / "rules.yaml"
    y.write_text("- name: QueueDeep\n  expr: kukeon_engine_queue_depth\n"
                 "  agg: avg\n  window: 2m\n  op: '>'\n  threshold: 5\n"
                 "  for: 30s\n  severity: info\n")
    assert load_user_rules(str(y)) == (rule,)
    # A single mapping is a list of one; unset/empty spec is no rules.
    assert load_user_rules(json.dumps(doc[0])) == (rule,)
    assert load_user_rules("") == ()
    # Shadowing a built-in (or duplicating) is an error, as is garbage.
    with pytest.raises(ValueError, match="duplicate"):
        load_user_rules(json.dumps(doc + doc))
    with pytest.raises(ValueError, match="duplicate"):
        load_user_rules(json.dumps([{**doc[0], "name": "SloBurnFast"}]))
    with pytest.raises(ValueError, match="cannot read"):
        load_user_rules(str(tmp_path / "missing.json"))
    with pytest.raises(ValueError, match="mapping"):
        load_user_rules("[1, 2]")


def test_builtin_rules_are_well_formed():
    from kukeon_tpu.obs.tsdb import AGGS
    names = set()
    for r in BUILTIN_RULES:
        assert r.name not in names
        names.add(r.name)
        assert r.agg in AGGS and r.op in alerts_mod.OPS
        assert r.severity in alerts_mod.SEVERITIES
        assert r.window_s > 0 and r.for_s >= 0
        parse_expr(r.expr)                   # must be parseable
    assert {"SloBurnFast", "SloBurnSlow", "ContainerRestartLoop",
            "HbmPressure", "QueueSaturation", "CellScrapeDown",
            "ColdStartRegression"} <= names


# --- state machine -----------------------------------------------------------


def _engine(rule, clock, registry=None, webhook=None):
    db = TSDB(retention_s=3600, clock=clock)
    eng = AlertEngine(db, rules=(rule,), registry=registry, clock=clock,
                      webhook_url=webhook or "")
    return db, eng


def test_for_duration_pending_never_fires_early():
    now = [0.0]
    clock = lambda: now[0]
    reg = Registry()
    rule = Rule(name="G", expr="kukeon_g", agg="latest", window_s=60,
                op=">", threshold=5, for_s=25, severity="critical")
    db, eng = _engine(rule, clock, registry=reg)
    firing = reg.get("kukeon_alerts_firing")
    transitions = []
    for at in (0, 10, 20):
        now[0] = at
        db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, 9)), at=at)
        transitions += eng.evaluate(at=at)
        # Breaching but inside for_s: pending, never firing.
        assert transitions == []
        (st,) = [s for s in eng.states() if s.get("labels")]
        assert st["state"] == "pending" and st["since"] == 0
        assert firing.value(alert="G", severity="critical") == 0
    now[0] = 30
    db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, 9)), at=30)
    (tr,) = eng.evaluate(at=30)
    assert tr["state"] == "firing" and tr["cell"] == "a"
    assert firing.value(alert="G", severity="critical") == 1
    # Breach clears -> resolved transition, state back to ok, gauge to 0.
    now[0] = 40
    db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, 1)), at=40)
    (tr,) = eng.evaluate(at=40)
    assert tr["state"] == "resolved"
    assert [s["state"] for s in eng.states()] == ["ok"]
    assert firing.value(alert="G", severity="critical") == 0
    assert [t_["state"] for t_ in eng.transitions()] == [
        "firing", "resolved"]


def test_for_zero_fires_on_first_breaching_tick():
    now = [0.0]
    rule = Rule(name="G", expr="kukeon_g", agg="latest", window_s=60,
                op=">", threshold=5, for_s=0)
    db, eng = _engine(rule, lambda: now[0])
    db.ingest(_fam("kukeon_g", "gauge", ({}, 9)), at=0)
    (tr,) = eng.evaluate(at=0)
    assert tr["state"] == "firing"


def test_pending_that_clears_cancels_silently():
    now = [0.0]
    rule = Rule(name="G", expr="kukeon_g", agg="latest", window_s=60,
                op=">", threshold=5, for_s=30)
    db, eng = _engine(rule, lambda: now[0])
    db.ingest(_fam("kukeon_g", "gauge", ({}, 9)), at=0)
    assert eng.evaluate(at=0) == []
    db.ingest(_fam("kukeon_g", "gauge", ({}, 1)), at=10)
    assert eng.evaluate(at=10) == []
    assert eng.transitions() == []          # the near-miss made no noise
    assert [s["state"] for s in eng.states()] == ["ok"]


def test_alerts_fire_per_labelset():
    now = [0.0]
    reg = Registry()
    rule = Rule(name="G", expr="kukeon_g", agg="latest", window_s=60,
                op=">", threshold=5, for_s=0, severity="warning")
    db, eng = _engine(rule, lambda: now[0], registry=reg)
    db.ingest(_fam("kukeon_g", "gauge",
                   ({"cell": "a"}, 9), ({"cell": "b"}, 1),
                   ({"cell": "c"}, 7)), at=0)
    trs = eng.evaluate(at=0)
    assert sorted(tr["cell"] for tr in trs) == ["a", "c"]
    assert reg.get("kukeon_alerts_firing").value(
        alert="G", severity="warning") == 2


def test_transition_carries_exemplar_trace_id():
    now = [0.0]
    rule = Rule(name="G", expr="kukeon_slo_burn_rate", agg="latest",
                window_s=60, op=">", threshold=5, for_s=0,
                exemplar_family="kukeon_engine_ttft_seconds")
    db, eng = _engine(rule, lambda: now[0])
    reg = Registry()
    h = reg.histogram("kukeon_engine_ttft_seconds", "t")
    h.observe(1.5, exemplar="cd" * 16)
    fams = fed.parse(expo.render(reg))
    fed.inject_label(fams, cell="r/s/st/c")
    db.ingest(fams, at=0)
    db.ingest(_fam("kukeon_slo_burn_rate", "gauge",
                   ({"cell": "r/s/st/c"}, 50.0)), at=0)
    (tr,) = eng.evaluate(at=0)
    assert tr["trace_id"] == "cd" * 16 and tr["cell"] == "r/s/st/c"


def test_webhook_posts_transitions():
    got: list[dict] = []

    class Hook(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            got.append(json.loads(body))
            self.send_response(200)
            self.end_headers()

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Hook)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        now = [0.0]
        reg = Registry()
        rule = Rule(name="G", expr="kukeon_g", agg="latest", window_s=60,
                    op=">", threshold=5, for_s=0)
        db, eng = _engine(
            rule, lambda: now[0], registry=reg,
            webhook=f"http://127.0.0.1:{srv.server_address[1]}/hook")
        db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, 9)), at=0)
        eng.evaluate(at=0)
        deadline = time.monotonic() + 5
        while not got and time.monotonic() < deadline:
            time.sleep(0.02)
        assert got and got[0]["alert"] == "G"
        assert got[0]["state"] == "firing" and got[0]["cell"] == "a"
        deadline = time.monotonic() + 5
        while (reg.get("kukeon_alerts_webhook_total").value(result="ok")
               < 1 and time.monotonic() < deadline):
            time.sleep(0.02)
        assert reg.get("kukeon_alerts_webhook_total").value(
            result="ok") == 1
    finally:
        srv.shutdown()
        srv.server_close()


def test_webhook_retries_once_then_delivers(monkeypatch):
    """Satellite: one bounded retry with backoff — a single dropped POST
    must not lose a page. The alerts.webhook fault point fails exactly the
    first attempt; the retry delivers and is counted result=retried."""
    import os

    from kukeon_tpu import faults
    from kukeon_tpu.obs import alerts as alerts_mod

    got: list[dict] = []

    class Hook(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            got.append(json.loads(body))
            self.send_response(200)
            self.end_headers()

    monkeypatch.setattr(alerts_mod, "WEBHOOK_RETRY_BACKOFF_S", 0.05)
    srv = ThreadingHTTPServer(("127.0.0.1", 0), Hook)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        now = [0.0]
        reg = Registry()
        rule = Rule(name="G", expr="kukeon_g", agg="latest", window_s=60,
                    op=">", threshold=5, for_s=0)
        db, eng = _engine(
            rule, lambda: now[0], registry=reg,
            webhook=f"http://127.0.0.1:{srv.server_address[1]}/hook")
        os.environ[faults.ENV] = "alerts.webhook:1:1"   # first attempt only
        db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, 9)), at=0)
        eng.evaluate(at=0)
        deadline = time.monotonic() + 5
        while not got and time.monotonic() < deadline:
            time.sleep(0.02)
        assert got and got[0]["alert"] == "G"
        assert faults.fired("alerts.webhook") == 1
        deadline = time.monotonic() + 5
        while (reg.get("kukeon_alerts_webhook_total").value(result="retried")
               < 1 and time.monotonic() < deadline):
            time.sleep(0.02)
        assert reg.get("kukeon_alerts_webhook_total").value(
            result="retried") == 1
        assert reg.get("kukeon_alerts_webhook_total").value(result="ok") == 0
        assert reg.get("kukeon_alerts_webhook_total").value(
            result="error") == 0
    finally:
        srv.shutdown()
        srv.server_close()


def test_webhook_both_attempts_fail_counts_error(monkeypatch):
    import os

    from kukeon_tpu import faults
    from kukeon_tpu.obs import alerts as alerts_mod

    monkeypatch.setattr(alerts_mod, "WEBHOOK_RETRY_BACKOFF_S", 0.05)
    now = [0.0]
    reg = Registry()
    rule = Rule(name="G", expr="kukeon_g", agg="latest", window_s=60,
                op=">", threshold=5, for_s=0)
    db, eng = _engine(rule, lambda: now[0], registry=reg,
                      webhook="http://127.0.0.1:1/hook")
    os.environ[faults.ENV] = "alerts.webhook"           # every attempt
    db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, 9)), at=0)
    eng.evaluate(at=0)
    deadline = time.monotonic() + 5
    while (reg.get("kukeon_alerts_webhook_total").value(result="error") < 1
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert reg.get("kukeon_alerts_webhook_total").value(result="error") == 1
    assert faults.fired("alerts.webhook") == 2          # attempt + retry


def test_cmd_alerts_check_exit_codes(monkeypatch, capsys):
    """Satellite: `kuke alerts --check` is a health gate — 0 quiet,
    1 while anything is firing, 2 on a broken user-rules file."""
    from kukeon_tpu.runtime import cli

    payload = {"alerts": [
        {"alert": "SloBurnFast", "severity": "critical", "state": "ok",
         "expr": "e", "threshold": 1, "description": ""}],
        "transitions": []}

    class _Client:
        def call(self, method, **params):
            assert method == "Alerts"
            return payload

    monkeypatch.setattr(cli, "_client", lambda args: _Client())

    def run(check=True, as_json=False):
        return cli.cmd_alerts(argparse.Namespace(
            json=as_json, transitions=50, check=check))

    assert run() == 0
    assert "fleet healthy" in capsys.readouterr().out
    payload["alerts"][0]["state"] = "firing"
    payload["alerts"][0].update({"value": 12.0, "since": 0.0,
                                 "labels": {"cell": "a"}})
    assert run() == 1
    assert "SloBurnFast" in capsys.readouterr().err
    assert run(as_json=True) == 1
    capsys.readouterr()
    payload["alerts"][0]["state"] = "ok"
    for k in ("value", "since", "labels"):
        payload["alerts"][0].pop(k)
    payload["rulesError"] = "rule 'broken' is missing field 'expr'"
    assert run() == 2
    assert run(as_json=True) == 2
    capsys.readouterr()
    # Without --check the verb stays informational: always 0.
    assert run(check=False) == 0
    capsys.readouterr()


# --- the fake-backend fleet --------------------------------------------------


class _MetricsHandler(BaseHTTPRequestHandler):
    registry: Registry = None  # type: ignore[assignment]

    def log_message(self, fmt, *a):
        pass

    def do_GET(self):
        if self.path != "/metrics":
            self.send_response(404)
            self.end_headers()
            return
        body = expo.render(self.registry).encode()
        self.send_response(200)
        self.send_header("Content-Type", expo.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class _Fleet:
    """Two live model cells (real /metrics HTTP endpoints backed by
    registries with engine counters + a SloTracker) plus one dead port,
    under a fake-backend controller, with one injectable clock shared by
    the cells' SLO windows and the daemon's telemetry loop."""

    def __init__(self, tmp_path, dead_cell=True):
        self.now = 1_000_000.0
        self.clock = lambda: self.now
        store = ResourceStore(MetadataStore(str(tmp_path)))
        runner = Runner(store, FakeBackend(), cgroups=None,
                        devices=TPUDeviceManager(store.ms,
                                                 chips=[0, 1, 2, 3]),
                        options=RunnerOptions(stop_grace_s=0.2),
                        registry=obs.Registry())
        self.ctl = Controller(store, runner)
        self.ctl.bootstrap()
        self.servers = []
        self.cells: dict[str, tuple] = {}
        names = ["llm-a", "llm-b"] + (["llm-dead"] if dead_cell else [])
        for name in names:
            if name == "llm-dead":
                port = _free_port()
            else:
                reg = Registry()
                reg.gauge("kukeon_cell_ready", "r").set(1)
                reg.gauge("kukeon_cell_uptime_seconds", "u").set_function(
                    lambda: self.now - 999_000.0)
                c = reg.counter("kukeon_engine_requests_total", "req",
                                labels=("outcome",))
                h = reg.histogram("kukeon_engine_ttft_seconds", "ttft")
                reg.gauge("kukeon_engine_queue_depth", "q").set(1)
                SloTracker(reg, clock=self.clock)
                self.cells[name] = (c, h)
                handler = type("H", (_MetricsHandler,),
                               {"registry": reg})
                srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
                threading.Thread(target=srv.serve_forever,
                                 daemon=True).start()
                self.servers.append(srv)
                port = srv.server_address[1]
            self.ctl.create_cell(t.Document(
                kind=t.KIND_CELL, metadata=t.Metadata(name=name),
                spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                                  port=port))))
        self.svc = RPCService(self.ctl)
        # Swap in a clock-driven telemetry backbone (the RPC service built
        # one with the wall clock).
        self.svc.telemetry = FleetTelemetry(self.ctl, clock=self.clock)

    def tick(self, dt=10.0, ok=0, timeout=0, ttft=()):
        """Advance time, apply traffic to both cells, run one telemetry
        pass; returns the alert transitions it produced."""
        self.now += dt
        for c, h in self.cells.values():
            if ok:
                c.inc(ok, outcome="ok")
            if timeout:
                c.inc(timeout, outcome="timeout")
            for v in ttft:
                h.observe(v, exemplar="ab" * 16)
        return self.svc.telemetry.tick()

    def close(self):
        for srv in self.servers:
            srv.shutdown()
            srv.server_close()


@pytest.fixture
def fleet(tmp_path):
    f = _Fleet(tmp_path)
    yield f
    f.close()


def test_scrape_health_instruments_and_scrape_down_alert(fleet):
    """Satellite: per-cell scrape-duration histogram + consecutive-failure
    gauge distinguish flapping from dead, and the CellScrapeDown builtin
    fires for the dead cell only after its for: duration."""
    transitions = []
    for _ in range(4):                       # t+10 .. t+40
        transitions += fleet.tick(ok=2, ttft=(0.02,))
    reg = fleet.ctl.runner.registry
    dead = "default/default/default/llm-dead"
    live = "default/default/default/llm-a"
    assert reg.get("kukeon_daemon_scrape_failures_consecutive").value(
        cell=dead) == 4
    assert reg.get("kukeon_daemon_scrape_failures_consecutive").value(
        cell=live) == 0
    counts, _total, n = reg.get(
        "kukeon_daemon_scrape_duration_seconds").snapshot(cell=live)
    assert n == 4
    assert reg.get("kukeon_daemon_scrape_ticks_total").value() == 4
    # scrape_ok history is queryable like any other series.
    vals = dict((labels["cell"], v) for labels, v in
                fleet.svc.telemetry.tsdb.query(
                    "kukeon_cell_scrape_ok", 60, "max", at=fleet.now))
    assert vals[live] == 1.0 and vals[dead] == 0.0
    # CellScrapeDown: pending from the first tick, firing once the breach
    # held for 30s — and only for the dead cell.
    fired = [tr for tr in transitions if tr["alert"] == "CellScrapeDown"
             and tr["state"] == "firing"]
    assert [tr["cell"] for tr in fired] == [dead]
    # The dead cell leaving the fleet resolves its alert.
    fleet.ctl.delete_cell("default", "default", "default", "llm-dead",
                          True)
    resolved = []
    for _ in range(8):
        resolved += [tr for tr in fleet.tick(ok=1)
                     if tr["alert"] == "CellScrapeDown"]
    assert [tr["state"] for tr in resolved] == ["resolved"]


def test_user_rules_error_is_surfaced_not_fatal(fleet, monkeypatch):
    monkeypatch.setenv(alerts_mod.RULES_ENV, '[{"name": "broken"}]')
    telem = FleetTelemetry(fleet.ctl, clock=fleet.clock)
    assert telem.user_rules_error and "broken" in telem.user_rules_error
    assert telem.alerts.rules == BUILTIN_RULES   # builtins still armed
    fleet.svc.telemetry = telem
    out = fleet.svc.Alerts()
    assert "broken" in out["rulesError"]


def test_user_rule_rides_along_and_fires(fleet, monkeypatch):
    monkeypatch.setenv(alerts_mod.RULES_ENV, json.dumps([{
        "name": "QueueNonEmpty", "expr": "kukeon_engine_queue_depth",
        "agg": "max", "window": "1m", "op": ">", "threshold": 0.5,
        "severity": "info"}]))
    fleet.svc.telemetry = FleetTelemetry(fleet.ctl, clock=fleet.clock)
    trs = fleet.tick(ok=1)                    # queue depth is 1 on both
    fired = [tr for tr in trs if tr["alert"] == "QueueNonEmpty"]
    assert len(fired) == 2 and all(tr["severity"] == "info"
                                   for tr in fired)


def test_query_rpc_validates(fleet):
    with pytest.raises(InvalidArgument):
        fleet.svc.Query(expr="a / b / c")
    with pytest.raises(InvalidArgument):
        fleet.svc.Query(expr="kukeon_g", agg="median")
    with pytest.raises(InvalidArgument):
        fleet.svc.Query(expr="kukeon_g", windowS="sideways")


# --- acceptance: history, storm, resolution ----------------------------------


def test_acceptance_windowed_p95_and_slo_burn_storm(fleet, capsys,
                                                    monkeypatch):
    """The ISSUE 10 acceptance spine: the daemon scrapes 2 live cells for
    30+ ticks; `kuke query --agg p95 --window 5m` matches each cell's own
    histogram percentile within one bucket; a deadline storm flips
    SloBurnFast to firing within 2 scrape intervals and it resolves after
    the storm — both transitions visible in `kuke alerts` and in the
    federated kukeon_alerts_firing series."""
    ttft = (0.01, 0.03, 0.08)
    for _ in range(32):                       # >= 30 ticks of history
        fleet.tick(ok=3, ttft=ttft)
    assert fleet.svc.telemetry.tsdb.stats()["ingests"] >= 32

    out = fleet.svc.Query(expr="kukeon_engine_ttft_seconds",
                          windowS="5m", agg="p95")
    rows = {r["labels"]["cell"]: r["value"] for r in out["series"]}
    h = fleet.cells["llm-a"][1]
    exact = h.percentile(0.95)

    def bucket_index(v):
        return next((i for i, b in enumerate(h.buckets) if v <= b),
                    len(h.buckets))

    for name in ("llm-a", "llm-b"):
        got = rows[f"default/default/default/{name}"]
        assert abs(bucket_index(got) - bucket_index(exact)) <= 1

    # Deadline storm: most requests start timing out. The cell's own
    # 5m-window SloTracker burn spikes on the next scrape, and the
    # SloBurnFast rule (for: 0) must fire within 2 scrape intervals.
    storm_transitions = []
    for i in range(2):
        storm_transitions += [
            (i, tr) for tr in fleet.tick(timeout=20, ttft=(2.5,))]
    fired = [(i, tr) for i, tr in storm_transitions
             if tr["alert"] == "SloBurnFast" and tr["state"] == "firing"]
    assert len(fired) == 2                    # one per cell
    assert all(i == 0 for i, _tr in fired)    # first post-storm tick
    (tr0) = fired[0][1]
    assert tr0["severity"] == "critical"
    assert tr0["trace_id"] == "ab" * 16       # TTFT exemplar rides along

    # Firing census is a real federated metric: the daemon Metrics RPC
    # exposition carries kukeon_alerts_firing{alert="SloBurnFast"} 2.
    fams = _parse_expo(fleet.svc.Metrics(federate=False)["text"])
    firing = {lab["alert"]: float(v) for _n, lab, v
              in fams["kukeon_alerts_firing"]["samples"]}
    assert firing["SloBurnFast"] == 2

    # Storm ends; healthy traffic resumes. The cell's 5m SLO window
    # slides past the storm and the alert resolves.
    resolutions = []
    for _ in range(45):
        resolutions += [tr for tr in fleet.tick(ok=5, ttft=(0.02,))
                        if tr["alert"] == "SloBurnFast"]
    assert [tr["state"] for tr in resolutions] == ["resolved", "resolved"]
    fams = _parse_expo(fleet.svc.Metrics(federate=False)["text"])
    firing = {lab["alert"]: float(v) for _n, lab, v
              in fams["kukeon_alerts_firing"]["samples"]}
    assert firing["SloBurnFast"] == 0

    # Both transitions render in `kuke alerts`.
    from kukeon_tpu.runtime import cli

    class _Client:
        def call(self, method, **params):
            return getattr(fleet.svc, method)(**params)

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    assert cli.cmd_alerts(argparse.Namespace(json=False,
                                             transitions=50)) == 0
    rendered = capsys.readouterr().out
    assert "SloBurnFast -> firing" in rendered
    assert "SloBurnFast -> resolved" in rendered
    assert "trace=" + "ab" * 16 in rendered
    assert "ALERT" in rendered and "SEVERITY" in rendered


def test_cmd_query_renders_table_and_sparkline(fleet, capsys, monkeypatch):
    for _ in range(12):
        fleet.tick(ok=4, ttft=(0.02, 0.05))
    from kukeon_tpu.runtime import cli

    class _Client:
        def call(self, method, **params):
            return getattr(fleet.svc, method)(**params)

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    args = argparse.Namespace(json=False,
                              expr="kukeon_engine_requests_total{outcome=ok}",
                              window="2m", agg="rate", step="30s")
    assert cli.cmd_query(args) == 0
    out = capsys.readouterr().out
    assert "SERIES" in out and "RATE" in out and "TREND" in out
    assert "cell=default/default/default/llm-a" in out
    # A family with no history exits 1 with a hint, not a traceback.
    args = argparse.Namespace(json=False, expr="kukeon_never_seen",
                              window="2m", agg="avg", step=None)
    assert cli.cmd_query(args) == 1
    assert "no data" in capsys.readouterr().out
    # JSON mode emits the raw RPC result.
    args = argparse.Namespace(json=True, expr="kukeon_engine_queue_depth",
                              window="2m", agg="latest", step=None)
    assert cli.cmd_query(args) == 0
    assert '"series"' in capsys.readouterr().out


def test_kuke_top_watch_repaints_with_sparklines(fleet, capsys,
                                                 monkeypatch):
    for _ in range(12):
        fleet.tick(ok=4, ttft=(0.02, 0.05))
    from kukeon_tpu.runtime import cli

    class _Client:
        def call(self, method, **params):
            return getattr(fleet.svc, method)(**params)

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    paints = []

    def fake_sleep(_s):
        paints.append(1)
        if len(paints) >= 2:                 # two repaints, then exit
            raise KeyboardInterrupt

    monkeypatch.setattr(cli.time, "sleep", fake_sleep)
    args = argparse.Namespace(json=False, watch=True, interval=0.01)
    assert cli.cmd_top(args) == 0
    out = capsys.readouterr().out
    assert "\x1b[H\x1b[2J" in out            # in-place repaint
    assert out.count("CELL") >= 2            # the table painted twice
    assert "history:" in out and "qps" in out and "queue" in out
    # Non-watch mode is unchanged: single table, no history rows.
    monkeypatch.setattr(cli.time, "sleep",
                        lambda s: (_ for _ in ()).throw(AssertionError))
    args = argparse.Namespace(json=False)
    assert cli.cmd_top(args) == 0
    out = capsys.readouterr().out
    assert "CELL" in out and "history:" not in out


def test_telemetry_tick_rpc(fleet):
    out = fleet.svc.TelemetryTick()
    assert out == {"transitions": []}
    assert fleet.ctl.runner.registry.get(
        "kukeon_daemon_scrape_ticks_total").value() == 1
    assert fleet.svc.telemetry.tsdb.stats()["series"] > 0
