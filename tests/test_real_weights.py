"""Real-weights serving path: HF safetensors conversion, tokenizer.json
loading, int8 quantization (VERDICT r1 item 3).

No network access in CI, so "real" checkpoints are synthesized in the HF
hub layout (config.json + sharded safetensors + tokenizer.json) and
round-tripped through the exact code paths a downloaded Llama-3 would use.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kukeon_tpu.models import hf_convert, llama


def _tiny_hf_checkpoint(tmp_path, shards: int = 1, tie: bool = False):
    """Write a llama_tiny-shaped checkpoint in HF hub layout."""
    cfg = llama.llama_tiny()
    cfg = __import__("dataclasses").replace(cfg, tie_embeddings=tie)
    rng = np.random.default_rng(0)
    H, I, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": rng.standard_normal((V, H), np.float32),
        "model.norm.weight": np.ones(H, np.float32),
    }
    if not tie:
        tensors["lm_head.weight"] = rng.standard_normal((V, H), np.float32)
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        tensors.update({
            p + "input_layernorm.weight": np.ones(H, np.float32),
            p + "self_attn.q_proj.weight": rng.standard_normal((cfg.q_dim, H), np.float32),
            p + "self_attn.k_proj.weight": rng.standard_normal((cfg.kv_dim, H), np.float32),
            p + "self_attn.v_proj.weight": rng.standard_normal((cfg.kv_dim, H), np.float32),
            p + "self_attn.o_proj.weight": rng.standard_normal((H, cfg.q_dim), np.float32),
            p + "post_attention_layernorm.weight": np.ones(H, np.float32),
            p + "mlp.gate_proj.weight": rng.standard_normal((I, H), np.float32),
            p + "mlp.up_proj.weight": rng.standard_normal((I, H), np.float32),
            p + "mlp.down_proj.weight": rng.standard_normal((H, I), np.float32),
        })
    from safetensors.numpy import save_file

    names = sorted(tensors)
    if shards == 1:
        save_file(tensors, str(tmp_path / "model.safetensors"))
    else:
        weight_map = {}
        for si in range(shards):
            part = {n: tensors[n] for n in names[si::shards]}
            fname = f"model-{si + 1:05d}-of-{shards:05d}.safetensors"
            save_file(part, str(tmp_path / fname))
            weight_map.update({n: fname for n in part})
        (tmp_path / "model.safetensors.index.json").write_text(
            json.dumps({"weight_map": weight_map})
        )
    (tmp_path / "config.json").write_text(json.dumps({
        "vocab_size": V, "hidden_size": H, "intermediate_size": I,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "rope_theta": cfg.rope_theta, "rms_norm_eps": cfg.rms_norm_eps,
        "max_position_embeddings": cfg.max_seq_len,
        "tie_word_embeddings": tie,
    }))
    return cfg, tensors


class TestHFConvert:
    def test_single_file_roundtrip(self, tmp_path):
        cfg, tensors = _tiny_hf_checkpoint(tmp_path)
        params, loaded_cfg = hf_convert.load_params(str(tmp_path), dtype=jnp.float32)
        assert loaded_cfg.num_layers == cfg.num_layers
        assert loaded_cfg.tie_embeddings is False
        # HF [out, in] transposed into our [in, out], stacked on layers.
        np.testing.assert_allclose(
            np.asarray(params["layers"]["wq"][0]),
            tensors["model.layers.0.self_attn.q_proj.weight"].T,
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(params["lm_head"]),
            tensors["lm_head.weight"].T, rtol=1e-6,
        )
        # The loaded tree runs.
        tokens = jnp.array([[1, 2, 3, 4]], jnp.int32)
        pos = jnp.arange(4, dtype=jnp.int32)[None, :]
        logits, _ = llama.forward(params, loaded_cfg, tokens, pos)
        assert logits.shape == (1, 4, cfg.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    def test_sharded_index(self, tmp_path):
        cfg, tensors = _tiny_hf_checkpoint(tmp_path, shards=3)
        params, _ = hf_convert.load_params(str(tmp_path), dtype=jnp.float32)
        np.testing.assert_allclose(
            np.asarray(params["layers"]["w_down"][1]),
            tensors["model.layers.1.mlp.down_proj.weight"].T, rtol=1e-6,
        )

    def test_tied_embeddings(self, tmp_path):
        cfg, _ = _tiny_hf_checkpoint(tmp_path, tie=True)
        params, loaded_cfg = hf_convert.load_params(str(tmp_path), dtype=jnp.float32)
        assert loaded_cfg.tie_embeddings is True
        assert "lm_head" not in params

    def test_unmapped_tensor_rejected(self, tmp_path):
        """An architecture mismatch must fail loudly, not silently drop."""
        _tiny_hf_checkpoint(tmp_path)
        from safetensors import safe_open
        from safetensors.numpy import save_file

        path = str(tmp_path / "model.safetensors")
        with safe_open(path, framework="numpy") as f:
            tensors = {n: f.get_tensor(n) for n in f.keys()}
        tensors["model.mystery.weight"] = np.ones(4, np.float32)
        save_file(tensors, path)
        with pytest.raises(ValueError, match="unmapped"):
            hf_convert.load_params(str(tmp_path))


class TestQuantization:
    def test_prefill_close_and_decode_argmax_agrees(self):
        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        qp = llama.quantize_params(params)
        prompt = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32)[None, :], (2, 16))
        lg, _ = llama.forward(params, cfg, prompt, pos)
        lgq, _ = llama.forward(qp, cfg, prompt, pos)
        rel = float(jnp.abs(lg - lgq).max() / jnp.abs(lg).max())
        assert rel < 0.05

        cache_f = llama.KVCache.create(cfg, batch=2, max_len=64)
        cache_q = llama.KVCache.create(cfg, batch=2, max_len=64)
        _, cache_f = llama.forward(params, cfg, prompt, pos, cache=cache_f)
        _, cache_q = llama.forward(qp, cfg, prompt, pos, cache=cache_q)
        t = jnp.array([[5], [7]], jnp.int32)
        lg1, _ = llama.forward(params, cfg, t, cache_f.lengths[:, None], cache=cache_f)
        lg1q, _ = llama.forward(qp, cfg, t, cache_q.lengths[:, None], cache=cache_q)
        assert bool((lg1.argmax(-1) == lg1q.argmax(-1)).all())

    def test_quantized_bytes_halve(self):
        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        qp = llama.quantize_params(params)

        def nbytes(tree):
            return sum(x.size * x.dtype.itemsize
                       for x in jax.tree_util.tree_leaves(tree))

        # tiny is f32 -> int8 is ~4x smaller; scales add a little back.
        assert nbytes(qp) < nbytes(params) / 3

    def test_engine_serves_quantized(self):
        from kukeon_tpu.parallel import make_mesh
        from kukeon_tpu.serving import SamplingParams, ServingEngine

        cfg = llama.llama_tiny()
        qp = llama.quantize_params(llama.init_params(jax.random.key(0), cfg))
        mesh = make_mesh(tensor=2, devices=jax.devices()[:2])
        engine = ServingEngine(cfg, qp, mesh, num_slots=2, max_seq_len=128)
        out = engine.generate(
            np.array([3, 1, 4, 1, 5], np.int32),
            SamplingParams(temperature=0.0, max_new_tokens=8),
        )
        assert len(out) == 8


class TestTokenizer:
    def _write_tokenizer(self, tmp_path):
        from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

        tk = Tokenizer(models.BPE(unk_token=None))
        tk.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tk.decoder = decoders.ByteLevel()   # real Llama tokenizer.json has one
        trainer = trainers.BpeTrainer(
            vocab_size=300,
            special_tokens=["<|begin_of_text|>", "<|end_of_text|>"],
            initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        )
        tk.train_from_iterator(
            ["the quick brown fox jumps over the lazy dog"] * 50, trainer
        )
        path = tmp_path / "tokenizer.json"
        tk.save(str(path))
        return path

    def test_hf_tokenizer_roundtrip(self, tmp_path):
        from kukeon_tpu.serving.tokenizer import load_tokenizer

        self._write_tokenizer(tmp_path)
        tok = load_tokenizer(str(tmp_path))
        ids = tok.encode("the quick fox")
        assert ids[0] == tok.bos_id
        assert tok.decode(ids).strip() == "the quick fox"

    def test_byte_fallback(self, tmp_path):
        from kukeon_tpu.serving.tokenizer import ByteTokenizer, load_tokenizer

        tok = load_tokenizer(str(tmp_path))   # no tokenizer.json here
        assert isinstance(tok, ByteTokenizer)
        assert tok.decode(tok.encode("hello")) == "hello"
