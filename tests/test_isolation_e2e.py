"""Black-box e2e for the namespace isolation layer (VERDICT r1 item 2).

Proves — through the real daemon + CLI, no fakes — that cells are NOT bare
host processes: they live in their own PID/UTS/NET/mount namespaces, see an
image rootfs as '/', get a minimal /dev, and honor the security spec
(readOnlyRootFilesystem, capabilities). Reference behaviors:
internal/ctr/spec.go:309-511 (OCI security/mounts/devices),
cmd/kukepause/main.go (in-sandbox PID 1).

Root-gated: skipped unless the host can create namespaces.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import time

import pytest

from kukeon_tpu.runtime.cells import namespace as nsb

from tests.test_runtime_e2e import Daemon  # reuse the daemon harness

pytestmark = pytest.mark.skipif(
    not (os.geteuid() == 0 and os.access(nsb.KUKECELL, os.X_OK)),
    reason="namespace isolation needs root + kukecell",
)


@pytest.fixture
def daemon():
    d = Daemon()
    yield d
    d.stop()


def _apply(daemon, manifest: str):
    daemon.kuke("apply", "-f", "-", stdin_data=manifest)


def _wait_exit(daemon, cell: str, timeout: float = 15.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        p = daemon.kuke("get", "cell", cell, check=False)
        if "exited" in p.stdout or "stopped" in p.stdout.lower():
            return
        time.sleep(0.2)


def _log(daemon, cell: str) -> str:
    return daemon.kuke("log", cell).stdout


CHECK_MANIFEST = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: {name}}}
spec:
  containers:
    - name: main
      command: ["sh", "-c", {cmd!r}]
      restartPolicy: {{policy: never}}
"""


class TestHostRootfsIsolation:
    """Cells without an image keep the host filesystem but still get
    PID/UTS/NET/mount/dev isolation."""

    def test_uts_pid_net_dev(self, daemon):
        cmd = (
            "echo HOST=$(hostname);"
            "echo PROCS=$(ls /proc | grep -c '^[0-9]*$');"
            "echo COMM1=$(cat /proc/1/comm);"
            "echo NETLINKS=$(ls /sys/class/net | tr '\\n' ',');"
            "echo DEVNODES=$(ls /dev | tr '\\n' ',')"
        )
        _apply(daemon, CHECK_MANIFEST.format(name="isoprobe", cmd=cmd))
        _wait_exit(daemon, "isoprobe")
        log = _log(daemon, "isoprobe")
        assert "HOST=isoprobe" in log            # UTS: hostname = cell name
        assert "COMM1=kukepause" in log          # PID: kukepause is PID 1
        # PID ns: only kukepause + the probe shell (+ children) visible.
        procs = int(log.split("PROCS=")[1].split()[0])
        assert procs < 6
        # NET ns: loopback only (veth attach is a separate milestone).
        netlinks = log.split("NETLINKS=")[1].split()[0]
        assert netlinks.strip(",") == "lo"
        # /dev is masked: standard nodes only, no host block devices.
        devnodes = log.split("DEVNODES=")[1].split()[0]
        assert "null" in devnodes and "loop0" not in devnodes

    def test_default_caps_deny_mount(self, daemon):
        cmd = (
            "grep CapBnd /proc/self/status;"
            "mount -t tmpfs none /mnt 2>&1 || echo MOUNT_DENIED"
        )
        _apply(daemon, CHECK_MANIFEST.format(name="capprobe", cmd=cmd))
        _wait_exit(daemon, "capprobe")
        log = _log(daemon, "capprobe")
        assert "CapBnd:\t00000000a80425fb" in log  # docker default bounded set
        assert "MOUNT_DENIED" in log

    def test_added_capability(self, daemon):
        manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: capadd}
spec:
  containers:
    - name: main
      command: ["sh", "-c", "grep CapBnd /proc/self/status"]
      capabilities: [NET_ADMIN]
      restartPolicy: {policy: never}
"""
        _apply(daemon, manifest)
        _wait_exit(daemon, "capadd")
        log = _log(daemon, "capadd")
        # a80425fb | 1<<12 (NET_ADMIN) = a80435fb
        assert "CapBnd:\t00000000a80435fb" in log

    def test_sandbox_lifecycle(self, daemon):
        _apply(daemon, CHECK_MANIFEST.format(name="sbox", cmd="sleep 30"))
        time.sleep(1.0)
        # Find the sandbox pid through the run path.
        matches = []
        for root, _dirs, files in os.walk(daemon.run_path):
            if "sandbox.pid" in files and "/sbox" in root:
                matches.append(os.path.join(root, "sandbox.pid"))
        assert matches, "sandbox.pid not created"
        pid = int(open(matches[0]).read())
        assert os.path.exists(f"/proc/{pid}")
        with open(f"/proc/{pid}/comm") as f:
            assert f.read().strip() == "kukepause"
        daemon.kuke("stop", "sbox")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and os.path.exists(f"/proc/{pid}"):
            time.sleep(0.05)
        assert not os.path.exists(f"/proc/{pid}"), "sandbox survived stop"
        assert not os.path.exists(matches[0]), "sandbox.pid not cleaned up"


@pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")
class TestImageRootfsIsolation:
    """Image-backed cells see the image rootfs as '/' via pivot_root."""

    CHECKER_SRC = r"""
#include <stdio.h>
#include <dirent.h>
#include <unistd.h>
int main() {
    FILE* f = fopen("/marker.txt", "r");
    printf("MARKER=%s\n", f ? "present" : "missing");
    if (f) fclose(f);
    printf("HOSTETC=%s\n", access("/etc/passwd", F_OK) == 0 ? "visible" : "hidden");
    DIR* d = opendir("/");
    int n = 0; struct dirent* e;
    while ((e = readdir(d))) n++;
    printf("ROOTENTRIES=%d\n", n);
    FILE* w = fopen("/write-probe", "w");
    printf("ROOTWRITE=%s\n", w ? "ok" : "denied");
    if (w) fclose(w);
    return 0;
}
"""

    @pytest.fixture
    def image(self, daemon, tmp_path):
        src = tmp_path / "checker.c"
        src.write_text(self.CHECKER_SRC)
        out = tmp_path / "checker"
        subprocess.run(
            ["g++", "-static", "-O1", "-o", str(out), str(src)], check=True
        )
        (tmp_path / "marker.txt").write_text("hello from image\n")
        (tmp_path / "Kukefile").write_text(
            "FROM scratch\nCOPY checker /checker\nCOPY marker.txt /marker.txt\n"
            "ENTRYPOINT [\"/checker\"]\n"
        )
        daemon.kuke("build", "-t", "isochk:v1", str(tmp_path))
        return "isochk:v1"

    def test_pivot_root(self, daemon, image):
        manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: imgiso}}
spec:
  containers:
    - name: main
      image: {image}
      restartPolicy: {{policy: never}}
"""
        _apply(daemon, manifest)
        _wait_exit(daemon, "imgiso")
        log = _log(daemon, "imgiso")
        assert "MARKER=present" in log      # image content at its real path
        assert "HOSTETC=hidden" in log      # host filesystem NOT visible
        # /: checker, marker.txt, dev, proc, tmp, etc, . , .. and little else
        entries = int(log.split("ROOTENTRIES=")[1].split()[0])
        assert entries < 12
        assert "ROOTWRITE=ok" in log        # rw rootfs by default

    def test_readonly_rootfs(self, daemon, image):
        manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: imgro}}
spec:
  containers:
    - name: main
      image: {image}
      readOnlyRootFilesystem: true
      restartPolicy: {{policy: never}}
"""
        _apply(daemon, manifest)
        _wait_exit(daemon, "imgro")
        assert "ROOTWRITE=denied" in _log(daemon, "imgro")


class TestSecretsAndVolumes:
    def test_secret_bind_in_cell_path(self, daemon):
        manifest = """
apiVersion: kukeon.io/v1beta1
kind: Secret
metadata: {name: api-key}
spec: {data: {TOKEN: sekrit}}
---
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: secprobe}
spec:
  containers:
    - name: main
      command: ["sh", "-c", "cat /run/kukeon/secrets/api-key.env; \
touch /run/kukeon/secrets/api-key.env 2>&1 || echo SECRET_RO"]
      secrets: [{name: api-key}]
      restartPolicy: {policy: never}
"""
        _apply(daemon, manifest)
        _wait_exit(daemon, "secprobe")
        log = _log(daemon, "secprobe")
        assert "TOKEN=sekrit" in log
        assert "SECRET_RO" in log
        # The secret must NOT exist at that path on the host.
        assert not os.path.exists("/run/kukeon/secrets/api-key.env")

    def test_volume_bind_mount(self, daemon, tmp_path):
        manifest = """
apiVersion: kukeon.io/v1beta1
kind: Volume
metadata: {name: scratch}
spec: {reclaimPolicy: delete}
---
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: volprobe}
spec:
  containers:
    - name: main
      command: ["sh", "-c", "echo persisted > /data/out.txt && echo WROTE"]
      volumes: [{name: scratch, path: /data}]
      restartPolicy: {policy: never}
"""
        _apply(daemon, manifest)
        _wait_exit(daemon, "volprobe")
        assert "WROTE" in _log(daemon, "volprobe")
        # Data landed in the volume's host data dir.
        found = []
        for root, _dirs, files in os.walk(daemon.run_path):
            if "out.txt" in files:
                found.append(os.path.join(root, "out.txt"))
        assert found and open(found[0]).read().strip() == "persisted"
        # No data leaked to a host-side /data (the bind target may exist as
        # an empty dir on host-rootfs cells; its content must not).
        assert not os.path.exists("/data/out.txt")

    def test_readonly_volume(self, daemon):
        manifest = """
apiVersion: kukeon.io/v1beta1
kind: Volume
metadata: {name: rodata}
spec: {reclaimPolicy: delete}
---
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: roprobe}
spec:
  containers:
    - name: main
      command: ["sh", "-c", "touch /rodata/x 2>&1 || echo VOLUME_RO"]
      volumes: [{name: rodata, path: /rodata, readOnly: true}]
      restartPolicy: {policy: never}
"""
        _apply(daemon, manifest)
        _wait_exit(daemon, "roprobe")
        assert "VOLUME_RO" in _log(daemon, "roprobe")


def test_tmpfs_mount_is_private_and_ephemeral(daemon):
    """tmpfs volume mounts (reference: OCI spec tmpfs, ctr/spec.go): a real
    tmpfs inside the cell's mount namespace — writable under a read-only
    root, invisible from the host, gone on restart."""
    # Mount over /tmp: exists on every host (host-rootfs cells must target
    # an existing dir — kukecell refuses to mkdir on the real host fs), and
    # doubles as proof the cell's scratch masks the host's /tmp.
    manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: scratchy}
spec:
  containers:
    - name: main
      command: ["sh", "-c",
                "grep ' /tmp tmpfs' /proc/mounts | head -1; \
                 echo private > /tmp/kuke-tmpfs-probe && cat /tmp/kuke-tmpfs-probe; \
                 sleep 30"]
      volumes: [{path: /tmp, tmpfs: true}]
"""
    _apply(daemon, manifest)
    time.sleep(1.5)
    log = _log(daemon, "scratchy")
    assert "tmpfs" in log, log
    assert "private" in log, log
    # Host's /tmp is untouched (the mount lives in the cell's namespace).
    assert not os.path.exists("/tmp/kuke-tmpfs-probe")
    daemon.kuke("delete", "cell", "scratchy", "--force")


def test_seccomp_denylist_blocks_namespace_escapes(daemon):
    """The default seccomp filter (reference: OCI seccomp profile via
    securityOpts, ctr/spec.go) denies namespace/kernel-surface syscalls with
    EPERM. Probe: unshare(CLONE_NEWUSER) needs NO capability (it would
    succeed in a plain process), so its failure isolates the filter;
    seccomp=unconfined restores it."""
    probe = (
        "import ctypes, os\n"
        "libc = ctypes.CDLL(None, use_errno=True)\n"
        "CLONE_NEWUSER = 0x10000000\n"
        "rc = libc.unshare(CLONE_NEWUSER)\n"
        "err = ctypes.get_errno()\n"
        "print('UNSHARE', 'OK' if rc == 0 else f'DENIED errno={err}')\n"
        "try:\n"
        "    os.open('/proc/1/root', os.O_RDONLY)\n"
        "    print('MOUNTPROBE unexpected')\n"
        "except OSError:\n"
        "    pass\n"
    )
    for name, opts, expect in (
        ("filt", "", "UNSHARE DENIED errno=1"),
        ("nofilt", "securityOpts: [seccomp=unconfined]", "UNSHARE OK"),
    ):
        manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: {name}}}
spec:
  containers:
    - name: main
      command: ["python3", "-S", "-c", {probe!r}]
      {opts}
      restartPolicy: {{policy: never}}
"""
        _apply(daemon, manifest)
        _wait_exit(daemon, name)
        log = _log(daemon, name)
        assert expect in log, f"{name}: {log}"
        daemon.kuke("delete", "cell", name, "--force")
