"""Networking layer: subnet allocator, bridge, egress rules, firewall, slice.

Mirrors the reference's seam strategy (SURVEY.md §4): iptables/ip shelling
behind a runner fake, rule generators tested as pure functions.
"""

import ipaddress

import pytest

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.errors import FailedPrecondition, InvalidArgument
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.net import (
    FORWARD_CHAIN,
    BridgeManager,
    FakeRunner,
    ForwardInstaller,
    IptablesEnforcer,
    NetworkManager,
    Policy,
    ResolvedRule,
    SliceTopology,
    SubnetAllocator,
    admission_rules,
    bridge_name,
    build_rules,
    discover_slice,
    dispatch_rule,
    resolve_policy,
    slice_mesh_rules,
)
from kukeon_tpu.runtime.net.bridge import render_conflist
from kukeon_tpu.runtime.runner import Runner
from kukeon_tpu.runtime.store import ResourceStore


@pytest.fixture
def store(tmp_path):
    s = ResourceStore(MetadataStore(str(tmp_path)))
    # Minimal hierarchy so space_parts resolve.
    s.ms.ensure_dir(consts.REALMS_DIR, "default", consts.SPACES_DIR, "a")
    s.ms.ensure_dir(consts.REALMS_DIR, "default", consts.SPACES_DIR, "b")
    s.ms.write_json({"kind": "Realm"}, consts.REALMS_DIR, "default", "realm.json")
    return s


class TestSubnetAllocator:
    def test_allocates_distinct_subnets(self, store):
        alloc = SubnetAllocator(store)
        a = alloc.allocate("default", "a")
        b = alloc.allocate("default", "b")
        assert a != b
        for cidr in (a, b):
            net = ipaddress.ip_network(cidr)
            assert net.prefixlen == 24
            assert net.subnet_of(ipaddress.ip_network("10.88.0.0/16"))

    def test_idempotent_and_survives_restart(self, store):
        a1 = SubnetAllocator(store).allocate("default", "a")
        # New allocator instance = daemon restart; on-disk state rules.
        a2 = SubnetAllocator(store).allocate("default", "a")
        assert a1 == a2

    def test_requested_subnet_honored_and_conflict_detected(self, store):
        alloc = SubnetAllocator(store)
        assert alloc.allocate("default", "a", "10.88.5.0/24") == "10.88.5.0/24"
        with pytest.raises(FailedPrecondition):
            alloc.allocate("default", "b", "10.88.5.0/24")

    def test_requested_outside_pool_rejected(self, store):
        with pytest.raises(InvalidArgument):
            SubnetAllocator(store).allocate("default", "a", "192.168.1.0/24")

    def test_requested_overlap_by_network_math(self, store):
        alloc = SubnetAllocator(store)
        alloc.allocate("default", "a", "10.88.5.0/24")
        # /25 inside a's /24: string-different but overlapping.
        with pytest.raises(FailedPrecondition):
            alloc.allocate("default", "b", "10.88.5.0/25")

    def test_requested_wider_than_carve_rejected(self, store):
        with pytest.raises(InvalidArgument):
            SubnetAllocator(store).allocate("default", "a", "10.88.0.0/16")

    def test_requested_ipv6_rejected(self, store):
        with pytest.raises(InvalidArgument):
            SubnetAllocator(store).allocate("default", "a", "2001:db8::/64")

    def test_auto_alloc_skips_overlapping_narrow_request(self, store):
        alloc = SubnetAllocator(store)
        alloc.allocate("default", "a", "10.88.0.128/25")
        b = alloc.allocate("default", "b")
        assert not ipaddress.ip_network(b).overlaps(
            ipaddress.ip_network("10.88.0.128/25"))

    def test_release_frees_subnet(self, store):
        alloc = SubnetAllocator(store)
        a = alloc.allocate("default", "a")
        alloc.release("default", "a")
        assert a not in alloc.in_use()

    def test_pool_exhaustion(self, store):
        alloc = SubnetAllocator(store, parent_cidr="10.99.0.0/30", prefix_len=31)
        store.ms.ensure_dir(consts.REALMS_DIR, "default", consts.SPACES_DIR, "c")
        alloc.allocate("default", "a")
        alloc.allocate("default", "b")
        with pytest.raises(FailedPrecondition):
            alloc.allocate("default", "c")


class TestBridge:
    def test_name_deterministic_and_prefixed(self):
        n1 = bridge_name("default", "a")
        assert n1 == bridge_name("default", "a")
        assert n1.startswith("k-") and len(n1) == 10
        assert n1 != bridge_name("default", "b")

    def test_conflist_shape(self):
        doc = render_conflist("default", "a", "10.88.3.0/24")
        bridge_plugin = doc["plugins"][0]
        assert bridge_plugin["type"] == "bridge"
        assert bridge_plugin["bridge"] == bridge_name("default", "a")
        assert bridge_plugin["ipam"]["ranges"][0][0]["subnet"] == "10.88.3.0/24"

    def test_ensure_idempotent(self):
        fake = FakeRunner()
        bm = BridgeManager(fake)
        bm.ensure("default", "a", "10.88.3.0/24")
        adds = [c for c in fake.calls if c[:3] == ["ip", "link", "add"]]
        # FakeRunner returns success for `ip link show`, so the bridge
        # "exists" and no add is attempted — idempotency via probe.
        assert adds == []
        addr_adds = [c for c in fake.calls if c[:3] == ["ip", "addr", "add"]]
        assert addr_adds and addr_adds[0][3] == "10.88.3.1/24"

    def test_ensure_creates_when_missing(self):
        fake = FakeRunner(fail_prefixes=[["ip", "link", "show"]])
        BridgeManager(fake).ensure("default", "a", "10.88.3.0/24")
        assert any(c[:3] == ["ip", "link", "add"] for c in fake.calls)


class TestEgressRules:
    def test_default_allow_terminal(self):
        p = Policy(realm="r", space="s", default="allow")
        rules = build_rules(p)
        assert "RELATED,ESTABLISHED" in rules[0].args
        assert rules[-1].args[-1] == "ACCEPT"

    def test_default_deny_terminal_drop(self):
        p = Policy(realm="r", space="s", default="deny")
        assert build_rules(p)[-1].args[-1] == "DROP"

    def test_allow_cidr_with_ports_expands(self):
        p = Policy(realm="r", space="s", default="deny", allow=[
            ResolvedRule(cidr="10.0.0.0/8", ports=[443, 80]),
        ])
        rules = build_rules(p)
        accepts = [r for r in rules if "--dport" in r.args]
        assert len(accepts) == 2
        assert ("-d", "10.0.0.0/8") == accepts[0].args[:2]

    def test_allow_host_resolves_to_slash32(self):
        spec = t.NetworkSpec(egress_default="deny", egress_allow=[
            t.EgressRule(host="example.test", ports=[443]),
        ])
        p = resolve_policy("r", "s", spec,
                           resolver=lambda h: ["192.0.2.1", "192.0.2.2"])
        rules = build_rules(p)
        dsts = [r.args[1] for r in rules if r.args[0] == "-d"]
        assert dsts == ["192.0.2.1/32", "192.0.2.2/32"]

    def test_unresolvable_host_contributes_nothing(self):
        def boom(host):
            raise OSError("nxdomain")
        spec = t.NetworkSpec(egress_default="deny", egress_allow=[
            t.EgressRule(host="gone.test"),
        ])
        p = resolve_policy("r", "s", spec, resolver=boom)
        # established + terminal only
        assert len(build_rules(p)) == 2

    def test_chain_name_truncated_under_iptables_limit(self):
        p = Policy(realm="a-very-long-realm-name", space="an-even-longer-space-name")
        assert len(p.chain_name()) <= 28

    def test_dispatch_rule_targets_space_chain(self):
        p = Policy(realm="r", space="s")
        d = dispatch_rule(p)
        assert d.chain == "KUKEON-EGRESS"
        assert d.args[:2] == ("-i", p.bridge)
        assert d.args[-1] == p.chain_name()


class TestIptablesEnforcer:
    def test_apply_replaces_chain_atomically(self):
        fake = FakeRunner(fail_prefixes=[["iptables", "-w", "-C"],
                                         ["iptables", "-w", "-n", "-L"]])
        enf = IptablesEnforcer(fake)
        p = Policy(realm="r", space="s", default="deny")
        enf.apply(p)
        # The chain content goes through one iptables-restore --noflush call
        # (atomic per-chain replace — no fail-open window), never -F + -A.
        restores = [i for c, i in zip(fake.calls, fake.inputs)
                    if c[0] == "iptables-restore"]
        assert len(restores) == 1
        payload = restores[0]
        assert payload.startswith("*filter\n:" + p.chain_name())
        assert payload.rstrip().endswith("COMMIT")
        assert "-j DROP" in payload
        ipt = fake.calls_for("iptables")
        assert not any("-F" in c for c in ipt)
        # Dispatch added after probe failed, FORWARD jump inserted, -w used.
        assert any(c[1] == "-w" and c[2] == "-A" and c[3] == "KUKEON-EGRESS"
                   for c in ipt)
        assert ["iptables", "-w", "-I", "FORWARD", "1", "-j", "KUKEON-EGRESS"] in ipt

    def test_apply_skips_existing_dispatch(self):
        fake = FakeRunner()  # -C succeeds: jump already present
        IptablesEnforcer(fake).apply(Policy(realm="r", space="s"))
        assert not any(
            "-A" in c and "KUKEON-EGRESS" in c
            for c in fake.calls_for("iptables")
        )

    def test_remove_deletes_chain(self):
        fake = FakeRunner()
        p = Policy(realm="r", space="s")
        IptablesEnforcer(fake).remove(p)
        ipt = fake.calls_for("iptables")
        assert ["iptables", "-w", "-X", p.chain_name()] in ipt


class TestForward:
    def test_admission_rules_shape(self):
        rules = admission_rules()
        assert rules[0][-1] == "ACCEPT" and "RELATED,ESTABLISHED" in rules[0]
        # Ingress rule is scoped to non-bridge sources (fail-closed egress).
        assert rules[1][2] == "!" and rules[1][4] == "k-+"

    def test_install_idempotent(self):
        fake = FakeRunner(fail_prefixes=[["iptables", "-C"], ["iptables", "-n"]])
        ForwardInstaller(fake).install()
        ipt = fake.calls_for("iptables")
        assert ["iptables", "-N", FORWARD_CHAIN] in ipt
        assert ["iptables", "-I", "FORWARD", "1", "-j", FORWARD_CHAIN] in ipt


class TestSlice:
    def test_discover_from_env(self):
        env = {"TPU_WORKER_HOSTNAMES": "w0,w1,w2", "TPU_WORKER_ID": "1"}
        topo = discover_slice(env)
        assert topo.multi_host and topo.peers() == ["w0", "w2"]

    def test_single_host_no_rules(self):
        assert slice_mesh_rules(SliceTopology(workers=["only"])) == []

    def test_mesh_rules_cover_peer_ports(self):
        topo = SliceTopology(worker_id=0, workers=["10.0.0.1", "10.0.0.2"],
                             ports=[8471])
        rules = slice_mesh_rules(topo)
        assert len(rules) == 1
        assert rules[0].ips == ["10.0.0.2"] and rules[0].ports == [8471]

    def test_hostname_peers_resolve(self):
        topo = SliceTopology(worker_id=0, workers=["me", "peer.test"])
        rules = slice_mesh_rules(topo, resolver=lambda h: ["203.0.113.9"])
        assert rules[0].ips == ["203.0.113.9"]


class TestNetworkManager:
    def test_ensure_space_network_allocates_and_renders(self, store, monkeypatch):
        monkeypatch.setenv("KUKEON_NET_ENFORCE", "0")
        nm = NetworkManager(store, runner=FakeRunner())
        state = nm.ensure_space_network("default", "a", t.SpaceSpec())
        assert state["subnet"].endswith("/24")
        assert state["bridge"].startswith("k-")
        assert not state["enforcing"]
        assert store.ms.exists(consts.REALMS_DIR, "default", consts.SPACES_DIR,
                               "a", "network.conflist")

    def test_enforcing_mode_programs_bridge_and_chain(self, store, monkeypatch):
        monkeypatch.setenv("KUKEON_NET_ENFORCE", "1")
        fake = FakeRunner(fail_prefixes=[["iptables", "-C"], ["iptables", "-n"],
                                         ["ip", "link", "show"]])
        nm = NetworkManager(store, runner=fake)
        nm.ensure_space_network("default", "a",
                                t.SpaceSpec(network=t.NetworkSpec(egress_default="deny")))
        assert any(c[:3] == ["ip", "link", "add"] for c in fake.calls)
        assert any(c[0] == "iptables" for c in fake.calls)

    def test_reconcile_all_covers_every_space(self, store, monkeypatch):
        monkeypatch.setenv("KUKEON_NET_ENFORCE", "0")
        store.ms.write_json({"kind": "Space", "name": "a", "specJson": {}},
                            consts.REALMS_DIR, "default", consts.SPACES_DIR, "a",
                            "space.json")
        store.ms.write_json({"kind": "Space", "name": "b", "specJson": {}},
                            consts.REALMS_DIR, "default", consts.SPACES_DIR, "b",
                            "space.json")
        nm = NetworkManager(store, runner=FakeRunner())
        out = nm.reconcile_all()
        assert set(out) == {"default/a", "default/b"}
        assert out["default/a"]["subnet"] != out["default/b"]["subnet"]


class TestRunnerIntegration:
    def test_ensure_space_provisions_network(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KUKEON_NET_ENFORCE", "0")
        from kukeon_tpu.runtime.cells.fake import FakeBackend

        store = ResourceStore(MetadataStore(str(tmp_path)))
        nm = NetworkManager(store, runner=FakeRunner())
        runner = Runner(store, FakeBackend(), netman=nm)
        runner.ensure_realm("default")
        runner.ensure_space("default", "web")
        st = nm.subnets.read_state("default", "web")
        assert st and st["subnetCIDR"].endswith("/24")

    def test_rejected_subnet_change_does_not_persist_spec(self, tmp_path, monkeypatch):
        """Provision-before-persist: a rejected spec must leave the stored
        spec untouched so the reconcile loop can still converge."""
        monkeypatch.setenv("KUKEON_NET_ENFORCE", "0")
        from kukeon_tpu.runtime.cells.fake import FakeBackend

        store = ResourceStore(MetadataStore(str(tmp_path)))
        nm = NetworkManager(store, runner=FakeRunner())
        runner = Runner(store, FakeBackend(), netman=nm)
        runner.ensure_realm("default")
        runner.ensure_space("default", "web",
                            t.SpaceSpec(subnet="10.88.7.0/24"))
        with pytest.raises(FailedPrecondition):
            runner.ensure_space("default", "web",
                                t.SpaceSpec(subnet="10.88.8.0/24"))
        from kukeon_tpu.runtime.api.wire import from_wire
        spec = from_wire(t.SpaceSpec, store.read_space("default", "web").spec_json)
        assert spec.subnet == "10.88.7.0/24"


class TestEgressProtocol:
    def test_portless_rule_defaults_to_all_protocols(self):
        from kukeon_tpu.runtime.api import types as t
        from kukeon_tpu.runtime.net.netpolicy import build_rules, resolve_policy

        p = resolve_policy("r", "s", t.NetworkSpec(
            egress_default="deny",
            egress_allow=[t.EgressRule(cidr="10.0.0.5/32")],
        ), resolver=lambda h: [])
        args = [r.args for r in build_rules(p)]
        accept = next(a for a in args if "-d" in a)
        assert "-p" not in accept   # all protocols

    def test_portless_udp_rule_constrains_protocol(self):
        from kukeon_tpu.runtime.api import types as t
        from kukeon_tpu.runtime.net.netpolicy import build_rules, resolve_policy

        p = resolve_policy("r", "s", t.NetworkSpec(
            egress_default="deny",
            egress_allow=[t.EgressRule(cidr="10.0.0.5/32", protocol="udp")],
        ), resolver=lambda h: [])
        args = [r.args for r in build_rules(p)]
        accept = next(a for a in args if "-d" in a)
        assert "-p" in accept and "udp" in accept

    def test_ports_default_tcp(self):
        from kukeon_tpu.runtime.api import types as t
        from kukeon_tpu.runtime.net.netpolicy import resolve_policy

        p = resolve_policy("r", "s", t.NetworkSpec(
            egress_allow=[t.EgressRule(cidr="10.0.0.5/32", ports=[443])],
        ), resolver=lambda h: [])
        assert p.allow[0].protocol == "tcp"
