"""Ring attention must exactly match single-device reference attention."""

import jax
import jax.numpy as jnp
import numpy as np

from kukeon_tpu.ops.attention import attention_mask, attention_reference, repeat_kv
from kukeon_tpu.parallel import make_mesh, ring_attention, set_mesh


def test_ring_matches_reference():
    B, S, NH, NKV, D = 2, 32, 4, 2, 16
    key = jax.random.key(0)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, NH, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, NKV, D), jnp.float32)
    v = jax.random.normal(kv_, (B, S, NKV, D), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))

    mask = attention_mask(positions, positions)
    ref = attention_reference(
        q, repeat_kv(k, NH // NKV), repeat_kv(v, NH // NKV), mask
    )

    mesh = make_mesh(seq=8)
    with set_mesh(mesh):
        out = jax.jit(
            lambda *a: ring_attention(
                a[0], a[1], a[2], q_positions=a[3], kv_positions=a[3], mesh=mesh
            )
        )(q, k, v, positions)

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_seq4_with_data_axis():
    """Ring attention composes with a data axis on the same mesh."""
    B, S, NH, NKV, D = 4, 16, 2, 1, 8
    key = jax.random.key(1)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, NH, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, NKV, D), jnp.float32)
    v = jax.random.normal(kv_, (B, S, NKV, D), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))

    ref = attention_reference(
        q, repeat_kv(k, NH), repeat_kv(v, NH), attention_mask(positions, positions)
    )

    mesh = make_mesh(data=2, seq=4)
    with set_mesh(mesh):
        out = jax.jit(
            lambda *a: ring_attention(
                a[0], a[1], a[2], q_positions=a[3], kv_positions=a[3], mesh=mesh
            )
        )(q, k, v, positions)

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
