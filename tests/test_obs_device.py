"""Device & fleet observability (PR 4): HBM/compile telemetry, the
on-demand profiler spool, SLO burn rates, trace request_id lookup, scrape
hardening, percentile edge contracts, and the README metric-table guard."""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from http.server import ThreadingHTTPServer

import jax
import numpy as np
import pytest

from kukeon_tpu import faults
from kukeon_tpu.models import llama
from kukeon_tpu.obs import (
    Registry,
    SloObjectives,
    SloTracker,
    device_memory_collector,
    percentile_from_counts,
    render,
)
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine

from test_obs import _parse_expo

PROMPT = np.arange(1, 9, dtype=np.int32)


def _tiny_engine(**kw):
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    kw.setdefault("num_slots", 2)
    return ServingEngine(cfg, params, mesh, max_seq_len=96,
                         decode_chunk=4, **kw)


# --- device memory collector -------------------------------------------------


def test_device_memory_families_always_declared():
    """The kukeon_hbm_* families are part of the scrape schema on EVERY
    backend; backends without memory stats (CPU) just contribute no
    samples. TPU/GPU samples carry device= labels."""
    reg = Registry()
    reg.register_collector(device_memory_collector)
    fams = _parse_expo(render(reg))
    for name in ("kukeon_hbm_bytes_in_use", "kukeon_hbm_bytes_limit",
                 "kukeon_hbm_bytes_peak"):
        assert fams.get(name, {}).get("type") == "gauge", name
        for _n, labels, _v in fams[name]["samples"]:
            assert "device" in labels


# --- compile tracking --------------------------------------------------------


def test_decode_compile_counter_flat_across_slot_churn():
    """Tier-1 acceptance: the engine docstring's 'occupancy changes never
    recompile' promise, measured. After warmup, slot churn (requests of
    different lengths entering and leaving the decode batch) must not move
    kukeon_compiles_total{program="decode"}."""
    eng = _tiny_engine()
    eng.warmup(8)
    base = eng.compiles.count("decode")
    assert base >= 1                      # warmup really compiled something

    # Churn: staggered submits so occupancy goes 1 -> 2 -> 1 -> 2 -> 0.
    r1 = eng.submit(PROMPT, SamplingParams(max_new_tokens=12))
    eng.step()
    r2 = eng.submit(PROMPT[:4], SamplingParams(max_new_tokens=3))
    while not r2.done.is_set():
        eng.step()
    r3 = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    while not (r1.done.is_set() and r3.done.is_set()):
        eng.step()
    assert eng.compiles.count("decode") == base, (
        "decode recompiled during slot churn")

    # The compile families land on the scrape with the right shapes.
    fams = _parse_expo(render(eng.registry))
    assert fams["kukeon_compiles_total"]["type"] == "counter"
    assert fams["kukeon_compile_seconds"]["type"] == "histogram"
    programs = {lab["program"] for _n, lab, _v
                in fams["kukeon_compiles_total"]["samples"]}
    assert {"prefill", "insert", "decode"} <= programs


def test_decode_compile_counter_flat_across_slot_churn_paged():
    """The same no-recompile invariant on the paged path (ISSUE 6): the
    block table is a static-shape [B, max_pages] array, so slot churn AND
    page churn (alloc/free/growth across requests of different lengths)
    must not move kukeon_compiles_total{program="decode"}."""
    eng = _tiny_engine(kv_page_tokens=16, kv_pool_pages=12)
    eng.warmup(8)
    base = eng.compiles.count("decode")
    assert base >= 1

    r1 = eng.submit(PROMPT, SamplingParams(max_new_tokens=12))
    eng.step()
    r2 = eng.submit(PROMPT[:4], SamplingParams(max_new_tokens=3))
    while not r2.done.is_set():
        eng.step()
    r3 = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    while not (r1.done.is_set() and r3.done.is_set()):
        eng.step()
    assert eng.compiles.count("decode") == base, (
        "paged decode recompiled during slot/page churn")
    # The pool drained page-granularly as requests finished.
    assert eng._pool.in_use == 0


def test_compile_tracker_counts_new_shapes():
    """A genuinely new shape (an unseen prefill bucket) IS counted — the
    tracker distinguishes real compiles from steady state, not just
    'nothing ever moves'."""
    eng = _tiny_engine()
    eng.generate(PROMPT, SamplingParams(max_new_tokens=2))
    before = eng.compiles.count("prefill")
    # 70 tokens pads to the 128 bucket: an unseen prefill shape.
    eng.generate(np.ones((70,), np.int32), SamplingParams(max_new_tokens=2))
    assert eng.compiles.count("prefill") > before


# --- serving cell endpoints (acceptance) -------------------------------------


@pytest.fixture(scope="module")
def device_cell():
    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    cell = ServingCell("tiny", num_slots=2, max_seq_len=96, checkpoint=None,
                       dtype=None, max_pending=8,
                       slo_ttft_p95_ms=500.0, slo_availability=0.995)
    cell.engine.start()
    cell.mark_ready()
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield cell, server.server_address[1]
    server.shutdown()
    server.server_close()
    cell.engine.stop()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, raw


def _post(port, path, obj):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    body = json.dumps(obj).encode()
    conn.request("POST", path, body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, json.loads(raw)


def test_cell_metrics_expose_device_and_slo_families(device_cell):
    """Acceptance: a cell scrape exposes the hbm, compile, and slo families
    (golden-parsed), and the declared SLO objectives surface."""
    cell, port = device_cell
    cell.engine.generate(PROMPT, SamplingParams(max_new_tokens=3))
    status, raw = _get(port, "/metrics")
    assert status == 200
    fams = _parse_expo(raw.decode())
    for name, kind in (
        ("kukeon_hbm_bytes_in_use", "gauge"),
        ("kukeon_hbm_bytes_limit", "gauge"),
        ("kukeon_hbm_bytes_peak", "gauge"),
        ("kukeon_compiles_total", "counter"),
        ("kukeon_compile_seconds", "histogram"),
        ("kukeon_slo_objective", "gauge"),
        ("kukeon_slo_burn_rate", "gauge"),
        ("kukeon_slo_error_budget_remaining", "gauge"),
        ("kukeon_profile_captures_total", "counter"),
        ("kukeon_scrape_errors_total", "counter"),
    ):
        assert fams.get(name, {}).get("type") == kind, name
    obj = {lab["slo"]: float(v) for _n, lab, v
           in fams["kukeon_slo_objective"]["samples"]}
    assert obj["availability"] == 0.995
    assert abs(obj["ttft_p95"] - 0.5) < 1e-9
    burn = {(lab["slo"], lab["window"]): float(v) for _n, lab, v
            in fams["kukeon_slo_burn_rate"]["samples"]}
    assert ("availability", "5m") in burn and ("ttft_p95", "1h") in burn


def test_trace_request_id_exact_match(device_cell):
    cell, port = device_cell
    eng = cell.engine
    req = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    assert req.done.wait(timeout=60)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        status, raw = _get(port, f"/v1/trace?request_id={req.id}")
        assert status == 200
        spans = json.loads(raw)["spans"]
        if spans:
            break
        time.sleep(0.02)
    assert spans and all(s["requestId"] == req.id for s in spans)
    # Absent id -> empty list, not an error; bogus id -> 400.
    status, raw = _get(port, "/v1/trace?request_id=999999")
    assert status == 200 and json.loads(raw)["spans"] == []
    status, _raw = _get(port, "/v1/trace?request_id=bogus")
    assert status == 400


def test_profile_capture_single_flight_and_spool(device_cell):
    cell, port = device_cell
    status, out = _post(port, "/v1/profile", {"durationMs": 400})
    assert status == 200 and out["started"]
    name = out["capture"]["name"]
    # Single-flight: a second start while one runs answers 409.
    status, out2 = _post(port, "/v1/profile", {"durationMs": 100})
    assert status == 409
    # The capture completes and lands in the spool listing.
    deadline = time.monotonic() + 30
    done = None
    while time.monotonic() < deadline:
        status, raw = _get(port, "/v1/profile")
        assert status == 200
        caps = json.loads(raw)["captures"]
        done = next((c for c in caps
                     if c["name"] == name and c["state"] == "done"), None)
        if done:
            break
        time.sleep(0.05)
    assert done is not None, "capture never completed"
    assert done["sizeBytes"] > 0
    assert os.path.isdir(done["path"])
    # Bad durations are rejected, not silently clamped.
    status, _ = _post(port, "/v1/profile", {"durationMs": -5})
    assert status == 400


@pytest.mark.faults
def test_profile_capture_fault_path(device_cell):
    """The profile.capture fault point fails the start cleanly (500 with
    the injected error) and releases the single-flight latch."""
    cell, port = device_cell
    os.environ[faults.ENV] = "profile.capture:1:1"
    status, out = _post(port, "/v1/profile", {"durationMs": 100})
    assert status == 500 and "injected fault" in out["error"]
    os.environ.pop(faults.ENV, None)
    faults.reset()
    # Latch released: the next capture starts fine.
    status, out = _post(port, "/v1/profile", {"durationMs": 100})
    assert status == 200
    deadline = time.monotonic() + 30
    while cell.profiler._active is not None:
        assert time.monotonic() < deadline
        time.sleep(0.05)


def test_profile_spool_keeps_last_k(tmp_path):
    from kukeon_tpu.obs import ProfileSpool

    spool = ProfileSpool(base_dir=str(tmp_path / "spool"), keep=2)
    for _ in range(4):
        spool.start(30)
        deadline = time.monotonic() + 30
        while spool._active is not None:
            assert time.monotonic() < deadline
            time.sleep(0.02)
    done = [c for c in spool.list() if c["state"] == "done"]
    assert len(done) <= 2
    on_disk = [e for e in os.scandir(spool.base_dir) if e.is_dir()]
    assert len(on_disk) <= 2


# --- SLO tracker -------------------------------------------------------------


def _slo_registry():
    reg = Registry()
    c = reg.counter("kukeon_engine_requests_total", "", labels=("outcome",))
    h = reg.histogram("kukeon_engine_ttft_seconds", "")
    return reg, c, h


def test_slo_burn_rates_windowed():
    clock = [0.0]
    reg, c, h = _slo_registry()
    tr = SloTracker(reg, SloObjectives(availability=0.99, ttft_p95_ms=100.0),
                    clock=lambda: clock[0])

    def collect():
        return {f[0]: f for f in tr.collect()}

    collect()                            # t=0 baseline snapshot (no traffic)

    # Clean traffic: 100 ok requests, all well under the TTFT bound.
    for _ in range(100):
        c.inc(outcome="ok")
        h.observe(0.01)
    clock[0] = 10.0
    fams = collect()
    burns = {(lab["slo"], lab["window"]): v
             for lab, v in fams["kukeon_slo_burn_rate"][3]}
    assert burns[("availability", "5m")] == 0.0
    assert burns[("ttft_p95", "1h")] == 0.0
    remaining = {lab["slo"]: v
                 for lab, v in fams["kukeon_slo_error_budget_remaining"][3]}
    assert remaining["availability"] == 1.0

    # 5 minutes later: a bad burst — 2 errors in 10 requests (20% bad
    # against a 1% allowance => burn 20 in the 5m window), every TTFT slow.
    clock[0] = 310.0
    for _ in range(8):
        c.inc(outcome="ok")
        h.observe(1.0)                   # >> 100ms objective
    for _ in range(2):
        c.inc(outcome="error")
    fams = collect()
    burns = {(lab["slo"], lab["window"]): v
             for lab, v in fams["kukeon_slo_burn_rate"][3]}
    assert abs(burns[("availability", "5m")] - 20.0) < 1e-6
    # The 1h window still includes the clean 100, diluting the burn.
    assert 0 < burns[("availability", "1h")] < burns[("availability", "5m")]
    assert burns[("ttft_p95", "5m")] > 1.0
    remaining = {lab["slo"]: v
                 for lab, v in fams["kukeon_slo_error_budget_remaining"][3]}
    assert remaining["availability"] == 0.0   # clamped, budget blown


def test_slo_no_traffic_is_clean():
    reg, _c, _h = _slo_registry()
    tr = SloTracker(reg, clock=lambda: 0.0)
    fams = {f[0]: f for f in tr.collect()}
    assert all(v == 0.0 for _l, v in fams["kukeon_slo_burn_rate"][3])
    assert all(v == 1.0 for _l, v
               in fams["kukeon_slo_error_budget_remaining"][3])


# --- scrape hardening (satellite) --------------------------------------------


def test_raising_gauge_callable_skips_sample_and_counts():
    reg = Registry()
    g = reg.gauge("kukeon_t_bad_gauge", "boom")
    g.set_function(lambda: 1 / 0)
    reg.gauge("kukeon_t_good_gauge", "fine").set(7)
    text = render(reg)
    fams = _parse_expo(text)             # exposition still parses
    assert fams["kukeon_t_bad_gauge"]["samples"] == []
    assert fams["kukeon_t_good_gauge"]["samples"][0][2] == "7"
    errs = {lab["metric"]: float(v) for _n, lab, v
            in fams["kukeon_scrape_errors_total"]["samples"]}
    assert errs["kukeon_t_bad_gauge"] >= 1


def test_raising_collector_skips_family_and_counts():
    reg = Registry()
    reg.gauge("kukeon_t_alive", "x").set(1)

    def bad_collector():
        raise RuntimeError("collector died")
        yield  # pragma: no cover

    reg.register_collector(bad_collector)
    fams = _parse_expo(render(reg))
    assert "kukeon_t_alive" in fams
    errs = {lab["metric"] for _n, lab, _v
            in fams["kukeon_scrape_errors_total"]["samples"]}
    assert any("bad_collector" in m for m in errs)


# --- percentile edge contracts (satellite) -----------------------------------


def test_percentile_empty_returns_sentinel():
    reg = Registry()
    h = reg.histogram("kukeon_t_p_seconds", "p")
    assert h.percentile(0.5) is None
    assert h.percentile(0.0) is None
    assert percentile_from_counts(h.buckets, [0] * (len(h.buckets) + 1),
                                  0.99) is None


def test_percentile_overflow_clamps_and_q_clamps():
    reg = Registry()
    h = reg.histogram("kukeon_t_q_seconds", "p")
    h.observe(1e9)                        # far past the top bucket
    assert h.percentile(0.5) == h.buckets[-1]
    assert h.percentile(1.0) == h.buckets[-1]
    # Out-of-range q clamps instead of fabricating ranks.
    h2 = reg.histogram("kukeon_t_q2_seconds", "p")
    for v in (0.001, 0.002, 0.004):
        h2.observe(v)
    assert h2.percentile(2.0) == h2.percentile(1.0)
    assert h2.percentile(-1.0) == h2.percentile(0.0)


# --- README metric-table guard (satellite) -----------------------------------


def test_every_metric_family_is_documented_in_readme():
    """Doc-drift guard (mirrors the PR-3 faults guard): every metric family
    named in the package must appear in README's metric reference table.

    Since PR 7 this rides kukelint's KUKE008 pass
    (kukeon_tpu/analysis/registries.py): families are the exact kukeon_*
    string constants in the AST (single- and double-quoted alike, no
    docstring false hits), and failures carry the literal's file:line.
    Verified against a few knowns so the scan can't decay into vacuity."""
    from kukeon_tpu.analysis import load_sources, run_analysis
    from kukeon_tpu.analysis.registries import collect_metric_literals

    pkg_root = os.path.dirname(os.path.abspath(faults.__file__))
    names = collect_metric_literals(load_sources(pkg_root))
    for must in ("kukeon_engine_ttft_seconds", "kukeon_compiles_total",
                 "kukeon_hbm_bytes_in_use", "kukeon_slo_burn_rate",
                 "kukeon_cell_scrape_ok", "kukeon_scrape_errors_total"):
        assert must in names, f"scan failed to find {must}"
    findings = run_analysis(pkg_root, select=["KUKE008"])
    assert findings == [], "\n".join(f.render() for f in findings)
