"""Streamed checkpoint boot pipeline (PR 14 tentpole): tensor-granular
stream parity against the materialized loaders, the disk/upload overlap
the bounded-buffer pipeline buys, fail-clean behavior at the
``checkpoint.stream`` fault point, and the cold-start sub-phase ledger
(disk / cast / upload) the cell exports on top of its serial phase
partition."""

from __future__ import annotations

import time

import jax
import numpy as np
import pytest

from kukeon_tpu import faults
from kukeon_tpu.models import checkpoints, hf_convert, llama
from kukeon_tpu.models.checkpoints import (
    CheckpointStream, CheckpointStreamError, TensorSpec, _walk_tree,
)
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine


def _tiny_cfg():
    return llama.llama_tiny()


def _quant_dir(tmp_path):
    cfg = _tiny_cfg()
    qp = llama.quantize_params(llama.init_params(jax.random.key(0), cfg))
    qdir = tmp_path / "q"
    checkpoints.save_quantized(str(qdir), jax.tree.map(np.asarray, qp), cfg)
    return str(qdir), cfg


def _assert_tree_equal(flat, ref_tree):
    flat_ref = dict(_walk_tree(ref_tree))
    assert set(flat) == set(flat_ref)
    for k in flat_ref:
        a, b = np.asarray(flat[k]), np.asarray(flat_ref[k])
        assert a.dtype == b.dtype, (k, a.dtype, b.dtype)
        assert a.shape == b.shape, (k, a.shape, b.shape)
        np.testing.assert_array_equal(a.astype(np.float32),
                                      b.astype(np.float32), err_msg=str(k))


class TestStreamParity:
    """Every leaf the streamed loaders yield must be byte-identical to the
    materialized twin — same dtype, same shape, same values — and the
    abstract tree (what precompile lowers against before any tensor byte
    is read) must mirror the real tree exactly."""

    def test_stream_quantized_matches_load_quantized(self, tmp_path):
        qdir, _cfg = _quant_dir(tmp_path)
        ref, _refcfg = checkpoints.load_quantized(qdir, dtype="bfloat16")
        stream = checkpoints.stream_quantized(qdir, dtype="bfloat16")
        flat = dict(stream)
        _assert_tree_equal(flat, ref)
        st = stream.stat_snapshot()
        assert st["tensors"] == len(flat)
        assert st["bytes"] > 0 and st["disk_s"] > 0.0

        # The abstract tree needs only the manifest + safetensors header.
        ab = dict(_walk_tree(stream.abstract_params))
        flat_ref = dict(_walk_tree(ref))
        assert set(ab) == set(flat_ref)
        for k, spec in ab.items():
            assert spec.shape == np.asarray(flat_ref[k]).shape
            assert np.dtype(spec.dtype) == np.asarray(flat_ref[k]).dtype

    def test_stream_params_matches_load_params(self, tmp_path):
        cfg = _tiny_cfg()
        checkpoints.synthesize_hf_checkpoint(str(tmp_path), cfg,
                                             dtype=np.float32,
                                             tokenizer=False)
        ref, _ = hf_convert.load_params(str(tmp_path), dtype="bfloat16")
        stream = hf_convert.stream_params(str(tmp_path), dtype="bfloat16")
        _assert_tree_equal(dict(stream), ref)

    def test_stream_params_quantized_matches_loader(self, tmp_path):
        cfg = _tiny_cfg()
        checkpoints.synthesize_hf_checkpoint(str(tmp_path), cfg,
                                             dtype=np.float32,
                                             tokenizer=False)
        ref, _ = hf_convert.load_params_quantized(str(tmp_path),
                                                  dtype="bfloat16")
        stream = hf_convert.stream_params_quantized(str(tmp_path),
                                                    dtype="bfloat16")
        _assert_tree_equal(dict(stream), ref)


class TestStreamedEngineBoot:
    def test_streamed_boot_greedy_parity(self, tmp_path):
        """An engine booted from a CheckpointStream (async_load, leaves
        uploaded as they arrive) must generate exactly what an engine
        booted from the materialized tree generates, and must account the
        transfer on load_stats — not on the serving-path sync ledger."""
        qdir, _cfg = _quant_dir(tmp_path)
        mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
        prompt = np.arange(3, 35, dtype=np.int32)
        sp = SamplingParams(temperature=0.0, max_new_tokens=8)

        ref, refcfg = checkpoints.load_quantized(qdir, dtype="bfloat16")
        want = ServingEngine(refcfg, ref, mesh, num_slots=2,
                             max_seq_len=64).generate(prompt, sp)

        stream = checkpoints.stream_quantized(qdir, dtype="bfloat16")
        eng = ServingEngine(stream.cfg, stream, mesh, num_slots=2,
                            max_seq_len=64, async_load=True)
        base_uploads = eng.sync_stats["uploads"]
        got = eng.generate(prompt, sp)
        assert got == want
        assert eng.load_stats["tensors"] == stream.total_leaves
        assert eng.load_stats["bytes"] > 0
        assert eng.load_stats["upload_s"] > 0.0
        # The checkpoint transfer ledger is separate from the decode-path
        # host-sync budget: uploads DID go through the counted seam.
        assert eng.sync_stats["uploads"] > base_uploads

        fams = {f[0]: f for f in eng._obs_collect()}
        by_stage = {lab["stage"]: v for lab, v in
                    fams["kukeon_checkpoint_load_seconds"][3]}
        assert by_stage["disk"] > 0.0
        assert by_stage["upload"] > 0.0
        (_lab, nbytes), = fams["kukeon_checkpoint_load_bytes_total"][3]
        assert nbytes == float(eng.load_stats["bytes"])

    def test_precompile_needs_no_tensor_bytes(self, tmp_path):
        """precompile() lowers against the abstract tree — it must finish
        while the stream has not yielded a single leaf (the compile leg
        of max(disk, transfer, compile) starts before any byte is read)."""
        import threading

        qdir, _cfg = _quant_dir(tmp_path)
        stream = checkpoints.stream_quantized(qdir, dtype="bfloat16")
        ref, refcfg = checkpoints.load_quantized(qdir, dtype="bfloat16")
        stream.close()
        gate = threading.Event()

        class Gated:
            """Duck-typed stream whose leaves arrive only after the gate
            opens — while it is shut, precompile is on its own."""
            abstract_params = stream.abstract_params
            cfg = stream.cfg

            def stat_snapshot(self):
                return {}

            def __iter__(self):
                gate.wait()
                yield from _walk_tree(jax.tree.map(np.asarray, ref))

        mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
        eng = ServingEngine(refcfg, Gated(), mesh, num_slots=2,
                            max_seq_len=64, async_load=True)
        eng.precompile((8,))   # must return with zero tensor bytes read
        gate.set()
        want = ServingEngine(refcfg, ref, mesh, num_slots=2,
                             max_seq_len=64).generate(
            np.arange(3, 11, dtype=np.int32),
            SamplingParams(temperature=0.0, max_new_tokens=4))
        got = eng.generate(np.arange(3, 11, dtype=np.int32),
                           SamplingParams(temperature=0.0, max_new_tokens=4))
        assert got == want

    def test_streamed_boot_overlaps_disk_and_upload(self):
        """The acceptance overlap proof, device-free: a throttled reader
        (every job sleeps D on 'disk') feeding a throttled consumer (U per
        leaf 'upload') must finish in ~max-leg pipeline time, far under
        the serial sum a materialize-then-upload boot pays."""
        N, D, U = 8, 0.05, 0.05
        abstract = {f"t{i}": TensorSpec((4,), np.float32) for i in range(N)}

        def make_job(i):
            def job():
                t0 = time.monotonic()
                time.sleep(D)   # the fake-slow disk read
                arr = np.full((4,), float(i), np.float32)
                return [((f"t{i}",), arr)], time.monotonic() - t0, 0.0
            return job

        stream = CheckpointStream(abstract, None, [make_job(i)
                                                   for i in range(N)],
                                  threads=2, buffer=2)
        t0 = time.monotonic()
        seen = []
        for path, arr in stream:
            time.sleep(U)       # the fake device upload
            seen.append(path)
        wall = time.monotonic() - t0
        assert len(seen) == N
        serial = N * (D + U)
        # Pipelined wall ~ N*U + D (consumer-bound with 2 reader threads);
        # anything under 75% of the serial sum proves the overlap.
        assert wall < serial * 0.75, (wall, serial)
        st = stream.stat_snapshot()
        assert st["disk_s"] >= N * D * 0.9


class TestCheckpointStreamFault:
    def test_armed_stream_fault_raises_clean(self, tmp_path, monkeypatch):
        """checkpoint.stream armed at prob 1 must surface as a
        CheckpointStreamError from the iterator (counted on the fault
        point), and an engine booting off that stream must fail its load
        with the stream error as the cause — never half-serve."""
        qdir, _cfg = _quant_dir(tmp_path)
        monkeypatch.setenv("KUKEON_FAULTS", "checkpoint.stream:1:1")
        faults.reset()
        stream = checkpoints.stream_quantized(qdir, dtype="bfloat16")
        with pytest.raises(CheckpointStreamError):
            dict(stream)
        assert faults.fired("checkpoint.stream") == 1

        monkeypatch.setenv("KUKEON_FAULTS", "checkpoint.stream:1:1")
        faults.reset()
        mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
        stream2 = checkpoints.stream_quantized(qdir, dtype="bfloat16")
        eng = ServingEngine(stream2.cfg, stream2, mesh, num_slots=2,
                            max_seq_len=64, async_load=True)
        with pytest.raises(RuntimeError) as ei:
            eng.generate(np.arange(3, 11, dtype=np.int32),
                         SamplingParams(temperature=0.0, max_new_tokens=2))
        assert isinstance(ei.value.__cause__, CheckpointStreamError)

    def test_serving_cell_exits_clean_on_stream_fault(self, tmp_path,
                                                      monkeypatch):
        """The cell-level contract: a mid-stream failure during boot is a
        SystemExit (which main()'s compile-cache-bust retry — an `except
        Exception` — does NOT swallow), so /readyz never flips on a
        half-loaded engine and the runner restart policy recovers."""
        from kukeon_tpu.runtime.serving_cell import ServingCell

        qdir, _cfg = _quant_dir(tmp_path)
        monkeypatch.setenv("KUKEON_FAULTS", "checkpoint.stream:1:1")
        faults.reset()
        cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                           checkpoint=qdir, dtype=None)
        with pytest.raises(SystemExit, match="checkpoint stream failed"):
            cell.warmup()
        assert faults.fired("checkpoint.stream") >= 1
        assert not isinstance(SystemExit(), Exception)  # retry-proof

    def test_armed_prob_zero_boots_fine(self, tmp_path, monkeypatch):
        """The other armed branch: the point armed at prob 0 must never
        fire — the streamed boot completes and serves."""
        from kukeon_tpu.runtime.serving_cell import ServingCell

        qdir, _cfg = _quant_dir(tmp_path)
        monkeypatch.setenv("KUKEON_FAULTS", "checkpoint.stream:0")
        faults.reset()
        cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                           checkpoint=qdir, dtype=None)
        cell.warmup(prompt_len=16)
        out = cell.generate({"promptTokens": [3, 1, 4], "maxNewTokens": 4,
                             "temperature": 0.0})
        assert out["numTokens"] == 4
        assert faults.fired("checkpoint.stream") == 0


class TestBootSubPhases:
    def test_finish_boot_exports_load_sub_phases(self, tmp_path):
        """A streamed boot's phase breakdown carries the disk/cast/upload
        work-time ledgers ON TOP of the serial partition — sum(phases)
        exceeds the total, and that excess is the measured overlap."""
        from kukeon_tpu.runtime.serving_cell import ServingCell

        qdir, _cfg = _quant_dir(tmp_path)
        cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                           checkpoint=qdir, dtype=None)
        cell.warmup(prompt_len=16)
        phases = cell.finish_boot()
        for stage in ("disk", "cast", "upload"):
            assert stage in phases, phases
        assert phases["disk"] > 0.0 and phases["upload"] > 0.0
        total = cell.registry.get("kukeon_cold_start_seconds").value()
        assert sum(phases.values()) > total
        g = cell.registry.get("kukeon_cold_start_phase_seconds")
        assert g is not None
