"""kukesan (kukeon_tpu/sanitize): the dynamic concurrency sanitizer.

Three layers of coverage:

- **Fixture proofs** that each detector actually fires: a seeded
  lock-order deadlock must raise with BOTH witness stacks (the tentpole
  acceptance criterion), an unguarded write to contract-guarded state must
  be caught with the offending stack, and blocking calls under a hot lock
  must be flagged (sleep / Event.wait / the explicit device-transfer
  seam).
- **Zero-overhead-off proofs**: unarmed, the factory returns raw
  ``threading`` primitives and ``guard_class`` is the identity.
- **Stress tests for the two raciest seams** — the gateway Router's
  concurrent poll/demote/route path and the serving-cell drain vs.
  in-flight accounting — each hammered by threads with the sanitizer
  armed, asserting kukesan stays quiet AND the invariants hold.

Every sanitized fixture resets the process-global graph on both sides so
deliberately seeded cycles never leak into other tests' graphs.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kukeon_tpu import sanitize
from kukeon_tpu.sanitize import contracts as san_contracts
from kukeon_tpu.sanitize import runtime as _rt


@pytest.fixture
def san(monkeypatch):
    """Arm the sanitizer for this test only, with a clean graph."""
    monkeypatch.setenv(sanitize.ENV, "1")
    _rt._reset_for_tests()
    yield sanitize
    _rt._reset_for_tests()


# --- unarmed: zero overhead --------------------------------------------------


def test_factory_returns_raw_primitives_when_off(monkeypatch):
    monkeypatch.delenv(sanitize.ENV, raising=False)
    lk = sanitize.lock("T.raw")
    assert type(lk) is type(threading.Lock())
    assert type(sanitize.rlock("T.raw_r")) is type(threading.RLock())
    assert isinstance(sanitize.event("T.raw_e"), threading.Event)
    assert isinstance(sanitize.condition(lk), threading.Condition)

    class C:
        pass

    orig_setattr = C.__setattr__
    assert sanitize.guard_class(C) is C
    assert C.__setattr__ is orig_setattr
    # The explicit blocking seam is a no-op, not an error.
    sanitize.blocking("engine._fetch device transfer")


# --- KUKESAN001: lock-order cycles -------------------------------------------


def _nest(outer, inner):
    with outer:
        with inner:
            pass


def test_seeded_deadlock_fires_with_both_witness_stacks(san):
    """The tentpole acceptance fixture: an a→b then b→a acquisition
    pattern is an observed deadlock — SanitizerError, hard, carrying the
    witness stack of every edge on the cycle."""
    a = san.lock("Fixture.a")
    b = san.lock("Fixture.b")
    _nest(a, b)
    with pytest.raises(san.SanitizerError) as exc:
        _nest(b, a)
    msg = str(exc.value)
    assert "KUKESAN001" in msg
    assert "Fixture.a" in msg and "Fixture.b" in msg
    # Both witness stacks: the held-at and acquired-at frames of both
    # edges point into this file's _nest helper.
    assert msg.count("_nest") >= 2
    assert "held at" in msg and "acquired at" in msg
    # The finding is also recorded for the per-test gate / reports.
    found = san.drain_findings()
    assert [f.rule for f in found] == ["KUKESAN001"]
    stacks = dict(found[0].stacks)
    assert len(stacks) == 4      # held+acquired for each of the 2 edges


def test_cycle_observed_across_threads(san):
    """The edges of a cycle need not come from one thread — thread A
    establishes a→b, the main thread's b→a closes it."""
    a = san.lock("XThread.a")
    b = san.lock("XThread.b")
    t = threading.Thread(target=_nest, args=(a, b))
    t.start()
    t.join()
    with pytest.raises(san.SanitizerError):
        _nest(b, a)
    san.drain_findings()


def test_consistent_order_stays_quiet_and_rlock_reenters(san):
    a = san.rlock("Quiet.a")
    b = san.lock("Quiet.b")
    for _ in range(3):
        with a:
            with a:          # re-entrant acquire: no self-edge, no churn
                with b:
                    pass
    assert san.drain_findings() == []
    edges = san.observed_edges()
    assert any(k[0].endswith("Quiet.a") and k[1].endswith("Quiet.b")
               for k in edges)


# --- KUKESAN002: guarded-by contract -----------------------------------------


def test_unguarded_write_is_caught_with_stack(san):
    @san.guard_class(contract={"depth": ("_lock",)})
    class Eng:
        def __init__(self):
            self._lock = san.lock("Eng._lock")
            self.depth = 0          # constructor: exempt

        def locked_bump(self):
            with self._lock:
                self.depth += 1

        def racy(self):
            self.depth = 5

    e = Eng()
    e.locked_bump()
    assert san.drain_findings() == []
    e.racy()
    found = san.drain_findings()
    assert [f.rule for f in found] == ["KUKESAN002"]
    rendered = found[0].render()
    assert "Eng.depth" in rendered and "_lock" in rendered
    assert "racy" in rendered       # the offending stack names the writer


def test_constructor_dynamic_extent_is_exempt(san):
    @san.guard_class(contract={"n": ("_lock",)})
    class C:
        def __init__(self):
            self._lock = san.lock("CtorExempt._lock")
            self._setup()           # helper inside __init__'s extent

        def _setup(self):
            self.n = 1

    C()
    assert san.drain_findings() == []


def test_contract_file_covers_the_real_classes(san):
    """The checked-in guarded_by.json names the engine's lock-guarded
    state: kukesan's hooks consume exactly what kukelint inferred."""
    san_contracts._reset_for_tests()
    from kukeon_tpu.runtime.serving_cell import LifecycleMixin
    from kukeon_tpu.serving.engine import ServingEngine

    eng = san_contracts.for_class(ServingEngine)
    assert eng.get("last_progress") == ("_lock",)
    assert eng.get("_pending_n") == ("_lock",)
    assert eng.get("_running") == ("_lock",)
    mixin = san_contracts.for_class(LifecycleMixin)
    assert mixin.get("draining") == ("_drain_lock",)
    assert mixin.get("_inflight") == ("_inflight_lock",)


# --- KUKESAN003: blocking under a hot lock -----------------------------------


def test_sleep_under_hot_lock_is_flagged(san):
    hot = san.lock("Hot.lock", hot=True)
    with hot:
        time.sleep(0.02)
    found = san.drain_findings()
    assert [f.rule for f in found] == ["KUKESAN003"]
    assert "time.sleep" in found[0].message
    assert "Hot.lock" in found[0].message


def test_short_sleep_and_cold_lock_stay_quiet(san):
    cold = san.lock("Cold.lock")
    with cold:
        time.sleep(0.02)            # blocking, but the lock is not hot
    time.sleep(0.02)                # blocking, but nothing held
    hot = san.lock("Hot2.lock", hot=True)
    with hot:
        time.sleep(0.001)           # below the 10ms threshold
    assert san.drain_findings() == []


def test_event_wait_and_transfer_seam_under_hot_lock(san):
    hot = san.lock("Hot3.lock", hot=True)
    ev = san.event("Hot3.event")
    with hot:
        ev.wait(timeout=0.02)       # unbounded-ish wait while hot-held
        san.blocking("engine._fetch device transfer")
    kinds = sorted(f.rule for f in san.drain_findings())
    assert kinds == ["KUKESAN003", "KUKESAN003"]


def test_set_event_wait_does_not_block_or_flag(san):
    hot = san.lock("Hot4.lock", hot=True)
    ev = san.event("Hot4.event")
    ev.set()
    with hot:
        assert ev.wait(timeout=10.0)    # returns immediately: not blocking
    assert san.drain_findings() == []


# --- the static/dynamic merge report -----------------------------------------


def test_merge_report_surfaces_runtime_only_edges(san, tmp_path):
    """Runtime edges the static pass cannot see land in runtime_only with
    their witness stacks; static edges the run never exercised land in
    static_only. (The real package's static graph is edge-free today —
    its locks never nest lexically — so a mini package provides the
    static side.)"""
    import textwrap

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "thing.py").write_text(textwrap.dedent('''
        import threading


        class C:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
    '''))
    a = san.lock("Merge.a")
    b = san.lock("Merge.b")
    _nest(a, b)
    report = san.merge_report(str(pkg))
    assert report["tool"] == "kukesan"
    assert report["static_edges"] == 1
    (static_only,) = report["static_only"]
    assert static_only["from"].endswith("C._a_lock")
    assert static_only["to"].endswith("C._b_lock")
    mine = [e for e in report["runtime_only"]
            if e["from"].endswith("Merge.a") and e["to"].endswith("Merge.b")]
    assert len(mine) == 1
    assert "_nest" in mine[0]["held_at"]
    assert "_nest" in mine[0]["acquired_at"]
    json.dumps(report)                        # JSON-able end to end

    # Against the real package the report still renders (today: zero
    # static edges — every lock is leaf-level; the runtime side is what
    # kukesan adds).
    real = san.merge_report()
    assert real["static_edges"] == 0
    assert any(e["from"].endswith("Merge.a") for e in real["runtime_only"])


# --- stress: gateway Router poll/demote/route --------------------------------


class _StatsHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        body = json.dumps(
            {"ready": True, "draining": False, "queueDepth": 1}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *a):
        pass


def test_router_poll_demote_route_hammer_stays_quiet(san):
    """The gateway's raciest seam: the poll loop rewriting snapshots,
    proxy threads demoting replicas mid-flight, and pickers routing +
    bumping in-flight counts — all at once, under the sanitizer. kukesan
    must stay quiet and the in-flight accounting must balance."""
    from kukeon_tpu.gateway.router import Router

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StatsHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        router = Router([(f"r{i}", url) for i in range(3)],
                        poll_interval_s=0.01)
        router.poll_once()
        stop = threading.Event()
        errors: list[BaseException] = []

        def poller():
            while not stop.is_set():
                router.poll_once()

        def demoter():
            while not stop.is_set():
                for rep in router.replicas:
                    router.mark_unready(rep)

        def picker():
            try:
                for i in range(400):
                    rep, _policy = router.pick(
                        prefix_id=f"s{i % 7}" if i % 2 else None)
                    if rep is not None:
                        rep.begin()
                        rep.end()
            except BaseException as e:  # noqa: BLE001 — surface hammer failures
                errors.append(e)

        threads = ([threading.Thread(target=poller) for _ in range(2)]
                   + [threading.Thread(target=demoter)]
                   + [threading.Thread(target=picker) for _ in range(4)])
        for t in threads[:3]:
            t.start()
        pickers = threads[3:]
        for t in pickers:
            t.start()
        for t in pickers:
            t.join(timeout=30)
        stop.set()
        for t in threads[:3]:
            t.join(timeout=10)
        assert not errors
        assert all(r.inflight == 0 for r in router.replicas)
        assert san.drain_findings() == []
    finally:
        srv.shutdown()
        srv.server_close()


# --- stress: serving-cell drain vs in-flight accounting ----------------------


def test_drain_vs_inflight_hammer_stays_quiet(san, monkeypatch):
    """The lifecycle seam PR 2 built: requests arriving while a drain
    flips the cell unready. Hammer _inflight_inc/_inflight_dec from many
    threads, start the drain mid-hammer, and require: the drain completes,
    the in-flight count balances to zero, admission is refused afterwards,
    and kukesan records nothing."""
    from kukeon_tpu.runtime.serving_cell import LifecycleMixin
    from kukeon_tpu.serving.engine import RejectedError

    monkeypatch.setenv("KUKEON_DRAIN_TIMEOUT_S", "20")

    @san.guard_class          # wraps __init__ so ctor writes stay exempt
    class MiniCell(LifecycleMixin):
        def __init__(self):
            self._init_lifecycle()

    cell = MiniCell()
    cell.mark_ready()
    shutdowns: list[int] = []
    cell.on_drained = lambda: shutdowns.append(1)

    def worker():
        for _ in range(300):
            try:
                cell.check_admission()
            except RejectedError:
                break
            cell._inflight_inc()
            cell._inflight_dec()

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    assert cell.begin_drain() is True
    assert cell.begin_drain() is False      # idempotent second drain
    for t in threads:
        t.join(timeout=30)
    assert cell.drained.wait(timeout=20)
    assert cell._inflight == 0
    assert shutdowns == [1]
    with pytest.raises(RejectedError):
        cell.check_admission()
    assert san.drain_findings() == []
