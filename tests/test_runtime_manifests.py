"""Manifest parsing, validation, normalization, metadata store."""

import pytest

from kukeon_tpu.runtime import consts, model
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.api.wire import from_wire, to_wire
from kukeon_tpu.runtime.apply import parser, scheme
from kukeon_tpu.runtime.errors import InvalidArgument
from kukeon_tpu.runtime.metadata import MetadataStore

CELL_YAML = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata:
  name: agent-1
  space: proj
spec:
  autoDelete: true
  containers:
    - name: shell
      command: ["/bin/sh", "-c", "sleep 5"]
      env:
        - {name: FOO, value: bar}
      restartPolicy: {policy: on-failure, backoffSeconds: 2.0, maxRetries: 3}
      attachable: true
      resources: {tpuChips: 2}
---
apiVersion: kukeon.io/v1beta1
kind: Realm
metadata:
  name: prod
"""


def test_parse_multi_doc():
    docs = parser.parse_documents(CELL_YAML)
    assert [d.kind for d in docs] == ["Cell", "Realm"]
    cell = docs[0]
    assert cell.metadata.name == "agent-1"
    assert cell.spec.auto_delete is True
    c = cell.spec.containers[0]
    assert c.command == ["/bin/sh", "-c", "sleep 5"]
    assert c.restart_policy.policy == "on-failure"
    assert c.restart_policy.max_retries == 3
    assert c.resources.tpu_chips == 2
    assert c.attachable


def test_parse_rejects_unknown_field():
    bad = CELL_YAML.replace("autoDelete", "autoDeleteTypo")
    with pytest.raises(InvalidArgument, match="autoDeleteTypo"):
        parser.parse_documents(bad)


def test_parse_rejects_bad_kind_and_names():
    with pytest.raises(InvalidArgument, match="unknown kind"):
        parser.parse_documents("apiVersion: kukeon.io/v1beta1\nkind: Nope\nmetadata: {name: x}")
    with pytest.raises(InvalidArgument, match="invalid"):
        parser.parse_documents(
            "apiVersion: kukeon.io/v1beta1\nkind: Realm\nmetadata: {name: Bad_Name}"
        )


def test_parse_model_cell():
    docs = parser.parse_documents("""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: llm}
spec:
  model: {model: llama3-8b, chips: 8, port: 9000, numSlots: 16}
""")
    assert docs[0].spec.model.chips == 8
    assert docs[0].spec.model.num_slots == 16


def test_scope_rules():
    with pytest.raises(InvalidArgument, match="not allowed"):
        parser.parse_documents(
            "apiVersion: kukeon.io/v1beta1\nkind: Realm\nmetadata: {name: r, space: s}"
        )
    with pytest.raises(InvalidArgument, match="stack scope requires space"):
        parser.parse_documents("""
apiVersion: kukeon.io/v1beta1
kind: Secret
metadata: {name: s, stack: st}
spec: {data: {K: v}}
""")


def test_normalize_defaults_scope():
    docs = parser.parse_documents(CELL_YAML)
    cell = scheme.normalize(docs[0])
    assert cell.metadata.realm == consts.DEFAULT_REALM
    assert cell.metadata.space == "proj"
    assert cell.metadata.stack == consts.DEFAULT_STACK


def test_sort_documents_dependency_order():
    blob = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: c}
spec: {containers: [{name: x, command: [sh]}]}
---
apiVersion: kukeon.io/v1beta1
kind: Realm
metadata: {name: r}
---
apiVersion: kukeon.io/v1beta1
kind: Secret
metadata: {name: s}
spec: {data: {K: v}}
"""
    docs = parser.sort_documents(parser.parse_documents(blob))
    assert [d.kind for d in docs] == ["Realm", "Secret", "Cell"]
    rev = parser.sort_documents(docs, reverse=True)
    assert [d.kind for d in rev] == ["Cell", "Secret", "Realm"]


def test_wire_roundtrip_cell_record():
    docs = parser.parse_documents(CELL_YAML)
    rec = model.cell_record_from_doc(scheme.normalize(docs[0]))
    d = rec.to_json()
    rec2 = model.CellRecord.from_json(d)
    assert rec2.name == rec.name
    assert rec2.spec.containers[0].restart_policy.backoff_seconds == 2.0
    assert rec2.spec.containers[0].resources.tpu_chips == 2


def test_metadata_store(tmp_path):
    store = MetadataStore(str(tmp_path))
    store.write_json({"a": 1}, "realms", "default", "realm.json")
    assert store.read_json("realms", "default", "realm.json") == {"a": 1}
    assert store.list_dirs("realms") == ["default"]
    with store.lock("realms", "default"):
        store.write_json({"a": 2}, "realms", "default", "realm.json")
    assert store.read_json("realms", "default", "realm.json")["a"] == 2
    assert store.delete("realms", "default", "realm.json")
    assert not store.delete("realms", "default", "realm.json")


def test_serving_cell_stop_strings():
    """`stop` strings cut generation (and text) at the first match in both
    modes; `stopTokens` stop token-exactly."""
    import numpy as np

    from kukeon_tpu.runtime.serving_cell import ServingCell

    cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                       checkpoint=None, dtype=None)
    base = cell.generate({"prompt": "hello", "maxNewTokens": 6})
    assert base["numTokens"] == 6

    # Token-level stop: replay greedy and stop at the 2nd generated token.
    stop_tok = base["tokens"][1]
    out = cell.generate({"prompt": "hello", "maxNewTokens": 6,
                         "stopTokens": [int(stop_tok)]})
    assert out["tokens"] == base["tokens"][:2]

    # String-level stop: pick a substring of the full decode that first
    # appears at a known offset; text must be cut before it.
    full = base["text"]
    if len(full) >= 2:
        stop_s = full[1:2]
        out = cell.generate({"prompt": "hello", "maxNewTokens": 6,
                             "stop": stop_s})
        assert stop_s not in out["text"]
        assert full.startswith(out["text"])

    # Streaming mode agrees: terminal record marks stopped and the joined
    # deltas equal the final text.
    recs = list(cell.generate_stream({"prompt": "hello", "maxNewTokens": 6,
                                      "stop": [full[1:2]] if len(full) >= 2
                                      else ["zzz"]}))
    final = recs[-1]
    assert "".join(r["text"] for r in recs[:-1]) == final["text"]

    # Validation: bad stop type is a clean 400-class error.
    import pytest as _pytest

    with _pytest.raises(ValueError, match="stop"):
        cell.generate({"prompt": "x", "stop": [42]})


def test_serving_cell_prefix_id_passthrough():
    """`prefixId` flows from the HTTP request shape through to the engine's
    prefix cache (hit visible in /v1/stats)."""
    from kukeon_tpu.runtime.serving_cell import ServingCell

    cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                       checkpoint=None, dtype=None)
    cell.generate({"prompt": "system prompt", "maxNewTokens": 2,
                   "prefixId": "sess"})
    cell.generate({"prompt": "system prompt and more", "maxNewTokens": 2,
                   "prefixId": "sess"})
    pc = cell.stats()["prefixCache"]
    assert pc == {"hits": 1, "misses": 1, "entries": 1}

    import pytest as _pytest

    with _pytest.raises(ValueError, match="prefixId"):
        cell.generate({"prompt": "x", "prefixId": 42})


def test_stream_deltas_survive_split_utf8_codepoint():
    """A multi-byte codepoint split across tokens decodes to U+FFFD until
    its last byte arrives; the stream must hold the provisional tail back
    (never emit a replacement char that will be rewritten) and the joined
    deltas must equal the final text (ADVICE r5, ISSUE 1 satellite)."""
    import threading

    import numpy as np  # noqa: F401 — prompt encoding below

    from kukeon_tpu.runtime.serving_cell import ServingCell

    cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                       checkpoint=None, dtype=None)

    # Script the engine: "h", then "é" split across two byte tokens, "!".
    script = [0x68] + list("é".encode()) + [0x21]

    class FakeReq:
        def __init__(self):
            self.done = threading.Event()
            self.error = None
            self.cancelled = False
            self.timed_out = False

        def cancel(self):
            self.cancelled = True

    class FakeEngine:
        _running = True   # consumer loop reads straight off the queue

        def submit(self, prompt, sp, emit=None, prefix_id=None,
                   deadline_s=None, trace_ctx=None):
            r = FakeReq()
            for i, tok in enumerate(script):
                emit(tok, i == len(script) - 1)
            r.done.set()
            return r

    cell.engine = FakeEngine()
    recs = list(cell.generate_stream({"prompt": "x", "maxNewTokens": 8}))
    final = recs[-1]
    deltas = [r["text"] for r in recs[:-1]]
    assert "".join(deltas) == "hé!" == final["text"]
    assert not any("�" in d for d in deltas)
    # The split codepoint's first byte emitted an empty (held back) delta,
    # completed on the next token.
    assert deltas == ["h", "", "é", "!"]


def test_ndjson_midstream_error_stays_in_band():
    """A generator failure AFTER headers went out must surface as a
    terminal {"error": ...} ndjson line — not as a second interleaved HTTP
    status line corrupting the open stream (ADVICE r5, ISSUE 1 satellite)."""
    import http.client
    import json
    import threading
    from http.server import ThreadingHTTPServer

    from kukeon_tpu.runtime.serving_cell import make_handler

    class BoomCell:
        model_name = "boom"

        def generate(self, req, trace_ctx=None):
            raise AssertionError("non-stream path not under test")

        def generate_stream(self, req, trace_ctx=None):
            yield {"token": 1, "text": "a"}
            yield {"token": 2, "text": "b"}
            raise RuntimeError("device lost mid-stream")

    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(BoomCell()))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1",
                                          server.server_address[1], timeout=10)
        conn.request("POST", "/v1/generate", body=json.dumps({
            "prompt": "x", "stream": True}), headers={
            "Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        raw = resp.read()
        conn.close()
    finally:
        server.shutdown()
        server.server_close()
    assert b"HTTP/" not in raw          # no second status line in the body
    lines = [json.loads(x) for x in raw.decode().splitlines()]
    assert lines[0] == {"token": 1, "text": "a"}
    assert lines[1] == {"token": 2, "text": "b"}
    assert lines[2]["error"].startswith("RuntimeError")
