"""Mixtral-style MoE model: routing numerics, expert parallelism, cache
decode, and the expert-sharded training step (the ``expert`` mesh axis's
workload — dispatch/combine all-to-alls inserted by GSPMD)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kukeon_tpu.models import moe
from kukeon_tpu.parallel import make_mesh, set_mesh


@pytest.fixture(scope="module")
def tiny():
    cfg = moe.moe_tiny()
    params = moe.init_params(jax.random.key(0), cfg)
    return cfg, params


def _naive_moe_block(h, w, cfg):
    """Reference: per-token python loop over top-k experts (no capacity)."""
    B, S, H = h.shape
    x = h.reshape(-1, H)
    logits = np.asarray(x.astype(jnp.float32) @ w["router"])
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    out = np.zeros_like(np.asarray(x), dtype=np.float32)
    K = cfg.experts_per_token
    for n in range(x.shape[0]):
        top = np.argsort(-probs[n])[:K]
        gates = probs[n][top]
        gates = gates / gates.sum()
        for gate, e in zip(gates, top):
            xe = np.asarray(x[n]).astype(np.float32)
            g = np.asarray(jax.nn.silu(jnp.asarray(xe @ np.asarray(w["w_gate"][e], np.float32))))
            u = xe @ np.asarray(w["w_up"][e], np.float32)
            y = (g * u) @ np.asarray(w["w_down"][e], np.float32)
            out[n] += gate * y
    return out.reshape(B, S, H)


def test_moe_block_matches_naive_loop(tiny):
    """Dense-dispatch einsum formulation == per-token expert loop when
    capacity is large enough that nothing drops."""
    cfg, params = tiny
    w = {k: v[0] for k, v in params["layers"].items()}   # layer 0 slice
    h = jax.random.normal(jax.random.key(3), (2, 6, cfg.hidden_size), jnp.float32)

    got, aux = moe.moe_block(h, w, cfg)
    want = _naive_moe_block(h, w, cfg)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)
    assert float(aux["load_balance"]) > 0.0
    assert float(aux["router_z"]) >= 0.0


def test_capacity_drops_overflow_tokens(tiny):
    """With capacity 1 slot per expert, most tokens overflow: the MoE output
    must stay finite and bounded (dropped tokens contribute zero, residual
    carries them)."""
    cfg, params = tiny
    cfg1 = dataclasses.replace(cfg, capacity_factor=1e-6)   # floor -> K slots
    w = {k: v[0] for k, v in params["layers"].items()}
    h = jax.random.normal(jax.random.key(4), (2, 8, cfg.hidden_size), jnp.float32)
    got, _ = moe.moe_block(h, w, cfg1)
    assert np.isfinite(np.asarray(got)).all()
    # Strictly fewer tokens served than the no-drop run touches.
    full, _ = moe.moe_block(h, w, cfg)
    served = np.count_nonzero(np.abs(np.asarray(got)).sum(-1) > 1e-9)
    served_full = np.count_nonzero(np.abs(np.asarray(full)).sum(-1) > 1e-9)
    assert served < served_full


def test_forward_shapes_and_determinism(tiny):
    cfg, params = tiny
    B, S = 2, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    logits, cache = moe.forward(params, cfg, tokens, positions)
    assert logits.shape == (B, S, cfg.vocab_size)
    assert cache is None
    logits2, _ = moe.forward(params, cfg, tokens, positions)
    np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))


def test_cached_decode_matches_full_forward(tiny):
    """Prefill-into-cache + single-token decode == uncached full forward at
    the same positions (the llama.KVCache layout carried over)."""
    from kukeon_tpu.models.llama import KVCache

    cfg, params = tiny
    B, S = 1, 12
    tokens = jax.random.randint(jax.random.key(2), (B, S + 1), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S + 1, dtype=jnp.int32)[None, :], (B, S + 1))

    full_logits, _ = moe.forward(params, cfg, tokens, positions)

    cache = KVCache.create(cfg, B, 32)
    _, cache = moe.forward(params, cfg, tokens[:, :S], positions[:, :S], cache)
    step_logits, cache = moe.forward(
        params, cfg, tokens[:, S:S + 1], positions[:, S:S + 1], cache
    )
    np.testing.assert_allclose(
        np.asarray(step_logits[0, 0]), np.asarray(full_logits[0, S]),
        rtol=2e-4, atol=2e-4,
    )


def test_expert_parallel_mesh_parity(tiny):
    """expert=2 x tensor=2 sharded forward == single-device forward: the
    all-to-all dispatch must not change numerics."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kukeon_tpu.parallel import moe_specs_for_params

    cfg, params = tiny
    B, S = 2, 8
    tokens = jax.random.randint(jax.random.key(5), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
    want, _ = moe.forward(params, cfg, tokens, positions)

    mesh = make_mesh(expert=2, tensor=2, data=2)
    specs = moe_specs_for_params(params)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P),
    )
    with set_mesh(mesh):
        got, _ = jax.jit(
            lambda p, t, pos: moe.forward(p, cfg, t, pos)
        )(sharded, tokens, positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_load_balance_loss_semantics(tiny):
    """Switch LB loss == 1.0 under perfectly uniform routing; >> 1 when the
    router collapses onto one expert."""
    cfg, _ = tiny
    E = cfg.num_experts
    N, H = 64, cfg.hidden_size
    h = jax.random.normal(jax.random.key(6), (1, N, H), jnp.float32)
    w_shapes = moe.init_params(jax.random.key(7), cfg)["layers"]
    w = {k: v[0] for k, v in w_shapes.items()}

    # Uniform router: zero logits -> equal probs; first-choice assignment is
    # argmax tie-broken to expert 0, so use tiny symmetric noise instead.
    w_uni = dict(w)
    w_uni["router"] = jnp.zeros((H, E), jnp.float32)
    _, aux_uni = moe.moe_block(h, w_uni, cfg)
    # f_e ~ onehot ties all to expert 0 with zero logits; accept [1, E].
    assert 1.0 <= float(aux_uni["load_balance"]) <= E + 1e-3

    # Collapsed router: huge bias onto expert 0 -> f_0 = P_0 = 1 -> loss = E.
    w_col = dict(w)
    router = np.zeros((H, E), np.float32)
    h_col = jnp.ones((1, N, H), jnp.float32)
    router[:, 0] = 1.0
    w_col["router"] = jnp.asarray(router)
    _, aux_col = moe.moe_block(h_col, w_col, cfg)
    assert float(aux_col["load_balance"]) >= E - 1e-2


def test_moe_train_step_on_expert_mesh():
    """One full MoE training step over an expert x tensor x data mesh:
    finite loss, step increments, metrics include the aux terms."""
    from kukeon_tpu.training import create_moe_train_state, make_moe_train_step
    from kukeon_tpu.training.train_step import make_optimizer

    cfg = moe.moe_tiny()
    mesh = make_mesh(expert=2, tensor=2, data=2)
    with set_mesh(mesh):
        optimizer = make_optimizer(warmup_steps=1, total_steps=10)
        state, optimizer = create_moe_train_state(cfg, mesh, jax.random.key(0), optimizer)
        train_step, batch_sharding = make_moe_train_step(cfg, mesh, optimizer)

        B, S = 4, 32
        tokens = jax.device_put(
            jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size),
            batch_sharding,
        )
        targets = jnp.roll(tokens, -1, axis=1)
        mask = jax.device_put(jnp.ones((B, S), jnp.float32), batch_sharding)
        state, metrics = train_step(state, tokens, targets, mask)
        loss0 = float(metrics["loss"])
        state, metrics = train_step(state, tokens, targets, mask)
    assert np.isfinite(loss0)
    assert np.isfinite(float(metrics["loss"]))
    assert int(state.step) == 2
    assert float(metrics["load_balance"]) > 0
    assert "ce" in metrics and "router_z" in metrics


def test_moe_serves_through_engine(tiny):
    """The continuous-batching engine is model-pluggable: moe.forward +
    expert specs serve through it, and greedy outputs match a direct
    uncached forward argmax loop."""
    from kukeon_tpu.parallel import moe_specs_for_params
    from kukeon_tpu.serving import SamplingParams, ServingEngine

    cfg, params = tiny
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=64,
                        forward_fn=moe.forward,
                        param_specs=moe_specs_for_params(params))
    prompt = np.arange(2, 12, dtype=np.int32) % cfg.vocab_size
    got = eng.generate(prompt, SamplingParams(temperature=0.0, max_new_tokens=6))

    tokens = list(prompt)
    want = []
    for _ in range(6):
        t = jnp.asarray(tokens, jnp.int32)[None, :]
        pos = jnp.arange(len(tokens), dtype=jnp.int32)[None, :]
        logits, _ = moe.forward(params, cfg, t, pos)
        nxt = int(jnp.argmax(logits[0, -1]))
        want.append(nxt)
        tokens.append(nxt)
    assert got == want


def test_moe_serving_cell_http_roundtrip():
    """ServingCell boots a mixtral-tiny engine and answers /v1/generate
    (model registry + engine pluggability end to end, no daemon)."""
    from kukeon_tpu.runtime.serving_cell import ServingCell

    cell = ServingCell("mixtral-tiny", num_slots=2, max_seq_len=64,
                       checkpoint=None, dtype=None)
    out = cell.generate({"prompt": "hi", "maxNewTokens": 4})
    assert out["numTokens"] == 4
    assert len(out["tokens"]) == 4

    with pytest.raises(SystemExit, match="kv-cache-int8"):
        ServingCell("mixtral-tiny", num_slots=2, max_seq_len=64,
                    checkpoint=None, dtype=None, kv_cache_int8=True)


def test_hf_mixtral_checkpoint_roundtrip(tmp_path, tiny):
    """moe params written in the HF Mixtral safetensors layout load back
    identically through hf_convert.load_moe_params (incl. the transposes),
    and the loaded tree's forward matches the original's."""
    import json

    from safetensors.numpy import save_file

    from kukeon_tpu.models import hf_convert

    cfg, params = tiny
    L, E = cfg.num_layers, cfg.num_experts
    flat = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    lw = params["layers"]
    for i in range(L):
        p = f"model.layers.{i}."
        flat[p + "input_layernorm.weight"] = np.asarray(lw["attn_norm"][i], np.float32)
        flat[p + "post_attention_layernorm.weight"] = np.asarray(lw["mlp_norm"][i], np.float32)
        for ours, hf in (("wq", "q_proj"), ("wk", "k_proj"),
                         ("wv", "v_proj"), ("wo", "o_proj")):
            flat[p + f"self_attn.{hf}.weight"] = np.ascontiguousarray(
                np.asarray(lw[ours][i], np.float32).T)
        flat[p + "block_sparse_moe.gate.weight"] = np.ascontiguousarray(
            np.asarray(lw["router"][i], np.float32).T)
        for e in range(E):
            q = f"{p}block_sparse_moe.experts.{e}."
            flat[q + "w1.weight"] = np.ascontiguousarray(
                np.asarray(lw["w_gate"][i, e], np.float32).T)
            flat[q + "w3.weight"] = np.ascontiguousarray(
                np.asarray(lw["w_up"][i, e], np.float32).T)
            flat[q + "w2.weight"] = np.ascontiguousarray(
                np.asarray(lw["w_down"][i, e], np.float32).T)
    save_file(flat, str(tmp_path / "model.safetensors"))
    (tmp_path / "config.json").write_text(json.dumps({
        "architectures": ["MixtralForCausalLM"],
        "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": L, "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads, "head_dim": cfg.head_dim,
        "num_local_experts": E, "num_experts_per_tok": cfg.experts_per_token,
        "rope_theta": cfg.rope_theta, "rms_norm_eps": cfg.rms_norm_eps,
        "max_position_embeddings": cfg.max_seq_len,
        "tie_word_embeddings": True,
    }))

    loaded, lcfg = hf_convert.load_moe_params(str(tmp_path), dtype=jnp.float32)
    assert lcfg.num_experts == E and lcfg.experts_per_token == cfg.experts_per_token
    # capacity_factor is a serving knob, not an HF field; align for parity.
    lcfg = dataclasses.replace(lcfg, capacity_factor=cfg.capacity_factor)
    for a, b in zip(jax.tree.leaves(loaded), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0, rtol=0)

    tokens = jax.random.randint(jax.random.key(8), (1, 8), 0, cfg.vocab_size)
    positions = jnp.arange(8, dtype=jnp.int32)[None, :]
    want, _ = moe.forward(params, cfg, tokens, positions)
    got, _ = moe.forward(loaded, lcfg, tokens, positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_inference_capacity_never_drops_decode_tokens(tiny):
    """Serving (cache-marked) capacity is exact for decode-sized batches:
    under routing collapse the training drop policy zeroes overflow tokens'
    expert compute, the inference policy must not (code-review r5)."""
    cfg, params = tiny
    tight = dataclasses.replace(cfg, capacity_factor=0.5)
    w = {k: v[0] for k, v in params["layers"].items()}
    # Collapse the router onto expert 0 for every token.
    w = dict(w)
    router = np.zeros((cfg.hidden_size, cfg.num_experts), np.float32)
    router[:, 0] = 1.0
    w["router"] = jnp.asarray(router)
    h = jnp.ones((2, 8, cfg.hidden_size), jnp.float32)   # N=16 tokens

    want = _naive_moe_block(h, w, tight)                 # no-drop reference
    got_inf, _ = moe.moe_block(h, w, tight, inference=True)
    np.testing.assert_allclose(np.asarray(got_inf), want, rtol=2e-4, atol=2e-4)

    got_train, _ = moe.moe_block(h, w, tight)            # drops by design
    assert not np.allclose(np.asarray(got_train), want, rtol=2e-4, atol=2e-4)


def test_quantized_moe_forward_tracks_fp(tiny):
    """Weights-only int8 MoE: logits stay close to full-precision (per-
    channel symmetric quantization noise only), and the quantized tree
    serves through the engine on an expert-sharded mesh identically to a
    single device."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kukeon_tpu.parallel import moe_specs_for_params
    from kukeon_tpu.serving import SamplingParams, ServingEngine

    cfg, params = tiny
    qp = moe.quantize_params(params)
    B, S = 1, 12
    tokens = jax.random.randint(jax.random.key(11), (B, S), 0, cfg.vocab_size)
    positions = jnp.arange(S, dtype=jnp.int32)[None, :]
    fp, _ = moe.forward(params, cfg, tokens, positions)
    q, _ = moe.forward(qp, cfg, tokens, positions)
    err = np.abs(np.asarray(q) - np.asarray(fp)).mean()
    scale = np.abs(np.asarray(fp)).mean() + 1e-9
    assert err / scale < 0.05, f"relative error {err/scale:.3f}"

    specs = moe_specs_for_params(qp)
    mesh2 = make_mesh(expert=2, tensor=2, data=2)
    eng2 = ServingEngine(cfg, qp, mesh2, num_slots=2, max_seq_len=64,
                         forward_fn=moe.forward, param_specs=specs)
    mesh1 = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng1 = ServingEngine(cfg, qp, mesh1, num_slots=2, max_seq_len=64,
                         forward_fn=moe.forward, param_specs=specs)
    prompt = np.arange(2, 12, dtype=np.int32) % cfg.vocab_size
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)
    assert eng2.generate(prompt, sp) == eng1.generate(prompt, sp)


def test_quantized_moe_serving_cell():
    from kukeon_tpu.runtime.serving_cell import ServingCell

    cell = ServingCell("mixtral-tiny", num_slots=2, max_seq_len=64,
                       checkpoint=None, dtype="int8")
    out = cell.generate({"prompt": "hi", "maxNewTokens": 3})
    assert out["numTokens"] == 3


def test_int8_pallas_moe_decode_parity(tiny):
    """MoE fused int8 decode (attention trunk via llama._mm, expert stacks
    via int8_matmul_expert) must match the dequant-in-einsum path
    numerically — the ISSUE 1 parity criterion for the MoE family."""
    import dataclasses

    cfg, params = tiny
    qp = moe.quantize_params(params)
    cfg_pl = dataclasses.replace(cfg, int8_pallas=True)
    B, S = 2, 8
    tokens = jax.random.randint(jax.random.key(3), (B, S), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    cache = moe.KVCache.create(cfg, B, 32)
    _, cache = moe.forward(qp, cfg, tokens, positions, cache)

    step = jax.random.randint(jax.random.key(4), (B, 1), 0, cfg.vocab_size)
    step_pos = cache.lengths[:, None]
    want, _ = moe.forward(qp, cfg, step, step_pos, cache)
    got, _ = moe.forward(qp, cfg_pl, step, step_pos, cache)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_int8_pallas_moe_engine_generation(tiny):
    """End-to-end: a quantized MoE engine with int8_pallas=True generates
    the same greedy tokens as the default routing."""
    import dataclasses

    from kukeon_tpu.parallel import moe_specs_for_params
    from kukeon_tpu.serving import SamplingParams, ServingEngine

    cfg, params = tiny
    qp = moe.quantize_params(params)
    specs = moe_specs_for_params(qp)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    prompt = np.arange(1, 20, dtype=np.int32) % cfg.vocab_size
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)

    eng = ServingEngine(cfg, qp, mesh, num_slots=2, max_seq_len=64,
                        forward_fn=moe.forward, param_specs=specs)
    want = eng.generate(prompt, sp)
    eng_pl = ServingEngine(cfg, qp, mesh, num_slots=2, max_seq_len=64,
                           forward_fn=moe.forward, param_specs=specs,
                           int8_pallas=True)
    assert eng_pl.cfg.int8_pallas
    got = eng_pl.generate(prompt, sp)
    assert got == want
