"""Disaggregated prefill/decode serving (ISSUE 11): two-stage routing,
page-granular KV export→import parity, one-trace handoff observability,
and graceful degradation to local decode when the decode pool fails.

Replica failure is always *scripted* (server shutdown, armed fault point,
stale routing snapshots), never timed — same philosophy as the gateway and
resilience suites. Every cell here is a REAL ServingCell over real HTTP;
the tiny model keeps it CPU-cheap.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from kukeon_tpu import faults
from kukeon_tpu.gateway.cell import GatewayCell, make_gateway_handler
from kukeon_tpu.gateway.router import (
    POLICY_AFFINITY,
    POLICY_PREFILL_QUEUE,
    Router,
)
from kukeon_tpu.runtime.serving_cell import (
    ServingCell,
    make_handler,
    pack_kv,
    unpack_kv,
)


# --- router two-stage units --------------------------------------------------


def _static_router(roles: list[str]) -> Router:
    r = Router([(f"r{i}", f"http://127.0.0.1:{21000 + i}")
                for i in range(len(roles))])
    for rep, role in zip(r.replicas, roles):
        rep.role = role
        rep.ready = True
    return r


def test_router_mixed_census_is_not_disaggregated():
    r = _static_router(["mixed", "mixed", "mixed"])
    assert not r.disaggregated()
    # pick() with no pool is the pre-role behavior: full set.
    rep, _ = r.pick()
    assert rep is not None


def test_router_pick_prefill_by_queue_depth():
    r = _static_router(["prefill", "prefill", "decode"])
    assert r.disaggregated()
    r.by_name["r0"].queue_depth = 5
    r.by_name["r1"].queue_depth = 1
    rep, policy = r.pick_prefill()
    assert rep.name == "r1"
    assert policy == POLICY_PREFILL_QUEUE
    # The decode-only replica is never a prefill candidate, even when
    # everything prefill-capable is excluded.
    rep, policy = r.pick_prefill(exclude={"r0", "r1"})
    assert rep is None and policy is None


def test_router_pick_decode_affinity_and_fallback():
    r = _static_router(["prefill", "decode", "decode"])
    # Rendezvous over the decode pool only: a prefix maps to one decode
    # replica, stably.
    affine = r.affine("sess-42", pool="decode")
    assert affine.name in ("r1", "r2")
    rep, policy = r.pick_decode("sess-42")
    assert rep.name == affine.name
    assert policy == POLICY_AFFINITY
    # Affine replica down -> least-loaded decode-capable fallback; the
    # prefill replica is never eligible.
    affine.ready = False
    rep, _policy = r.pick_decode("sess-42")
    assert rep is not None and rep.name != affine.name
    assert rep.decode_capable()


def test_router_pool_filter_on_pick():
    r = _static_router(["prefill", "decode"])
    rep, _ = r.pick(pool="prefill")
    assert rep.name == "r0"
    rep, _ = r.pick(pool="decode")
    assert rep.name == "r1"


# --- real-cell stack helpers -------------------------------------------------


def _make_cell(role: str, **kw) -> tuple[ServingCell, ThreadingHTTPServer]:
    cell = ServingCell("tiny", num_slots=2, max_seq_len=128,
                       checkpoint=None, dtype=None, kv_page_tokens=16,
                       max_pending=256, role=role, **kw)
    cell.engine.start()
    cell.mark_ready()
    srv = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return cell, srv


def _make_stack(roles=("prefill", "decode"), poll_interval_s=0.05):
    cells, servers, urls = [], [], []
    for role in roles:
        cell, srv = _make_cell(role)
        cells.append(cell)
        servers.append(srv)
        urls.append(f"http://127.0.0.1:{srv.server_address[1]}")
    gw = GatewayCell("tiny", urls, poll_interval_s=poll_interval_s,
                     request_timeout_s=60.0)
    gw.start()
    gw.router.poll_once()
    gw_srv = ThreadingHTTPServer(("127.0.0.1", 0), make_gateway_handler(gw))
    threading.Thread(target=gw_srv.serve_forever, daemon=True).start()
    return cells, servers, gw, gw_srv


def _teardown(cells, servers, gw, gw_srv):
    gw_srv.shutdown()
    gw_srv.server_close()
    gw.stop()
    for srv in servers:
        try:
            srv.shutdown()
            srv.server_close()
        except OSError:
            pass
    for cell in cells:
        cell.engine.stop()


def _post(port: int, path: str, body, timeout: float = 60.0,
          headers: dict | None = None, raw: bool = False):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    payload = body if isinstance(body, (bytes, bytearray)) else \
        json.dumps(body)
    conn.request("POST", path, body=payload,
                 headers={"Content-Type": "application/json",
                          **(headers or {})})
    resp = conn.getresponse()
    data = resp.read()
    status = resp.status
    conn.close()
    if raw:
        return status, data
    return status, (json.loads(data) if data else {})


# --- role census -------------------------------------------------------------


def test_role_census_in_stats_and_gateway_snapshot():
    cells, servers, gw, gw_srv = _make_stack(("prefill", "decode"))
    try:
        assert cells[0].stats()["role"] == "prefill"
        assert cells[1].stats()["role"] == "decode"
        # The gateway learned both roles from its poll and reports them in
        # its own stats (the fleet's routing view).
        snap = {r["name"]: r["role"]
                for r in gw.stats()["replicas"]}
        assert snap == {"r0": "prefill", "r1": "decode"}
        assert gw.router.disaggregated()
    finally:
        _teardown(cells, servers, gw, gw_srv)


# --- export -> import parity -------------------------------------------------


def test_paged_export_import_roundtrip_greedy_parity():
    """A handed-off request decodes byte-identically to a single-cell one:
    export on engine A, import on paged engine B, greedy tokens equal the
    single-engine reference."""
    import jax

    from kukeon_tpu.models import llama
    from kukeon_tpu.parallel import auto_mesh_shape, make_mesh
    from kukeon_tpu.serving import SamplingParams, ServingEngine

    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    shape = auto_mesh_shape(len(jax.devices()))
    mesh = make_mesh(data=shape["data"], tensor=shape["tensor"])
    sp = SamplingParams(max_new_tokens=8)
    prompt = np.arange(1, 24, dtype=np.int32)

    def paged_engine():
        return ServingEngine(cfg, params, mesh, num_slots=2,
                             max_seq_len=128, kv_page_tokens=16)

    ref_eng = paged_engine()
    ref = ref_eng.generate(prompt, sp)
    assert len(ref) == 8

    exporter = paged_engine()
    r = exporter.submit(prompt, sp, export=True)
    while not r.done.is_set():
        exporter.step()
    p = r.export_payload
    assert p["token"] == ref[0]
    assert p["length"] == prompt.size
    assert p["k"].shape[2] == prompt.size     # trimmed to real rows
    # No slot, no pages: the exporter's pool is untouched.
    assert exporter._pool.in_use == 0
    assert all(s is None for s in exporter._slot_req)

    importer = paged_engine()
    r2 = importer.submit(prompt, sp, kv_import={
        "token": p["token"], "length": p["length"],
        "k": p["k"], "v": p["v"]})
    while not r2.done.is_set():
        importer.step()
    assert r2.error is None
    assert r2.generated == ref
    # Pages were allocated and freed page-granularly.
    assert importer._pool.in_use == 0

    # The legacy contiguous layout imports the same block identically.
    legacy = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                           kv_page_tokens=0)
    r3 = legacy.submit(prompt, sp, kv_import={
        "token": p["token"], "length": p["length"],
        "k": p["k"], "v": p["v"]})
    while not r3.done.is_set():
        legacy.step()
    assert r3.generated == ref


def test_kv_wire_format_roundtrip():
    k = np.arange(24, dtype=np.float32).reshape(2, 1, 3, 2, 2)
    v = k + 100
    body = pack_kv({"token": 7, "length": 3}, k, v)
    header, k2, v2 = unpack_kv(body)
    assert header["token"] == 7
    assert header["shape"] == [2, 1, 3, 2, 2]
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)
    with pytest.raises(ValueError, match="truncated"):
        unpack_kv(body[:-4])


# --- disaggregated e2e: one trace, two hops ---------------------------------


def test_disagg_e2e_one_trace_with_both_hops():
    cells, servers, gw, gw_srv = _make_stack(("prefill", "decode"))
    try:
        ref = cells[1].generate({"promptTokens": list(range(1, 20)),
                                 "maxNewTokens": 6})
        status, out = _post(gw_srv.server_address[1], "/v1/generate",
                            {"promptTokens": list(range(1, 20)),
                             "maxNewTokens": 6, "prefixId": "sess-1"})
        assert status == 200
        # The handed-off request decodes exactly like the single cell.
        assert out["tokens"] == ref["tokens"]

        # ONE trace: the gateway span is the root; the prefill cell's and
        # decode cell's engine spans are its children.
        gspan = next(s for s in gw.tracer.recent(10)
                     if s["component"] == "gateway"
                     and s.get("attrs", {}).get("route") == "/v1/generate")
        trace_id = gspan["traceId"]
        # The decode engine's tracer.finish runs on the driver thread just
        # after the terminal token is emitted — poll briefly rather than
        # racing it.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            pspans = cells[0].engine.tracer.for_trace(trace_id)
            dspans = cells[1].engine.tracer.for_trace(trace_id)
            if pspans and dspans:
                break
            time.sleep(0.01)
        assert len(pspans) == 1 and len(dspans) == 1
        for espan in (pspans[0], dspans[0]):
            assert espan["parentSpanId"] == gspan["spanId"]
            # Engine phases partition the hop's wall time exactly.
            assert abs(sum(espan["phasesS"].values())
                       - espan["e2eS"]) < 1e-3
        # The hops are recognizably the two halves of the handoff.
        assert any(e["event"] == "kv_exported"
                   for e in pspans[0]["events"])
        assert any(e["event"] == "kv_imported"
                   for e in dspans[0]["events"])
        # The gateway span records the handoff itself, and `kuke trace`
        # renders the hop.
        hand = next(e for e in gspan["events"]
                    if e["event"] == "kv_handoff")
        assert hand["attrs"]["prefill"] == "r0"
        assert hand["attrs"]["decode"] == "r1"
        assert hand["attrs"]["pages"] >= 1

        from kukeon_tpu.runtime.cli import render_trace

        rendered = render_trace(
            trace_id, [gspan, pspans[0], dspans[0]])
        assert "handoff r0->r1" in rendered

        # The handoff cost is on the gateway's own instruments.
        assert gw.registry.get("kukeon_handoff_pages_total").value() >= 1
        assert gw.registry.get("kukeon_handoff_bytes_total").value() > 0
        assert sum(gw.registry.get(
            "kukeon_handoff_seconds").snapshot()[0]) >= 1
    finally:
        _teardown(cells, servers, gw, gw_srv)


def test_disagg_streaming_preserves_tokens_and_text():
    cells, servers, gw, gw_srv = _make_stack(("prefill", "decode"))
    try:
        ref = cells[1].generate({"prompt": "hello world",
                                 "maxNewTokens": 6})
        status, data = _post(gw_srv.server_address[1], "/v1/generate",
                             {"prompt": "hello world", "maxNewTokens": 6,
                              "stream": True}, raw=True)
        assert status == 200
        lines = [json.loads(ln) for ln in data.splitlines()]
        toks = [ln["token"] for ln in lines if "token" in ln]
        assert toks == ref["tokens"]
        text = "".join(ln.get("text", "") for ln in lines if "token" in ln)
        assert text == ref["text"]
        assert lines[-1]["done"] is True
    finally:
        _teardown(cells, servers, gw, gw_srv)


def test_mixed_roles_still_route_single_hop():
    """An all-mixed census must keep today's single-hop path: no handoff
    counters move, requests flow exactly as before roles existed."""
    cells, servers, gw, gw_srv = _make_stack(("mixed", "mixed"))
    try:
        assert not gw.router.disaggregated()
        status, out = _post(gw_srv.server_address[1], "/v1/generate",
                            {"promptTokens": [1, 2, 3], "maxNewTokens": 4})
        assert status == 200
        assert len(out["tokens"]) == 4
        assert gw.registry.get("kukeon_handoff_pages_total").value() == 0
        assert sum(gw.registry.get(
            "kukeon_handoff_seconds").snapshot()[0]) == 0
    finally:
        _teardown(cells, servers, gw, gw_srv)


# --- robustness: kv.handoff fault + decode-pool death ------------------------


def test_kv_handoff_fault_falls_back_to_local_decode(monkeypatch):
    """The armed ``kv.handoff`` fault kills the first import; the gateway
    counts the failure and degrades that request to local decode on the
    prefill-capable replica — the client still gets its 200."""
    cells, servers, gw, gw_srv = _make_stack(("prefill", "decode"))
    try:
        monkeypatch.setenv("KUKEON_FAULTS", "kv.handoff:1:1")
        faults.reset()
        status, out = _post(gw_srv.server_address[1], "/v1/generate",
                            {"promptTokens": list(range(1, 10)),
                             "maxNewTokens": 4})
        assert status == 200
        assert len(out["tokens"]) == 4
        assert faults.fired("kv.handoff") == 1
        assert gw.registry.get("kukeon_handoff_failures_total").value(
            stage="import") == 1
        assert gw.registry.get("kukeon_handoff_fallback_total").value() == 1
        # The fault is exhausted: the next request handoffs normally.
        status, out = _post(gw_srv.server_address[1], "/v1/generate",
                            {"promptTokens": list(range(1, 10)),
                             "maxNewTokens": 4})
        assert status == 200
        assert gw.registry.get("kukeon_handoff_pages_total").value() >= 1
    finally:
        _teardown(cells, servers, gw, gw_srv)


def test_decode_replica_death_mid_handoff_only_200_or_429():
    """Kill the decode replica mid-flood: the router's snapshot still says
    ready (slow poll), so imports dial a dead socket — every affected
    request must degrade to local decode (200) or shed (429); a 5xx is a
    failure of the degradation contract."""
    cells, servers, gw, gw_srv = _make_stack(("prefill", "decode"),
                                             poll_interval_s=30.0)
    try:
        # Warm one full handoff so the import path is proven live first.
        status, _ = _post(gw_srv.server_address[1], "/v1/generate",
                          {"promptTokens": list(range(1, 10)),
                           "maxNewTokens": 3})
        assert status == 200

        # The decode replica dies. The stale routing snapshot still lists
        # it ready — the next imports hit a refused connection.
        servers[1].shutdown()
        servers[1].server_close()
        cells[1].engine.stop()

        statuses: dict[int, int] = {}
        lock = threading.Lock()

        def one(i: int) -> None:
            s, _ = _post(gw_srv.server_address[1], "/v1/generate",
                         {"promptTokens": list(range(1, 10 + i)),
                          "maxNewTokens": 3}, timeout=60.0)
            with lock:
                statuses[s] = statuses.get(s, 0) + 1

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(6)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert set(statuses) <= {200, 429}, statuses
        assert statuses.get(200, 0) >= 1
        assert gw.registry.get("kukeon_handoff_fallback_total").value() >= 1
        assert gw.registry.get("kukeon_handoff_failures_total").value(
            stage="import") >= 1
    finally:
        _teardown(cells, servers, gw, gw_srv)


def test_import_sheds_429_when_decode_queue_full():
    """An import landing on a saturated decode engine sheds with the same
    429 + Retry-After contract as /v1/generate — the gateway (and any
    client) needs no new failure vocabulary."""
    cell, srv = _make_cell("decode")
    try:
        eng = cell.engine
        # Saturate: stop the engine loop so nothing drains, fill pending.
        eng.stop()
        eng.max_pending = 1
        eng.submit(np.asarray([1, 2, 3], np.int32))
        body = pack_kv({"token": 5, "length": 3,
                        "promptTokens": [1, 2, 3], "maxNewTokens": 4},
                       np.zeros((2, 1, 3, 2, 32), np.float32),
                       np.zeros((2, 1, 3, 2, 32), np.float32))
        status, out = _post(srv.server_address[1], "/v1/kv/import", body)
        assert status == 429
        assert "error" in out
    finally:
        srv.shutdown()
        srv.server_close()
        cell.engine.stop()
