"""Native toolchain provenance: the shipped binaries must be rebuildable.

The round-2/3 verdicts found ``runtime/bin/kukecell`` one commit stale versus
its source — a security binary whose provenance could not be verified.  This
suite makes that class of drift a test failure: every native tool must compile
cleanly from the checked-in source, and the freshly built binary must be
byte-identical to the shipped one (same host, same g++, -O2 — deterministic
in practice; if a toolchain bump ever breaks byte-identity the assertion
message says how to re-provenance).

Reference analog: the reference builds its binaries in CI on every commit
(Makefile:44, .github/workflows/test.yaml) so binaries can never go stale;
we ship prebuilt binaries and verify instead.
"""

import pathlib
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
SHIPPED = REPO / "kukeon_tpu" / "runtime" / "bin"
TOOLS = ["kukepause", "kukeshim", "kuketty", "kukecell", "kukenet"]

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="native toolchain not available",
)


@pytest.fixture(scope="module")
def fresh_build(tmp_path_factory):
    """Build all native tools from source into a scratch BIN dir."""
    bin_dir = tmp_path_factory.mktemp("native-bin")
    proc = subprocess.run(
        ["make", "-C", str(NATIVE), f"BIN={bin_dir}"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, (
        f"make -C native failed:\n{proc.stdout}\n{proc.stderr}"
    )
    return bin_dir


def test_all_tools_compile(fresh_build):
    for tool in TOOLS:
        assert (fresh_build / tool).exists(), f"{tool} not produced by make"


@pytest.mark.parametrize("tool", TOOLS)
def test_shipped_binary_matches_source(fresh_build, tool):
    shipped = SHIPPED / tool
    assert shipped.exists(), f"shipped binary missing: {shipped}"
    fresh = (fresh_build / tool).read_bytes()
    assert shipped.read_bytes() == fresh, (
        f"{tool}: shipped binary differs from a fresh build of the checked-in "
        f"source — it is stale. Run `make -C native` and commit runtime/bin/{tool}."
    )


def test_kukecell_user_validation_shipped():
    """The --user numeric-validation fix must actually be in the shipped binary."""
    data = (SHIPPED / "kukecell").read_bytes()
    assert b"numeric UID" in data, (
        "shipped kukecell lacks the --user numeric-validation string; rebuild"
    )
