"""Checkpoint tooling: HF-layout synthesis, streaming int8 load, quantized
checkpoint save/load, and the int8_pallas flag plumbing (VERDICT r3 items
1 & 4)."""

from __future__ import annotations

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np

from kukeon_tpu.models import checkpoints, hf_convert, llama


def _tiny_cfg():
    return llama.llama_tiny()


class TestSynthesize:
    def test_hub_layout_and_loadable(self, tmp_path):
        cfg = _tiny_cfg()
        path = checkpoints.synthesize_hf_checkpoint(
            str(tmp_path), cfg, dtype=np.float32, tokenizer=False
        )
        assert (tmp_path / "config.json").exists()
        assert (tmp_path / "model.safetensors.index.json").exists()
        index = json.loads((tmp_path / "model.safetensors.index.json").read_text())
        # canonical n-of-m shard names
        for shard in index["weight_map"].values():
            assert shard.startswith("model-000")
        params, loaded = hf_convert.load_params(path, dtype=jnp.float32)
        assert loaded.hidden_size == cfg.hidden_size
        tokens = jnp.array([[1, 2, 3]], jnp.int32)
        pos = jnp.arange(3, dtype=jnp.int32)[None, :]
        logits, _ = llama.forward(params, loaded, tokens, pos)
        assert bool(jnp.isfinite(logits).all())

    def test_idempotent(self, tmp_path):
        cfg = _tiny_cfg()
        checkpoints.synthesize_hf_checkpoint(str(tmp_path), cfg,
                                             dtype=np.float32, tokenizer=False)
        before = sorted(p.name for p in tmp_path.iterdir())
        checkpoints.synthesize_hf_checkpoint(str(tmp_path), cfg,
                                             dtype=np.float32, tokenizer=False)
        assert sorted(p.name for p in tmp_path.iterdir()) == before

    def test_sharding_by_size(self, tmp_path):
        cfg = _tiny_cfg()
        checkpoints.synthesize_hf_checkpoint(
            str(tmp_path), cfg, dtype=np.float32, tokenizer=False,
            max_shard_bytes=256 * 1024,
        )
        index = json.loads((tmp_path / "model.safetensors.index.json").read_text())
        assert len(set(index["weight_map"].values())) > 1
        params, loaded = hf_convert.load_params(str(tmp_path), dtype=jnp.float32)
        assert params["layers"]["wq"].shape[0] == loaded.num_layers

    def test_tokenizer_json_real(self, tmp_path):
        from kukeon_tpu.serving.tokenizer import HFTokenizer, load_tokenizer

        checkpoints.write_tokenizer_json(str(tmp_path))
        tok = load_tokenizer(str(tmp_path))
        assert isinstance(tok, HFTokenizer)
        ids = tok.encode("def main(argv):")
        assert ids[0] == tok.bos_id
        assert tok.decode(ids) == "def main(argv):"


class TestStreamingQuantizedLoad:
    def test_matches_load_then_quantize(self, tmp_path):
        """load_params_quantized == quantize_params(load_params) leaf-wise."""
        cfg = _tiny_cfg()
        checkpoints.synthesize_hf_checkpoint(str(tmp_path), cfg,
                                             dtype=np.float32, tokenizer=False)
        qp_stream, cfg_s = hf_convert.load_params_quantized(str(tmp_path))
        params, _ = hf_convert.load_params(str(tmp_path), dtype=jnp.float32)
        qp_ref = llama.quantize_params(params)

        np.testing.assert_array_equal(
            np.asarray(qp_stream["layers"]["wq"]["q"]),
            np.asarray(qp_ref["layers"]["wq"]["q"]),
        )
        np.testing.assert_allclose(
            np.asarray(qp_stream["layers"]["w_down"]["s"]),
            np.asarray(qp_ref["layers"]["w_down"]["s"]), rtol=1e-6,
        )
        np.testing.assert_array_equal(
            np.asarray(qp_stream["embed"]["q"]), np.asarray(qp_ref["embed"]["q"])
        )

    def test_forward_runs_from_streamed_tree(self, tmp_path):
        cfg = _tiny_cfg()
        checkpoints.synthesize_hf_checkpoint(str(tmp_path), cfg,
                                             dtype=np.float32, tokenizer=False)
        qp, cfg2 = hf_convert.load_params_quantized(str(tmp_path))
        cfg2 = dataclasses.replace(cfg2, dtype=jnp.float32)
        qp = jax.tree.map(jnp.asarray, qp)
        tokens = jnp.array([[1, 2, 3, 4]], jnp.int32)
        pos = jnp.arange(4, dtype=jnp.int32)[None, :]
        logits, _ = llama.forward(qp, cfg2, tokens, pos)
        assert bool(jnp.isfinite(logits).all())


class TestQuantizedCheckpoint:
    def test_roundtrip(self, tmp_path):
        cfg = _tiny_cfg()
        params = llama.init_params(jax.random.key(0), cfg)
        qp = llama.quantize_params(params)
        qdir = tmp_path / "quant"
        checkpoints.save_quantized(str(qdir), jax.tree.map(np.asarray, qp), cfg)
        assert checkpoints.is_quantized_checkpoint(str(qdir))

        loaded, cfg2 = checkpoints.load_quantized(str(qdir), dtype=jnp.float32)
        assert cfg2.vocab_size == cfg.vocab_size
        np.testing.assert_array_equal(
            loaded["layers"]["w_gate"]["q"], np.asarray(qp["layers"]["w_gate"]["q"])
        )
        # Serves identically to the in-memory quantized tree (greedy).
        from kukeon_tpu.parallel import make_mesh
        from kukeon_tpu.serving import SamplingParams, ServingEngine

        mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
        sp = SamplingParams(temperature=0.0, max_new_tokens=8)
        prompt = np.array([3, 1, 4, 1, 5], np.int32)
        out_mem = ServingEngine(cfg, qp, mesh, num_slots=2,
                                max_seq_len=64).generate(prompt, sp)
        out_disk = ServingEngine(cfg2, loaded, mesh, num_slots=2,
                                 max_seq_len=64).generate(prompt, sp)
        assert out_mem == out_disk

    def test_not_quantized_dir(self, tmp_path):
        assert not checkpoints.is_quantized_checkpoint(str(tmp_path))


class TestServingCellLoaders:
    def test_quantized_checkpoint_path(self, tmp_path):
        """ServingCell must take the zero-work int8 path for quantized dirs."""
        import dataclasses

        from kukeon_tpu.runtime.serving_cell import ServingCell

        cfg = dataclasses.replace(_tiny_cfg())
        qp = llama.quantize_params(llama.init_params(jax.random.key(0), cfg))
        qdir = tmp_path / "q"
        checkpoints.save_quantized(str(qdir), jax.tree.map(np.asarray, qp), cfg)
        cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                           checkpoint=str(qdir), dtype=None)
        out = cell.generate({"promptTokens": [3, 1, 4], "maxNewTokens": 4,
                             "temperature": 0.0})
        assert out["numTokens"] == 4

    def test_hf_dir_int8_streams(self, tmp_path, monkeypatch):
        """--dtype int8 + HF dir must stream-quantize, never materialize
        the bf16 tree (the 8B-OOM path the loaders exist to avoid)."""
        from kukeon_tpu.models import hf_convert
        from kukeon_tpu.runtime.serving_cell import ServingCell

        checkpoints.synthesize_hf_checkpoint(str(tmp_path), _tiny_cfg(),
                                             dtype=np.float32, tokenizer=False)

        def boom(*a, **k):
            raise AssertionError("full bf16 load_params used on int8 path")

        monkeypatch.setattr(hf_convert, "load_params", boom)
        cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                           checkpoint=str(tmp_path), dtype="int8")
        out = cell.generate({"promptTokens": [3, 1, 4], "maxNewTokens": 4,
                             "temperature": 0.0})
        assert out["numTokens"] == 4


class TestInt8PallasFlag:
    def test_flag_plumbing_cpu_fallback(self):
        """int8_pallas=True must be a no-op numerically (CPU backend routes
        through the XLA fallback inside int8_matmul)."""
        cfg = _tiny_cfg()
        qp = llama.quantize_params(llama.init_params(jax.random.key(0), cfg))
        cfg8 = dataclasses.replace(cfg, int8_pallas=True)
        B = 2
        cache = llama.KVCache.create(cfg, B, 32)
        cache8 = llama.KVCache.create(cfg, B, 32)
        prompt = jax.random.randint(jax.random.key(1), (B, 8), 0, cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32)[None, :], (B, 8))
        _, cache = llama.forward(qp, cfg, prompt, pos, cache=cache)
        _, cache8 = llama.forward(qp, cfg8, prompt, pos, cache=cache8)
        t = jnp.array([[5], [7]], jnp.int32)
        lg, _ = llama.forward(qp, cfg, t, cache.lengths[:, None], cache=cache)
        lg8, _ = llama.forward(qp, cfg8, t, cache8.lengths[:, None], cache=cache8)
        np.testing.assert_allclose(np.asarray(lg), np.asarray(lg8),
                                   rtol=1e-5, atol=1e-5)

    def test_engine_auto_flag_off_on_cpu(self):
        from kukeon_tpu.parallel import make_mesh
        from kukeon_tpu.serving import ServingEngine

        cfg = _tiny_cfg()
        qp = llama.quantize_params(llama.init_params(jax.random.key(0), cfg))
        mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
        eng = ServingEngine(cfg, qp, mesh, num_slots=2, max_seq_len=64)
        assert eng.cfg.int8_pallas is False   # cpu backend -> auto stays off

    def test_env_knob_requires_tpu_and_auto_clears_on_multichip(self, monkeypatch):
        """KUKEON_INT8_PALLAS=true must not enable pallas on CPU, and auto
        mode must CLEAR a pallas-enabled cfg on a multi-chip mesh (the
        per-layer all-gather hazard)."""
        import dataclasses

        from kukeon_tpu.parallel import make_mesh
        from kukeon_tpu.serving import ServingEngine

        cfg = _tiny_cfg()
        qp = llama.quantize_params(llama.init_params(jax.random.key(0), cfg))
        monkeypatch.setenv("KUKEON_INT8_PALLAS", "true")
        mesh1 = make_mesh(tensor=1, devices=jax.devices()[:1])
        eng = ServingEngine(cfg, qp, mesh1, num_slots=2, max_seq_len=64)
        assert eng.cfg.int8_pallas is False   # cpu backend blocks the env knob

        cfg8 = dataclasses.replace(cfg, int8_pallas=True)
        mesh2 = make_mesh(tensor=2, devices=jax.devices()[:2])
        eng = ServingEngine(cfg8, qp, mesh2, num_slots=2, max_seq_len=64)
        assert eng.cfg.int8_pallas is False   # multi-chip auto-clears

        mesh1b = make_mesh(tensor=1, devices=jax.devices()[:1])
        eng = ServingEngine(cfg8, qp, mesh1b, num_slots=2, max_seq_len=64)
        assert eng.cfg.int8_pallas is True    # single-device cfg flag honored

    def test_engine_explicit_false_clears_cfg_flag(self):
        """int8_pallas=False must override a flag already set on cfg (a
        multi-chip engine handed a pallas cfg would all-gather weights)."""
        import dataclasses

        from kukeon_tpu.parallel import make_mesh
        from kukeon_tpu.serving import ServingEngine

        cfg = dataclasses.replace(_tiny_cfg(), int8_pallas=True)
        qp = llama.quantize_params(llama.init_params(jax.random.key(0), cfg))
        mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
        eng = ServingEngine(cfg, qp, mesh, num_slots=2, max_seq_len=64,
                            int8_pallas=False)
        assert eng.cfg.int8_pallas is False


class TestTokenizerRobustness:
    def test_decode_tolerates_out_of_vocab_ids(self, tmp_path):
        """A random-init model samples the MODEL vocab (e.g. 128256); the
        tokenizer's vocab can be smaller — decode must degrade, not raise."""
        from kukeon_tpu.serving.tokenizer import load_tokenizer

        checkpoints.write_tokenizer_json(str(tmp_path))
        tok = load_tokenizer(str(tmp_path))
        ids = tok.encode("hello")
        garbled = ids + [tok.vocab_size + 999, 127999, -5]
        out = tok.decode(garbled)
        assert "hello" in out
