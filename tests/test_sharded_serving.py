"""Multi-chip tensor-parallel serving (ISSUE 15): a `chips: 2` serving
mesh must be invisible to clients — greedy tokens identical to the
single-chip engine on the legacy, paged, and disagg KV-handoff paths —
while keeping the single-chip engine's compile-stability and host-sync
budgets, and surfacing per-chip HBM through the fleet summarizer.

Every test here uses chips=2 so the file passes under any even forced
device count: 8 locally (conftest default) and 4 in the CI
sharded-serving job (KUKEON_TEST_DEVICES=4).
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kukeon_tpu.models import llama
from kukeon_tpu.obs import Registry, expo
from kukeon_tpu.obs import federate as fed
from kukeon_tpu.parallel import auto_mesh_shape, make_mesh, serving_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine

from test_obs import _parse_expo
from test_serving import _reference_greedy

PROMPT = np.arange(1, 9, dtype=np.int32)
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=8)


def _cfg_params():
    cfg = llama.llama_tiny()           # num_kv_heads=2: shards on chips=2
    return cfg, llama.init_params(jax.random.key(0), cfg)


def _mesh1():
    return make_mesh(tensor=1, devices=jax.devices()[:1])


# --- mesh construction (satellite: non-power-of-two counts) ------------------


def test_auto_mesh_shape_non_power_of_two():
    """auto_mesh_shape must factorize ANY device count (the old
    power-of-two halving loop returned shapes whose product lost chips
    on counts like 6 or 12)."""
    for n in (1, 2, 3, 4, 5, 6, 7, 8, 12, 16, 24):
        shape = auto_mesh_shape(n)
        assert shape["data"] * shape["tensor"] == n, (n, shape)
        assert shape["tensor"] <= 8
    assert auto_mesh_shape(6) == {"data": 1, "tensor": 6}
    assert auto_mesh_shape(12) == {"data": 2, "tensor": 6}
    assert auto_mesh_shape(7) == {"data": 1, "tensor": 7}


def test_serving_mesh_exact_grant_and_loud_failures():
    """serving_mesh(n) is the `chips: n` grant: exactly n devices, all on
    the tensor axis — including non-power-of-two n — and a loud ValueError
    when the grant exceeds what the process can see."""
    m = serving_mesh(2)
    assert m.devices.size == 2 and m.shape["tensor"] == 2
    m3 = serving_mesh(3)                 # non-power-of-two grant
    assert m3.devices.size == 3 and m3.shape["tensor"] == 3
    with pytest.raises(ValueError, match=">= 1 device"):
        serving_mesh(0)
    with pytest.raises(ValueError, match="visible"):
        serving_mesh(len(jax.devices()) + 1)


# --- greedy parity: sharded == single-chip -----------------------------------


def test_sharded_greedy_parity_legacy(chips2_mesh):
    """The tentpole acceptance: a chips=2 engine on the legacy contiguous
    KV layout produces token-identical greedy output to the single-chip
    engine and the uncached full-forward reference, with the KV pool
    actually sharded over the mesh and the gauge reporting 2 chips."""
    cfg, params = _cfg_params()
    eng2 = ServingEngine(cfg, params, chips2_mesh, num_slots=2,
                         max_seq_len=128)
    # llama_tiny's 2 kv heads divide tensor=2: the cache must be sharded,
    # not silently replicated.
    kv_sh, _sc_sh = eng2._cache_shardings()
    assert any(kv_sh.spec), kv_sh.spec
    fams = _parse_expo(expo.render(eng2.registry))
    assert [float(v) for _n, _lab, v
            in fams["kukeon_engine_mesh_chips"]["samples"]] == [2.0]

    got2 = eng2.generate(PROMPT, GREEDY)
    eng1 = ServingEngine(cfg, params, _mesh1(), num_slots=2, max_seq_len=128)
    got1 = eng1.generate(PROMPT, GREEDY)
    want = _reference_greedy(cfg, params, PROMPT, 8)
    assert got2 == got1 == want, (got2, got1, want)

    # Concurrent requests on the sharded mesh keep slot isolation.
    prompts = [np.arange(1 + i, 12 + i, dtype=np.int32) for i in range(3)]
    serial = [eng2.generate(p, GREEDY) for p in prompts]
    reqs = [eng2.submit(p, GREEDY) for p in prompts]
    while not all(r.done.is_set() for r in reqs):
        eng2.step()
    assert [r.generated for r in reqs] == serial


def test_sharded_greedy_parity_paged(chips2_mesh):
    """Same parity on the paged path: the page pool lives sharded over the
    mesh's kv axis while the host-side PageAllocator stays the single
    source of truth — tokens identical, pages drained."""
    cfg, params = _cfg_params()

    def paged(mesh):
        return ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                             kv_page_tokens=16, kv_pool_pages=16)

    eng2 = paged(chips2_mesh)
    got2 = eng2.generate(PROMPT, GREEDY)
    eng1 = paged(_mesh1())
    got1 = eng1.generate(PROMPT, GREEDY)
    assert got2 == got1 == _reference_greedy(cfg, params, PROMPT, 8)
    assert eng2._pool.in_use == 0


def test_sharded_kv_shard_off_replicates_and_matches(chips2_mesh):
    """kv_shard=False (the autotuner's `kvrepl` arm and the divisibility
    fallback) replicates the cache over the sharded mesh — spec empty —
    and still matches the sharded engine token-for-token."""
    cfg, params = _cfg_params()
    eng_rep = ServingEngine(cfg, params, chips2_mesh, num_slots=2,
                            max_seq_len=128, kv_shard=False)
    kv_sh, _ = eng_rep._cache_shardings()
    assert not any(kv_sh.spec), kv_sh.spec
    eng_shd = ServingEngine(cfg, params, chips2_mesh, num_slots=2,
                            max_seq_len=128)
    assert eng_rep.generate(PROMPT, GREEDY) == eng_shd.generate(PROMPT, GREEDY)


def test_sharded_disagg_handoff_parity(chips2_mesh):
    """The disagg KV handoff across sharded engines: export on a chips=2
    paged engine (payload is host numpy, mesh-agnostic), import on another
    chips=2 paged engine, tokens equal the single-chip reference."""
    cfg, params = _cfg_params()
    prompt = np.arange(1, 24, dtype=np.int32)
    sp = SamplingParams(temperature=0.0, max_new_tokens=8)

    def paged(mesh):
        return ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                             kv_page_tokens=16, kv_pool_pages=16)

    ref = paged(_mesh1()).generate(prompt, sp)

    exporter = paged(chips2_mesh)
    r = exporter.submit(prompt, sp, export=True)
    while not r.done.is_set():
        exporter.step()
    p = r.export_payload
    assert p["token"] == ref[0]
    assert p["length"] == prompt.size
    assert isinstance(p["k"], np.ndarray)      # host-side, mesh-agnostic
    assert exporter._pool.in_use == 0

    importer = paged(chips2_mesh)
    r2 = importer.submit(prompt, sp, kv_import={
        "token": p["token"], "length": p["length"],
        "k": p["k"], "v": p["v"]})
    while not r2.done.is_set():
        importer.step()
    assert r2.error is None
    assert r2.generated == ref
    assert importer._pool.in_use == 0

    # The legacy contiguous layout on the sharded mesh imports the same
    # block identically (the decode-cell fallback path).
    legacy = ServingEngine(cfg, params, chips2_mesh, num_slots=2,
                           max_seq_len=128, kv_page_tokens=0)
    r3 = legacy.submit(prompt, sp, kv_import={
        "token": p["token"], "length": p["length"],
        "k": p["k"], "v": p["v"]})
    while not r3.done.is_set():
        legacy.step()
    assert r3.generated == ref


# --- compile stability on the sharded mesh -----------------------------------


def _churn(eng):
    """The slot-churn pattern from test_obs_device: occupancy
    1 -> 2 -> 1 -> 2 -> 0 across requests of different lengths."""
    r1 = eng.submit(PROMPT, SamplingParams(max_new_tokens=12))
    eng.step()
    r2 = eng.submit(PROMPT[:4], SamplingParams(max_new_tokens=3))
    while not r2.done.is_set():
        eng.step()
    r3 = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    while not (r1.done.is_set() and r3.done.is_set()):
        eng.step()


def test_decode_compile_flat_across_churn_sharded(chips2_mesh):
    """Slot churn on a chips=2 mesh must not move
    kukeon_compiles_total{program="decode"}: the explicit in/out shardings
    keep every donated buffer's layout stable across occupancy changes."""
    cfg, params = _cfg_params()
    eng = ServingEngine(cfg, params, chips2_mesh, num_slots=2,
                        max_seq_len=96, decode_chunk=4)
    eng.warmup(8)
    base = eng.compiles.count("decode")
    assert base >= 1
    _churn(eng)
    assert eng.compiles.count("decode") == base, (
        "sharded decode recompiled during slot churn")


def test_decode_compile_flat_across_churn_sharded_paged(chips2_mesh):
    """Slot AND page churn on the sharded paged path: block-table updates
    and page alloc/free must not move the decode compile counter, and the
    pool must drain page-granularly."""
    cfg, params = _cfg_params()
    eng = ServingEngine(cfg, params, chips2_mesh, num_slots=2,
                        max_seq_len=96, decode_chunk=4,
                        kv_page_tokens=16, kv_pool_pages=12)
    eng.warmup(8)
    base = eng.compiles.count("decode")
    assert base >= 1
    _churn(eng)
    assert eng.compiles.count("decode") == base, (
        "sharded paged decode recompiled during slot/page churn")
    assert eng._pool.in_use == 0


# --- host-sync budget on the sharded mesh ------------------------------------


def test_decode_host_sync_budget_sharded(chips2_mesh):
    """The decode roofline contract holds unchanged at chips=2: ONE
    blocking device->host transfer per dispatched chunk and O(1) uploads
    per request — a sharded device_put is still exactly one counted
    upload, never one per shard."""
    cfg, params = _cfg_params()
    eng = ServingEngine(cfg, params, chips2_mesh, num_slots=2,
                        max_seq_len=128, decode_chunk=4)

    for prompt in (np.arange(1, 9, dtype=np.int32),
                   np.arange(3, 17, dtype=np.int32)):
        base = dict(eng.sync_stats)
        req = eng.submit(prompt, SamplingParams(max_new_tokens=24))
        while not req.done.is_set():
            eng.step()
        d = {k: eng.sync_stats[k] - base[k] for k in base}
        assert len(req.generated) == 24
        assert d["chunks"] >= 5
        assert d["fetches"] <= d["chunks"] + 1
        assert d["fetches"] >= d["chunks"] - 1
        # Same budget as the single-chip contract in test_serving.py:
        # prompt tokens + the three sampling arrays, NOT per chunk and
        # NOT per chip.
        assert d["uploads"] == 4, d


def test_decode_host_sync_budget_sharded_paged(chips2_mesh):
    """The paged budget at chips=2: 2 prefill uploads (tokens, page-ids)
    + 3 sampling arrays + 2 block-table uploads — the single-chip
    contract's exact numbers, unchanged by sharding."""
    cfg, params = _cfg_params()
    eng = ServingEngine(cfg, params, chips2_mesh, num_slots=2,
                        max_seq_len=128, decode_chunk=4,
                        kv_page_tokens=16, kv_pool_pages=16)

    for prompt in (np.arange(1, 9, dtype=np.int32),
                   np.arange(3, 17, dtype=np.int32)):
        base = dict(eng.sync_stats)
        req = eng.submit(prompt, SamplingParams(max_new_tokens=24))
        while not req.done.is_set():
            eng.step()
        d = {k: eng.sync_stats[k] - base[k] for k in base}
        assert len(req.generated) == 24
        assert d["chunks"] >= 5
        assert d["fetches"] <= d["chunks"] + 1
        assert d["fetches"] >= d["chunks"] - 1
        assert d["uploads"] == 7, d


# --- serving cell plumbing ---------------------------------------------------


def test_serving_cell_chips2_stats_and_metrics():
    """The --chips flag end to end in-process: a chips=2 ServingCell
    builds the exact 2-chip tensor mesh, reports it in /v1/stats, and
    exports kukeon_engine_mesh_chips=2 on its scrape."""
    from kukeon_tpu.runtime.serving_cell import ServingCell

    cell = ServingCell("tiny", num_slots=2, max_seq_len=96, checkpoint=None,
                       dtype=None, chips=2)
    mesh = cell.stats()["mesh"]
    assert mesh["chips"] == 2
    assert mesh["shape"] == {"tensor": 2}
    assert mesh["kvSharded"] is True       # tiny's 2 kv heads / tensor=2
    fams = _parse_expo(expo.render(cell.engine.registry))
    assert [float(v) for _n, _lab, v
            in fams["kukeon_engine_mesh_chips"]["samples"]] == [2.0]


def test_serving_cell_overgrant_dies_loudly():
    """A chips grant exceeding the visible devices must be a loud boot
    failure (SystemExit naming the flag), never a silent serve on fewer
    chips than the ModelSpec promised."""
    from kukeon_tpu.runtime.serving_cell import ServingCell

    with pytest.raises(SystemExit, match="--chips 64"):
        ServingCell("tiny", num_slots=2, max_seq_len=96, checkpoint=None,
                    dtype=None, chips=64)


# --- fleet summarizer: per-chip HBM + mesh size ------------------------------


def _sharded_cell_registry() -> Registry:
    reg = Registry()
    reg.gauge("kukeon_cell_ready", "ready").set(1)
    reg.gauge("kukeon_cell_info", "info", labels=("model", "kind")).set(
        1, model="tiny", kind="decoder")
    reg.gauge("kukeon_engine_mesh_chips", "mesh").set(2)
    for name, base in (("kukeon_hbm_bytes_in_use", 1000),
                       ("kukeon_hbm_bytes_limit", 4000),
                       ("kukeon_hbm_bytes_peak", 2000)):
        g = reg.gauge(name, "hbm", labels=("device",))
        g.set(base, device="0")
        g.set(base + 100, device="1")
    return reg


def test_summarize_cell_scrape_per_chip_hbm_and_mesh():
    """summarize_cell_scrape federates the device-labelled HBM samples
    into an hbmPerDevice breakdown (aggregates stay for single-chip rows
    and alert rules) and lifts the mesh-size gauge."""
    from kukeon_tpu.runtime.daemon import summarize_cell_scrape

    fams = fed.parse(expo.render(_sharded_cell_registry()))
    row = summarize_cell_scrape(fams)
    assert row["meshChips"] == 2
    assert row["hbmInUseBytes"] == 2100          # aggregate = sum over chips
    assert row["hbmLimitBytes"] == 8100
    assert list(row["hbmPerDevice"]) == ["0", "1"]
    assert row["hbmPerDevice"]["0"] == {
        "inUse": 1000, "limit": 4000, "peak": 2000}
    assert row["hbmPerDevice"]["1"] == {
        "inUse": 1100, "limit": 4100, "peak": 2100}


def test_kuke_top_renders_per_chip_rows():
    """`kuke top` shows one line per chip of a sharded cell (shard skew is
    invisible in the aggregate HBM cell) and none for single-chip rows."""
    from kukeon_tpu.runtime.cli import render_top

    row = {"cell": "g/s/st/c0", "ok": True, "ready": True, "model": "tiny",
           "meshChips": 2,
           "hbmPerDevice": {"0": {"inUse": 1000, "limit": 4000, "peak": 2000},
                            "1": {"inUse": 1100, "limit": 4100, "peak": 2100}}}
    out = render_top([row])
    assert "chip 0:" in out and "chip 1:" in out
    single = dict(row, meshChips=1)
    assert "chip 0:" not in render_top([single])


# --- tune persistence for the autotuner's new knobs --------------------------


def test_serving_tune_mesh_fields_roundtrip():
    """ServingTune carries the autotuner's sharding-layout winner
    (mesh_tensor, kv_shard) through to_dict/from_dict, and dicts written
    before ISSUE 15 (no mesh keys) still load."""
    from kukeon_tpu.serving.tuning import ServingTune

    t = ServingTune(decode_chunk=8, mesh_tensor=2, kv_shard=False)
    d = t.to_dict()
    assert d["mesh_tensor"] == 2 and d["kv_shard"] is False
    back = ServingTune.from_dict(d)
    assert back.mesh_tensor == 2 and back.kv_shard is False

    old = {k: v for k, v in d.items()
           if k not in ("mesh_tensor", "kv_shard")}
    legacy = ServingTune.from_dict(old)
    assert legacy.mesh_tensor is None and legacy.kv_shard is None
