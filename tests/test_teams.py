"""Teams subsystem: types, host config, source resolution, secrets, render,
and the full `team init` pipeline against an in-process controller.

The agents-source fixture is a REAL local git repo (git is a hard dependency
of the subsystem, same as the reference), reached via the TeamsConfig
sources transport override — no network.
"""

import os
import subprocess

import pytest

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.cells.fake import FakeBackend
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.errors import InvalidArgument
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import Runner
from kukeon_tpu.runtime.store import ResourceStore
from kukeon_tpu.runtime.teams import (
    TeamHost,
    TeamSource,
    TeamSourceResolver,
    load_team_secrets,
    parse_team_documents,
    render_team,
    secret_documents,
    team_init,
)
from kukeon_tpu.runtime.teams import types as tt
from kukeon_tpu.runtime.teams.init import load_project_team


ROLE_YAML = """\
apiVersion: kuketeams.io/v1
kind: Role
metadata:
  name: coder
spec:
  skills: [git, python]
  harnesses:
    claude:
      settings: settings.json
      secrets: [api-key]
  needs:
    image: [python]
    secrets: [api-key]
"""

HARNESS_YAML = """\
apiVersion: kuketeams.io/v1
kind: Harness
metadata:
  name: claude
spec:
  skillPath: /opt/skills
  makeTarget: claude-image
  template: blueprint.yaml.j2
"""

TEMPLATE = """\
apiVersion: kukeon.io/v1beta1
kind: CellBlueprint
metadata:
  name: rendered
spec:
  params:
    - name: PROMPT
      default: "you are {{ role.NAME }}"
  cell:
    containers:
      - name: agent
        command: ["/bin/sh", "-c", "echo {{ role.NAME }}@{{ image.IMAGE }}"]
        env:
          - name: GIT_AUTHOR_NAME
            value: "{{ operator.GIT_NAME }}"
          - name: SKILLS
            value: "{{ role.SKILLS | join(',') }}"
        secrets:
          - name: api-key
            env: API_KEY
        attachable: false
"""

IMAGES_YAML = """\
apiVersion: kuketeams.io/v1
kind: ImageCatalog
spec:
  images:
    - ref: claude-basic
      harness: claude
      image: kukeon.internal/claude-basic:v1
      build: {context: images/basic, dockerfile: Kukefile}
      capabilities: [git]
    - ref: claude-py
      harness: claude
      image: kukeon.internal/claude-py:v1
      build: {context: images/py, dockerfile: Kukefile}
      capabilities: [git, python]
"""

PROJECT_YAML = """\
apiVersion: kuketeams.io/v1
kind: ProjectTeam
metadata:
  name: myproj
spec:
  source:
    repo: example.com/acme/agents
    tag: v1.0.0
  defaults:
    harnesses: [claude]
  roles:
    - ref: coder
"""


def _git(cwd, *argv):
    subprocess.run(["git", *argv], cwd=cwd, check=True, capture_output=True,
                   env={**os.environ,
                        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                        "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"})


@pytest.fixture
def agents_repo(tmp_path):
    repo = tmp_path / "agents-remote"
    repo.mkdir()
    (repo / "coder").mkdir()
    (repo / "coder" / "role.yaml").write_text(ROLE_YAML)
    (repo / "harnesses" / "claude").mkdir(parents=True)
    (repo / "harnesses" / "claude" / "harness.yaml").write_text(HARNESS_YAML)
    (repo / "harnesses" / "claude" / "blueprint.yaml.j2").write_text(TEMPLATE)
    (repo / "harnesses" / "images.yaml").write_text(IMAGES_YAML)
    (repo / "images" / "basic").mkdir(parents=True)
    (repo / "images" / "basic" / "Kukefile").write_text(
        "FROM scratch\nENV LAYER=basic\n"
    )
    (repo / "images" / "py").mkdir(parents=True)
    (repo / "images" / "py" / "Kukefile").write_text(
        "ARG REGISTRY\nFROM kukeon.internal/claude-basic:v1\nENV LAYER=py\n"
    )
    _git(repo, "init", "-q", "-b", "main")
    _git(repo, "add", ".")
    _git(repo, "commit", "-q", "-m", "v1")
    _git(repo, "tag", "v1.0.0")
    return str(repo)


@pytest.fixture
def team_host(tmp_path, agents_repo):
    base = tmp_path / "kuke-home"
    host = TeamHost(str(base))
    os.makedirs(base, mode=0o700, exist_ok=True)
    (base / "kuketeams.yaml").write_text(f"""\
apiVersion: kuketeams.io/v1
kind: TeamsConfig
spec:
  git:
    name: Op Erator
    email: op@example.com
  registry: reg.example.com
  sources:
    example.com/acme/agents: {agents_repo}
  secrets:
    api-key: {{from: secrets.env, key: API_KEY}}
""")
    return host


class TestTypes:
    def test_source_exactly_one_ref(self):
        with pytest.raises(InvalidArgument):
            TeamSource(repo="a/b", tag="v1", branch="main").ref()
        with pytest.raises(InvalidArgument):
            TeamSource(repo="a/b").ref()
        assert TeamSource(repo="a/b", tag="v1").ref() == ("v1", "tag")

    def test_source_host_defaulting(self):
        assert TeamSource(repo="acme/agents", tag="v1").qualified_repo() \
            == "github.com/acme/agents"
        assert TeamSource(repo="gitlab.com/acme/agents", tag="v1").owner == "acme"

    def test_string_source_rejected_with_migration_error(self):
        with pytest.raises(InvalidArgument, match="structured"):
            parse_team_documents("""\
apiVersion: kuketeams.io/v1
kind: ProjectTeam
metadata: {name: p}
spec:
  source: acme/agents@v1
  roles: [{ref: coder}]
""")

    def test_project_team_requires_roles(self):
        with pytest.raises(InvalidArgument, match="at least one role"):
            parse_team_documents("""\
apiVersion: kuketeams.io/v1
kind: ProjectTeam
metadata: {name: p}
spec:
  source: {repo: a/b, tag: v1}
  roles: []
""")

    def test_teams_config_rejects_inline_secret_values(self):
        with pytest.raises(InvalidArgument, match="from"):
            parse_team_documents("""\
apiVersion: kuketeams.io/v1
kind: TeamsConfig
spec:
  secrets:
    api-key: {value: oops}
""")

    def test_wrong_api_version(self):
        with pytest.raises(InvalidArgument, match="apiVersion"):
            parse_team_documents("apiVersion: v1\nkind: Role\n")


class TestHost:
    def test_scaffold_and_load_config(self, tmp_path):
        host = TeamHost(str(tmp_path / "home"))
        cfg = host.load_config()
        assert isinstance(cfg, tt.TeamsConfig)
        assert os.path.exists(host.config_path())

    def test_dropin_roundtrip(self, tmp_path):
        host = TeamHost(str(tmp_path / "home"))
        entry = tt.TeamEntry(name="p", path="/src/p",
                             source=TeamSource(repo="a/b", branch="main"))
        host.write_dropin(entry)
        got = host.load_dropin("p")
        assert got.path == "/src/p"
        assert got.source.branch == "main"

    def test_missing_dropin_is_none(self, tmp_path):
        assert TeamHost(str(tmp_path / "home")).load_dropin("nope") is None


class TestSecrets:
    def test_two_layer_merge_per_team_wins(self, team_host):
        cfg = team_host.load_config()
        os.makedirs(os.path.dirname(team_host.shared_secrets_path()), exist_ok=True)
        with open(team_host.shared_secrets_path(), "w") as f:
            f.write("API_KEY=shared\n")
        os.makedirs(os.path.dirname(team_host.team_secrets_path("myproj")), exist_ok=True)
        with open(team_host.team_secrets_path("myproj"), "w") as f:
            f.write("API_KEY=per-team\n")
        vals = load_team_secrets(team_host, cfg, "myproj")
        assert vals == {"api-key": "per-team"}

    def test_scaffolded_empty_per_team_key_does_not_mask_shared(self, team_host):
        """First init scaffolds `API_KEY=` per-team; a filled shared layer
        must still win on the next init."""
        cfg = team_host.load_config()
        load_team_secrets(team_host, cfg, "myproj")   # scaffolds empty key
        os.makedirs(os.path.dirname(team_host.shared_secrets_path()), exist_ok=True)
        with open(team_host.shared_secrets_path(), "w") as f:
            f.write("API_KEY=from-shared\n")
        assert load_team_secrets(team_host, cfg, "myproj") \
            == {"api-key": "from-shared"}

    def test_scaffolds_missing_keys_0600(self, team_host):
        cfg = team_host.load_config()
        vals = load_team_secrets(team_host, cfg, "myproj")
        assert vals == {"api-key": ""}
        path = team_host.team_secrets_path("myproj")
        assert open(path).read() == "API_KEY=\n"
        assert (os.stat(path).st_mode & 0o777) == 0o600

    def test_secret_documents_shape(self):
        docs = secret_documents({"api-key": "s3cr3t"}, "proj", "default")
        assert len(docs) == 1
        assert docs[0].metadata.labels["kukeon.io/team"] == "proj"
        assert docs[0].spec.data == {"value": "s3cr3t"}


class TestSource:
    def test_pinned_tag_clones_once_then_reuses(self, team_host):
        cfg = team_host.load_config()
        src = TeamSource(repo="example.com/acme/agents", tag="v1.0.0")
        r = TeamSourceResolver(team_host, cfg)
        d1 = r.resolve(src)
        assert os.path.exists(os.path.join(d1, "coder", "role.yaml"))
        marker = os.path.join(d1, "MARKER")
        open(marker, "w").close()
        d2 = r.resolve(src)          # pinned: reused as-is
        assert d2 == d1 and os.path.exists(marker)

    def test_floating_branch_resets_to_tip(self, team_host, agents_repo):
        cfg = team_host.load_config()
        src = TeamSource(repo="example.com/acme/agents", branch="main")
        r = TeamSourceResolver(team_host, cfg)
        d1 = r.resolve(src)
        # Remote moves forward.
        with open(os.path.join(agents_repo, "NEW"), "w") as f:
            f.write("x")
        _git(agents_repo, "add", "NEW")
        _git(agents_repo, "commit", "-q", "-m", "tip")
        d2 = r.resolve(src)
        assert d2 == d1
        assert os.path.exists(os.path.join(d2, "NEW"))

    def test_load_bundle(self, team_host):
        cfg = team_host.load_config()
        team = load_project_team_from_str(PROJECT_YAML)
        r = TeamSourceResolver(team_host, cfg)
        bundle = r.load_bundle(team, r.resolve(team.source))
        assert bundle.roles["coder"].needs.image == ["python"]
        assert bundle.harnesses["claude"].template == "blueprint.yaml.j2"
        assert len(bundle.catalog.images) == 2


def load_project_team_from_str(s: str) -> tt.ProjectTeam:
    return [d for d in parse_team_documents(s)
            if isinstance(d, tt.ProjectTeam)][0]


class TestRender:
    @pytest.fixture
    def bundle(self, team_host):
        cfg = team_host.load_config()
        team = load_project_team_from_str(PROJECT_YAML)
        r = TeamSourceResolver(team_host, cfg)
        return team, r.load_bundle(team, r.resolve(team.source)), cfg

    def test_renders_pair_per_role_harness(self, bundle):
        team, b, cfg = bundle
        res = render_team(team, b, cfg)
        assert len(res.blueprints) == 1 and len(res.configs) == 1
        bp, cf = res.blueprints[0], res.configs[0]
        assert bp.metadata.name == "myproj-coder-claude"
        assert cf.spec.blueprint == bp.metadata.name
        assert bp.metadata.labels["kukeon.io/team"] == "myproj"
        assert cf.metadata.labels["kukeon.io/team"] == "myproj"

    def test_image_select_picks_capability_superset(self, bundle):
        team, b, cfg = bundle
        res = render_team(team, b, cfg)
        # needs [git?, python] -> claude-py (claude-basic lacks python)
        assert res.images_used[0].ref == "claude-py"
        cmd = res.blueprints[0].spec.cell.containers[0].command
        assert "coder@kukeon.internal/claude-py:v1" in cmd[-1]

    def test_image_select_miss_names_capability(self, bundle):
        team, b, cfg = bundle
        team.roles[0].needs.image.append("rust")
        with pytest.raises(InvalidArgument, match="rust"):
            render_team(team, b, cfg)

    def test_operator_facts_rendered_and_bound(self, bundle):
        team, b, cfg = bundle
        res = render_team(team, b, cfg)
        env = {e.name: e.value
               for e in res.blueprints[0].spec.cell.containers[0].env}
        assert env["GIT_AUTHOR_NAME"] == "Op Erator"
        assert env["SKILLS"] == "git,python"
        assert res.configs[0].spec.values["OPERATOR_REGISTRY"] == "reg.example.com"

    def test_secret_binding_only_for_declared_slots(self, bundle):
        team, b, cfg = bundle
        res = render_team(team, b, cfg)
        assert [s.slot for s in res.configs[0].spec.secrets] == ["api-key"]
        assert res.secrets_needed == ["api-key"]

    def test_undeclared_secret_errors(self, bundle):
        team, b, cfg = bundle
        cfg.secrets.pop("api-key")
        with pytest.raises(InvalidArgument, match="api-key"):
            render_team(team, b, cfg)

    def test_deterministic(self, bundle):
        team, b, cfg = bundle
        from kukeon_tpu.runtime.apply.parser import dump_documents

        r1 = render_team(team, b, cfg)
        r2 = render_team(team, b, cfg)
        assert dump_documents(r1.blueprints + r1.configs) \
            == dump_documents(r2.blueprints + r2.configs)


class TestTeamInit:
    def test_full_pipeline_applies_and_prunes(self, tmp_path, team_host):
        # Fill the secret so init can ship it.
        os.makedirs(os.path.dirname(team_host.team_secrets_path("myproj")),
                    exist_ok=True)
        with open(team_host.team_secrets_path("myproj"), "w") as f:
            f.write("API_KEY=k\n")
        project_file = tmp_path / "team.yaml"
        project_file.write_text(PROJECT_YAML)

        store = ResourceStore(MetadataStore(str(tmp_path / "rp")))
        ctl = Controller(store, Runner(store, FakeBackend()))
        ctl.bootstrap()

        def apply_fn(blob, team, prune):
            return [vars(r) for r in
                    ctl.apply_documents(blob, team=team, prune=prune)]

        res = team_init(apply_fn, str(project_file), host=team_host)
        actions = {(r["kind"], r["name"]): r["action"] for r in res.applied}
        assert actions[("Secret", "api-key")] == "applied"
        assert actions[("CellBlueprint", "myproj-coder-claude")] == "applied"
        assert actions[("CellConfig", "myproj-coder-claude")] == "applied"
        # Config materialized its cell.
        cells = ctl.list_cells(consts.DEFAULT_REALM)
        names = [c["name"] for c in cells]
        assert "myproj-coder-claude" in names

    def test_reinit_prunes_removed_roles(self, tmp_path, team_host):
        os.makedirs(os.path.dirname(team_host.team_secrets_path("myproj")),
                    exist_ok=True)
        with open(team_host.team_secrets_path("myproj"), "w") as f:
            f.write("API_KEY=k\n")
        project_file = tmp_path / "team.yaml"
        project_file.write_text(PROJECT_YAML)

        store = ResourceStore(MetadataStore(str(tmp_path / "rp")))
        ctl = Controller(store, Runner(store, FakeBackend()))
        ctl.bootstrap()

        def apply_fn(blob, team, prune):
            return [vars(r) for r in
                    ctl.apply_documents(blob, team=team, prune=prune)]

        team_init(apply_fn, str(project_file), host=team_host)
        # Re-apply the team with an empty roster slice (just the secret):
        # every rendered object must be pruned.
        blob = ("apiVersion: kukeon.io/v1beta1\nkind: Secret\n"
                "metadata: {name: api-key, realm: default}\n"
                "spec: {data: {value: k}}\n")
        results = ctl.apply_documents(blob, team="myproj", prune=True)
        pruned = {(r.kind, r.name) for r in results if r.action == "pruned"}
        assert ("Cell", "myproj-coder-claude") in pruned
        assert ("CellConfig", "myproj-coder-claude") in pruned
        assert ("CellBlueprint", "myproj-coder-claude") in pruned
        assert ("Secret", "api-key") not in pruned   # still in the roster

    def test_build_walks_from_order(self, tmp_path, team_host):
        """--build: bases build before leaves regardless of catalog order."""
        from kukeon_tpu.runtime.images import ImageBuilder, ImageStore

        project_file = tmp_path / "team.yaml"
        project_file.write_text(PROJECT_YAML)
        store = ImageStore(str(tmp_path / "rp"))
        res = team_init(None, str(project_file), host=team_host,
                        dry_run=True, build=True,
                        builder=ImageBuilder(store))
        assert res.built_images == ["kukeon.internal/claude-basic:v1",
                                    "kukeon.internal/claude-py:v1"]
        py = store.get("kukeon.internal/claude-py:v1")
        assert py.parent == "kukeon.internal/claude-basic:v1"
        assert py.env["LAYER"] == "py"

    def test_build_push_targets_config_registry(self, tmp_path, team_host):
        """--build --push: every built image is pushed to the TeamsConfig
        registry (reference: teambuild's REGISTRY threading + kukebuild
        push auth, internal/teambuild/teambuild.go:17-42)."""
        from kukeon_tpu.runtime.images import ImageBuilder, ImageStore

        project_file = tmp_path / "team.yaml"
        project_file.write_text(PROJECT_YAML)
        store = ImageStore(str(tmp_path / "rp"))
        pushed = []

        def pusher(tag, reg):
            pushed.append((tag, reg))
            return f"{reg}/{tag}"

        res = team_init(None, str(project_file), host=team_host,
                        dry_run=True, build=True,
                        builder=ImageBuilder(store), pusher=pusher)
        assert [r for _, r in pushed] == ["reg.example.com"] * 2
        assert res.pushed_images == [
            "reg.example.com/kukeon.internal/claude-basic:v1",
            "reg.example.com/kukeon.internal/claude-py:v1",
        ]

    def test_push_without_build_rejected(self, tmp_path, team_host):
        project_file = tmp_path / "team.yaml"
        project_file.write_text(PROJECT_YAML)
        with pytest.raises(InvalidArgument, match="--push requires --build"):
            team_init(None, str(project_file), host=team_host,
                      dry_run=True, pusher=lambda t, r: t)

    def test_dry_run_touches_nothing(self, tmp_path, team_host):
        project_file = tmp_path / "team.yaml"
        project_file.write_text(PROJECT_YAML)
        res = team_init(None, str(project_file), host=team_host, dry_run=True)
        assert res.rendered is not None
        assert res.applied == []

    def test_missing_secret_value_fails_with_path_hint(self, tmp_path, team_host):
        project_file = tmp_path / "team.yaml"
        project_file.write_text(PROJECT_YAML)
        with pytest.raises(InvalidArgument, match="secrets.env"):
            team_init(lambda *a: [], str(project_file), host=team_host)
