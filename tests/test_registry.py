"""OCI registry pull: distribution API, Bearer auth, docker-config
credentials, layer application with whiteouts (VERDICT r3 item 8).

No network egress in CI, so the registry is a real in-process HTTP server
speaking the distribution protocol — the client exercises the exact bytes
a Docker Hub / GCR pull would."""

from __future__ import annotations

import base64
import gzip
import hashlib
import io
import json
import os
import tarfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kukeon_tpu.runtime import registry
from kukeon_tpu.runtime.errors import KukeonError, NotFound
from kukeon_tpu.runtime.images import ImageStore


def _tar_layer(files: dict[str, bytes | None]) -> bytes:
    """files: path -> content; None marks a whiteout entry; paths ending in
    an executable bit hint ('!x' suffix) get mode 0755."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for path, content in files.items():
            mode = 0o644
            if path.endswith("!x"):
                path, mode = path[:-2], 0o755
            if content is None:
                d, b = os.path.split(path)
                path = os.path.join(d, ".wh." + b)
                content = b""
            info = tarfile.TarInfo(path)
            info.size = len(content)
            info.mode = mode
            tf.addfile(info, io.BytesIO(content))
    return gzip.compress(buf.getvalue())


def _digest(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


class FakeRegistry:
    """Minimal OCI distribution server: /v2 ping, token endpoint, manifests
    (list + image), blobs. Optionally requires Bearer auth."""

    def __init__(self, *, require_auth: bool = False,
                 user: str = "kuke", password: str = "sekrit",
                 upload_redirect_base: str | None = None,
                 put_redirect_base: str | None = None):
        self.blobs: dict[str, bytes] = {}
        self.manifests: dict[tuple[str, str], tuple[bytes, str]] = {}
        self.require_auth = require_auth
        self.user, self.password = user, password
        self.token = "tok-" + hashlib.sha256(password.encode()).hexdigest()[:8]
        self.token_requests: list[str] = []
        # Absolute base URL to redirect blob uploads to (the object-storage
        # redirect pattern); None keeps uploads on this server.
        self.upload_redirect_base = upload_redirect_base
        # Answer blob PUTs themselves with 307 -> this base (S3-backed
        # registries redirect the byte PUT, not just the session Location).
        self.put_redirect_base = put_redirect_base
        self.put_redirects_sent: list[str] = []
        self.upload_auth_seen: list[str | None] = []

        reg = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body=b"", ctype="application/json",
                      headers=()):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/token"):
                    auth = self.headers.get("Authorization", "")
                    want = base64.b64encode(
                        f"{reg.user}:{reg.password}".encode()).decode()
                    reg.token_requests.append(self.path)
                    if reg.require_auth and auth != f"Basic {want}":
                        self._send(401, b'{"error": "bad creds"}')
                        return
                    self._send(200, json.dumps({"token": reg.token}).encode())
                    return
                if reg.require_auth and self.headers.get(
                    "Authorization"
                ) != f"Bearer {reg.token}":
                    self._send(
                        401, b"{}",
                        headers=[(
                            "WWW-Authenticate",
                            f'Bearer realm="http://{self.headers["Host"]}/token",'
                            f'service="fake",scope="repository:pull"',
                        )],
                    )
                    return
                parts = self.path.split("/")
                if len(parts) >= 5 and parts[1] == "v2":
                    repo = "/".join(parts[2:-2])
                    kind, ref = parts[-2], parts[-1]
                    if kind == "manifests":
                        entry = reg.manifests.get((repo, ref))
                        if not entry:
                            self._send(404, b"{}")
                            return
                        body, mt = entry
                        self._send(200, body, ctype=mt)
                        return
                    if kind == "blobs":
                        blob = reg.blobs.get(ref)
                        if blob is None:
                            self._send(404, b"{}")
                            return
                        self._send(200, blob,
                                   ctype="application/octet-stream")
                        return
                self._send(404, b"{}")

            # --- push endpoints --------------------------------------------

            def do_HEAD(self):
                parts = self.path.split("/")
                if len(parts) >= 5 and parts[1] == "v2" and parts[-2] == "blobs":
                    if parts[-1] in reg.blobs:
                        self._send(200)
                    else:
                        self._send(404)
                    return
                self._send(404)

            def do_POST(self):
                # /v2/<repo>/blobs/uploads/ -> upload session Location
                path = self.path.rstrip("/")
                if path.endswith("/blobs/uploads"):
                    repo = "/".join(path.split("/")[2:-2])
                    base = reg.upload_redirect_base or ""
                    self._send(202, headers=[
                        ("Location", f"{base}/v2/{repo}/blobs/uploads/sess1"),
                    ])
                    return
                self._send(404, b"{}")

            def do_PUT(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                split = self.path.split("?")[0].split("/")
                if reg.put_redirect_base and "uploads" in split:
                    # 307 preserves method+body; the client must re-PUT the
                    # bytes at the Location (drain the body first so the
                    # connection stays usable).
                    reg.put_redirects_sent.append(self.path)
                    self._send(307, headers=[
                        ("Location", f"{reg.put_redirect_base}{self.path}"),
                    ])
                    return
                reg.upload_auth_seen.append(self.headers.get("Authorization"))
                if "uploads" in split:
                    # blob PUT at the session Location with ?digest=
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    digest = q.get("digest", [""])[0]
                    if _digest(body) != digest:
                        self._send(400, b'{"error": "digest mismatch"}')
                        return
                    reg.blobs[digest] = body
                    self._send(201)
                    return
                if len(split) >= 5 and split[1] == "v2" and split[-2] == "manifests":
                    repo = "/".join(split[2:-2])
                    tag = split[-1]
                    mt = self.headers.get("Content-Type", "")
                    reg.manifests[(repo, tag)] = (body, mt)
                    reg.manifests[(repo, _digest(body))] = (body, mt)
                    self._send(201)
                    return
                self._send(404, b"{}")

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    @property
    def host(self) -> str:
        return f"127.0.0.1:{self.port}"

    def add_image(self, repo: str, tag: str,
                  layers: list[bytes], config: dict,
                  *, via_index: bool = False) -> None:
        cfg_bytes = json.dumps(config).encode()
        self.blobs[_digest(cfg_bytes)] = cfg_bytes
        layer_descs = []
        for data in layers:
            self.blobs[_digest(data)] = data
            layer_descs.append({
                "mediaType": "application/vnd.oci.image.layer.v1.tar+gzip",
                "digest": _digest(data), "size": len(data),
            })
        manifest = json.dumps({
            "schemaVersion": 2,
            "mediaType": registry.MT_OCI_MANIFEST,
            "config": {"mediaType": "application/vnd.oci.image.config.v1+json",
                       "digest": _digest(cfg_bytes), "size": len(cfg_bytes)},
            "layers": layer_descs,
        }).encode()
        mdigest = _digest(manifest)
        self.manifests[(repo, mdigest)] = (manifest, registry.MT_OCI_MANIFEST)
        if via_index:
            import platform

            arch = {"x86_64": "amd64", "aarch64": "arm64"}.get(
                platform.machine(), platform.machine())
            index = json.dumps({
                "schemaVersion": 2,
                "mediaType": registry.MT_OCI_INDEX,
                "manifests": [
                    {"mediaType": registry.MT_OCI_MANIFEST, "digest": mdigest,
                     "size": len(manifest),
                     "platform": {"os": "linux", "architecture": "s390x"}},
                    {"mediaType": registry.MT_OCI_MANIFEST, "digest": mdigest,
                     "size": len(manifest),
                     "platform": {"os": "linux", "architecture": arch}},
                ],
            }).encode()
            self.manifests[(repo, tag)] = (index, registry.MT_OCI_INDEX)
        else:
            self.manifests[(repo, tag)] = (manifest, registry.MT_OCI_MANIFEST)

    def close(self):
        self.server.shutdown()


CONFIG = {
    "architecture": "amd64", "os": "linux",
    "config": {
        "Entrypoint": ["/bin/app"], "Cmd": ["--serve"],
        "Env": ["PATH=/usr/bin", "MODE=prod"],
        "WorkingDir": "/srv", "Labels": {"team": "kukeon"},
    },
}


class TestParseRef:
    def test_registry_detection(self):
        assert registry.parse_image_ref("localhost:5000/a/b:v1") == (
            "localhost:5000", "a/b", "v1")
        assert registry.parse_image_ref("gcr.io/proj/img") == (
            "gcr.io", "proj/img", "latest")
        assert registry.parse_image_ref("busybox:1.36") == ("", "busybox", "1.36")

    def test_bare_ref_rejected(self):
        from kukeon_tpu.runtime.errors import InvalidArgument

        with pytest.raises(InvalidArgument, match="registry"):
            registry.RegistryClient("")


class TestPull:
    def test_pull_layers_config_and_whiteouts(self, tmp_path):
        reg = FakeRegistry()
        try:
            layers = [
                _tar_layer({"etc/keep.txt": b"keep", "etc/gone.txt": b"tmp",
                            "bin/app": b"#!app"}),
                _tar_layer({"etc/gone.txt": None, "etc/new.txt": b"new"}),
            ]
            reg.add_image("team/tool", "v1", layers, CONFIG)
            store = ImageStore(str(tmp_path))
            m = registry.pull(store, f"{reg.host}/team/tool:v1")
            assert m.entrypoint == ["/bin/app"]
            assert m.cmd == ["--serve"]
            assert m.env["MODE"] == "prod"
            assert m.workdir == "/srv"
            assert m.labels["team"] == "kukeon"
            root = store.rootfs(m.ref)
            assert open(os.path.join(root, "etc/keep.txt")).read() == "keep"
            assert open(os.path.join(root, "etc/new.txt")).read() == "new"
            assert not os.path.exists(os.path.join(root, "etc/gone.txt"))
            assert not os.path.exists(os.path.join(root, "etc/.wh.gone.txt"))
        finally:
            reg.close()

    def test_pull_via_manifest_list_picks_platform(self, tmp_path):
        reg = FakeRegistry()
        try:
            reg.add_image("ml/model", "latest",
                          [_tar_layer({"x": b"y"})], CONFIG, via_index=True)
            store = ImageStore(str(tmp_path))
            m = registry.pull(store, f"{reg.host}/ml/model")
            assert os.path.exists(os.path.join(store.rootfs(m.ref), "x"))
        finally:
            reg.close()

    def test_digest_mismatch_rejected(self, tmp_path):
        reg = FakeRegistry()
        try:
            reg.add_image("a/b", "v1", [_tar_layer({"f": b"data"})], CONFIG)
            # Corrupt every blob in place (keys = digests of the originals).
            for key in list(reg.blobs):
                reg.blobs[key] = reg.blobs[key] + b"X"
            store = ImageStore(str(tmp_path))
            with pytest.raises(KukeonError, match="digest mismatch"):
                registry.pull(store, f"{reg.host}/a/b:v1")
            assert not store.exists(f"{reg.host}/a/b:v1")
        finally:
            reg.close()

    def test_missing_image_is_not_found(self, tmp_path):
        reg = FakeRegistry()
        try:
            store = ImageStore(str(tmp_path))
            with pytest.raises(NotFound):
                registry.pull(store, f"{reg.host}/no/such:tag")
        finally:
            reg.close()


class TestAuth:
    def test_bearer_dance_with_docker_config(self, tmp_path, monkeypatch):
        """401 -> WWW-Authenticate -> token endpoint with docker-config
        basic creds -> retried pull succeeds (reference: auth.go
        precedence)."""
        reg = FakeRegistry(require_auth=True)
        try:
            cfg_dir = tmp_path / "docker"
            cfg_dir.mkdir()
            auth = base64.b64encode(b"kuke:sekrit").decode()
            (cfg_dir / "config.json").write_text(json.dumps(
                {"auths": {reg.host: {"auth": auth}}}
            ))
            monkeypatch.setenv("DOCKER_CONFIG", str(cfg_dir))
            monkeypatch.delenv("KUKE_REGISTRY_USER", raising=False)
            reg.add_image("priv/img", "v1", [_tar_layer({"f": b"x"})], CONFIG)
            store = ImageStore(str(tmp_path / "store"))
            m = registry.pull(store, f"{reg.host}/priv/img:v1")
            assert reg.token_requests, "token endpoint was never hit"
            assert store.exists(m.ref)
        finally:
            reg.close()

    def test_env_overrides_docker_config(self, tmp_path, monkeypatch):
        reg = FakeRegistry(require_auth=True)
        try:
            cfg_dir = tmp_path / "docker"
            cfg_dir.mkdir()
            bad = base64.b64encode(b"kuke:wrong").decode()
            (cfg_dir / "config.json").write_text(json.dumps(
                {"auths": {reg.host: {"auth": bad}}}
            ))
            monkeypatch.setenv("DOCKER_CONFIG", str(cfg_dir))
            monkeypatch.setenv("KUKE_REGISTRY_USER", "kuke")
            monkeypatch.setenv("KUKE_REGISTRY_PASSWORD", "sekrit")
            reg.add_image("priv/img", "v1", [_tar_layer({"f": b"x"})], CONFIG)
            store = ImageStore(str(tmp_path / "store"))
            m = registry.pull(store, f"{reg.host}/priv/img:v1")
            assert store.exists(m.ref)
        finally:
            reg.close()

    def test_bad_creds_fail_clearly(self, tmp_path, monkeypatch):
        reg = FakeRegistry(require_auth=True)
        try:
            monkeypatch.setenv("DOCKER_CONFIG", str(tmp_path))  # no config.json
            monkeypatch.delenv("KUKE_REGISTRY_USER", raising=False)
            reg.add_image("priv/img", "v1", [_tar_layer({"f": b"x"})], CONFIG)
            store = ImageStore(str(tmp_path / "store"))
            with pytest.raises(KukeonError):
                registry.pull(store, f"{reg.host}/priv/img:v1")
        finally:
            reg.close()


class TestMultiStageBuild:
    def test_copy_from_builder_stage(self, tmp_path):
        from kukeon_tpu.runtime.images import ImageBuilder

        store = ImageStore(str(tmp_path))
        ctx = tmp_path / "ctx"
        ctx.mkdir()
        (ctx / "src.txt").write_text("artifact-source")
        kf = ctx / "Kukefile"
        kf.write_text(
            "FROM scratch AS builder\n"
            "COPY src.txt /build/input.txt\n"
            "RUN cp build/input.txt build/output.txt\n"
            "\n"
            "FROM scratch\n"
            "COPY --from=builder /build/output.txt /app/artifact.txt\n"
            "ENTRYPOINT [\"/app/run\"]\n"
        )
        b = ImageBuilder(store)
        m = b.build(str(kf), str(ctx), "multi:1")
        root = store.rootfs(m.ref)
        assert open(os.path.join(root, "app/artifact.txt")).read() == "artifact-source"
        # Builder stage contents must NOT leak into the final image.
        assert not os.path.exists(os.path.join(root, "build"))
        assert m.entrypoint == ["/app/run"]
        # Builder stagings are cleaned up.
        leftovers = [e for e in os.listdir(store.root) if e.startswith(".staging")]
        assert not leftovers

    def test_copy_from_unknown_stage_rejected(self, tmp_path):
        from kukeon_tpu.runtime.errors import InvalidArgument
        from kukeon_tpu.runtime.images import ImageBuilder

        store = ImageStore(str(tmp_path))
        ctx = tmp_path / "ctx"
        ctx.mkdir()
        kf = ctx / "Kukefile"
        kf.write_text(
            "FROM scratch\nCOPY --from=nope /x /y\n"
        )
        with pytest.raises(InvalidArgument, match="unknown stage"):
            ImageBuilder(store).build(str(kf), str(ctx), "bad:1")


class TestPullE2E:
    def test_kuke_image_pull_and_serve_from_pulled_image(self, tmp_path):
        """Black-box: `kuke image pull` from a live local registry through
        the real daemon, then a cell runs the pulled image's entrypoint
        inside its pivot_root'd rootfs (the image carries a static binary —
        a from-scratch rootfs has no shell)."""
        import subprocess
        import sys
        import time as _t

        sys.path.insert(0, os.path.dirname(__file__))
        from test_runtime_e2e import Daemon

        src = tmp_path / "cat.c"
        src.write_text(
            '#include <stdio.h>\n'
            'int main(void) {\n'
            '    FILE* f = fopen("/app/hello.txt", "r");\n'
            '    if (!f) { printf("NOFILE\\n"); return 1; }\n'
            '    char buf[64] = {0};\n'
            '    fread(buf, 1, 63, f);\n'
            '    printf("%s", buf);\n'
            '    return 0;\n'
            '}\n'
        )
        binary = tmp_path / "catapp"
        subprocess.run(["g++", "-static", "-O1", "-o", str(binary), str(src)],
                       check=True, capture_output=True)

        reg = FakeRegistry()
        d = Daemon()
        try:
            config = json.loads(json.dumps(CONFIG))
            config["config"]["Entrypoint"] = ["/bin/catapp"]
            config["config"]["Cmd"] = []
            reg.add_image("team/tool", "v1", [_tar_layer({
                "app/hello.txt": b"pulled-bytes\n",
                "bin/catapp!x": binary.read_bytes(),
            })], config)
            ref = f"{reg.host}/team/tool:v1"
            p = d.kuke("image", "pull", ref)
            assert "pulled" in p.stdout
            out = d.kuke("image", "list").stdout
            assert "team/tool" in out

            manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: pulled}}
spec:
  containers:
    - name: main
      image: "{ref}"
      restartPolicy: {{policy: never}}
"""
            d.kuke("apply", "-f", "-", stdin_data=manifest)
            deadline = _t.monotonic() + 15
            log = ""
            while _t.monotonic() < deadline:
                log = d.kuke("log", "pulled", check=False).stdout
                if "pulled-bytes" in log or "NOFILE" in log:
                    break
                _t.sleep(0.5)
            assert "pulled-bytes" in log, f"cell log: {log!r}"
        finally:
            d.stop()
            reg.close()


class TestPushE2E:
    def test_kuke_build_push_pullback_run(self, tmp_path):
        """Black-box round trip (VERDICT r4 item 6 'done' criterion):
        `kuke build` an image -> `kuke image push` to a live local registry
        -> delete local -> `kuke image pull` back -> a cell runs it."""
        import subprocess
        import sys
        import time as _t

        sys.path.insert(0, os.path.dirname(__file__))
        from test_runtime_e2e import Daemon

        ctx = tmp_path / "ctx"
        ctx.mkdir()
        (ctx / "hello.txt").write_text("pushed-bytes\n")
        # Static binary: a from-scratch rootfs has no shell/cat to exec.
        src = tmp_path / "cat.c"
        src.write_text(
            '#include <stdio.h>\n'
            'int main(void) {\n'
            '    FILE* f = fopen("/app/hello.txt", "r");\n'
            '    if (!f) { printf("NOFILE\\n"); return 1; }\n'
            '    char buf[64] = {0};\n'
            '    fread(buf, 1, 63, f);\n'
            '    printf("%s", buf);\n'
            '    return 0;\n'
            '}\n'
        )
        subprocess.run(["g++", "-static", "-O1", "-o", str(ctx / "catapp"),
                        str(src)], check=True, capture_output=True)
        (ctx / "Kukefile").write_text(
            "FROM scratch\n"
            "COPY hello.txt /app/hello.txt\n"
            "COPY catapp /bin/catapp\n"
            'ENTRYPOINT ["/bin/catapp"]\n'
        )

        reg = FakeRegistry()
        d = Daemon()
        try:
            d.kuke("build", str(ctx), "-t", "tool:v1")
            dest = f"{reg.host}/team/tool:v1"
            p = d.kuke("image", "push", "tool:v1", "--to", dest)
            assert dest in p.stdout
            d.kuke("image", "delete", "tool:v1")

            d.kuke("image", "pull", dest)
            manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: rt}}
spec:
  containers:
    - name: main
      image: "{dest}"
      restartPolicy: {{policy: never}}
"""
            d.kuke("apply", "-f", "-", stdin_data=manifest)
            deadline = _t.monotonic() + 15
            log = ""
            while _t.monotonic() < deadline:
                log = d.kuke("log", "rt", check=False).stdout
                if "pushed-bytes" in log:
                    break
                _t.sleep(0.5)
            assert "pushed-bytes" in log, f"cell log: {log!r}"
        finally:
            d.stop()
            reg.close()


class TestLayerSafety:
    def test_escaping_whiteout_rejected(self, tmp_path):
        """A hostile layer naming ../../<host>/.wh.x must fail the pull,
        never delete outside the staging rootfs (the daemon pulls as root)."""
        import io as _io
        import tarfile as _tarfile

        buf = _io.BytesIO()
        with _tarfile.open(fileobj=buf, mode="w") as tf:
            info = _tarfile.TarInfo("a/../../../../outside/.wh.victim")
            info.size = 0
            tf.addfile(info, _io.BytesIO(b""))
        evil = gzip.compress(buf.getvalue())

        victim = tmp_path / "outside" / "victim"
        victim.parent.mkdir()
        victim.write_text("precious")

        reg = FakeRegistry()
        try:
            reg.add_image("evil/img", "v1", [evil], CONFIG)
            store = ImageStore(str(tmp_path / "store"))
            from kukeon_tpu.runtime.errors import InvalidArgument

            with pytest.raises(InvalidArgument, match="escapes"):
                registry.pull(store, f"{reg.host}/evil/img:v1")
            assert victim.read_text() == "precious"
            assert not store.exists(f"{reg.host}/evil/img:v1")
        finally:
            reg.close()


class TestStageMetadataInheritance:
    def test_from_stage_inherits_config(self, tmp_path):
        from kukeon_tpu.runtime.images import ImageBuilder

        store = ImageStore(str(tmp_path))
        ctx = tmp_path / "ctx"
        ctx.mkdir()
        (ctx / "f").write_text("x")
        kf = ctx / "Kukefile"
        kf.write_text(
            "FROM scratch AS base\n"
            "ENV MODE=prod\n"
            "WORKDIR /srv\n"
            "ENTRYPOINT [\"/bin/app\"]\n"
            "\n"
            "FROM base\n"
            "COPY f /f\n"
        )
        m = ImageBuilder(store).build(str(kf), str(ctx), "inherit:1")
        assert m.env.get("MODE") == "prod"
        assert m.workdir == "/srv"
        assert m.entrypoint == ["/bin/app"]


class TestGlobalArgsAcrossStages:
    def test_pre_from_arg_visible_in_every_from(self, tmp_path):
        from kukeon_tpu.runtime.images import ImageBuilder

        store = ImageStore(str(tmp_path))
        ctx = tmp_path / "ctx"
        ctx.mkdir()
        (ctx / "f").write_text("x")
        # Base image both stages resolve via ${TAG}.
        base_kf = ctx / "Base.kukefile"
        base_kf.write_text("FROM scratch\nENV BASE=yes\n")
        b = ImageBuilder(store)
        b.build(str(base_kf), str(ctx), "base:v1")

        kf = ctx / "Kukefile"
        kf.write_text(
            "ARG TAG=v1\n"
            "FROM base:${TAG} AS builder\n"
            "COPY f /built\n"
            "FROM base:${TAG}\n"
            "COPY --from=builder /built /out\n"
        )
        m = b.build(str(kf), str(ctx), "multiarg:1")
        assert m.env.get("BASE") == "yes"   # second FROM resolved base:v1
        assert os.path.exists(os.path.join(store.rootfs(m.ref), "out"))


class TestPush:
    """`kuke image push`: local bundle -> OCI blobs + manifest (VERDICT r4
    item 6; reference: kukebuild pushes what it builds)."""

    @staticmethod
    def _local_image(tmp_path, name="myapp", tag="v1"):
        from kukeon_tpu.runtime.images import ImageManifest

        store = ImageStore(str(tmp_path / "src-store"))
        m = ImageManifest(
            name=name, tag=tag,
            entrypoint=["/bin/app"], cmd=["--serve"],
            env={"MODE": "prod"}, workdir="/srv",
            labels={"team": "kukeon"},
        )
        store.put(m)
        rootfs = store.rootfs(m.ref)
        os.makedirs(os.path.join(rootfs, "srv"), exist_ok=True)
        with open(os.path.join(rootfs, "srv", "data.txt"), "w") as f:
            f.write("payload")
        os.makedirs(os.path.join(rootfs, "bin"), exist_ok=True)
        with open(os.path.join(rootfs, "bin", "app"), "w") as f:
            f.write("#!/bin/sh\necho hi\n")
        return store, m

    def test_push_pull_roundtrip(self, tmp_path):
        store, m = self._local_image(tmp_path)
        reg = FakeRegistry()
        try:
            pushed = registry.push(store, m.ref,
                                   dest=f"{reg.host}/team/myapp:v1")
            assert pushed == f"{reg.host}/team/myapp:v1"

            back = ImageStore(str(tmp_path / "dst-store"))
            got = registry.pull(back, pushed)
            assert got.entrypoint == ["/bin/app"]
            assert got.cmd == ["--serve"]
            assert got.env.get("MODE") == "prod"
            assert got.workdir == "/srv"
            assert got.labels.get("team") == "kukeon"
            data = os.path.join(back.rootfs(got.ref), "srv", "data.txt")
            with open(data) as f:
                assert f.read() == "payload"
        finally:
            reg.close()

    def test_second_push_dedups_blobs(self, tmp_path):
        store, m = self._local_image(tmp_path)
        reg = FakeRegistry()
        try:
            registry.push(store, m.ref, dest=f"{reg.host}/team/myapp:v1")
            puts_first = len(reg.upload_auth_seen)
            assert puts_first == 3  # config blob + layer blob + manifest
            registry.push(store, m.ref, dest=f"{reg.host}/team/myapp:v1")
            # Identical content: HEAD-dedup skips both blobs; only the
            # manifest is re-PUT.
            assert len(reg.upload_auth_seen) == puts_first + 1
        finally:
            reg.close()

    def test_cross_origin_upload_redirect_strips_auth(self, tmp_path,
                                                      monkeypatch):
        """A registry that redirects blob uploads to object storage must not
        receive our registry credentials at the third-party host (ADVICE r4:
        docker-style clients strip auth on cross-host redirects)."""
        storage = FakeRegistry()
        primary = FakeRegistry(
            upload_redirect_base=f"http://{storage.host}"
        )
        monkeypatch.setenv("KUKE_REGISTRY_USER", "kuke")
        monkeypatch.setenv("KUKE_REGISTRY_PASSWORD", "sekrit")
        store, m = self._local_image(tmp_path)
        try:
            registry.push(store, m.ref, dest=f"{primary.host}/team/myapp:v1")
            # Blob PUTs landed on the storage host WITHOUT Authorization...
            assert storage.upload_auth_seen, "uploads never hit storage host"
            assert all(a is None for a in storage.upload_auth_seen)
            # ...while the manifest PUT to the registry itself carried it.
            assert primary.upload_auth_seen
            assert all(a and a.startswith("Basic ")
                       for a in primary.upload_auth_seen)
        finally:
            primary.close()
            storage.close()

    def test_blob_put_307_redirect_followed(self, tmp_path, monkeypatch):
        """A registry answering the blob byte-PUT itself with 307 to object
        storage (S3-backed pattern): _send must re-issue the PUT — same
        body, re-seeked — at the Location, with credentials stripped on the
        cross-host hop (ADVICE r5: this used to fail the push with
        'PUT -> 307')."""
        storage = FakeRegistry()
        primary = FakeRegistry(put_redirect_base=f"http://{storage.host}")
        monkeypatch.setenv("KUKE_REGISTRY_USER", "kuke")
        monkeypatch.setenv("KUKE_REGISTRY_PASSWORD", "sekrit")
        store, m = self._local_image(tmp_path)
        try:
            registry.push(store, m.ref, dest=f"{primary.host}/team/myapp:v1")
            # Both blob PUTs were redirected and their bytes landed intact
            # (the storage fake digest-verifies every PUT body).
            assert len(primary.put_redirects_sent) == 2
            assert len(storage.blobs) == 2
            assert storage.upload_auth_seen
            assert all(a is None for a in storage.upload_auth_seen)
            # Manifest stayed on the registry, authenticated; it references
            # exactly the blobs that landed on the storage host.
            body, _mt = primary.manifests[("team/myapp", "v1")]
            mani = json.loads(body)
            digests = {mani["config"]["digest"]} | {
                layer["digest"] for layer in mani["layers"]}
            assert digests == set(storage.blobs)
            assert all(a and a.startswith("Basic ")
                       for a in primary.upload_auth_seen)
        finally:
            primary.close()
            storage.close()


class TestOpaqueWhiteoutSameLayer:
    def test_opaque_dir_repopulated_in_same_layer(self, tmp_path):
        """A layer that marks a directory opaque AND adds files under it in
        the SAME layer: lower content drops, same-layer adds survive
        (VERDICT r4 weak 8 — ordering was untested)."""
        import io as _io
        import tarfile as _tarfile

        lower = _tar_layer({"app/old.txt": b"stale", "app/keepname": b"old"})

        buf = _io.BytesIO()
        with _tarfile.open(fileobj=buf, mode="w") as tf:
            for name, content in (
                ("app/.wh..wh..opq", b""),
                ("app/new.txt", b"fresh"),
                ("app/keepname", b"replaced"),
            ):
                info = _tarfile.TarInfo(name)
                info.size = len(content)
                tf.addfile(info, _io.BytesIO(content))
        upper = gzip.compress(buf.getvalue())

        reg = FakeRegistry()
        try:
            reg.add_image("lib/img", "v1", [lower, upper], CONFIG)
            store = ImageStore(str(tmp_path / "store"))
            m = registry.pull(store, f"{reg.host}/lib/img:v1")
            rootfs = store.rootfs(m.ref)
            assert not os.path.exists(os.path.join(rootfs, "app", "old.txt"))
            with open(os.path.join(rootfs, "app", "new.txt")) as f:
                assert f.read() == "fresh"
            with open(os.path.join(rootfs, "app", "keepname")) as f:
                assert f.read() == "replaced"
        finally:
            reg.close()
