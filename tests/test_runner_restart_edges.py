"""Runner restart-policy edges + chip-grant stability under crash loops.

The serving resilience chain ends at the runner: a watchdog-tripped cell
exits nonzero and the restart policy must bring it back — with ITS chips,
within its retry budget, after its backoff — or the recovery story has a
hole. These pin the edges the main controller suite doesn't."""

import time

import pytest

from kukeon_tpu.runtime import model
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.cells import FakeBackend
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.devices import TPUDeviceManager
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import (
    OUTCOME_RESTARTED,
    Runner,
    RunnerOptions,
)
from kukeon_tpu.runtime.store import ResourceStore


@pytest.fixture
def ctl(tmp_path):
    store = ResourceStore(MetadataStore(str(tmp_path)))
    backend = FakeBackend()
    devices = TPUDeviceManager(store.ms, chips=[0, 1, 2, 3])
    runner = Runner(store, backend, cgroups=None, devices=devices,
                    options=RunnerOptions(stop_grace_s=0.2))
    c = Controller(store, runner)
    c.bootstrap()
    return c, backend, store, devices


def _cell_doc(name="c1", **cell_kw):
    return t.Document(
        kind=t.KIND_CELL,
        metadata=t.Metadata(name=name),
        spec=t.CellSpec(
            containers=[t.ContainerSpec(name="main", command=["/bin/true"])],
            **cell_kw,
        ),
    )


def _refresh(c, name="c1"):
    return c.runner.refresh_cell("default", "default", "default", name)


def test_never_policy_leaves_cell_stopped(ctl):
    c, backend, store, _ = ctl
    doc = _cell_doc()
    doc.spec.containers[0].restart_policy = t.RestartPolicy(policy="never")
    c.create_cell(doc)
    cdir = store.container_dir("default", "default", "default", "c1", "main")
    backend.exit(cdir, 1)

    for _ in range(3):
        _, outcome = _refresh(c)
        assert outcome != OUTCOME_RESTARTED
    rec = store.read_cell("default", "default", "default", "c1")
    st = rec.status.container("main")
    assert st.restarts == 0
    assert st.state == model.C_EXITED
    assert rec.status.phase == model.FAILED        # nonzero exit, no revival
    assert backend.entries[cdir].starts == 1       # the original start only


def test_never_policy_clean_exit_is_stopped_not_failed(ctl):
    c, backend, store, _ = ctl
    doc = _cell_doc()
    doc.spec.containers[0].restart_policy = t.RestartPolicy(policy="never")
    c.create_cell(doc)
    cdir = store.container_dir("default", "default", "default", "c1", "main")
    backend.exit(cdir, 0)
    _, outcome = _refresh(c)
    assert outcome != OUTCOME_RESTARTED
    rec = store.read_cell("default", "default", "default", "c1")
    assert rec.status.phase == model.STOPPED


def test_backoff_is_honored_between_restarts(ctl):
    """No restart inside the backoff window; a prompt restart right after
    it elapses — the crash-loop damper actually damps, and recovery is not
    deferred past the window."""
    c, backend, store, _ = ctl
    doc = _cell_doc()
    doc.spec.containers[0].restart_policy = t.RestartPolicy(
        policy="always", backoff_seconds=0.3
    )
    c.create_cell(doc)
    cdir = store.container_dir("default", "default", "default", "c1", "main")
    backend.exit(cdir, 1)

    # Inside the window: repeated reconcile ticks must not restart.
    for _ in range(2):
        _, outcome = _refresh(c)
        assert outcome != OUTCOME_RESTARTED
    assert backend.entries[cdir].starts == 1

    time.sleep(0.35)
    _, outcome = _refresh(c)
    assert outcome == OUTCOME_RESTARTED
    assert backend.entries[cdir].starts == 2

    # Second crash: the window re-anchors at the RESTART time, not the
    # first crash's — an immediate refresh stays put again.
    backend.exit(cdir, 1)
    _, outcome = _refresh(c)
    assert outcome != OUTCOME_RESTARTED
    time.sleep(0.35)
    _, outcome = _refresh(c)
    assert outcome == OUTCOME_RESTARTED
    assert backend.entries[cdir].starts == 3


def test_on_failure_budget_exhaustion_reports_reason(ctl):
    c, backend, store, _ = ctl
    doc = _cell_doc()
    doc.spec.containers[0].restart_policy = t.RestartPolicy(
        policy="on-failure", backoff_seconds=0.0, max_retries=1
    )
    c.create_cell(doc)
    cdir = store.container_dir("default", "default", "default", "c1", "main")

    backend.exit(cdir, 7)
    _, outcome = _refresh(c)
    assert outcome == OUTCOME_RESTARTED

    backend.exit(cdir, 7)
    _, outcome = _refresh(c)
    assert outcome != OUTCOME_RESTARTED
    rec = store.read_cell("default", "default", "default", "c1")
    assert rec.status.container("main").restarts == 1
    assert "restart budget exhausted" in (rec.status.reason or "")
    # Further ticks stay put — no zombie restarts past the budget.
    _, outcome = _refresh(c)
    assert outcome != OUTCOME_RESTARTED
    assert backend.entries[cdir].starts == 2


def test_crash_looping_model_cell_keeps_its_chip_grant(ctl):
    """A serving cell that crash-loops (e.g. the TPU watchdog exiting
    WEDGED_EXIT_CODE) must be restarted with the SAME chip grant every
    time: visibility env identical across restarts, and a neighbor cell's
    grant never raided."""
    c, backend, store, devices = ctl
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=2, port=9123)),
    )
    c.create_cell(doc)
    cdir = store.container_dir(
        "default", "default", "default", "llm", "model-server")
    first_env = backend.started[-1].env
    assert first_env["TPU_VISIBLE_DEVICES"] == "0,1"

    # A neighbor takes the remaining chips — nothing is free anymore.
    doc2 = _cell_doc("other")
    doc2.spec.containers[0].resources = t.Resources(tpu_chips=2)
    c.create_cell(doc2)
    assert devices.free_chips() == []

    # Crash-loop the model cell through several restarts (the model
    # container's policy is always/backoff=2.0; the first refresh records
    # the exit and honors the backoff, so the test crosses the window by
    # rewinding the recorded timestamps rather than sleeping).
    for i in range(3):
        backend.exit(cdir, 86)
        _, outcome = _refresh(c, "llm")          # records exit; inside backoff
        assert outcome != OUTCOME_RESTARTED
        rec = store.read_cell("default", "default", "default", "llm")
        st = rec.status.container("model-server")
        if st.last_restart_at:
            st.last_restart_at -= 10.0
        if st.finished_at:
            st.finished_at -= 10.0
        store.write_cell(rec)
        _, outcome = _refresh(c, "llm")
        assert outcome == OUTCOME_RESTARTED, f"restart #{i + 1} did not happen"
        env = backend.started[-1].env
        assert env["TPU_VISIBLE_DEVICES"] == "0,1", "chip grant drifted"

    # The allocation record never changed hands.
    rec = store.read_cell("default", "default", "default", "llm")
    assert rec.status.tpu_chips == [0, 1]
    assert devices.allocated()[0] == "default/default/default/llm"
    assert devices.allocated()[2] == "default/default/default/other"
