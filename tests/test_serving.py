"""Serving engine: continuous batching correctness on a CPU tensor mesh."""

import jax
import numpy as np
import pytest

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine


@pytest.fixture(scope="module")
def engine():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=2, data=4)
    return ServingEngine(cfg, params, mesh, num_slots=4, max_seq_len=128), cfg, params


def _reference_greedy(cfg, params, prompt, n_new):
    """Greedy decode via direct full forward passes (no cache)."""
    import jax.numpy as jnp

    tokens = list(prompt)
    out = []
    for _ in range(n_new):
        t = jnp.asarray(tokens, jnp.int32)[None, :]
        pos = jnp.arange(len(tokens), dtype=jnp.int32)[None, :]
        logits, _ = llama.forward(params, cfg, t, pos)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        tokens.append(nxt)
    return out


def test_greedy_matches_uncached_reference(engine):
    eng, cfg, params = engine
    prompt = np.arange(1, 9, dtype=np.int32)  # 8 tokens
    got = eng.generate(prompt, SamplingParams(max_new_tokens=8))
    want = _reference_greedy(cfg, params, prompt, 8)
    assert got == want


def test_concurrent_requests_isolation(engine):
    """4 concurrent requests must produce the same output as 4 serial ones."""
    eng, cfg, params = engine
    prompts = [np.arange(1 + i, 12 + i, dtype=np.int32) for i in range(4)]
    serial = [eng.generate(p, SamplingParams(max_new_tokens=6)) for p in prompts]

    reqs = [eng.submit(p, SamplingParams(max_new_tokens=6)) for p in prompts]
    while not all(r.done.is_set() for r in reqs):
        eng.step()
    concurrent = [r.generated for r in reqs]
    assert concurrent == serial


def test_max_new_tokens_respected(engine):
    eng, _, _ = engine
    got = eng.generate(np.array([5, 6, 7], np.int32), SamplingParams(max_new_tokens=3))
    assert len(got) == 3


def test_sampling_temperature_differs(engine):
    eng, _, _ = engine
    prompt = np.arange(1, 20, dtype=np.int32)
    a = eng.generate(prompt, SamplingParams(temperature=1.5, top_k=50, max_new_tokens=12))
    b = eng.generate(prompt, SamplingParams(temperature=1.5, top_k=50, max_new_tokens=12))
    assert len(a) == 12 and len(b) == 12
    # Engine key advances between requests, so sampled outputs should differ.
    assert a != b


def test_background_thread_mode(engine):
    eng, cfg, params = engine
    eng.start()
    try:
        prompt = np.arange(3, 30, dtype=np.int32)
        got = eng.generate(prompt, SamplingParams(max_new_tokens=5))
        want = _reference_greedy(cfg, params, prompt, 5)
        assert got == want
    finally:
        eng.stop()


def test_v5e8_mesh_serving_at_8b_kv_divisibility():
    """VERDICT r3 item 10: the exact v5e-8 serving path — an 8-device
    tensor mesh with the 8B config's kv-head count (8 kv heads / tensor=8,
    every kv head on its own chip) — must produce the same greedy tokens as
    a single-device engine. Shapes are scaled down; the PARTITIONING
    (kv=tensor=8, head grouping, vocab sharding) is the 8B layout."""
    import dataclasses

    import jax

    from kukeon_tpu.models import llama
    from kukeon_tpu.parallel import make_mesh
    from kukeon_tpu.serving import ServingEngine

    cfg = dataclasses.replace(
        llama.llama_tiny(),
        num_heads=8, num_kv_heads=8, head_dim=16, hidden_size=128,
        intermediate_size=256, vocab_size=512, num_layers=2,
        tie_embeddings=True,
    )
    params = llama.init_params(jax.random.key(7), cfg)
    qp = llama.quantize_params(params)   # int8, as the 8B target serves

    mesh8 = make_mesh(tensor=8)
    assert mesh8.devices.size == 8
    mesh1 = make_mesh(tensor=1, devices=jax.devices()[:1])

    prompt = np.arange(5, 37, dtype=np.int32) % cfg.vocab_size
    sp = SamplingParams(temperature=0.0, max_new_tokens=12)

    eng8 = ServingEngine(cfg, qp, mesh8, num_slots=4, max_seq_len=128)
    got8 = eng8.generate(prompt, sp)
    eng1 = ServingEngine(cfg, qp, mesh1, num_slots=4, max_seq_len=128)
    got1 = eng1.generate(prompt, sp)
    assert len(got8) == 12
    assert got8 == got1, f"8-dev mesh diverged: {got8} vs {got1}"

    # Concurrent sessions on the 8-device mesh (the BASELINE config-3 shape).
    reqs = [eng8.submit((prompt + i) % cfg.vocab_size, sp) for i in range(4)]
    while not all(r.done.is_set() for r in reqs):
        eng8.step()
    assert all(len(r.generated) == 12 for r in reqs)

    # int8 KV cache on the SAME kv-head-sharded layout (ADVICE r4: the
    # quantized-cache scale sharding was only single-device-tested): the
    # fused-dequant decode must agree with the single-device int8-KV engine.
    eng8q = ServingEngine(cfg, qp, mesh8, num_slots=4, max_seq_len=128,
                          kv_cache_int8=True)
    assert eng8q.state.cache.quantized
    got8q = eng8q.generate(prompt, sp)
    eng1q = ServingEngine(cfg, qp, mesh1, num_slots=4, max_seq_len=128,
                          kv_cache_int8=True)
    got1q = eng1q.generate(prompt, sp)
    assert len(got8q) == 12
    assert got8q == got1q, f"8-dev int8-KV diverged: {got8q} vs {got1q}"


def test_int8_kv_cache_engine_parity():
    """An int8-KV engine must complete continuous-batching generation and
    track the bf16 engine's greedy outputs closely (identical on a tiny
    model whose logit gaps dwarf the quantization noise)."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng_q = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                          kv_cache_int8=True)
    assert eng_q.state.cache.quantized
    eng_f = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128)

    prompts = [np.arange(1, 9, dtype=np.int32),
               np.arange(3, 17, dtype=np.int32)]
    sp = SamplingParams(max_new_tokens=8)
    got_q = [eng_q.generate(p, sp) for p in prompts]
    got_f = [eng_f.generate(p, sp) for p in prompts]
    assert all(len(g) == 8 for g in got_q)
    agree = sum(a == b for gq, gf in zip(got_q, got_f)
                for a, b in zip(gq, gf))
    assert agree >= 14, (got_q, got_f)

    # Concurrent int8 decode matches its own serial outputs (slot isolation
    # with the quantized cache).
    reqs = [eng_q.submit(p, sp) for p in prompts]
    while not all(r.done.is_set() for r in reqs):
        eng_q.step()
    assert [r.generated for r in reqs] == got_q


def test_async_load_engine_parity():
    """async_load=True (weight transfer off-thread, the cold-start overlap
    path) must produce identical generations to the synchronous engine."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    prompt = np.arange(3, 35, dtype=np.int32) % cfg.vocab_size
    sp = SamplingParams(temperature=0.0, max_new_tokens=8)

    eng_sync = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128)
    want = eng_sync.generate(prompt, sp)

    eng_async = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                              async_load=True)
    got = eng_async.generate(prompt, sp)   # step() blocks on the load
    assert got == want


def test_precompile_runs_before_weights_arrive():
    """precompile() needs shapes only: it must complete against an engine
    whose weight transfer hasn't been waited on, and the subsequent warmup
    + generate must work unchanged (the ServingCell cold-start sequence)."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(1), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                        async_load=True)
    eng.precompile((64,))
    eng.warmup(64)
    toks = eng.generate(np.arange(1, 20, dtype=np.int32) % cfg.vocab_size,
                        SamplingParams(temperature=0.0, max_new_tokens=4))
    assert len(toks) == 4


def test_async_load_failure_surfaces(monkeypatch):
    """A failed weight transfer must raise from step(), not hang waiters."""
    from kukeon_tpu.parallel import sharding as shd

    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(2), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])

    def boom(*a, **kw):
        raise OSError("device lost mid-transfer")

    monkeypatch.setattr(shd, "shard_params", boom)
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                        async_load=True)
    with pytest.raises(RuntimeError, match="weight load failed"):
        eng.generate(np.ones((4,), np.int32), SamplingParams(max_new_tokens=2))


def test_fail_all_sends_terminal_emit_event():
    """Engine-loop failure must deliver the (-1, True) terminal event to
    emit-channel consumers — a streaming client blocks on its queue, not on
    req.done (code-review r5: it would hang forever otherwise)."""
    import queue as q

    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=64)

    events: q.Queue = q.Queue()
    r = eng.submit(np.arange(1, 9, dtype=np.int32),
                   SamplingParams(max_new_tokens=4),
                   emit=lambda tok, done: events.put((tok, done)))
    eng._fail_all(RuntimeError("device lost"))
    tok, done = events.get(timeout=5)
    assert (tok, done) == (-1, True)
    assert r.done.is_set()
    assert isinstance(r.error, RuntimeError)


def test_per_request_stop_tokens():
    """A request's stop_tokens end ITS generation early (slot frees) while
    other requests keep their own budgets."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=64)
    prompt = np.arange(1, 9, dtype=np.int32)

    free = eng.generate(prompt, SamplingParams(temperature=0.0, max_new_tokens=8))
    assert len(free) == 8
    stop_at = free[2]
    stopped = eng.generate(
        prompt, SamplingParams(temperature=0.0, max_new_tokens=8,
                               stop_tokens=(int(stop_at),)))
    assert stopped == free[:3]          # stop token included, then ends


class TestPrefixCache:
    """Prefix caching: agent sessions reuse their shared context's KV."""

    def _eng(self, **kw):
        cfg = llama.llama_tiny()
        params = llama.init_params(jax.random.key(0), cfg)
        mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
        return ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                             **kw), cfg, params

    def test_hit_matches_uncached_output_exactly(self):
        """Suffix-only prefill over the stored prefix KV must produce the
        SAME greedy continuation as a full prefill of the whole prompt."""
        eng, cfg, params = self._eng()
        system = np.arange(1, 70, dtype=np.int32) % cfg.vocab_size  # 69 toks
        turn1 = np.concatenate([system, np.array([7, 8, 9], np.int32)])
        sp = SamplingParams(temperature=0.0, max_new_tokens=6)

        want = eng.generate(turn1, sp)                      # no prefix id
        r = eng.submit(system, sp, prefix_id="sess")        # seeds the cache
        while not r.done.is_set():
            eng.step()
        assert eng.prefix_misses == 1

        r = eng.submit(turn1, sp, prefix_id="sess")
        while not r.done.is_set():
            eng.step()
        assert eng.prefix_hits == 1
        assert r.generated == want

    def test_growing_conversation_rolls_forward(self):
        """Each turn re-stores the full prompt KV, so turn N+1 hits on turn
        N's whole context (system + conversation so far)."""
        eng, cfg, _ = self._eng()
        sp = SamplingParams(temperature=0.0, max_new_tokens=4)
        prompt = np.arange(1, 40, dtype=np.int32) % cfg.vocab_size
        for turn in range(3):
            r = eng.submit(prompt, sp, prefix_id="chat")
            while not r.done.is_set():
                eng.step()
            prompt = np.concatenate(
                [prompt, np.asarray(r.generated, np.int32),
                 np.array([11 + turn], np.int32)])
        assert eng.prefix_misses == 1      # only the first turn
        assert eng.prefix_hits == 2

    def test_mismatched_prefix_is_a_miss_and_restores(self):
        eng, cfg, _ = self._eng()
        sp = SamplingParams(temperature=0.0, max_new_tokens=2)
        a = np.arange(1, 30, dtype=np.int32)
        b = np.arange(2, 40, dtype=np.int32)    # NOT an extension of a
        for p in (a, b):
            r = eng.submit(p, sp, prefix_id="s")
            while not r.done.is_set():
                eng.step()
        assert eng.prefix_hits == 0
        assert eng.prefix_misses == 2
        # But b is now the stored prefix: extending it hits.
        r = eng.submit(np.concatenate([b, np.array([5], np.int32)]), sp,
                       prefix_id="s")
        while not r.done.is_set():
            eng.step()
        assert eng.prefix_hits == 1

    def test_lru_eviction(self):
        eng, cfg, _ = self._eng(prefix_cache_size=2)
        sp = SamplingParams(temperature=0.0, max_new_tokens=1)
        for name in ("a", "b", "c"):
            r = eng.submit(np.arange(1, 20, dtype=np.int32), sp,
                           prefix_id=name)
            while not r.done.is_set():
                eng.step()
        assert set(eng._prefix_cache) == {"b", "c"}


def test_prefix_cache_byte_budget_and_canonical_shapes():
    """Stored blocks stay at canonical bucket shapes (bounded compile set)
    and the byte budget evicts LRU-first; an over-budget single entry is
    not kept."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    sp = SamplingParams(temperature=0.0, max_new_tokens=2)

    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=256)
    prompt = np.arange(1, 70, dtype=np.int32) % cfg.vocab_size   # bucket 128
    r = eng.submit(prompt, sp, prefix_id="a")
    while not r.done.is_set():
        eng.step()
    from kukeon_tpu.serving.engine import PREFILL_BUCKETS
    Pb = eng._prefix_cache["a"].kv_k.shape[2]
    assert Pb in PREFILL_BUCKETS
    entry_bytes = eng._prefix_cache["a"].nbytes   # 128-bucket entry
    # Growing turn: the re-stored block is ALSO canonical (prefix bucket +
    # tail bucket re-bucketed, not an ad-hoc sum).
    grown = np.concatenate([prompt, np.asarray(r.generated, np.int32)])
    r = eng.submit(grown, sp, prefix_id="a")
    while not r.done.is_set():
        eng.step()
    Pb2 = eng._prefix_cache["a"].kv_k.shape[2]
    assert Pb2 in PREFILL_BUCKETS or Pb2 == 256

    # Budget that fits exactly one such entry: storing a second evicts the
    # first; a budget smaller than one entry keeps none.
    eng2 = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=256,
                         prefix_cache_bytes=entry_bytes)
    for name in ("x", "y"):
        r = eng2.submit(prompt, sp, prefix_id=name)
        while not r.done.is_set():
            eng2.step()
    assert list(eng2._prefix_cache) == ["y"]

    eng3 = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=256,
                         prefix_cache_bytes=entry_bytes // 2)
    r = eng3.submit(prompt, sp, prefix_id="z")
    while not r.done.is_set():
        eng3.step()
    assert len(eng3._prefix_cache) == 0


def test_decode_host_sync_budget():
    """The decode roofline contract (ISSUE 1): steady-state decode performs
    exactly ONE blocking device→host transfer per dispatched chunk (the
    token-block fetch) and re-uploads sampling arrays only when the slot
    composition changes — asserted through the engine's transfer-counting
    seam instead of guessed from timings."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                        decode_chunk=4)

    base = dict(eng.sync_stats)
    req = eng.submit(np.arange(1, 9, dtype=np.int32),
                     SamplingParams(max_new_tokens=24))
    prefill_steps = 0
    while not req.done.is_set():
        before = eng.sync_stats["uploads"]
        eng.step()
        if eng.sync_stats["uploads"] > before:
            prefill_steps += 1
    d = {k: eng.sync_stats[k] - base[k] for k in base}
    assert len(req.generated) == 24
    # Several chunks ran (24 tokens at chunk<=4), each fetched exactly once;
    # the only extra fetch is the prefill's stacked first-token readback.
    # A trailing overshoot chunk may stay unfetched when the request
    # finishes during the flush of the previous one.
    assert d["chunks"] >= 5
    assert d["fetches"] <= d["chunks"] + 1
    assert d["fetches"] >= d["chunks"] - 1
    # Uploads happen only at composition changes: prompt tokens + the three
    # sampling arrays once, NOT per chunk.
    assert prefill_steps == 1
    assert d["uploads"] == 4, d

    # Steady state with an unchanged slot map: a second request re-uploads
    # once (composition changed at insert + release), still O(1) not
    # O(chunks).
    base = dict(eng.sync_stats)
    req = eng.submit(np.arange(3, 17, dtype=np.int32),
                     SamplingParams(max_new_tokens=24))
    while not req.done.is_set():
        eng.step()
    d = {k: eng.sync_stats[k] - base[k] for k in base}
    assert d["chunks"] >= 5
    assert d["uploads"] == 4, d


def test_decode_host_sync_budget_paged():
    """The same roofline contract on the paged path (ISSUE 6): steady-state
    decode still performs exactly ONE blocking device→host transfer per
    dispatched chunk. Uploads stay O(1) per request — prompt tokens and
    scatter page-ids at prefill, the three sampling arrays at composition
    change, and the block table only when a slot's page list changes
    (insert + one page-growth here), never per chunk."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=128,
                        decode_chunk=4, kv_page_tokens=16, kv_pool_pages=16)

    for prompt in (np.arange(1, 9, dtype=np.int32),
                   np.arange(3, 17, dtype=np.int32)):
        base = dict(eng.sync_stats)
        req = eng.submit(prompt, SamplingParams(max_new_tokens=24))
        while not req.done.is_set():
            eng.step()
        d = {k: eng.sync_stats[k] - base[k] for k in base}
        assert len(req.generated) == 24
        # One fetch per chunk plus the prefill's stacked first-token
        # readback; a trailing overshoot chunk may stay unfetched.
        assert d["chunks"] >= 5
        assert d["fetches"] <= d["chunks"] + 1
        assert d["fetches"] >= d["chunks"] - 1
        # 2 prefill uploads (tokens, page-ids) + 3 sampling arrays +
        # 2 block-table uploads (insert dirty + one page growth) — O(1)
        # per request, not O(chunks).
        assert d["uploads"] == 7, d


def test_submit_rejects_overlong_prompt():
    """Prompts that cannot fit the KV slot fail loudly at submit() — on
    BOTH the fresh path and the prefix-cache hit path (ADVICE r5: the hit
    path used to silently truncate KV rows instead)."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=64)

    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(np.ones((64,), np.int32))        # == max_seq_len
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(np.ones((100,), np.int32))       # > max_seq_len
    with pytest.raises(ValueError, match="non-empty"):
        eng.submit(np.ones((0,), np.int32))

    # Seed a prefix, then try to extend it past the window: the hit path
    # must reject at submit too, and the engine must still serve afterwards.
    sp = SamplingParams(temperature=0.0, max_new_tokens=2)
    prefix = np.arange(1, 50, dtype=np.int32)
    r = eng.submit(prefix, sp, prefix_id="sess")
    while not r.done.is_set():
        eng.step()
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.submit(np.arange(1, 80, dtype=np.int32), sp, prefix_id="sess")
    ok = eng.generate(np.arange(1, 10, dtype=np.int32), sp)
    assert len(ok) == 2
