"""Serving engine: continuous batching correctness on a CPU tensor mesh."""

import jax
import numpy as np
import pytest

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine


@pytest.fixture(scope="module")
def engine():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=2, data=4)
    return ServingEngine(cfg, params, mesh, num_slots=4, max_seq_len=128), cfg, params


def _reference_greedy(cfg, params, prompt, n_new):
    """Greedy decode via direct full forward passes (no cache)."""
    import jax.numpy as jnp

    tokens = list(prompt)
    out = []
    for _ in range(n_new):
        t = jnp.asarray(tokens, jnp.int32)[None, :]
        pos = jnp.arange(len(tokens), dtype=jnp.int32)[None, :]
        logits, _ = llama.forward(params, cfg, t, pos)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        tokens.append(nxt)
    return out


def test_greedy_matches_uncached_reference(engine):
    eng, cfg, params = engine
    prompt = np.arange(1, 9, dtype=np.int32)  # 8 tokens
    got = eng.generate(prompt, SamplingParams(max_new_tokens=8))
    want = _reference_greedy(cfg, params, prompt, 8)
    assert got == want


def test_concurrent_requests_isolation(engine):
    """4 concurrent requests must produce the same output as 4 serial ones."""
    eng, cfg, params = engine
    prompts = [np.arange(1 + i, 12 + i, dtype=np.int32) for i in range(4)]
    serial = [eng.generate(p, SamplingParams(max_new_tokens=6)) for p in prompts]

    reqs = [eng.submit(p, SamplingParams(max_new_tokens=6)) for p in prompts]
    while not all(r.done.is_set() for r in reqs):
        eng.step()
    concurrent = [r.generated for r in reqs]
    assert concurrent == serial


def test_max_new_tokens_respected(engine):
    eng, _, _ = engine
    got = eng.generate(np.array([5, 6, 7], np.int32), SamplingParams(max_new_tokens=3))
    assert len(got) == 3


def test_sampling_temperature_differs(engine):
    eng, _, _ = engine
    prompt = np.arange(1, 20, dtype=np.int32)
    a = eng.generate(prompt, SamplingParams(temperature=1.5, top_k=50, max_new_tokens=12))
    b = eng.generate(prompt, SamplingParams(temperature=1.5, top_k=50, max_new_tokens=12))
    assert len(a) == 12 and len(b) == 12
    # Engine key advances between requests, so sampled outputs should differ.
    assert a != b


def test_background_thread_mode(engine):
    eng, cfg, params = engine
    eng.start()
    try:
        prompt = np.arange(3, 30, dtype=np.int32)
        got = eng.generate(prompt, SamplingParams(max_new_tokens=5))
        want = _reference_greedy(cfg, params, prompt, 5)
        assert got == want
    finally:
        eng.stop()
