"""Sharded training: loss decreases, runs on fsdp×tensor and seq meshes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh, set_mesh
from kukeon_tpu.training import create_train_state, make_train_step
from kukeon_tpu.training.train_step import make_optimizer


def _fake_batch(key, cfg, B, S):
    tokens = jax.random.randint(key, (B, S), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.ones((B, S), jnp.float32).at[:, -1].set(0.0)
    return tokens, targets, mask


@pytest.mark.parametrize(
    "mesh_kw",
    [
        dict(fsdp=4, tensor=2),
        dict(data=2, seq=4),
    ],
    ids=["fsdp4_tp2", "dp2_sp4"],
)
def test_train_step_loss_decreases(mesh_kw):
    cfg = llama.llama_tiny()
    mesh = make_mesh(**mesh_kw)
    with set_mesh(mesh):
        optimizer = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=100)
        state, optimizer = create_train_state(cfg, mesh, jax.random.key(0), optimizer)
        train_step, batch_sharding = make_train_step(cfg, mesh, optimizer)

        B, S = 8, 32
        tokens, targets, mask = _fake_batch(jax.random.key(1), cfg, B, S)
        tokens = jax.device_put(tokens, batch_sharding)
        targets = jax.device_put(targets, batch_sharding)
        mask = jax.device_put(mask, batch_sharding)

        losses = []
        for _ in range(5):
            state, loss = train_step(state, tokens, targets, mask)
            losses.append(float(loss))

    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 5
