"""Roofline flight recorder (ISSUE 19): per-program timers and the MFU
gauges they derive, the per-layer cost profiler and its FLOPs-sum
contract, the engine-step flight recorder (ring bounds, concurrent
ingest/readers, GET /v1/timeline), federation staleness, and the
`kuke timeline` / `kuke profile layers` renderers.

The acceptance spine: a flooded tiny engine exposes nonzero
kukeon_program_mfu <= 1.0 for the programs that ran, `bench.py
--profile-layers`'s per-component FLOPs sum matches the whole-model
reference within 5%, and /v1/timeline steps cross-link to trace ids the
tracer resolves. The whole file must stay green under KUKEON_SANITIZE=1
(check.yml runs it in both slices).
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import numpy as np
import pytest

from kukeon_tpu import faults
from kukeon_tpu.models import llama
from kukeon_tpu.obs import (
    FlightRecorder,
    Registry,
    profile_layers,
    render,
)
from kukeon_tpu.obs import federate as fed
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine

from test_obs import _parse_expo

PROMPT = np.arange(1, 9, dtype=np.int32)


def _tiny_engine(**kw):
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    kw.setdefault("num_slots", 2)
    return ServingEngine(cfg, params, mesh, max_seq_len=96,
                         decode_chunk=4, **kw)


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, raw


def _post(port, path, body):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, raw


# --- the flight-recorder ring ------------------------------------------------


def test_flight_recorder_ring_bounds_and_drop_counter():
    """Memory contract: the ring never holds more than its capacity, the
    overwritten records are counted both on .dropped and the
    kukeon_timeline_dropped_total counter, and snapshot(n) is the newest
    n oldest-first."""
    reg = Registry()
    rec = FlightRecorder(capacity=8, registry=reg)
    for i in range(20):
        rec.record({"tokens": i})
    assert len(rec) == 8
    assert rec.dropped == 12
    assert [s["seq"] for s in rec.snapshot()] == list(range(12, 20))
    assert [s["tokens"] for s in rec.snapshot(3)] == [17, 18, 19]
    assert rec.snapshot(0) == []
    # Every record got stamped with a wall-clock second.
    assert all(s["t"] > 0 for s in rec.snapshot())

    fams = _parse_expo(render(reg))
    assert fams["kukeon_timeline_dropped_total"]["type"] == "counter"
    [(_n, _l, dropped)] = fams["kukeon_timeline_dropped_total"]["samples"]
    assert float(dropped) == 12.0
    [(_n, _l, depth)] = fams["kukeon_timeline_depth"]["samples"]
    assert float(depth) == 8.0


def test_flight_recorder_concurrent_flood():
    """Satellite: ingest hammers from several threads while readers flood
    snapshot() and the registry scrape — no torn reads, ring stays
    bounded, every drop accounted. Green under KUKEON_SANITIZE=1."""
    reg = Registry()
    rec = FlightRecorder(capacity=64, registry=reg)
    writers, per_writer = 4, 300
    stop = threading.Event()
    errors: list[BaseException] = []

    def hammer(base):
        try:
            for i in range(per_writer):
                rec.record({"tokens": base + i})
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                snap = rec.snapshot(16)
                seqs = [s["seq"] for s in snap]
                assert seqs == sorted(seqs)       # oldest-first, no tears
                assert len(snap) <= 64
                render(reg)                        # scrape-path collector
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(i * per_writer,))
               for i in range(writers)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers + threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not errors, errors[0]
    total = writers * per_writer
    assert len(rec) == 64
    assert rec.dropped == total - 64
    fams = _parse_expo(render(reg))
    [(_n, _l, dropped)] = fams["kukeon_timeline_dropped_total"]["samples"]
    assert float(dropped) == float(total - 64)


# --- per-program timers: the engine flood ------------------------------------


def test_engine_flood_exposes_nonzero_mfu_gauges():
    """Acceptance: after precompile (static costs) + a request flood
    (measured busy time), kukeon_program_mfu and
    kukeon_program_membw_util are nonzero and <= 1.0 for the programs
    that ran, and the dispatch/tokens counters line up with the work."""
    eng = _tiny_engine()
    eng.precompile((8,))      # cost_analysis denominators land here
    eng.warmup(8)
    reqs = [eng.submit(PROMPT, SamplingParams(max_new_tokens=12))
            for _ in range(2)]
    while not all(r.done.is_set() for r in reqs):
        eng.step()
    eng.timers.settle()

    snap = eng.timers.snapshot()
    for program in ("prefill", "decode_chunk"):
        assert snap[program]["dispatches"] >= 1
        assert snap[program]["settled"] >= 1
        assert snap[program]["busy_s"] > 0.0
        assert snap[program]["flops"] > 0.0          # CPU reports costs
        assert 0.0 < snap[program]["mfu"] <= 1.0
        assert 0.0 < snap[program]["membw_util"] <= 1.0
    # Decode counted batch*k token work; prefill counted the prompt rows.
    assert snap["decode_chunk"]["tokens"] >= 2 * 12
    assert snap["prefill"]["tokens"] >= 2 * len(PROMPT)

    fams = _parse_expo(render(eng.registry))
    mfu = {l["program"]: float(v)
           for _n, l, v in fams["kukeon_program_mfu"]["samples"]}
    for program in ("prefill", "decode_chunk"):
        assert 0.0 < mfu[program] <= 1.0
    # Histogram of settled wall times exists per program.
    assert any(l.get("program") == "decode_chunk"
               for _n, l, _v in fams["kukeon_program_seconds"]["samples"])
    # The engine's flight recorder saw the same flood.
    assert len(eng.recorder) >= 1
    step = eng.recorder.snapshot(1)[0]
    for key in ("seq", "t", "wall_s", "occupancy", "slots", "tokens",
                "programs", "traces", "queue_depth"):
        assert key in step


# --- the per-layer cost profiler ---------------------------------------------


def test_profile_layers_flops_sum_matches_whole_model():
    """Acceptance: per-component prefill FLOPs sum to the whole-model
    reference within 5% (the scan-free lowering makes this structural,
    not lucky), with one entry per component."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    prof = profile_layers(params, cfg, prefill_len=16, decode_batch=2,
                          measure=False)
    assert prof["schema"] == "kukeon-layer-profile/v1"
    assert prof["errors"] == 0
    names = [c["name"] for c in prof["components"]]
    assert names == ["embed"] + [f"layer{i}" for i in
                                 range(cfg.num_layers)] + ["head"]
    assert prof["model_flops"] > 0
    total = sum(c["prefill"]["flops"] for c in prof["components"])
    assert abs(total - prof["model_flops"]) / prof["model_flops"] < 0.05
    # Both shapes costed for every component.
    for c in prof["components"]:
        for shape in ("prefill", "decode"):
            assert c[shape]["flops"] > 0
            assert c[shape]["bytes"] > 0


def test_profile_layers_measures_wall_time():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    prof = profile_layers(params, cfg, prefill_len=8, decode_batch=1,
                          measure=True, reps=1)
    assert prof["errors"] == 0
    assert all(c["prefill"]["wall_s"] >= 0 for c in prof["components"])


def test_profile_layers_armed_fault_degrades_cleanly():
    """Satellite: the profile.layers fault point. Armed at probability 1
    every component records an error entry instead of raising — a
    partial/empty profile, never a dead caller."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    os.environ[faults.ENV] = "profile.layers:1"
    prof = profile_layers(params, cfg, prefill_len=8, decode_batch=1,
                          measure=False)
    # embed + layers + head each failed; the whole-model reference does
    # not pass through the fault point, so it may still cost out.
    assert prof["errors"] >= cfg.num_layers + 2
    failed = [c for c in prof["components"] if c.get("error")]
    assert len(failed) >= cfg.num_layers + 2
    assert all("FaultInjected" in c["error"] for c in failed)


# --- the live cell: /v1/timeline and POST /v1/profile {"layers": true} -------


@pytest.fixture(scope="module")
def real_cell():
    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    cell = ServingCell("tiny", num_slots=2, max_seq_len=96, checkpoint=None,
                       dtype=None, max_pending=8)
    cell.warmup(prompt_len=16)
    cell.engine.start()
    cell.mark_ready()
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield cell, server.server_address[1]
    server.shutdown()
    server.server_close()
    cell.engine.stop()


def test_timeline_endpoint_cross_links_to_traces(real_cell):
    """Acceptance: GET /v1/timeline reconstructs the engine's recent
    steps, and the trace ids seated in those steps resolve through the
    same tracer `kuke trace` reads."""
    cell, port = real_cell
    status, raw = _post(port, "/v1/generate",
                        {"promptTokens": [1, 2, 3, 4], "maxNewTokens": 4})
    assert status == 200 and json.loads(raw)["numTokens"] == 4

    # The engine thread records the step before the terminal token by a
    # hair's width — poll briefly for a step that carries a trace id.
    deadline = time.monotonic() + 5.0
    tids: set[str] = set()
    while not tids and time.monotonic() < deadline:
        status, raw = _get(port, "/v1/timeline?n=50")
        assert status == 200
        body = json.loads(raw)
        tids = {t for s in body["steps"] for t in (s.get("traces") or ())}
        if not tids:
            time.sleep(0.01)
    assert body["capacity"] == cell.engine.recorder.capacity
    assert body["steps"], "flight recorder saw no steps"
    for step in body["steps"]:
        assert step["slots"] == 2
        assert step["wall_s"] >= 0
        assert isinstance(step["programs"], dict)
    assert tids, "no step carried a seated trace id"
    # The span lands in the tracer ring when the engine thread finishes
    # it — a hair after the terminal token is emitted. Poll briefly.
    while (not any(cell.engine.tracer.for_trace(t) for t in tids)
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert any(cell.engine.tracer.for_trace(t) for t in tids)

    status, _raw = _get(port, "/v1/timeline?n=bogus")
    assert status == 400


def test_cell_layer_profile_over_http_persists(real_cell, monkeypatch,
                                               tmp_path):
    """POST /v1/profile {"layers": true} profiles the live model and
    persists next to the serving tune; `kuke profile layers` renders the
    stored profile without touching jax."""
    from kukeon_tpu.runtime.cli import render_layer_profile
    from kukeon_tpu.serving import tuning

    cell, port = real_cell
    store = tmp_path / "layer_profile.json"
    monkeypatch.setenv("KUKEON_LAYER_PROFILE_PATH", str(store))
    status, raw = _post(port, "/v1/profile",
                        {"layers": True, "prefillLen": 8, "decodeBatch": 2})
    assert status == 200
    prof = json.loads(raw)
    assert prof["errors"] == 0
    assert prof["path"] == str(store)
    assert "|" in prof["key"]

    stored = tuning.load_layer_profiles()
    assert prof["key"] in stored
    assert stored[prof["key"]]["profiled_at"]
    out = render_layer_profile(prof["key"], stored[prof["key"]])
    assert "COMPONENT" in out and "layer0" in out and "prefill" in out


def test_cell_layer_profile_fault_recorded_not_fatal(real_cell):
    """Satellite, the other fault branch: an armed profile.layers fault
    during an HTTP-triggered profile comes back RECORDED in the body
    (200, errors counted, nothing persisted) and the cell keeps
    serving."""
    cell, port = real_cell
    os.environ[faults.ENV] = "profile.layers:1"
    try:
        status, raw = _post(port, "/v1/profile", {"layers": True,
                                                  "prefillLen": 8,
                                                  "decodeBatch": 1})
    finally:
        os.environ.pop(faults.ENV, None)
        faults.reset()
    assert status == 200
    prof = json.loads(raw)
    assert prof["errors"] > 0
    assert "path" not in prof                    # partial -> not persisted
    status, raw = _post(port, "/v1/generate",
                        {"promptTokens": [1, 2, 3], "maxNewTokens": 2})
    assert status == 200 and json.loads(raw)["numTokens"] == 2


# --- federation: fetch_timelines + scrape staleness --------------------------


def test_fetch_timelines_unions_sorts_and_tags():
    """The daemon-side union: steps from every reachable cell come back
    tagged with the cell key and sorted by wall-clock stamp; dead cells
    contribute nothing (and never raise)."""
    from kukeon_tpu.runtime.daemon import fetch_timelines

    steps = [{"seq": 1, "t": 20.0}, {"seq": 0, "t": 10.0}]

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            assert self.path == "/v1/timeline?n=5"
            body = json.dumps({"steps": steps}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        got = fetch_timelines([("ns/c0", url, {}),
                               ("ns/dead", "http://127.0.0.1:9", {})],
                              n=5, timeout_s=5.0)
    finally:
        srv.shutdown()
        srv.server_close()
    assert [s["seq"] for s in got] == [0, 1]          # re-sorted by t
    assert all(s["cell"] == "ns/c0" for s in got)


def test_telemetry_scrape_ages_track_last_good_and_departures():
    """Satellite: kukeon_cell_scrape_age_seconds bookkeeping. A failing
    cell's age grows from its last GOOD scrape; a departed cell's age is
    forgotten with the cell; a cell never seen good contributes no
    sample."""
    from kukeon_tpu.runtime.daemon import FleetTelemetry

    now = [100.0]
    telem = FleetTelemetry(None, registry=Registry(),
                           clock=lambda: now[0], rules=[])
    ages = telem.note_scrapes([{"cell": "a", "ok": True},
                               {"cell": "b", "ok": False}], at=100.0)
    assert ages == {"a": 0.0}                         # b never seen good
    ages = telem.note_scrapes([{"cell": "a", "ok": False},
                               {"cell": "b", "ok": True}], at=107.0)
    assert ages == {"a": 7.0, "b": 0.0}
    now[0] = 109.0
    assert telem.scrape_ages() == {"a": 9.0, "b": 2.0}
    # "a" left the fleet: its frozen age must not read "stale" forever.
    ages = telem.note_scrapes([{"cell": "b", "ok": True}], at=110.0)
    assert ages == {"b": 0.0}
    assert telem.scrape_ages(at=111.0) == {"b": 1.0}

    fam = fed.scrape_age_family(telem.scrape_ages(at=111.5))
    assert fam.name == "kukeon_cell_scrape_age_seconds"
    assert fam.samples == [("kukeon_cell_scrape_age_seconds",
                            {"cell": "b"}, "1.500")]


def test_scrape_age_family_sorts_and_clamps():
    fam = fed.scrape_age_family({"z": 2.0, "a": -0.5})
    assert [(s[1]["cell"], s[2]) for s in fam.samples] == [
        ("a", "0.000"), ("z", "2.000")]


# --- renderers ---------------------------------------------------------------


def test_render_timeline_table():
    from kukeon_tpu.runtime.cli import render_timeline

    steps = [
        {"t": 1000.25, "seq": 4, "wall_s": 0.012, "occupancy": 2,
         "slots": 4, "chunk_k": 8, "tokens": 16, "fetches": 1,
         "uploads": 0, "preemptions": 0, "queue_depth": 3,
         "programs": {"decode_chunk": 0.0101}, "traces": ["abc123"],
         "cell": "ns/c0"},
        {"t": 1000.0, "seq": 3, "wall_s": 0.5, "occupancy": 1, "slots": 4,
         "tokens": 1},
    ]
    out = render_timeline(steps)
    lines = out.splitlines()
    assert "SEQ" in lines[0] and "TOKENS" in lines[0]
    # Sorted by wall-clock stamp: seq 3 first despite list order.
    assert lines[1].split()[1] == "3"
    assert "+0.000s" in lines[1] and "+0.250s" in lines[2]
    assert "2/4" in lines[2]
    assert "decode_chunk 10.1ms" in lines[2]
    assert "traces=abc123" in lines[2] and "[ns/c0]" in lines[2]
    assert "no recorded engine steps" in render_timeline([])


def test_render_layer_profile_marks_failed_components():
    from kukeon_tpu.runtime.cli import render_layer_profile

    prof = {"schema": "kukeon-layer-profile/v1", "num_layers": 2,
            "prefill_len": 16, "decode_batch": 2, "model_flops": 1.2e7,
            "model_bytes": 3.4e6, "errors": 1,
            "components": [
                {"name": "embed",
                 "prefill": {"flops": 2144.0, "bytes": 268.0,
                             "wall_s": 0.001},
                 "decode": {"flops": 268.0, "bytes": 34.0}},
                {"name": "layer0", "error": "FaultInjected: boom"},
            ]}
    out = render_layer_profile("tiny|cpu|1", prof)
    assert "tiny|cpu|1" in out
    assert "1 component(s) failed to profile" in out
    assert "(FaultInjected: boom)" in out
    assert "1.00ms" in out                         # measured wall column
    assert "model_flops=12.0M" in out


def test_render_top_dims_stale_rows(monkeypatch):
    """Satellite: a row whose last good scrape is older than 2 scrape
    intervals renders ANSI-dim; fresh rows render normally."""
    from kukeon_tpu.runtime.cli import render_top

    monkeypatch.delenv("KUKEON_SCRAPE_INTERVAL_S", raising=False)
    row = {"cell": "ns/fresh", "model": "tiny", "ready": True, "ok": True,
           "qps": 1.0, "queueDepth": 0, "restarts": 0}
    stale = dict(row, cell="ns/stale", scrapeAgeS=21.0)   # > 2 * 10s
    out = render_top([row, stale])
    fresh_line = next(ln for ln in out.splitlines() if "ns/fresh" in ln)
    stale_line = next(ln for ln in out.splitlines() if "ns/stale" in ln)
    assert not fresh_line.startswith("\x1b[2m")
    assert stale_line.startswith("\x1b[2m") and stale_line.endswith("\x1b[0m")
    # Tighter interval drags the threshold down with it.
    monkeypatch.setenv("KUKEON_SCRAPE_INTERVAL_S", "2")
    out = render_top([dict(row, scrapeAgeS=5.0)])
    assert out.splitlines()[-1].startswith("\x1b[2m")


# --- bench artifact v8 -------------------------------------------------------


def test_bench_compare_upgrades_v7_and_diffs_mfu(tmp_path):
    """v7 artifacts upgrade in place (program_costs/mfu default None —
    reported as n/a, never a regression) and an MFU drop past the
    threshold flags with higher-is-better polarity."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_compare_v8", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "bench_compare.py"))
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)

    old = tmp_path / "BENCH_r1.json"
    old.write_text(json.dumps({"schema": "kukeon-bench/v7",
                               "tok_per_s": 100.0}))
    art = bc.read_artifact(str(old))
    assert art["schema"] == "kukeon-bench/v8"
    assert art["program_costs"] is None and art["mfu"] is None

    new = dict(art, schema="kukeon-bench/v8", mfu=0.5,
               program_costs={"decode_chunk": {"mfu": 0.5}})
    prev = dict(art, mfu=0.9)
    rows, regressed = bc.compare(prev, new, threshold_pct=10.0)
    mfu_row = next(r for r in rows if r[0] == "MFU")
    assert mfu_row[4] == "REGRESSION" and regressed
    # Missing on one side: informational, never a regression.
    rows, regressed = bc.compare(art, new, threshold_pct=10.0)
    assert next(r for r in rows if r[0] == "MFU")[4] == "n/a"
    assert not regressed
