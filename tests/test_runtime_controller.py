"""Controller + runner against the fake backend (the reference's test seam:
controller tested against fake runner/ctr clients — SURVEY.md section 4)."""

import dataclasses

import pytest

from kukeon_tpu.runtime import consts, model
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.apply import parser
from kukeon_tpu.runtime.cells import FakeBackend
from kukeon_tpu.runtime.controller import (
    BREAKING,
    COMPATIBLE,
    UNCHANGED,
    Controller,
    diff_cell_spec,
    substitute_blueprint,
)
from kukeon_tpu.runtime.devices import TPUDeviceManager
from kukeon_tpu.runtime.errors import FailedPrecondition, InvalidArgument, NotFound
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import (
    OUTCOME_AUTO_DELETED,
    OUTCOME_RESTARTED,
    Runner,
    RunnerOptions,
)
from kukeon_tpu.runtime.store import ResourceStore


@pytest.fixture
def ctl(tmp_path):
    store = ResourceStore(MetadataStore(str(tmp_path)))
    backend = FakeBackend()
    devices = TPUDeviceManager(store.ms, chips=[0, 1, 2, 3])
    runner = Runner(store, backend, cgroups=None, devices=devices,
                    options=RunnerOptions(stop_grace_s=0.2))
    c = Controller(store, runner)
    c.bootstrap()
    return c, backend, store, devices


def _cell_doc(name="c1", **cell_kw):
    return t.Document(
        kind=t.KIND_CELL,
        metadata=t.Metadata(name=name),
        spec=t.CellSpec(
            containers=[t.ContainerSpec(name="main", command=["/bin/true"])],
            **cell_kw,
        ),
    )


def test_bootstrap_hierarchy(ctl):
    c, _, store, _ = ctl
    assert set(c.list_realms()) == {consts.DEFAULT_REALM, consts.SYSTEM_REALM}
    assert c.list_spaces("default") == ["default"]
    assert c.list_stacks("default", "default") == ["default"]


def test_cell_lifecycle(ctl):
    c, backend, store, _ = ctl
    rec = c.create_cell(_cell_doc())
    assert rec["status"]["phase"] == model.READY
    assert rec["realm"] == "default"

    got = c.get_cell("default", "default", "default", "c1")
    assert got["status"]["containers"][0]["state"] == model.C_RUNNING

    stopped = c.stop_cell("default", "default", "default", "c1")
    assert stopped["status"]["phase"] == model.STOPPED

    c.delete_cell("default", "default", "default", "c1")
    with pytest.raises(NotFound):
        c.get_cell("default", "default", "default", "c1")


def test_delete_running_requires_force(ctl):
    c, _, _, _ = ctl
    c.create_cell(_cell_doc())
    with pytest.raises(FailedPrecondition, match="running"):
        c.delete_cell("default", "default", "default", "c1")
    c.delete_cell("default", "default", "default", "c1", force=True)


def test_restart_policy_on_failure(ctl):
    c, backend, store, _ = ctl
    doc = _cell_doc()
    doc.spec.containers[0].restart_policy = t.RestartPolicy(
        policy="on-failure", backoff_seconds=0.0, max_retries=2
    )
    c.create_cell(doc)
    cdir = store.container_dir("default", "default", "default", "c1", "main")
    backend.exit(cdir, 1)

    _, outcome = c.runner.refresh_cell("default", "default", "default", "c1")
    assert outcome == OUTCOME_RESTARTED
    rec = store.read_cell("default", "default", "default", "c1")
    assert rec.status.container("main").restarts == 1

    # Exits cleanly now -> on-failure does NOT restart.
    backend.exit(cdir, 0)
    _, outcome = c.runner.refresh_cell("default", "default", "default", "c1")
    assert outcome != OUTCOME_RESTARTED

    # Fail twice more: max_retries=2 caps restarts at 2.
    backend.exit(cdir, 1)
    _, o1 = c.runner.refresh_cell("default", "default", "default", "c1")
    backend.exit(cdir, 1)
    _, o2 = c.runner.refresh_cell("default", "default", "default", "c1")
    rec = store.read_cell("default", "default", "default", "c1")
    assert rec.status.container("main").restarts == 2
    assert o2 != OUTCOME_RESTARTED


def test_restart_backoff_delays(ctl):
    c, backend, store, _ = ctl
    doc = _cell_doc()
    doc.spec.containers[0].restart_policy = t.RestartPolicy(
        policy="always", backoff_seconds=9999.0
    )
    c.create_cell(doc)
    cdir = store.container_dir("default", "default", "default", "c1", "main")
    backend.exit(cdir, 1)
    _, outcome = c.runner.refresh_cell("default", "default", "default", "c1")
    # Backoff not yet elapsed (finished_at just set) -> no restart.
    assert outcome != OUTCOME_RESTARTED


def test_auto_delete_reaps(ctl):
    c, backend, store, _ = ctl
    c.create_cell(_cell_doc(auto_delete=True))
    cdir = store.container_dir("default", "default", "default", "c1", "main")
    backend.exit(cdir, 0)
    _, outcome = c.runner.refresh_cell("default", "default", "default", "c1")
    assert outcome == OUTCOME_AUTO_DELETED
    assert not store.cell_exists("default", "default", "default", "c1")


def test_apply_create_unchanged_update_recreate(ctl):
    c, backend, store, _ = ctl
    yaml1 = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: web}
spec:
  containers:
    - {name: main, command: [/bin/true], env: [{name: A, value: "1"}]}
"""
    r1 = c.apply_documents(yaml1)
    assert r1[0].action == "created"
    r2 = c.apply_documents(yaml1)
    assert r2[0].action == "unchanged"

    # env change = compatible -> updated in place (no recreate).
    r3 = c.apply_documents(yaml1.replace('value: "1"', 'value: "2"'))
    assert r3[0].action == "updated"
    rec = store.read_cell("default", "default", "default", "web")
    assert rec.generation == 2
    assert backend.entries[store.container_dir("default", "default", "default", "web", "main")].starts == 1

    # command change = breaking -> recreated.
    r4 = c.apply_documents(yaml1.replace("/bin/true", "/bin/false"))
    assert r4[0].action == "recreated"


def test_diff_classification():
    a = t.CellSpec(containers=[t.ContainerSpec(name="m", command=["a"])])
    assert diff_cell_spec(a, dataclasses.replace(a)) == UNCHANGED
    b = t.CellSpec(containers=[t.ContainerSpec(name="m", command=["a"],
                                               env=[t.EnvVar(name="X", value="1")])])
    assert diff_cell_spec(a, b) == COMPATIBLE
    c = t.CellSpec(containers=[t.ContainerSpec(name="m", command=["b"])])
    assert diff_cell_spec(a, c) == BREAKING


def test_tpu_chip_allocation(ctl):
    c, backend, store, devices = ctl
    doc = _cell_doc("tpu1")
    doc.spec.containers[0].resources = t.Resources(tpu_chips=2)
    rec = c.create_cell(doc)
    assert rec["status"]["tpuChips"] == [0, 1]
    assert devices.free_chips() == [2, 3]

    doc2 = _cell_doc("tpu2")
    doc2.spec.containers[0].resources = t.Resources(tpu_chips=3)
    with pytest.raises(FailedPrecondition, match="not enough TPU chips"):
        c.create_cell(doc2)

    # Stop releases chips.
    c.stop_cell("default", "default", "default", "tpu1")
    assert devices.free_chips() == [0, 1, 2, 3]


def test_model_cell_materializes_serving_container(ctl):
    c, backend, store, devices = ctl
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=2, port=9123)),
    )
    rec = c.create_cell(doc)
    names = [cs["name"] for cs in rec["status"]["containers"]]
    assert names == ["model-server"]
    assert rec["status"]["tpuChips"] == [0, 1]
    cdir = store.container_dir("default", "default", "default", "llm", "model-server")
    assert backend.entries[cdir].starts == 1


def test_secret_staging_env(ctl, tmp_path):
    c, backend, store, _ = ctl
    c.put_secret(t.Document(
        kind=t.KIND_SECRET, metadata=t.Metadata(name="api-key"),
        spec=t.SecretSpec(data={"KEY": "s3cr3t"}),
    ))
    doc = _cell_doc("sec")
    doc.spec.containers[0].secrets = [t.SecretRef(name="api-key", env="API_KEY")]
    c.create_cell(doc)
    # The staged file exists mode 0400 with the value.
    import glob, os
    cdir = store.container_dir("default", "default", "default", "sec", "main")
    staged = os.path.join(cdir, "secrets", "api-key.env")
    assert open(staged).read() == "KEY=s3cr3t\n"
    assert (os.stat(staged).st_mode & 0o777) == 0o400


def test_missing_secret_fails_start(ctl):
    c, _, _, _ = ctl
    doc = _cell_doc("sec2")
    doc.spec.containers[0].secrets = [t.SecretRef(name="nope")]
    with pytest.raises(NotFound, match="secret 'nope'"):
        c.create_cell(doc)


def test_blueprint_substitution_and_run(ctl):
    c, _, store, _ = ctl
    bp = t.Document(
        kind=t.KIND_CELL_BLUEPRINT, metadata=t.Metadata(name="agent"),
        spec=t.CellBlueprintSpec(
            params=[t.BlueprintParam(name="msg", required=True),
                    t.BlueprintParam(name="shell", default="/bin/sh")],
            cell=t.CellSpec(containers=[t.ContainerSpec(
                name="main", command=["${shell}", "-c", "echo ${msg}"],
            )]),
            name_prefix="agent",
        ),
    )
    c.put_blueprint(bp)
    with pytest.raises(InvalidArgument, match="requires params"):
        c.run_blueprint("default", "default", "default", "agent", {})
    rec = c.run_blueprint("default", "default", "default", "agent", {"msg": "hi"})
    assert rec["name"].startswith("agent-")
    assert rec["spec"]["containers"][0]["command"] == ["/bin/sh", "-c", "echo hi"]
    assert rec["labels"]["kukeon.io/blueprint"] == "agent"


def test_config_materialization_deterministic_name(ctl):
    c, _, store, _ = ctl
    c.put_blueprint(t.Document(
        kind=t.KIND_CELL_BLUEPRINT, metadata=t.Metadata(name="bp"),
        spec=t.CellBlueprintSpec(
            params=[t.BlueprintParam(name="cmd", default="/bin/true")],
            cell=t.CellSpec(containers=[t.ContainerSpec(name="m", command=["${cmd}"])]),
        ),
    ))
    c.put_config(t.Document(
        kind=t.KIND_CELL_CONFIG, metadata=t.Metadata(name="cfg1"),
        spec=t.CellConfigSpec(blueprint="bp", cell_name="thecell"),
    ))
    rec = c.materialize_config("default", None, None, "cfg1")
    assert rec["name"] == "thecell"
    assert rec["labels"]["kukeon.io/config"] == "cfg1"
    # Re-materialize: idempotent (same live cell).
    rec2 = c.materialize_config("default", None, None, "cfg1")
    assert rec2["name"] == "thecell"


def test_cascade_purge_and_volume_retention(ctl):
    c, _, store, _ = ctl
    c.create_space("default", "proj")
    c.create_stack("default", "proj", "s1")
    c.put_volume(t.Document(
        kind=t.KIND_VOLUME,
        metadata=t.Metadata(name="keepme", realm="default", space="proj", stack="s1"),
        spec=t.VolumeSpec(reclaim_policy="retain"),
    ))
    c.put_volume(t.Document(
        kind=t.KIND_VOLUME,
        metadata=t.Metadata(name="dropme", realm="default", space="proj", stack="s1"),
        spec=t.VolumeSpec(reclaim_policy="delete"),
    ))
    with pytest.raises(FailedPrecondition, match="purge to cascade"):
        c.delete_space("default", "proj")
    c.delete_stack("default", "proj", "s1", purge=True)
    # Retained volume record survives the stack's metadata tree removal?
    # Reference semantics: retained volumes survive cascade purge (they are
    # reclaimed by owning-scope purge only when policy=delete).
    # Our stack purge removes the whole stack dir, so retained volumes are
    # re-homed... simplest contract: retain means the volume record was not
    # deleted by _reclaim_volumes before tree removal.


def test_team_prune(ctl):
    c, _, store, _ = ctl
    y1 = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: a1}
spec: {containers: [{name: m, command: [/bin/true]}]}
---
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: a2}
spec: {containers: [{name: m, command: [/bin/true]}]}
"""
    c.apply_documents(y1, team="t1")
    y2 = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: a1}
spec: {containers: [{name: m, command: [/bin/true]}]}
"""
    results = c.apply_documents(y2, team="t1", prune=True)
    pruned = [r for r in results if r.action == "pruned"]
    assert [p.name for p in pruned] == ["a2"]
    assert not store.cell_exists("default", "default", "default", "a2")
    assert store.cell_exists("default", "default", "default", "a1")


def test_delete_documents_reverse_order(ctl):
    c, _, store, _ = ctl
    blob = """
apiVersion: kukeon.io/v1beta1
kind: Space
metadata: {name: temp}
---
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: t1, space: temp}
spec: {containers: [{name: m, command: [/bin/true]}]}
"""
    c.apply_documents(blob)
    assert store.cell_exists("default", "temp", "default", "t1")
    results = c.delete_documents(blob)
    assert [r.action for r in results] == ["deleted", "deleted"]
    assert "temp" not in c.list_spaces("default")


def _put_bp_cfg(c, *, cmd_default="/bin/true", cfg_name="cfg1"):
    c.put_blueprint(t.Document(
        kind=t.KIND_CELL_BLUEPRINT, metadata=t.Metadata(name="bp"),
        spec=t.CellBlueprintSpec(
            params=[t.BlueprintParam(name="cmd", default=cmd_default)],
            cell=t.CellSpec(containers=[t.ContainerSpec(name="m", command=["${cmd}"])]),
        ),
    ))
    c.put_config(t.Document(
        kind=t.KIND_CELL_CONFIG, metadata=t.Metadata(name=cfg_name),
        spec=t.CellConfigSpec(blueprint="bp", cell_name="sync-cell"),
    ))


def test_out_of_sync_synced_and_drift(ctl):
    c, _, store, _ = ctl
    _put_bp_cfg(c)
    c.materialize_config("default", None, None, "cfg1")

    # Fresh materialization: synced.
    counts = c.reconcile_cells()
    assert counts.get("out_of_sync", 0) == 0
    rec = store.read_cell("default", "default", "default", "sync-cell")
    assert rec.status.out_of_sync is False
    assert rec.status.out_of_sync_reason is None

    # Operator edits the config (new command) without re-applying: drift.
    c.put_config(t.Document(
        kind=t.KIND_CELL_CONFIG, metadata=t.Metadata(name="cfg1"),
        spec=t.CellConfigSpec(blueprint="bp", cell_name="sync-cell",
                              values={"cmd": "/bin/false"}),
    ))
    counts = c.reconcile_cells()
    assert counts["out_of_sync"] == 1
    rec = store.read_cell("default", "default", "default", "sync-cell")
    assert rec.status.out_of_sync is True
    assert "spec differs" in rec.status.out_of_sync_reason

    # Re-materializing converges back to synced.
    c.materialize_config("default", None, None, "cfg1")
    c.reconcile_cells()
    rec = store.read_cell("default", "default", "default", "sync-cell")
    assert rec.status.out_of_sync is False


def test_out_of_sync_config_deleted(ctl):
    c, _, store, _ = ctl
    _put_bp_cfg(c)
    c.materialize_config("default", None, None, "cfg1")
    c.delete_config("default", None, None, "cfg1")
    counts = c.reconcile_cells()
    assert counts["out_of_sync"] == 1
    rec = store.read_cell("default", "default", "default", "sync-cell")
    assert rec.status.out_of_sync is True
    assert rec.status.out_of_sync_reason == "lineage Config deleted"


def test_out_of_sync_blueprint_missing_is_error_not_drift(ctl):
    c, _, store, _ = ctl
    _put_bp_cfg(c)
    c.materialize_config("default", None, None, "cfg1")
    c.delete_blueprint("default", None, None, "bp")
    counts = c.reconcile_cells()
    # Undecidable: OutOfSyncError set, out_of_sync stays False.
    assert counts.get("out_of_sync", 0) == 0
    rec = store.read_cell("default", "default", "default", "sync-cell")
    assert rec.status.out_of_sync is False
    assert rec.status.out_of_sync_error
    assert "bp" in rec.status.out_of_sync_error


def test_out_of_sync_skips_hand_built_cells(ctl):
    c, _, store, _ = ctl
    c.create_cell(_cell_doc())
    c.reconcile_cells()
    rec = store.read_cell("default", "default", "default", "c1")
    assert rec.status.out_of_sync is False
    assert rec.status.out_of_sync_reason is None
    assert rec.status.out_of_sync_error is None


def test_out_of_sync_does_not_resurrect_auto_deleted_cell(ctl):
    """Review regression: an auto-delete cell with drifted config must stay
    deleted — the out-of-sync pass must not write the record back."""
    c, backend, store, _ = ctl
    c.put_blueprint(t.Document(
        kind=t.KIND_CELL_BLUEPRINT, metadata=t.Metadata(name="bp2"),
        spec=t.CellBlueprintSpec(
            cell=t.CellSpec(
                auto_delete=True,
                containers=[t.ContainerSpec(name="m", command=["/bin/true"])],
            ),
        ),
    ))
    c.put_config(t.Document(
        kind=t.KIND_CELL_CONFIG, metadata=t.Metadata(name="cfg2"),
        spec=t.CellConfigSpec(blueprint="bp2", cell_name="ghost"),
    ))
    c.materialize_config("default", None, None, "cfg2")
    # Drift the lineage, then let the workload exit -> auto delete.
    c.delete_config("default", None, None, "cfg2")
    backend.exit(store.container_dir("default", "default", "default", "ghost", "m"), 0)
    counts = c.reconcile_cells()
    assert counts.get("auto-deleted") == 1
    assert not store.cell_exists("default", "default", "default", "ghost")
    # And it stays gone on the next tick.
    c.reconcile_cells()
    assert not store.cell_exists("default", "default", "default", "ghost")


# --- crash-loop visibility + restart-budget replenishment (VERDICT r4 5/8) --


def test_crash_reason_and_last_error_surface(ctl):
    """A crashing container's log tail lands in container.lastError and the
    cell reason (reference: markCellFailed with reason, runner/start.go)."""
    import os

    c, backend, store, _ = ctl
    doc = _cell_doc()
    doc.spec.containers[0].restart_policy = t.RestartPolicy(
        policy="always", backoff_seconds=0.0, max_retries=2
    )
    c.create_cell(doc)
    cdir = store.container_dir("default", "default", "default", "c1", "main")
    with open(os.path.join(cdir, consts.SHIM_LOG), "w") as f:
        f.write("loading model...\nTraceback (most recent call last):\n"
                "RuntimeError: libtpu version mismatch\n")
    backend.exit(cdir, 1)

    _, outcome = c.runner.refresh_cell("default", "default", "default", "c1")
    assert outcome == OUTCOME_RESTARTED
    got = c.get_cell("default", "default", "default", "c1")
    cs = got["status"]["containers"][0]
    assert "libtpu version mismatch" in (cs["lastError"] or "")
    assert "crashed (exit 1" in (got["status"]["reason"] or "")

    # Exhaust the budget: the reason now names the exhausted budget.
    backend.exit(cdir, 1)
    c.runner.refresh_cell("default", "default", "default", "c1")
    backend.exit(cdir, 1)
    c.runner.refresh_cell("default", "default", "default", "c1")
    got = c.get_cell("default", "default", "default", "c1")
    assert "restart budget exhausted" in got["status"]["reason"]
    assert got["status"]["phase"] == model.FAILED


def test_restart_budget_replenishes_after_healthy_uptime(ctl):
    """Healthy uptime resets the restart count so bounded maxRetries guards
    crash LOOPS, not lifetime crash totals (refresh.go:1224-1458 analog)."""
    c, backend, store, _ = ctl
    doc = _cell_doc()
    doc.spec.containers[0].restart_policy = t.RestartPolicy(
        policy="always", backoff_seconds=0.0, max_retries=1
    )
    c.create_cell(doc)
    cdir = store.container_dir("default", "default", "default", "c1", "main")
    backend.exit(cdir, 1)
    _, outcome = c.runner.refresh_cell("default", "default", "default", "c1")
    assert outcome == OUTCOME_RESTARTED
    rec = store.read_cell("default", "default", "default", "c1")
    assert rec.status.container("main").restarts == 1

    # Budget exhausted: another crash would NOT restart...
    # ...but a healthy-uptime window replenishes it first.
    c.runner.RESTART_RESET_UPTIME_S = 0.0
    c.runner.refresh_cell("default", "default", "default", "c1")
    rec = store.read_cell("default", "default", "default", "c1")
    assert rec.status.container("main").restarts == 0

    backend.exit(cdir, 1)
    _, outcome = c.runner.refresh_cell("default", "default", "default", "c1")
    assert outcome == OUTCOME_RESTARTED


def test_get_cell_surfaces_cgroup_metrics(tmp_path):
    """kuke get cell -o json shows live memory/cpu per container
    (reference: internal/ctr/cgroups.go:484 feeding status)."""
    import os

    from kukeon_tpu.runtime.cgroups import CgroupManager
    from kukeon_tpu.runtime.metadata import MetadataStore

    croot = tmp_path / "cgroup"
    croot.mkdir()
    (croot / "cgroup.controllers").write_text("cpu memory pids\n")
    store = ResourceStore(MetadataStore(str(tmp_path / "run")))
    backend = FakeBackend()
    runner = Runner(store, backend, cgroups=CgroupManager(root=str(croot)),
                    devices=TPUDeviceManager(store.ms, chips=[0]),
                    options=RunnerOptions(stop_grace_s=0.2))
    c = Controller(store, runner)
    c.bootstrap()
    c.create_cell(_cell_doc())

    leaf = croot / "kukeon" / "default" / "default" / "default" / "c1" / "main"
    assert leaf.is_dir()  # created at start by _container_context
    (leaf / "memory.current").write_text("123456789\n")
    (leaf / "pids.current").write_text("7\n")
    (leaf / "cpu.stat").write_text("usage_usec 4242\nuser_usec 4000\n")

    got = c.get_cell("default", "default", "default", "c1")
    m = got["metrics"]["main"]
    assert m["memory_bytes"] == 123456789
    assert m["pids"] == 7
    assert m["cpu_usec"] == 4242
