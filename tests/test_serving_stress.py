"""Serving engine under churn: more requests than slots, concurrent
submitters, mixed lengths/sampling, engine-thread mode. The reference gets
its safety from structure (per-cell locks, single reconcile driver —
SURVEY §5.2); the engine's analog is the single-driver step loop + locked
queues, and this suite shakes it."""

from __future__ import annotations

import threading

import jax
import numpy as np

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import RejectedError, SamplingParams, ServingEngine


def test_many_requests_few_slots_background_loop():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=96,
                        decode_chunk=4)
    eng.start()
    try:
        results: dict[int, tuple[int, list[int]]] = {}
        errors: list[Exception] = []
        lock = threading.Lock()

        def submitter(tid: int):
            # Per-thread Generator: numpy Generators are not thread-safe.
            rng = np.random.default_rng(tid)
            try:
                for j in range(3):
                    n = int(rng.integers(4, 40))
                    prompt = np.arange(1, 1 + n, dtype=np.int32) % cfg.vocab_size
                    want = int(rng.integers(1, 9))
                    got = eng.generate(
                        prompt, SamplingParams(temperature=0.0,
                                               max_new_tokens=want)
                    )
                    with lock:
                        results[tid * 10 + j] = (want, got)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(e)

        threads = [threading.Thread(target=submitter, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "submitters deadlocked"
        assert not errors, errors
        assert len(results) == 12
        for want, got in results.values():
            assert len(got) == want
        # Every slot must be free again (no leaked slot bookkeeping).
        assert len(eng._free_slots()) == eng.num_slots
        assert eng.error is None
    finally:
        eng.stop()


def test_greedy_determinism_survives_churn():
    """A request's greedy output must not depend on which slot it lands in
    or what its neighbors are doing."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(1), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=3, max_seq_len=96,
                        decode_chunk=4)
    prompt = np.arange(7, 27, dtype=np.int32) % cfg.vocab_size
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)
    baseline = eng.generate(prompt, sp)

    # Same prompt repeatedly, interleaved with noise requests of varying
    # lengths (occupying different slots each round).
    rng = np.random.default_rng(2)
    for round_ in range(3):
        noise = [
            eng.submit(rng.integers(1, cfg.vocab_size, size=int(rng.integers(3, 30)))
                       .astype(np.int32),
                       SamplingParams(temperature=1.0, max_new_tokens=5))
            for _ in range(2)
        ]
        again = eng.submit(prompt, sp)
        while not (again.done.is_set() and all(r.done.is_set() for r in noise)):
            eng.step()
        assert again.generated == baseline, f"round {round_} diverged"


def test_cancel_frees_slot_and_wakes_waiter():
    """A cancelled request releases its slot on the next driver iteration;
    a queued-but-unstarted cancelled request never occupies one."""
    import time as _time

    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(3), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=1, max_seq_len=96,
                        decode_chunk=4)
    prompt = np.arange(1, 12, dtype=np.int32)

    # Long-running request occupies THE slot...
    hog = eng.submit(prompt, SamplingParams(temperature=0.0,
                                            max_new_tokens=64))
    # ...a second request queues behind it, and a third is cancelled
    # while still queued.
    waiter = eng.submit(prompt, SamplingParams(temperature=0.0,
                                               max_new_tokens=3))
    ghost = eng.submit(prompt, SamplingParams(temperature=0.0,
                                              max_new_tokens=3))
    ghost.cancel()

    for _ in range(3):
        eng.step()
    assert not hog.done.is_set()
    hog.cancel()

    deadline = _time.monotonic() + 60
    while not (hog.done.is_set() and waiter.done.is_set()
               and ghost.done.is_set()):
        if _time.monotonic() > deadline:
            raise AssertionError("cancel did not unblock the queue")
        eng.step()

    assert len(hog.generated) < 64          # stopped early
    assert len(waiter.generated) == 3       # got the freed slot
    assert ghost.generated == []            # never ran
    assert len(eng._free_slots()) == eng.num_slots
    assert not eng._requests                # no leaked request records


def test_overload_sheds_and_nothing_hangs():
    """Flood far past max_pending: every submit either completes, sheds
    with RejectedError, or times out on its deadline — and the shed/timeout
    accounting in /v1/stats adds up. No request may hang forever (the
    fair-weather failure this layer exists to remove)."""
    import http.client
    import json
    import time as _time
    from http.server import ThreadingHTTPServer

    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    cell = ServingCell("tiny", num_slots=2, max_seq_len=96, checkpoint=None,
                       dtype=None, max_pending=4)
    eng = cell.engine
    eng.start()
    cell.mark_ready()
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        prompt = np.arange(1, 9, dtype=np.int32)
        accepted = []
        rejected = 0
        # Tight flood: submits are far faster than the driver can slot, so
        # the bound MUST shed some of these.
        for i in range(30):
            # A few requests carry a deadline that will already have passed
            # when their turn comes -> counted as timed_out, still terminal.
            dl = 0.001 if i % 7 == 3 else 30.0
            try:
                accepted.append(eng.submit(
                    prompt, SamplingParams(temperature=0.0, max_new_tokens=3),
                    deadline_s=dl))
            except RejectedError:
                rejected += 1
        assert rejected > 0, "flood past max_pending did not shed"
        assert rejected + len(accepted) == 30

        deadline = _time.monotonic() + 120
        for r in accepted:
            assert r.done.wait(timeout=max(0.0, deadline - _time.monotonic())), \
                "an admitted request hung forever"
        timed_out = sum(1 for r in accepted if r.timed_out)
        completed = sum(1 for r in accepted
                        if r.error is None and not r.cancelled)
        assert timed_out + completed == len(accepted)
        for r in accepted:
            if r.error is None:
                assert len(r.generated) == 3

        # The counters the operator sees must match what actually happened.
        conn = http.client.HTTPConnection("127.0.0.1",
                                          server.server_address[1], timeout=30)
        conn.request("GET", "/v1/stats")
        stats = json.loads(conn.getresponse().read())
        conn.close()
        assert stats["rejected"] == rejected
        assert stats["timedOut"] == timed_out
        assert stats["queueDepth"] == 0          # backlog fully drained
        assert eng.queue_depth == 0
        assert len(eng._free_slots()) == eng.num_slots
        assert not eng._requests
    finally:
        server.shutdown()
        server.server_close()
        eng.stop()


def test_queued_cancel_completes_while_slots_stay_busy():
    """Cancelling a QUEUED request must complete it promptly even when no
    slot ever frees, and its emit callback gets the (-1, True) terminal."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(4), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=1, max_seq_len=96,
                        decode_chunk=4)
    prompt = np.arange(1, 10, dtype=np.int32)
    hog = eng.submit(prompt, SamplingParams(temperature=0.0,
                                            max_new_tokens=64))
    events: list[tuple[int, bool]] = []
    ghost = eng.submit(prompt,
                       SamplingParams(temperature=0.0, max_new_tokens=3),
                       emit=lambda tok, done: events.append((tok, done)))
    ghost.cancel()
    for _ in range(3):
        eng.step()
    assert ghost.done.is_set()              # completed without a free slot
    assert events == [(-1, True)]           # terminal sentinel delivered
    assert not hog.done.is_set()            # the busy slot was untouched
    hog.cancel()
    while not hog.done.is_set():
        eng.step()
