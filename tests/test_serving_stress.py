"""Serving engine under churn: more requests than slots, concurrent
submitters, mixed lengths/sampling, engine-thread mode. The reference gets
its safety from structure (per-cell locks, single reconcile driver —
SURVEY §5.2); the engine's analog is the single-driver step loop + locked
queues, and this suite shakes it."""

from __future__ import annotations

import threading

import jax
import numpy as np

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine


def test_many_requests_few_slots_background_loop():
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=2, max_seq_len=96,
                        decode_chunk=4)
    eng.start()
    try:
        results: dict[int, tuple[int, list[int]]] = {}
        errors: list[Exception] = []
        lock = threading.Lock()

        def submitter(tid: int):
            # Per-thread Generator: numpy Generators are not thread-safe.
            rng = np.random.default_rng(tid)
            try:
                for j in range(3):
                    n = int(rng.integers(4, 40))
                    prompt = np.arange(1, 1 + n, dtype=np.int32) % cfg.vocab_size
                    want = int(rng.integers(1, 9))
                    got = eng.generate(
                        prompt, SamplingParams(temperature=0.0,
                                               max_new_tokens=want)
                    )
                    with lock:
                        results[tid * 10 + j] = (want, got)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(e)

        threads = [threading.Thread(target=submitter, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "submitters deadlocked"
        assert not errors, errors
        assert len(results) == 12
        for want, got in results.values():
            assert len(got) == want
        # Every slot must be free again (no leaked slot bookkeeping).
        assert len(eng._free_slots()) == eng.num_slots
        assert eng.error is None
    finally:
        eng.stop()


def test_greedy_determinism_survives_churn():
    """A request's greedy output must not depend on which slot it lands in
    or what its neighbors are doing."""
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(1), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=3, max_seq_len=96,
                        decode_chunk=4)
    prompt = np.arange(7, 27, dtype=np.int32) % cfg.vocab_size
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)
    baseline = eng.generate(prompt, sp)

    # Same prompt repeatedly, interleaved with noise requests of varying
    # lengths (occupying different slots each round).
    rng = np.random.default_rng(2)
    for round_ in range(3):
        noise = [
            eng.submit(rng.integers(1, cfg.vocab_size, size=int(rng.integers(3, 30)))
                       .astype(np.int32),
                       SamplingParams(temperature=1.0, max_new_tokens=5))
            for _ in range(2)
        ]
        again = eng.submit(prompt, sp)
        while not (again.done.is_set() and all(r.done.is_set() for r in noise)):
            eng.step()
        assert again.generated == baseline, f"round {round_} diverged"
