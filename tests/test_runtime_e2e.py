"""Black-box e2e: real daemon process, real CLI, real supervised workloads.

Mirrors the reference's e2e harness (e2e/harness_daemon_test.go:26-60):
per-test daemon on a temp run-path with a SUN_PATH-safe /tmp socket, <=10s
startup budget, SIGTERM + 5s -> SIGKILL teardown. This is BASELINE config 1:
"single Interactive cell via kuke apply + kuke attach (CPU e2e harness)".
"""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import time
import uuid

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = [sys.executable, "-m", "kukeon_tpu.runtime.cli"]


class Daemon:
    def __init__(self, chips: str = "0,1", env_overrides: dict | None = None,
                 run_path: str | None = None):
        self.run_path = run_path or tempfile.mkdtemp(prefix="kuke-e2e-")
        self.socket_path = f"/tmp/kuked-{uuid.uuid4().hex[:8]}.sock"
        env = dict(os.environ)
        env.update({
            "KUKEON_TPU_CHIPS": chips,
            "KUKEOND_RECONCILE_INTERVAL": "1.0",
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
        })
        env.update(env_overrides or {})
        self.env = env
        self.proc = subprocess.Popen(
            CLI + ["daemon", "serve", "--run-path", self.run_path,
                   "--socket", self.socket_path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if os.path.exists(self.socket_path):
                try:
                    s = socket.socket(socket.AF_UNIX)
                    s.connect(self.socket_path)
                    s.close()
                    return
                except OSError:
                    pass
            if self.proc.poll() is not None:
                out = self.proc.stdout.read().decode()
                raise RuntimeError(f"daemon died at startup:\n{out}")
            time.sleep(0.05)
        raise RuntimeError("daemon socket did not appear within 10s")

    def kuke(self, *args, check=True, stdin_data=None) -> subprocess.CompletedProcess:
        p = subprocess.run(
            CLI + ["--socket", self.socket_path, "--run-path", self.run_path] + list(args),
            env=self.env, capture_output=True, text=True, timeout=60,
            input=stdin_data,
        )
        if check and p.returncode != 0:
            raise AssertionError(
                f"kuke {' '.join(args)} rc={p.returncode}\nstdout:{p.stdout}\nstderr:{p.stderr}"
            )
        return p

    def stop_daemon_only(self):
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    def stop(self):
        self.stop_daemon_only()
        import shutil

        shutil.rmtree(self.run_path, ignore_errors=True)


@pytest.fixture
def daemon():
    d = Daemon()
    yield d
    d.stop()


CELL_MANIFEST = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: web}
spec:
  containers:
    - name: main
      command: ["/bin/sh", "-c", "while true; do echo tick; sleep 0.2; done"]
"""

ATTACH_MANIFEST = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: term}
spec:
  containers:
    - name: shell
      command: ["/bin/sh", "-i"]
      attachable: true
      tty:
        onInit: ["echo stage-one-done"]
"""


def test_cell_lifecycle_e2e(daemon):
    d = daemon
    d.kuke("apply", "-f", "-", stdin_data=CELL_MANIFEST)

    out = d.kuke("get", "cells").stdout
    assert "web" in out and "ready" in out

    # Logs flow from the supervised workload.
    time.sleep(0.6)
    log = d.kuke("log", "web").stdout
    assert "tick" in log

    # Re-apply: unchanged.
    out = d.kuke("apply", "-f", "-", stdin_data=CELL_MANIFEST).stdout
    assert "unchanged" in out

    d.kuke("stop", "web")
    out = d.kuke("--json", "get", "cells", "web").stdout
    rec = json.loads(out)
    assert rec["status"]["phase"] == "stopped"
    assert rec["status"]["containers"][0]["state"] == "exited"

    d.kuke("start", "web")
    rec = json.loads(d.kuke("--json", "get", "cells", "web").stdout)
    assert rec["status"]["phase"] == "ready"

    d.kuke("delete", "cell", "web", "--force")
    out = d.kuke("get", "cells").stdout
    assert "web" not in out


def test_run_rm_autodelete_and_restart_policy(daemon):
    d = daemon
    manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: oneshot}
spec:
  containers:
    - {name: main, command: ["/bin/sh", "-c", "exit 0"]}
"""
    d.kuke("run", "-d", "--rm", "-f", "-", stdin_data=manifest)
    # The 1s reconcile ticker reaps the exited autoDelete cell.
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if "oneshot" not in d.kuke("get", "cells").stdout:
            break
        time.sleep(0.5)
    assert "oneshot" not in d.kuke("get", "cells").stdout

    # Restart policy: always-restart keeps a crashing container coming back.
    crash = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: crashy}
spec:
  containers:
    - name: main
      command: ["/bin/sh", "-c", "sleep 0.1; exit 1"]
      restartPolicy: {policy: always, backoffSeconds: 0.1}
"""
    d.kuke("apply", "-f", "-", stdin_data=crash)
    deadline = time.monotonic() + 20.0
    restarts = 0
    while time.monotonic() < deadline:
        rec = json.loads(d.kuke("--json", "get", "cells", "crashy").stdout)
        restarts = rec["status"]["containers"][0].get("restarts", 0)
        if restarts >= 2:
            break
        time.sleep(0.5)
    assert restarts >= 2
    d.kuke("delete", "cell", "crashy", "--force")


def test_attach_e2e(daemon):
    d = daemon
    d.kuke("apply", "-f", "-", stdin_data=ATTACH_MANIFEST)

    info = None
    # Resolve the attach socket via the daemon (AttachContainer RPC path).
    import json as _json

    rec = _json.loads(d.kuke("--json", "get", "cells", "term").stdout)
    assert rec["status"]["phase"] == "ready"
    sock_path = os.path.join(
        d.run_path, "realms", "default", "spaces", "default", "stacks", "default",
        "cells", "term", "containers", "shell", "tty.sock",
    )
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not os.path.exists(sock_path):
        time.sleep(0.1)
    s = socket.socket(socket.AF_UNIX)
    s.connect(sock_path)
    s.sendall(b"D" + struct.pack(">I", 22) + b"echo marker-$((41+1))\n")
    time.sleep(0.8)
    s.settimeout(2.0)
    out = b""
    try:
        while True:
            c = s.recv(4096)
            if not c:
                break
            out += c
    except socket.timeout:
        pass
    s.close()
    assert b"marker-42" in out

    # Capture transcript includes the init stage and survives detach.
    cap = d.kuke("log", "term").stdout
    assert "stage-one-done" in cap

    # Daemon restart does NOT kill the attached workload (supervisor owns it).
    rec_before = _json.loads(d.kuke("--json", "get", "cells", "term").stdout)
    pid = rec_before["status"]["containers"][0]["pid"]
    os.kill(pid, 0)   # alive
    d.kuke("delete", "cell", "term", "--force")


def test_model_cell_e2e(daemon):
    """BASELINE config 2 analog on CPU: a model cell comes up via kuke apply;
    the runner materializes the in-tree serving container; generation works
    over its HTTP port; chips are granted and released."""
    d = daemon
    manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: llm}
spec:
  model: {model: tiny, chips: 1, port: 9471, numSlots: 2, maxSeqLen: 128,
          hostNetwork: true}
"""
    # hostNetwork: the explicit opt-out of the space network (this suite
    # runs with net enforcement disabled, so an in-space model cell would
    # have no bridge; the in-policy path is tests/test_netpolicy_e2e.py).
    d.kuke("apply", "-f", "-", stdin_data=manifest)
    rec = json.loads(d.kuke("--json", "get", "cells", "llm").stdout)
    assert rec["status"]["tpuChips"] == [0]
    assert rec["status"]["containers"][0]["name"] == "model-server"

    import urllib.request

    deadline = time.monotonic() + 90.0
    healthy = False
    while time.monotonic() < deadline:
        try:
            r = urllib.request.urlopen("http://127.0.0.1:9471/v1/health", timeout=1)
            healthy = json.loads(r.read())["status"] == "ok"
            break
        except OSError:
            rec = json.loads(d.kuke("--json", "get", "cells", "llm").stdout)
            st = rec["status"]["containers"][0]
            if st["state"] == "exited":
                log = d.kuke("log", "llm", "--container", "model-server", check=False).stdout
                raise AssertionError(f"model server exited ({st['exitCode']}):\n{log}")
            time.sleep(1.0)
    assert healthy, "model server did not become healthy in 90s"

    body = json.dumps({"prompt": "hi", "maxNewTokens": 4}).encode()
    r = urllib.request.urlopen(
        urllib.request.Request("http://127.0.0.1:9471/v1/generate", data=body,
                               headers={"Content-Type": "application/json"}),
        timeout=60,
    )
    out = json.loads(r.read())
    assert out["numTokens"] == 4

    # Streaming: newline-delimited JSON, one record per token + a terminal
    # record that matches the non-streaming aggregate shape.
    body = json.dumps({"prompt": "hi", "maxNewTokens": 4, "stream": True}).encode()
    r = urllib.request.urlopen(
        urllib.request.Request("http://127.0.0.1:9471/v1/generate", data=body,
                               headers={"Content-Type": "application/json"}),
        timeout=60,
    )
    assert r.headers.get("Content-Type") == "application/x-ndjson"
    records = [json.loads(ln) for ln in r.read().splitlines() if ln.strip()]
    tok_records, final = records[:-1], records[-1]
    assert len(tok_records) == 4
    assert all("token" in t for t in tok_records)
    assert final["done"] is True and final["numTokens"] == 4
    assert final["tokens"] == [t["token"] for t in tok_records]
    # Prefix-diff contract: concatenated deltas == the final decode (BPE
    # merging must not be broken by per-token decoding).
    assert "".join(t["text"] for t in tok_records) == final["text"]

    d.kuke("delete", "cell", "llm", "--force")
    status = json.loads(d.kuke("--json", "status").stdout)
    assert status["tpuChips"]["free"] == 2


def test_tpu_chip_accounting_e2e(daemon):
    d = daemon
    manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: tpuweb}
spec:
  containers:
    - name: main
      command: ["/bin/sh", "-c", "echo chips=$TPU_VISIBLE_DEVICES; sleep 30"]
      resources: {tpuChips: 2}
"""
    d.kuke("apply", "-f", "-", stdin_data=manifest)
    rec = json.loads(d.kuke("--json", "get", "cells", "tpuweb").stdout)
    assert rec["status"]["tpuChips"] == [0, 1]

    status = json.loads(d.kuke("--json", "status").stdout)
    assert status["tpuChips"]["total"] == 2
    assert status["tpuChips"]["free"] == 0

    # The workload actually sees the visibility env.
    time.sleep(0.5)
    log = d.kuke("log", "tpuweb").stdout
    assert "chips=0,1" in log

    d.kuke("delete", "cell", "tpuweb", "--force")
    status = json.loads(d.kuke("--json", "status").stdout)
    assert status["tpuChips"]["free"] == 2


def test_create_verb_and_autocomplete_e2e(daemon):
    # Imperative scope creates.
    daemon.kuke("create", "realm", "prod")
    daemon.kuke("create", "space", "edge", "--realm", "prod")
    daemon.kuke("create", "stack", "web", "--realm", "prod", "--space", "edge")
    assert "prod" in daemon.kuke("get", "realms").stdout

    # Cell with --no-start stays pending; then start brings it up.
    daemon.kuke("create", "cell", "idle", "--no-start",
                "--command", "/bin/sleep", "30")
    out = daemon.kuke("get", "cell", "idle", "--json").stdout
    rec = json.loads(out)
    assert rec["status"]["phase"] == "pending"
    daemon.kuke("start", "idle")
    rec = json.loads(daemon.kuke("get", "cell", "idle", "--json").stdout)
    assert rec["status"]["phase"] == "ready"

    # Secret + volume imperative creates land in their stores.
    daemon.kuke("create", "secret", "tok", "--data", "API_KEY=abc")
    assert "tok" in daemon.kuke("get", "secrets").stdout
    daemon.kuke("create", "volume", "scratch", "--reclaim-policy", "retain")
    assert "scratch" in daemon.kuke("get", "volumes").stdout

    # Autocomplete lists live resources; bash emits the script.
    assert "idle" in daemon.kuke("autocomplete", "cells").stdout.split()
    assert "prod" in daemon.kuke("autocomplete", "realms").stdout.split()
    assert "_kuke_complete" in daemon.kuke("autocomplete", "bash").stdout

    daemon.kuke("delete", "cell", "idle", "--force")


def test_server_configuration_written_and_effective(daemon):
    # First daemon start wrote the commented ServerConfiguration document.
    cfg = os.path.join(daemon.run_path, "kukeond.yaml")
    assert os.path.exists(cfg)
    text = open(cfg).read()
    assert "kind: ServerConfiguration" in text
    assert "reconcileInterval" in text
    # The doc carries the values the daemon actually bound to (env said 1.0).
    assert "reconcileInterval: 1.0" in text


def test_embedding_cell_e2e(daemon):
    """BASELINE config 5 analog on CPU: an embedding model cell (bge shape)
    comes up beside the runtime and serves /v1/embed."""
    d = daemon
    manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: embedder}
spec:
  model: {model: bge-tiny, chips: 1, port: 9473, numSlots: 4,
          hostNetwork: true}
"""
    d.kuke("apply", "-f", "-", stdin_data=manifest)

    import urllib.request

    deadline = time.monotonic() + 90.0
    healthy = False
    while time.monotonic() < deadline:
        try:
            r = urllib.request.urlopen("http://127.0.0.1:9473/v1/health", timeout=1)
            healthy = json.loads(r.read())["status"] == "ok"
            break
        except OSError:
            rec = json.loads(d.kuke("--json", "get", "cells", "embedder").stdout)
            st = rec["status"]["containers"][0]
            if st["state"] == "exited":
                log = d.kuke("log", "embedder", "--container", "model-server",
                             check=False).stdout
                raise AssertionError(f"embedder exited ({st['exitCode']}):\n{log}")
            time.sleep(1.0)
    assert healthy, "embedding server did not become healthy in 90s"

    body = json.dumps({"inputs": ["hello world", "tpu native"]}).encode()
    r = urllib.request.urlopen(
        urllib.request.Request("http://127.0.0.1:9473/v1/embed", data=body,
                               headers={"Content-Type": "application/json"}),
        timeout=60,
    )
    out = json.loads(r.read())
    assert out["numSequences"] == 2
    assert len(out["embeddings"]) == 2
    assert len(out["embeddings"][0]) == out["dim"]
    import math

    norm = math.sqrt(sum(x * x for x in out["embeddings"][0]))
    assert abs(norm - 1.0) < 1e-3

    # The generate route must clearly reject on an embedding cell.
    req = urllib.request.Request("http://127.0.0.1:9473/v1/generate",
                                 data=b"{}",
                                 headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=10)
        raise AssertionError("generate on an embedding cell should 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404

    d.kuke("delete", "cell", "embedder", "--force")


def test_host_port_conflict_rejected(daemon):
    """VERDICT r3 item 7: host-network cells claim real host ports at create;
    a second cell claiming the same port/proto must be rejected with a
    pointer to the holder, not fail later with EADDRINUSE in the workload."""
    d = daemon
    manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: {name}}}
spec:
  containers:
    - name: main
      command: ["sleep", "30"]
      hostNetwork: true
      ports: [{{port: 9777}}]
"""
    d.kuke("apply", "-f", "-", stdin_data=manifest.format(name="portsa"))
    p = d.kuke("apply", "-f", "-", stdin_data=manifest.format(name="portsb"),
               check=False)
    assert p.returncode != 0
    assert "9777" in (p.stdout + p.stderr)
    assert "portsa" in (p.stdout + p.stderr)

    # UDP on the same number is a distinct claim; and deleting the holder
    # frees the TCP claim.
    udp = manifest.format(name="portsc").replace(
        "ports: [{port: 9777}]", "ports: [{port: 9777, protocol: udp}]")
    d.kuke("apply", "-f", "-", stdin_data=udp)
    d.kuke("delete", "cell", "portsa", "--force")
    d.kuke("apply", "-f", "-", stdin_data=manifest.format(name="portsb"))

    # Compatible update (ports are a compatible field) must move the claim:
    # portsb drops 9777 for 9778, freeing 9777 for a new cell.
    moved = manifest.format(name="portsb").replace("port: 9777", "port: 9778")
    out = d.kuke("apply", "-f", "-", stdin_data=moved).stdout
    assert "updated" in out
    d.kuke("apply", "-f", "-", stdin_data=manifest.format(name="portsd"))


def test_repo_clone_and_setup_status(daemon, tmp_path):
    """VERDICT r3 item 7: a cell with a repo spec sees the clone at its
    declared path and the setup status is reported (reference:
    cmd/kuketty/repos.go + internal/kuketty/setupstatus)."""
    d = daemon
    import subprocess as sp

    src = tmp_path / "srcrepo"
    src.mkdir()
    (src / "hello.txt").write_text("from-the-repo\n")
    for argv in (["git", "init", "-q"],
                 ["git", "add", "."],
                 ["git", "-c", "user.email=t@t", "-c", "user.name=t",
                  "commit", "-qm", "init"]):
        sp.run(argv, cwd=src, check=True, capture_output=True)

    manifest = f"""
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {{name: repocell}}
spec:
  containers:
    - name: main
      command: ["sh", "-c",
                "cat /work/hello.txt; cat /run/kukeon/setup-status.json; sleep 20"]
      repos:
        - {{url: "file://{src}", path: /work}}
"""
    d.kuke("apply", "-f", "-", stdin_data=manifest)
    time.sleep(2)
    rec = json.loads(d.kuke("--json", "get", "cells", "repocell").stdout)
    setup = rec["status"].get("setup") or []
    assert setup and setup[0]["state"] == "ready", setup
    assert setup[0]["path"] == "/work"

    log = d.kuke("log", "repocell").stdout
    assert "from-the-repo" in log
    assert '"state": "ready"' in log   # in-cell setup-status report
    d.kuke("delete", "cell", "repocell", "--force")


def test_repo_clone_failure_reported_not_fatal(daemon):
    """A bad repo URL must surface as setup state=failed while the cell
    still starts (report-don't-block, like the reference's stages)."""
    d = daemon
    manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: badrepo}
spec:
  containers:
    - name: main
      command: ["sh", "-c", "echo alive; sleep 15"]
      repos:
        - {url: "file:///nonexistent/nowhere.git", path: /work}
"""
    d.kuke("apply", "-f", "-", stdin_data=manifest)
    time.sleep(2)
    rec = json.loads(d.kuke("--json", "get", "cells", "badrepo").stdout)
    setup = rec["status"].get("setup") or []
    assert setup and setup[0]["state"] == "failed", setup
    assert setup[0].get("error")
    assert rec["status"]["containers"][0]["state"] == "running"
    d.kuke("delete", "cell", "badrepo", "--force")


def test_instance_pinning_refuses_reconfigured_run_path(daemon):
    """VERDICT r3 item 9: a daemon must refuse a run path bootstrapped under
    different settings (reference: internal/instance/instance.go:21-28)."""
    d = daemon
    # The fixture's daemon pinned the default subnet pool at bootstrap.
    assert os.path.exists(os.path.join(d.run_path, "instance.json"))
    d.stop_daemon_only()
    with pytest.raises(RuntimeError, match="bootstrapped under different"):
        Daemon(run_path=d.run_path,
               env_overrides={"KUKEON_POD_SUBNET_CIDR": "10.200.0.0/16"})


def test_doctor_lists_enforcement_layers(daemon):
    out = daemon.kuke("doctor").stdout
    for tool in ("kukepause", "kukeshim", "kuketty", "kukecell", "kukenet"):
        assert f"native/{tool}" in out and "MISSING" not in out.split(f"native/{tool}")[1].split("\n")[0]
    assert "isolation" in out
    assert "net-enforce" in out
    assert "instance" in out


def test_init_provisions_kukeon_group():
    """kuke init (root) provisions the `kukeon` group and the daemon socket
    carries its gid (reference: internal/sysuser + SocketGID)."""
    import grp
    import stat as _stat

    if os.geteuid() != 0:
        pytest.skip("group provisioning needs root")
    sys.path.insert(0, REPO)
    from kukeon_tpu.runtime import sysuser

    gid = sysuser.ensure_group()
    assert gid is not None
    assert grp.getgrnam("kukeon").gr_gid == gid
    # A daemon started after provisioning hands the socket to the group.
    d = Daemon()
    try:
        st = os.stat(d.socket_path)
        assert st.st_gid == gid
        assert _stat.S_IMODE(st.st_mode) == 0o660
    finally:
        d.stop()


def test_attach_through_real_pty(daemon):
    """VERDICT r3 item 10 (carried since r1): drive the ACTUAL `kuke attach`
    client under a real PTY — raw mode, keystrokes, Ctrl-] Ctrl-] detach,
    workload survival, and re-attach continuity (reference:
    e2e/e2e_pty_test.go:33-45 drives kuke attach with creack/pty)."""
    import errno
    import pty as _pty
    import select as _select

    d = daemon
    d.kuke("apply", "-f", "-", stdin_data=ATTACH_MANIFEST)

    def spawn_attach():
        pid, fd = _pty.fork()
        if pid == 0:  # child: exec the real CLI under the PTY
            os.execvpe(
                sys.executable,
                CLI + ["--socket", d.socket_path, "--run-path", d.run_path,
                       "attach", "term"],
                d.env,
            )
        return pid, fd

    def read_until(fd, needle: bytes, timeout: float = 30.0) -> bytes:
        buf = b""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            r, _, _ = _select.select([fd], [], [], 0.5)
            if not r:
                continue
            try:
                chunk = os.read(fd, 4096)
            except OSError as e:
                if e.errno == errno.EIO:   # PTY closed
                    break
                raise
            if not chunk:
                break
            buf += chunk
            if needle in buf:
                return buf
        raise AssertionError(f"never saw {needle!r} in PTY output:\n{buf!r}")

    # --- session 1: banner, command echo, detach --------------------------
    pid, fd = spawn_attach()
    try:
        read_until(fd, b"(attached")
        os.write(fd, b"echo pty-marker-$((40+2))\n")
        read_until(fd, b"pty-marker-42")
        os.write(fd, b"\x1d\x1d")          # Ctrl-] twice = detach
        _, status = os.waitpid(pid, 0)
        assert os.waitstatus_to_exitcode(status) == 0, "detach must exit 0"
    finally:
        try:
            os.close(fd)
        except OSError:
            pass

    # The workload survives the detach.
    rec = json.loads(d.kuke("--json", "get", "cells", "term").stdout)
    st = rec["status"]["containers"][0]
    assert st["state"] == "running"
    os.kill(st["pid"], 0)

    # --- session 2: re-attach sees terminal continuity ---------------------
    pid, fd = spawn_attach()
    try:
        read_until(fd, b"(attached")
        os.write(fd, b"echo second-session-$((41+1))\n")
        read_until(fd, b"second-session-42")
        os.write(fd, b"\x1d\x1d")
        os.waitpid(pid, 0)
    finally:
        try:
            os.close(fd)
        except OSError:
            pass

    # The capture transcript records both sessions (continuity evidence).
    cap = d.kuke("log", "term").stdout
    assert "pty-marker-42" in cap
    assert "second-session-42" in cap
    d.kuke("delete", "cell", "term", "--force")


def test_doctor_tpu_runtime_probe(monkeypatch):
    """probe_tpu_runtime distinguishes a live runtime from a wedged one
    (r4/r5 failure family: device nodes visible, first transfer hangs)."""
    import os as _os

    from kukeon_tpu.runtime.devices import probe_tpu_runtime

    # Pin the child to CPU: the probe must exercise a REAL backend, and the
    # CPU platform is the one this CI host can always answer on. The axon
    # sitecustomize would override JAX_PLATFORMS, so strip it.
    parts = [p for p in _os.environ.get("PYTHONPATH", "").split(_os.pathsep)
             if p and "axon" not in p]
    monkeypatch.setenv("PYTHONPATH", _os.pathsep.join(parts))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("KUKEON_TPU_CHIPS", "")   # no chips claimed
    state, detail = probe_tpu_runtime(timeout_s=120.0)
    assert state == "ok", detail
    assert "backend=cpu" in detail

    # Chips visible but the backend fell back to CPU (TPU init failed
    # non-fatally): must NOT read as ok.
    monkeypatch.setenv("KUKEON_TPU_CHIPS", "0,1")
    state, detail = probe_tpu_runtime(timeout_s=120.0)
    assert state == "unavailable"
    assert "chips visible but backend=cpu" in detail

    # A wedged runtime = the child never returns: simulated with a child
    # that blocks forever (what a hung libtpu transfer looks like).
    import subprocess as _sp

    real_run = _sp.run

    def hang(cmd, **kw):
        return real_run([cmd[0], "-c", "import time; time.sleep(60)"],
                        **{**kw, "timeout": kw.get("timeout")})

    monkeypatch.setattr(_sp, "run", hang)
    state, detail = probe_tpu_runtime(timeout_s=0.5)
    assert state == "wedged"
    assert "did not finish" in detail


def test_moe_model_cell_e2e(daemon):
    """A mixtral (MoE) model cell boots through the same manifest path and
    answers /v1/generate — the model registry + pluggable engine running
    under the real daemon."""
    import urllib.request

    d = daemon
    manifest = """
apiVersion: kukeon.io/v1beta1
kind: Cell
metadata: {name: moe}
spec:
  model: {model: mixtral-tiny, chips: 1, port: 9478, numSlots: 2,
          maxSeqLen: 128, hostNetwork: true}
"""
    d.kuke("apply", "-f", "-", stdin_data=manifest)
    deadline = time.monotonic() + 120.0
    healthy = False
    while time.monotonic() < deadline:
        try:
            r = urllib.request.urlopen("http://127.0.0.1:9478/v1/health", timeout=1)
            healthy = json.loads(r.read())["status"] == "ok"
            break
        except OSError:
            rec = json.loads(d.kuke("--json", "get", "cells", "moe").stdout)
            st = rec["status"]["containers"][0]
            if st["state"] == "exited":
                log = d.kuke("log", "moe", "--container", "model-server",
                             check=False).stdout
                raise AssertionError(
                    f"moe server exited ({st['exitCode']}):\n{log}")
            time.sleep(1.0)
    assert healthy, "moe model server did not become healthy in 120s"

    body = json.dumps({"prompt": "hello", "maxNewTokens": 3}).encode()
    r = urllib.request.urlopen(
        urllib.request.Request("http://127.0.0.1:9478/v1/generate", data=body,
                               headers={"Content-Type": "application/json"}),
        timeout=60,
    )
    assert json.loads(r.read())["numTokens"] == 3
    d.kuke("delete", "cell", "moe", "--force")
