"""Table-driven invalid-manifest suite: every malformed spec must die at
parse/normalize, never in the runner (VERDICT r2/r3 item: apischeme depth;
reference: internal/apischeme/scheme.go:43-885, apply/parser.go:220-823)."""

from __future__ import annotations

import pytest

from kukeon_tpu.runtime.apply import parser
from kukeon_tpu.runtime.errors import InvalidArgument

HEADER = "apiVersion: kukeon.io/v1beta1\n"


def cell(spec_yaml: str, name: str = "c1") -> str:
    return HEADER + f"kind: Cell\nmetadata: {{name: {name}}}\nspec:\n{spec_yaml}"


INVALID = [
    # --- envelope / scope ------------------------------------------------
    ("bad-apiversion", "apiVersion: v2\nkind: Cell\nmetadata: {name: a}\nspec: {}",
     "apiVersion"),
    ("unknown-kind", HEADER + "kind: Pod\nmetadata: {name: a}\nspec: {}", "kind"),
    ("unknown-top-field", HEADER + "kind: Cell\nmetadata: {name: a}\nstatus: {}\nspec:\n  containers: [{name: m, command: [sh]}]",
     "top-level"),
    ("unknown-spec-field", cell("  bogus: 1\n  containers: [{name: m, command: [sh]}]"),
     "unknown field"),
    ("bad-name", HEADER + "kind: Cell\nmetadata: {name: 'Bad Name!'}\nspec:\n  containers: [{name: m, command: [sh]}]",
     "name"),
    ("realm-scoped-realm", HEADER + "kind: Realm\nmetadata: {name: a, realm: b}\nspec: {}",
     "not allowed"),
    ("space-scoped-space", HEADER + "kind: Space\nmetadata: {name: a, space: b}\nspec: {}",
     "not allowed"),
    ("volume-cell-scope", HEADER + "kind: Volume\nmetadata: {name: v, cell: c}\nspec: {}",
     "cell-scoped"),
    ("stack-scope-needs-space", HEADER + "kind: Secret\nmetadata: {name: s, stack: st}\nspec:\n  data: {A: b}",
     "requires space"),
    # --- cell / container ------------------------------------------------
    ("cell-empty", cell("  containers: []"), "containers or a model"),
    ("container-no-command", cell("  containers: [{name: m}]"), "command"),
    ("container-dup-name", cell(
        "  containers:\n    - {name: m, command: [sh]}\n    - {name: m, command: [sh]}"),
     "duplicate container"),
    ("bad-env-name", cell(
        "  containers: [{name: m, command: [sh], env: [{name: '1BAD', value: x}]}]"),
     "env name"),
    ("workdir-relative", cell(
        "  containers: [{name: m, command: [sh], workdir: rel/path}]"), "absolute"),
    ("bad-user", cell(
        "  containers: [{name: m, command: [sh], user: 'not a user!'}]"), "user"),
    ("port-zero", cell(
        "  containers: [{name: m, command: [sh], ports: [{port: 0}]}]"), "range"),
    ("port-huge", cell(
        "  containers: [{name: m, command: [sh], ports: [{port: 70000}]}]"), "range"),
    ("port-bad-proto", cell(
        "  containers: [{name: m, command: [sh], ports: [{port: 80, protocol: sctp}]}]"),
     "tcp|udp"),
    ("port-dup-in-container", cell(
        "  containers: [{name: m, command: [sh], ports: [{port: 80}, {port: 80}]}]"),
     "duplicate port"),
    ("port-dup-across-containers", cell(
        "  containers:\n"
        "    - {name: a, command: [sh], ports: [{port: 80}]}\n"
        "    - {name: b, command: [sh], ports: [{port: 80}]}"),
     "more than one container"),
    ("tmpfs-with-source", cell(
        "  containers: [{name: m, command: [sh], volumes: [{path: /scratch, tmpfs: true, name: v}]}]"),
     "tmpfs"),
    ("tmpfs-no-path", cell(
        "  containers: [{name: m, command: [sh], volumes: [{tmpfs: true}]}]"),
     "tmpfs"),
    ("volume-no-source", cell(
        "  containers: [{name: m, command: [sh], volumes: [{path: /data}]}]"),
     "exactly one"),
    ("volume-two-sources", cell(
        "  containers: [{name: m, command: [sh], volumes: [{name: v, hostPath: /x, path: /data}]}]"),
     "exactly one"),
    ("volume-relative-path", cell(
        "  containers: [{name: m, command: [sh], volumes: [{name: v, path: data}]}]"),
     "absolute"),
    ("hostpath-relative", cell(
        "  containers: [{name: m, command: [sh], volumes: [{hostPath: x, path: /d}]}]"),
     "absolute"),
    ("networks-unsupported", cell(
        "  containers: [{name: m, command: [sh], networks: [other]}]"), "networks"),
    ("bad-capability", cell(
        "  containers: [{name: m, command: [sh], capabilities: ['cap sys admin']}]"),
     "capability"),
    ("device-not-dev", cell(
        "  containers: [{name: m, command: [sh], devices: [/tmp/x]}]"), "/dev"),
    ("bad-memory", cell(
        "  containers: [{name: m, command: [sh], resources: {memory: lots}}]"),
     "memory"),
    ("cpu-zero", cell(
        "  containers: [{name: m, command: [sh], resources: {cpu: 0}}]"), "cpu"),
    ("pids-zero", cell(
        "  containers: [{name: m, command: [sh], resources: {pids: 0}}]"), "pids"),
    ("negative-chips", cell(
        "  containers: [{name: m, command: [sh], resources: {tpuChips: -1}}]"),
     "tpuChips"),
    ("bad-secret-env", cell(
        "  containers: [{name: m, command: [sh], secrets: [{name: s, env: 'no-dash'}]}]"),
     "env name"),
    ("secret-rel-path", cell(
        "  containers: [{name: m, command: [sh], secrets: [{name: s, path: rel}]}]"),
     "absolute"),
    ("repo-no-url", cell(
        "  containers: [{name: m, command: [sh], repos: [{path: /src}]}]"), "url"),
    ("repo-no-path", cell(
        "  containers: [{name: m, command: [sh], repos: [{url: 'https://x/y.git'}]}]"),
     "path"),
    ("repo-option-url", cell(
        "  containers: [{name: m, command: [sh], repos: [{url: '--upload-pack=x', path: /src}]}]"),
     "url"),
    ("repo-nonurl", cell(
        "  containers: [{name: m, command: [sh], repos: [{url: 'just-words', path: /src}]}]"),
     "url"),
    ("repo-option-ref", cell(
        "  containers: [{name: m, command: [sh], repos: [{url: 'https://x/y.git', path: /src, ref: '--hard'}]}]"),
     "ref"),
    ("bad-restart-policy", cell(
        "  containers: [{name: m, command: [sh], restartPolicy: {policy: sometimes}}]"),
     "restartPolicy"),
    ("negative-backoff", cell(
        "  containers: [{name: m, command: [sh], restartPolicy: {policy: always, backoffSeconds: -1}}]"),
     "backoffSeconds"),
    ("negative-retries", cell(
        "  containers: [{name: m, command: [sh], restartPolicy: {policy: always, maxRetries: -2}}]"),
     "maxRetries"),
    ("tty-without-attachable", cell(
        "  containers: [{name: m, command: [sh], tty: {prompt: '$ '}}]"),
     "attachable"),
    ("tty-bad-loglevel", cell(
        "  containers: [{name: m, command: [sh], attachable: true, tty: {logLevel: loud}}]"),
     "logLevel"),
    # --- model cells -----------------------------------------------------
    ("model-no-name", cell("  model: {chips: 1}"), "model.model"),
    ("model-zero-chips", cell("  model: {model: tiny, chips: 0}"), "chips"),
    ("model-bad-port", cell("  model: {model: tiny, port: 99999}"), "range"),
    ("model-zero-slots", cell("  model: {model: tiny, numSlots: 0}"), "numSlots"),
    ("model-tiny-seq", cell("  model: {model: tiny, maxSeqLen: 4}"), "maxSeqLen"),
    ("model-bad-dtype", cell("  model: {model: tiny, dtype: fp4}"), "dtype"),
    ("model-port-collision", cell(
        "  model: {model: tiny, port: 8080}\n"
        "  containers: [{name: m, command: [sh], ports: [{port: 8080}]}]"),
     "collides"),
    ("model-zero-replicas", cell("  model: {model: tiny, replicas: 0}"),
     "replicas"),
    ("model-replica-range-overflow", cell(
        "  model: {model: tiny, port: 65530, replicas: 8}"), "65535"),
    ("model-replica-port-collides-with-container", cell(
        "  model: {model: tiny, port: 8080, replicas: 3}\n"
        # 8082 sits inside the replica range 8080..8083.
        "  containers: [{name: m, command: [sh], ports: [{port: 8082}]}]"),
     "collides"),
    # --- autoscaling bounds ----------------------------------------------
    ("model-min-without-max", cell(
        "  model: {model: tiny, minReplicas: 2}"), "maxReplicas"),
    ("model-max-below-two", cell(
        "  model: {model: tiny, maxReplicas: 1}"), ">= 2"),
    ("model-max-below-min", cell(
        "  model: {model: tiny, minReplicas: 3, maxReplicas: 2}"),
     "minReplicas"),
    ("model-replicas-outside-bounds", cell(
        "  model: {model: tiny, replicas: 5, minReplicas: 1, "
        "maxReplicas: 4}"), "bounds"),
    ("model-autoscale-role-split", cell(
        "  model: {model: tiny, replicas: 2, maxReplicas: 3, "
        "role: 'prefill,decode'}"), "autoscaling"),
    # An autoscaled cell claims its FULL maxReplicas port range up front.
    ("model-autoscale-range-overflow", cell(
        "  model: {model: tiny, port: 65530, replicas: 2, maxReplicas: 8}"),
     "65535"),
    # Cross-document: two ModelSpecs in ONE manifest whose replica port
    # ranges overlap (9000..9004 vs 9003..9005) — the error names both.
    ("manifest-replica-port-ranges-collide",
     cell("  model: {model: tiny, port: 9000, replicas: 4}", name="llm-a")
     + "\n---\n"
     + cell("  model: {model: tiny, port: 9003, replicas: 2}", name="llm-b"),
     "collides with Cell/llm-a"),
    ("manifest-single-port-inside-replica-range",
     cell("  model: {model: tiny, port: 9100, replicas: 2}", name="big")
     + "\n---\n"
     + cell("  model: {model: tiny, port: 9102}", name="small"),
     "collides with Cell/big"),
    # --- space networking ------------------------------------------------
    ("egress-bad-default", HEADER + "kind: Space\nmetadata: {name: s}\nspec:\n  network: {egressDefault: maybe}",
     "egressDefault"),
    ("egress-host-and-cidr", HEADER + "kind: Space\nmetadata: {name: s}\nspec:\n  network:\n    egressAllow: [{host: x.com, cidr: 1.2.3.0/24}]",
     "exactly one"),
    ("egress-neither", HEADER + "kind: Space\nmetadata: {name: s}\nspec:\n  network:\n    egressAllow: [{ports: [80]}]",
     "exactly one"),
    ("egress-bad-cidr", HEADER + "kind: Space\nmetadata: {name: s}\nspec:\n  network:\n    egressAllow: [{cidr: 500.1.2.0/24}]",
     "cidr"),
    ("egress-bad-port", HEADER + "kind: Space\nmetadata: {name: s}\nspec:\n  network:\n    egressAllow: [{cidr: 1.2.3.0/24, ports: [0]}]",
     "range"),
    ("subnet-invalid", HEADER + "kind: Space\nmetadata: {name: s}\nspec:\n  subnet: not-a-subnet",
     "subnet"),
    ("subnet-too-small", HEADER + "kind: Space\nmetadata: {name: s}\nspec:\n  subnet: 10.1.0.0/31",
     "too small"),
    # --- secrets / volumes / blueprints / configs ------------------------
    ("secret-empty", HEADER + "kind: Secret\nmetadata: {name: s}\nspec:\n  data: {}",
     "empty"),
    ("secret-bad-key", HEADER + "kind: Secret\nmetadata: {name: s}\nspec:\n  data: {'my key': v}",
     "key"),
    ("volume-bad-reclaim", HEADER + "kind: Volume\nmetadata: {name: v}\nspec:\n  reclaimPolicy: keep",
     "reclaimPolicy"),
    ("volume-bad-size", HEADER + "kind: Volume\nmetadata: {name: v}\nspec:\n  size: big",
     "size"),
    ("blueprint-dup-param", HEADER + "kind: CellBlueprint\nmetadata: {name: b}\nspec:\n"
     "  params: [{name: p}, {name: p}]\n"
     "  cell: {containers: [{name: m, command: [sh]}]}", "duplicate param"),
    ("blueprint-required-default", HEADER + "kind: CellBlueprint\nmetadata: {name: b}\nspec:\n"
     "  params: [{name: p, required: true, default: x}]\n"
     "  cell: {containers: [{name: m, command: [sh]}]}", "required and defaulted"),
    ("blueprint-bad-cell", HEADER + "kind: CellBlueprint\nmetadata: {name: b}\nspec:\n"
     "  cell: {containers: []}", "containers or a model"),
    ("config-no-blueprint", HEADER + "kind: CellConfig\nmetadata: {name: c}\nspec: {}",
     "blueprint"),
    ("config-dup-slot", HEADER + "kind: CellConfig\nmetadata: {name: c}\nspec:\n"
     "  blueprint: b\n  secrets: [{slot: s, secret: a}, {slot: s, secret: b}]",
     "duplicate secret slot"),
    ("config-bad-value-key", HEADER + "kind: CellConfig\nmetadata: {name: c}\nspec:\n"
     "  blueprint: b\n  values: {'bad key': v}", "value key"),
]


@pytest.mark.parametrize("case,manifest,msg", INVALID, ids=[c[0] for c in INVALID])
def test_invalid_manifest_rejected_at_parse(case, manifest, msg):
    with pytest.raises(InvalidArgument) as exc:
        parser.parse_documents(manifest)
    assert msg.lower() in str(exc.value).lower(), (
        f"{case}: expected {msg!r} in error, got: {exc.value}"
    )


VALID = [
    ("minimal-cell", cell("  containers: [{name: m, command: [sh]}]")),
    ("full-container", cell(
        "  containers:\n"
        "    - name: m\n"
        "      command: [python3, -c, 'print(1)']\n"
        "      env: [{name: FOO, value: bar}]\n"
        "      workdir: /work\n"
        "      user: '1000:1000'\n"
        "      ports: [{port: 8080}, {port: 53, protocol: udp}]\n"
        "      volumes: [{name: data, path: /data, readOnly: true}]\n"
        "      capabilities: [CAP_NET_BIND_SERVICE]\n"
        "      devices: [/dev/accel0]\n"
        "      resources: {memory: 2Gi, cpu: 1.5, pids: 256, tpuChips: 1}\n"
        "      secrets: [{name: tok, env: TOKEN}]\n"
        "      repos: [{url: 'https://x/y.git', path: /src, ref: main}]\n"
        "      restartPolicy: {policy: on-failure, backoffSeconds: 2, maxRetries: 3}\n"
        "      attachable: true\n"
        "      tty: {prompt: '$ ', logLevel: debug}\n")),
    ("tmpfs-mount", cell(
        "  containers: [{name: m, command: [sh], volumes: [{path: /scratch, tmpfs: true}]}]")),
    ("model-cell", cell(
        "  model: {model: llama3-8b, chips: 8, port: 9000, numSlots: 16,\n"
        "          maxSeqLen: 4096, dtype: int8, hostNetwork: true}")),
    ("replicated-model-cell", cell(
        "  model: {model: llama3-8b, chips: 2, port: 9000, replicas: 4}")),
    ("autoscaled-model-cell", cell(
        "  model: {model: llama3-8b, chips: 1, port: 9000, replicas: 2,\n"
        "          minReplicas: 1, maxReplicas: 6, maxPending: 32}")),
    # Disjoint replica ranges in one manifest: 9000..9004 then 9005..9007.
    ("replicated-models-disjoint",
     cell("  model: {model: tiny, port: 9000, replicas: 4}", name="llm-a")
     + "\n---\n"
     + cell("  model: {model: tiny, port: 9005, replicas: 2}", name="llm-b")),
    ("space-deny", HEADER + "kind: Space\nmetadata: {name: s}\nspec:\n"
     "  network:\n    egressDefault: deny\n"
     "    egressAllow:\n      - {host: api.example.com, ports: [443]}\n"
     "      - {cidr: 10.0.0.0/8}\n  subnet: 10.99.0.0/24"),
    ("blueprint-with-params", HEADER + "kind: CellBlueprint\nmetadata: {name: b}\nspec:\n"
     "  params: [{name: model, default: tiny}, {name: tok, required: true}]\n"
     "  cell:\n    containers:\n"
     "      - {name: m, command: [sh], env: [{name: MODEL, value: '${model}'}],\n"
     "         resources: {memory: '${mem}'}}"),
]


@pytest.mark.parametrize("case,manifest", VALID, ids=[c[0] for c in VALID])
def test_valid_manifest_accepted(case, manifest):
    docs = parser.parse_documents(manifest)
    assert docs
