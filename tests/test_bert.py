"""bge-base embedding model: forward shapes, mask correctness, pooling,
sharded embed over the tensor axis, and the EmbeddingEngine's bucketing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kukeon_tpu.models import bert
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import EmbeddingEngine
from kukeon_tpu.serving.embedding import bucket_length


@pytest.fixture(scope="module")
def setup():
    cfg = bert.bge_tiny()
    params = bert.init_params(jax.random.key(0), cfg)
    return cfg, params


class TestModel:
    def test_forward_shapes(self, setup):
        cfg, params = setup
        B, S = 3, 17
        tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
        mask = jnp.ones((B, S), jnp.int32)
        hidden = bert.forward(params, cfg, tokens, mask)
        assert hidden.shape == (B, S, cfg.hidden_size)
        assert hidden.dtype == jnp.float32

    def test_embed_unit_norm(self, setup):
        cfg, params = setup
        tokens = jax.random.randint(jax.random.key(2), (2, 9), 0, cfg.vocab_size)
        mask = jnp.ones((2, 9), jnp.int32)
        for pooling in ("cls", "mean"):
            v = bert.embed(params, cfg, tokens, mask, pooling=pooling)
            assert v.shape == (2, cfg.hidden_size)
            np.testing.assert_allclose(
                np.linalg.norm(np.asarray(v), axis=-1), 1.0, rtol=1e-5
            )

    def test_padding_invariance(self, setup):
        """The same sequence must embed identically regardless of how much
        padding follows it — the padding mask has to be airtight."""
        cfg, params = setup
        seq = jax.random.randint(jax.random.key(3), (1, 8), 1, cfg.vocab_size)

        short_tokens = seq
        short_mask = jnp.ones((1, 8), jnp.int32)
        v_short = bert.embed(params, cfg, short_tokens, short_mask)

        long_tokens = jnp.concatenate(
            [seq, jnp.zeros((1, 24), jnp.int32)], axis=1
        )
        long_mask = jnp.concatenate(
            [short_mask, jnp.zeros((1, 24), jnp.int32)], axis=1
        )
        v_long = bert.embed(params, cfg, long_tokens, long_mask)
        np.testing.assert_allclose(
            np.asarray(v_short), np.asarray(v_long), atol=2e-5
        )

    def test_bidirectional_not_causal(self, setup):
        """Changing a LATER token must change an EARLIER position's hidden
        state (encoders attend both ways; a causal bug would freeze it)."""
        cfg, params = setup
        base = jax.random.randint(jax.random.key(4), (1, 8), 1, cfg.vocab_size)
        mask = jnp.ones((1, 8), jnp.int32)
        h1 = bert.forward(params, cfg, base, mask)
        changed = base.at[0, 7].set((base[0, 7] + 1) % cfg.vocab_size)
        h2 = bert.forward(params, cfg, changed, mask)
        assert not np.allclose(np.asarray(h1[0, 0]), np.asarray(h2[0, 0]))

    def test_param_count_matches_tree(self, setup):
        cfg, params = setup
        total = sum(x.size for x in jax.tree.leaves(params))
        assert total == cfg.param_count()


class TestEngine:
    def test_bucket_length(self):
        assert bucket_length(5, 512) == 16
        assert bucket_length(16, 512) == 16
        assert bucket_length(17, 512) == 32
        assert bucket_length(600, 512) == 512
        assert bucket_length(100, 64) == 64   # clamped to model max

    def test_embed_batch_matches_direct(self, setup):
        cfg, params = setup
        mesh = make_mesh(tensor=2, data=4)
        engine = EmbeddingEngine(cfg, params, mesh, batch_size=4)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).astype(np.int32)
                   for n in (5, 30, 12, 3, 21)]   # 5 prompts > batch 4
        vecs = engine.embed_batch(prompts)
        assert vecs.shape == (5, cfg.hidden_size)
        # Each row matches the unsharded single-sequence embedding.
        for i, p in enumerate(prompts):
            direct = bert.embed(
                params, cfg, jnp.asarray(p)[None, :],
                jnp.ones((1, p.size), jnp.int32),
            )
            np.testing.assert_allclose(vecs[i], np.asarray(direct[0]), atol=3e-5)

    def test_oversized_sequence_rejected(self, setup):
        cfg, params = setup
        mesh = make_mesh(tensor=1, data=8)
        engine = EmbeddingEngine(cfg, params, mesh, batch_size=2)
        too_long = np.ones((cfg.max_position_embeddings + 1,), np.int32)
        with pytest.raises(ValueError, match="max_position_embeddings"):
            engine.embed_batch([too_long])

    def test_empty_batch(self, setup):
        cfg, params = setup
        mesh = make_mesh(tensor=1, data=8)
        engine = EmbeddingEngine(cfg, params, mesh, batch_size=2)
        assert engine.embed_batch([]).shape == (0, cfg.hidden_size)
