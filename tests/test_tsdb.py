"""The in-daemon time-series store (obs/tsdb.py): selector/window parsing,
counter-reset-aware rates, histogram-aware windowed percentiles against
exact values, retention + series-cap bounds under flood, a sanitizer-armed
concurrent ingest/query hammer, and the bench_compare trajectory diff."""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import time

import pytest

from kukeon_tpu.obs import Registry, expo, percentile_from_counts
from kukeon_tpu.obs import federate as fed
from kukeon_tpu.obs.tsdb import (
    TSDB,
    parse_expr,
    parse_selector,
    parse_window,
    sparkline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fam(name: str, kind: str, *samples) -> dict:
    """families dict with one family; samples are (labels, value) pairs
    (sample name == family name — counters/gauges)."""
    return {name: fed.Family(name, kind, "", [
        (name, dict(labels), str(value)) for labels, value in samples])}


# --- parsing -----------------------------------------------------------------


def test_parse_window_units():
    assert parse_window("30s") == 30.0
    assert parse_window("5m") == 300.0
    assert parse_window("1h") == 3600.0
    assert parse_window("250ms") == 0.25
    assert parse_window(300) == 300.0
    assert parse_window("300") == 300.0
    for bad in ("", "abc", "5x", "-3s", 0, -1):
        with pytest.raises(ValueError):
            parse_window(bad)


def test_parse_selector_label_forms():
    s = parse_selector('kukeon_x{a=1,b="two words",c=v}')
    assert s.family == "kukeon_x"
    assert dict(s.matchers) == {"a": "1", "b": "two words", "c": "v"}
    assert parse_selector("kukeon_x").matchers == ()
    for bad in ("", "{a=1}", "kukeon_x{a}", "kukeon_x{a=1", "1bad"):
        with pytest.raises(ValueError):
            parse_selector(bad)


def test_parse_expr_ratio():
    left, right = parse_expr("kukeon_a{x=1} / kukeon_b{x=1}")
    assert left.family == "kukeon_a" and right.family == "kukeon_b"
    left, right = parse_expr("kukeon_a")
    assert right is None
    with pytest.raises(ValueError):
        parse_expr("a / b / c")


# --- counters and resets -----------------------------------------------------


def test_counter_rate_handles_reset():
    """A cell restart drops its cumulative counters to ~0 mid-window; the
    increase must treat the post-reset value as growth since the reset,
    never as a negative delta."""
    db = TSDB(retention_s=3600, clock=lambda: 0)
    for at, v in ((0, 10), (10, 20), (20, 30), (30, 4), (40, 9)):
        db.ingest(_fam("kukeon_c_total", "counter", ({}, v)), at=at)
    # increases: 10 + 10 + 4 (reset: post-reset value) + 5 = 29
    [(labels, delta)] = db.query("kukeon_c_total", 100, "delta", at=40)
    assert delta == 29.0
    [(_l, rate)] = db.query("kukeon_c_total", 100, "rate", at=40)
    assert rate == pytest.approx(0.29)
    # Without the reset the same window reads last-baseline correctly.
    [(_l, d2)] = db.query("kukeon_c_total", 25, "delta", at=20)
    assert d2 == 20.0   # baseline point at t=0 + window (0, 20]


def test_gauge_window_aggregations():
    db = TSDB(retention_s=3600, clock=lambda: 0)
    for at, v in ((0, 5), (10, 1), (20, 9), (30, 3)):
        db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, v)), at=at)
    q = lambda agg, w=100, at=30: db.query("kukeon_g", w, agg, at=at)
    assert q("avg") == [({"cell": "a"}, 4.5)]
    assert q("max") == [({"cell": "a"}, 9.0)]
    assert q("min") == [({"cell": "a"}, 1.0)]
    assert q("latest") == [({"cell": "a"}, 3.0)]
    # Gauge delta is signed last-minus-first (no reset detection).
    assert q("delta", w=25) == [({"cell": "a"}, -2.0)]
    # No points inside the window -> series omitted, not a zero.
    assert q("avg", w=5, at=100) == []
    with pytest.raises(ValueError):
        q("median")


# --- histograms --------------------------------------------------------------


def _hist_families(h_reg: Registry) -> dict:
    return fed.parse(expo.render(h_reg))


def test_windowed_percentile_matches_exact():
    """Full-window percentile over ingested scrapes equals the live
    histogram's own estimate (same buckets, same interpolation)."""
    reg = Registry()
    h = reg.histogram("kukeon_t_seconds", "t")
    db = TSDB(retention_s=3600, clock=lambda: 0)
    # Baseline scrape before any traffic: a counter's first-ever sample
    # is a baseline, not an in-window increase (a daemon restarting next
    # to mid-life cells must not read their lifetime totals as fresh).
    db.ingest(_hist_families(reg), at=5)
    values = (0.001, 0.004, 0.004, 0.02, 0.09, 0.3, 1.7)
    for i, v in enumerate(values):
        h.observe(v)
        db.ingest(_hist_families(reg), at=10 * (i + 1))
    for q, agg in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        [(labels, est)] = db.query("kukeon_t_seconds", 1000, agg, at=80)
        assert labels == {}
        assert est == pytest.approx(h.percentile(q))


def test_windowed_percentile_is_a_window_delta():
    """Only in-window bucket growth counts: a flood of fast observations
    before the window must not drag the windowed p95 down."""
    reg = Registry()
    h = reg.histogram("kukeon_t_seconds", "t")
    db = TSDB(retention_s=3600, clock=lambda: 0)
    for _ in range(500):
        h.observe(0.001)                      # ancient, outside the window
    db.ingest(_hist_families(reg), at=10)
    slow = (0.5, 0.6, 0.9, 1.3)
    for v in slow:
        h.observe(v)
    db.ingest(_hist_families(reg), at=100)
    [(_l, est)] = db.query("kukeon_t_seconds", 95, "p95", at=100)
    # Expected: the p95 of JUST the slow delta, bucket-estimated.
    counts = [0] * (len(h.buckets) + 1)
    for v in slow:
        for i, b in enumerate(h.buckets):
            if v <= b:
                counts[i] += 1
                break
    want = percentile_from_counts(h.buckets, counts, 0.95)
    assert est == pytest.approx(want)
    # Sanity: the since-boot estimate is far lower (fast flood dominates).
    assert h.percentile(0.95) < 0.01 < est


def test_histogram_reset_mid_window_stays_sane():
    """Cell restart: cumulative bucket counters drop to a fresh process's
    small values. Windowed percentiles must clamp, not go negative or
    raise."""
    reg = Registry()
    h = reg.histogram("kukeon_t_seconds", "t")
    db = TSDB(retention_s=3600, clock=lambda: 0)
    for _ in range(50):
        h.observe(0.004)
    db.ingest(_hist_families(reg), at=10)
    reg2 = Registry()                          # the restarted cell
    h2 = reg2.histogram("kukeon_t_seconds", "t")
    for _ in range(3):
        h2.observe(0.03)
    db.ingest(_hist_families(reg2), at=20)
    [(_l, est)] = db.query("kukeon_t_seconds", 100, "p95", at=20)
    assert 0 < est <= h.buckets[-1]
    # Post-reset observations count as the increase: p95 lands near the
    # restarted cell's 0.03 bucket, not the dead process's 0.004.
    assert est >= 0.01


def test_ratio_query_label_join():
    db = TSDB(retention_s=3600, clock=lambda: 0)
    db.ingest(_fam("kukeon_hbm_bytes_in_use", "gauge",
                   ({"cell": "a", "device": "0"}, 90),
                   ({"cell": "b", "device": "0"}, 10)), at=10)
    db.ingest(_fam("kukeon_hbm_bytes_limit", "gauge",
                   ({"cell": "a", "device": "0"}, 100),
                   ({"cell": "b", "device": "0"}, 100)), at=10)
    res = dict((labels["cell"], v) for labels, v in db.query(
        "kukeon_hbm_bytes_in_use / kukeon_hbm_bytes_limit",
        60, "max", at=10))
    assert res == {"a": pytest.approx(0.9), "b": pytest.approx(0.1)}


# --- bounds ------------------------------------------------------------------


def test_retention_eviction_under_flood():
    db = TSDB(retention_s=100, clock=lambda: 0)
    for i in range(500):
        db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, i)), at=i * 10)
    st = db.stats()
    assert st["series"] == 1
    # 100s retention at 10s cadence: ~10 live points, never 500.
    assert st["points"] <= 12
    assert db.query("kukeon_g", 100, "latest", at=4990) == [
        ({"cell": "a"}, 499.0)]
    # A series that stops updating is GC'd after a full retention window.
    db.ingest(_fam("kukeon_other", "gauge", ({}, 1)), at=5000)
    for i in range(30):
        db.ingest(_fam("kukeon_g", "gauge", ({"cell": "a"}, i)),
                  at=5000 + (i + 1) * 10)
    assert ("kukeon_other" not in
            {name for (name, _k) in db._series.keys()})


def test_series_cap_drops_and_counts():
    db = TSDB(retention_s=100, max_series=5, clock=lambda: 0)
    for i in range(10):
        db.ingest(_fam("kukeon_g", "gauge", ({"cell": str(i)}, 1)), at=1)
    st = db.stats()
    assert st["series"] == 5
    assert st["droppedSeries"] == 5


# --- ranges, sparklines, exemplars -------------------------------------------


def test_query_range_and_sparkline():
    db = TSDB(retention_s=3600, clock=lambda: 0)
    for i in range(10):
        db.ingest(_fam("kukeon_c_total", "counter", ({"cell": "a"}, i * 6)),
                  at=i * 10)
    [(labels, vals)] = db.query_range("kukeon_c_total", 60, 20, "rate",
                                      at=90)
    assert labels == {"cell": "a"}
    assert len(vals) == 3
    assert all(v == pytest.approx(0.6) for v in vals)
    # Sparkline: gaps render as spaces, values as blocks.
    line = sparkline([1.0, None, 8.0, 4.0])
    assert len(line) == 4 and line[1] == " " and line[0] != " "


def test_latest_exemplar_roundtrip():
    reg = Registry()
    h = reg.histogram("kukeon_t_seconds", "t")
    h.observe(0.02, exemplar="ab" * 16)
    db = TSDB(retention_s=3600, clock=lambda: 0)
    fams = _hist_families(reg)
    fed.inject_label(fams, cell="r/s/st/c")
    db.ingest(fams, at=10)
    got = db.latest_exemplar("kukeon_t_seconds", cell="r/s/st/c")
    assert got is not None and got[0] == "ab" * 16
    assert db.latest_exemplar("kukeon_t_seconds", cell="nope") is None


# --- concurrency -------------------------------------------------------------


def test_concurrent_ingest_query_hammer():
    """Ingest/query/stats from many threads at once; under a
    KUKEON_SANITIZE=1 session the conftest gate also fails this test on
    any lock-discipline finding (the tsdb builds rows outside its lock)."""
    db = TSDB(retention_s=50, max_series=256)
    reg = Registry()
    h = reg.histogram("kukeon_t_seconds", "t")
    for v in (0.001, 0.02, 0.3):
        h.observe(v)
    base_fams = expo.render(reg)
    stop = threading.Event()
    errors: list[BaseException] = []

    def ingester(i: int):
        n = 0
        while not stop.is_set():
            fams = fed.parse(base_fams)
            fed.inject_label(fams, cell=f"cell-{i}")
            db.ingest(fams, at=time.time() + n)
            n += 1

    def querier():
        while not stop.is_set():
            db.query("kukeon_t_seconds", 30, "p95")
            db.query("kukeon_t_seconds_count", 30, "rate")
            db.query_range("kukeon_t_seconds_count", 30, 10, "delta")
            db.stats()

    def run(fn, *a):
        def wrapped():
            try:
                fn(*a)
            except BaseException as e:  # noqa: BLE001 — surface to the main thread
                errors.append(e)
        t = threading.Thread(target=wrapped, daemon=True)
        t.start()
        return t

    threads = [run(ingester, i) for i in range(4)] + [
        run(querier) for _ in range(4)]
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors, errors
    st = db.stats()
    assert st["series"] > 0 and st["ingests"] > 0


# --- bench_compare -----------------------------------------------------------


def _load_bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(REPO_ROOT, "tools",
                                      "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _artifact(**over) -> dict:
    base = {
        "schema": "kukeon-bench/v3", "at": "2026-01-01T00:00:00Z",
        "backend": "cpu", "n_chips": 1, "model": "tiny", "replicas": 1,
        "sessions": 4, "tok_per_s": 1000.0, "trials": [1000.0],
        "vs_baseline": None,
        "latency_s": {"ttft": {"p50": 0.01, "p95": 0.05, "p99": 0.09},
                      "e2e": {"p50": 0.1, "p95": 0.4, "p99": 0.6}},
        "compiles": None, "peak_hbm_bytes": 1000000,
        "kv_page_tokens": 16, "max_sessions": 4,
        "cold_start": {"p50_s": 30.0}, "embedding": None, "mixed": None,
    }
    base.update(over)
    return base


def test_bench_compare_regression_table(tmp_path, capsys):
    bc = _load_bench_compare()
    for n, art in ((1, _artifact()),
                   (2, _artifact(tok_per_s=850.0,
                                 latency_s={"ttft": {"p95": 0.07},
                                            "e2e": {"p95": 0.41}},
                                 cold_start=None))):
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(art))
    rc = bc.main(["--dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSION" in out and "tok/s" in out
    assert "ttft p95" in out and "+40.0%" in out
    assert "cold start" in out and "n/a" in out     # missing on one side
    # Looser threshold: the 15% tok/s drop passes at 40%.
    assert bc.main(["--dir", str(tmp_path), "--threshold", "45"]) == 0


def test_bench_compare_skips_non_artifacts(tmp_path, capsys):
    bc = _load_bench_compare()
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"n": 1, "cmd": "x", "rc": 0}))   # early raw transcript
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(_artifact()))
    assert bc.main(["--dir", str(tmp_path)]) == 0
    assert "1 comparable artifact" in capsys.readouterr().out


def test_bench_compare_schema_upgrade_matches_bench(tmp_path):
    """The zero-dep loader in tools/bench_compare.py must upgrade a v1
    artifact exactly like bench.read_artifact (pinned so they cannot
    drift)."""
    import bench
    bc = _load_bench_compare()
    v1 = _artifact()
    v1["schema"] = "kukeon-bench/v1"
    for k in ("replicas", "kv_page_tokens", "max_sessions"):
        v1.pop(k)
    path = tmp_path / "BENCH_r03.json"
    path.write_text(json.dumps(v1))
    assert bc.read_artifact(str(path)) == bench.read_artifact(str(path))
