"""Flash attention kernel logic, via the Pallas interpreter on CPU.

Real-TPU numerical/perf validation lives in the verify recipe (the kernel is
27x faster than the XLA path at S=8192 on v5e); here we check the tiling /
online-softmax logic exactly in interpret mode.
"""

import jax
import jax.numpy as jnp
import numpy as np

from kukeon_tpu.ops.attention import attention_mask, attention_reference
from kukeon_tpu.ops.flash_attention import _flash_forward, supports


def _fold(x):
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def test_flash_interpret_matches_reference():
    B, S, H, D = 1, 256, 2, 32
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))

    ref = attention_reference(q, k, v, attention_mask(pos, pos))
    out = _flash_forward(
        _fold(q), _fold(k), _fold(v), block_q=128, block_k=128, interpret=True
    )
    out = out.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_uneven_blocks():
    """block_q != block_k exercises the partial-mask predication."""
    B, S, H, D = 1, 256, 1, 32
    key = jax.random.key(1)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))

    ref = attention_reference(q, k, v, attention_mask(pos, pos))
    out = _flash_forward(
        _fold(q), _fold(k), _fold(v), block_q=128, block_k=64, interpret=True
    )
    out = out.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_supports_guard():
    assert supports(2048, 2048)
    assert not supports(2048, 1024)   # cross-attention shape
    assert not supports(100, 100)     # not tileable
    assert supports(256, 256)
