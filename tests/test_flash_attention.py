"""Flash attention kernel logic, via the Pallas interpreter on CPU.

Real-TPU numerical/perf validation lives in the verify recipe (the kernel is
27x faster than the XLA path at S=8192 on v5e); here we check the tiling /
online-softmax / position-masking logic exactly in interpret mode.
"""

import jax
import jax.numpy as jnp
import numpy as np

from kukeon_tpu.ops.attention import attention_mask, attention_reference
from kukeon_tpu.ops.flash_attention import _flash_forward, supports


def _fold(x):
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _run(q, k, v, pos, block_q, block_k, kv_pos=None):
    B, S, H, D = q.shape
    out = _flash_forward(
        _fold(q), _fold(k), _fold(v),
        pos.astype(jnp.int32),
        (kv_pos if kv_pos is not None else pos).astype(jnp.int32),
        H, block_q=block_q, block_k=block_k, interpret=True,
    )
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _rand(key, B, S, H, D):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (B, S, H, D), jnp.float32),
            jax.random.normal(kk, (B, S, H, D), jnp.float32),
            jax.random.normal(kv, (B, S, H, D), jnp.float32))


def test_flash_interpret_matches_reference():
    B, S, H, D = 1, 256, 2, 32
    q, k, v = _rand(jax.random.key(0), B, S, H, D)
    pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    ref = attention_reference(q, k, v, attention_mask(pos, pos))
    out = _run(q, k, v, pos, 128, 128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_uneven_blocks():
    """block_q != block_k exercises the partial-mask predication."""
    B, S, H, D = 1, 256, 1, 32
    q, k, v = _rand(jax.random.key(1), B, S, H, D)
    pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    ref = attention_reference(q, k, v, attention_mask(pos, pos))
    out = _run(q, k, v, pos, 128, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_offset_positions():
    """Non-arange positions (sequence continuation offsets) must mask
    exactly like the reference — the bug class the kernel's position inputs
    exist to prevent."""
    B, S, H, D = 2, 256, 2, 32
    q, k, v = _rand(jax.random.key(2), B, S, H, D)
    # Per-batch offsets: batch 0 starts at 100, batch 1 at 7.
    offsets = jnp.array([[100], [7]], jnp.int32)
    pos = offsets + jnp.arange(S)[None, :]
    ref = attention_reference(q, k, v, attention_mask(pos, pos))
    out = _run(q, k, v, pos, 128, 128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_supports_guard():
    assert supports(2048, 2048)
    assert not supports(2048, 1024)   # cross-attention shape
    assert not supports(100, 100)     # not tileable
    assert supports(256, 256)
