"""Self-healing fleet (ISSUE 12): SLO-driven autoscaling (FleetScaler),
gateway spillover, and the chaos contract around both.

Everything runs against fakes over real HTTP, the same philosophy as the
gateway/rollout suites: replica failure is scripted, never timed; the
scaler's clock is injectable so a two-minute hysteresis window costs
milliseconds of wall time; and the acceptance spine is the diurnal ramp —
traffic triples, replicas grow min->max, scale-down drains with zero lost
requests, and `kuke alerts --check` stays quiet throughout."""

from __future__ import annotations

import argparse
import http.client
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kukeon_tpu import faults, obs
from kukeon_tpu.gateway.cell import GatewayCell, make_gateway_handler
from kukeon_tpu.obs import Registry, expo
from kukeon_tpu.runtime import scaler as scaler_mod
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.cells import FakeBackend
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.daemon import FleetTelemetry, RPCService
from kukeon_tpu.runtime.devices import TPUDeviceManager
from kukeon_tpu.runtime.errors import InvalidArgument
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import Runner, RunnerOptions
from kukeon_tpu.runtime.store import ResourceStore

from test_gateway import FakeReplica, _free_port_block, _gateway, _post, _teardown


# --- the simulated replica ---------------------------------------------------


class SimReplica:
    """A model-serving replica for the fleet simulator: the full surface
    the gateway, the rollout machinery, AND the telemetry scrape consume —
    /v1/generate, /v1/stats, /readyz, /drain, plus a real /metrics backed
    by a Registry whose queue-depth and SLO-burn gauges the test scripts
    (the scaler's sensors read these through the daemon's own scrape
    path, so the loop under test is the production one end to end)."""

    def __init__(self, port: int = 0, max_pending: int = 10,
                 delay_s: float = 0.0, drainable: bool = True):
        self.queue_depth = 0.0
        self.burn = 0.2               # 5m SLO burn; well under SloBurnFast
        self.max_pending = max_pending
        self.delay_s = delay_s
        self.drainable = drainable    # False = never reports drained
        self.ready = True
        self.draining = False
        self.drained = False
        self.shed_429 = False
        self.requests = 0
        self.inflight = 0
        self._lock = threading.Lock()

        reg = Registry()
        reg.gauge("kukeon_cell_ready", "ready").set_function(
            lambda: 1.0 if self.ready and not self.draining else 0.0)
        reg.gauge("kukeon_engine_queue_depth", "queue").set_function(
            lambda: float(self.queue_depth))
        reg.gauge("kukeon_engine_max_pending", "cap").set(max_pending)
        reg.gauge("kukeon_slo_burn_rate", "burn",
                  labels=("slo", "window")).set_function(
            lambda: float(self.burn), slo="availability", window="5m")
        self.registry = reg
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):
                pass

            def _json(self, code, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    body = expo.render(outer.registry).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", expo.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/readyz":
                    ok = outer.ready and not outer.draining
                    self._json(200 if ok else 503, {"ready": ok})
                elif self.path == "/v1/stats":
                    self._json(200, outer.stats())
                elif self.path in ("/healthz", "/v1/health"):
                    self._json(200, {"status": "ok"})
                else:
                    self._json(404, {"error": self.path})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if self.path == "/drain":
                    self._json(200, {"draining": True,
                                     "started": outer.begin_drain()})
                    return
                if self.path != "/v1/generate":
                    self._json(404, {"error": self.path})
                    return
                if outer.draining or not outer.ready:
                    self._json(503, {"error": "draining"},
                               {"Retry-After": "1"})
                    return
                if outer.shed_429:
                    self._json(429, {"error": "queue full"},
                               {"Retry-After": "1"})
                    return
                with outer._lock:
                    outer.requests += 1
                    outer.inflight += 1
                try:
                    if outer.delay_s:
                        time.sleep(outer.delay_s)
                    self._json(200, {"tokens": [1, 2], "text": "ab",
                                     "numTokens": 2, "seconds": 0.0})
                finally:
                    with outer._lock:
                        outer.inflight -= 1

        self.server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stats(self) -> dict:
        drained = (self.drainable and self.draining
                   and not self.inflight)
        return {"model": "tiny",
                "ready": self.ready and not self.draining,
                "draining": self.draining and self.drainable,
                "queueDepth": 0 if self.draining else int(self.queue_depth),
                "inflight": self.inflight,
                "drained": drained}

    def begin_drain(self) -> bool:
        if not self.drainable:
            return False      # scripted wedge: admits forever, never drains
        if self.draining:
            return False
        self.draining = True

        def _loop():
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and self.inflight:
                time.sleep(0.02)
            self.drained = True
            self.kill()

        threading.Thread(target=_loop, daemon=True).start()
        return True

    def kill(self) -> None:
        try:
            self.server.shutdown()
            self.server.server_close()
        except OSError:
            pass


# --- controller fixtures -----------------------------------------------------


def _controller(tmp_path):
    store = ResourceStore(MetadataStore(str(tmp_path)))
    backend = FakeBackend()
    runner = Runner(store, backend, cgroups=None,
                    devices=TPUDeviceManager(store.ms, chips=[0, 1, 2, 3]),
                    options=RunnerOptions(stop_grace_s=0.2),
                    registry=obs.Registry())
    ctl = Controller(store, runner)
    ctl.bootstrap()
    return ctl, backend, store


def _autoscaled_doc(port: int, replicas=1, mn=1, mx=3,
                    max_pending=10) -> t.Document:
    return t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(
            model="tiny", chips=1, port=port, replicas=replicas,
            min_replicas=mn, max_replicas=mx, max_pending=max_pending)))


KEY = "default/default/default/llm"


# --- runner: bound materialization + parked replicas -------------------------


def test_runner_materializes_bound_and_parks_above_target(tmp_path):
    ctl, backend, store = _controller(tmp_path)
    ctl.create_cell(_autoscaled_doc(9300))
    started = {c.spec.name for c in backend.started}
    # Only the active replica and the gateway START...
    assert started == {"model-server-0", "gateway"}
    # ...but the FULL bound is materialized: the gateway knows every
    # replica URL, and the chip partition covers all three replicas so a
    # later scale-up starts replica i on exactly its chips.
    gcmd = next(c for c in backend.started
                if c.spec.name == "gateway").command
    assert [u for f, u in zip(gcmd, gcmd[1:]) if f == "--replica"] == [
        "http://127.0.0.1:9301", "http://127.0.0.1:9302",
        "http://127.0.0.1:9303"]
    rec = store.read_cell("default", "default", "default", "llm")
    assert rec.status.tpu_chips == [0, 1, 2]
    # Parked replicas do not count against readiness.
    assert rec.status.phase == "ready"
    assert {c.name for c in rec.status.containers} == {
        "model-server-0", "model-server-1", "model-server-2", "gateway"}


def test_scale_model_cell_up_down_and_reconcile_respects_parked(tmp_path):
    ctl, backend, store = _controller(tmp_path)
    ctl.create_cell(_autoscaled_doc(9300))
    runner = ctl.runner

    rec = runner.scale_model_cell("default", "default", "default", "llm", 3)
    assert rec.status.target_replicas == 3
    started = {c.spec.name for c in backend.started}
    assert {"model-server-1", "model-server-2"} <= started
    # Replica i came back on ITS deterministic chip.
    by_name = {c.spec.name: c for c in backend.started}
    assert by_name["model-server-1"].env["TPU_VISIBLE_DEVICES"] == "1"
    assert by_name["model-server-2"].env["TPU_VISIBLE_DEVICES"] == "2"
    assert rec.status.phase == "ready"

    rec = runner.scale_model_cell("default", "default", "default", "llm", 1)
    assert rec.status.target_replicas == 1
    assert rec.status.container("model-server-1").state == "exited"
    assert rec.status.container("model-server-2").state == "exited"
    assert rec.status.phase == "ready"   # parked exits are not failures

    # The reconcile loop must NOT tug against the scaler: a scaled-down
    # replica stays down across refresh passes.
    for _ in range(2):
        _rec, outcome = runner.refresh_cell("default", "default", "default",
                                            "llm")
        assert outcome in ("steady", "healed")
    rec = store.read_cell("default", "default", "default", "llm")
    assert rec.status.container("model-server-1").state == "exited"

    with pytest.raises(InvalidArgument, match="outside"):
        runner.scale_model_cell("default", "default", "default", "llm", 4)
    with pytest.raises(InvalidArgument, match="outside"):
        runner.scale_model_cell("default", "default", "default", "llm", 0)


# --- the scaler's debounce + hysteresis --------------------------------------


class _Clock:
    def __init__(self, at=1_000_000.0):
        self.now = at

    def __call__(self):
        return self.now


def _scaler_rig(tmp_path, monkeypatch, port=9300, mx=3):
    """Controller + autoscaled cell + a clock-driven FleetScaler whose
    sensors are fed by direct TSDB ingest (no HTTP; the full-HTTP loop is
    the acceptance sim below). Scale-downs drain against dead ports —
    unreachable-means-drained, so they complete instantly."""
    from kukeon_tpu.obs import federate as fed
    from kukeon_tpu.obs.tsdb import TSDB

    ctl, backend, store = _controller(tmp_path)
    ctl.create_cell(_autoscaled_doc(port, mx=mx))
    clock = _Clock()
    tsdb = TSDB(clock=clock)
    sc = scaler_mod.FleetScaler(ctl, tsdb, registry=ctl.runner.registry,
                                clock=clock, drain_timeout_s=1.0)

    def feed(queue_per_replica: float):
        """Ingest one scrape's worth of per-replica queue depth for the
        ACTIVE replicas (what the telemetry loop would have scraped)."""
        rec = store.read_cell("default", "default", "default", "llm")
        active = ctl.runner.model_target(rec)
        fam = fed.Family(
            "kukeon_engine_queue_depth", "gauge", "",
            [("kukeon_engine_queue_depth", {"cell": f"{KEY}/r{i}"},
              str(queue_per_replica)) for i in range(active)])
        tsdb.ingest({"kukeon_engine_queue_depth": fam}, at=clock.now)

    def tick(queue_per_replica: float, dt: float = 10.0):
        clock.now += dt
        feed(queue_per_replica)
        return sc.tick(at=clock.now)

    return ctl, store, sc, clock, tick


def test_scaler_debounces_scale_up_and_steps_to_max(tmp_path, monkeypatch):
    ctl, store, sc, clock, tick = _scaler_rig(tmp_path, monkeypatch)

    # First breaching tick: PENDING, not acted on — a one-tick spike must
    # never add a replica.
    assert tick(9.0) == []
    # Held for the for: duration -> firing -> one step up.
    evs = tick(9.0)
    assert [(e["direction"], e["result"], e["to"]) for e in evs] == [
        ("up", "ok", 2)]
    rec = store.read_cell("default", "default", "default", "llm")
    assert ctl.runner.model_target(rec) == 2
    # Pressure persists (per-replica queue still deep): keep growing, one
    # step per tick, and STOP at the bound.
    assert [e["to"] for e in tick(9.0)] == [3]
    assert tick(9.0) == []          # at maxReplicas: firing but capped
    assert ctl.runner.model_target(
        store.read_cell("default", "default", "default", "llm")) == 3
    states = {s["cell"]: s for s in sc.states()}
    assert states[KEY]["active"] == 3
    assert states[KEY]["rules"]["ScaleUpQueue"] == "firing"


def test_scaler_scale_down_is_hysteretic_and_respects_min(tmp_path,
                                                          monkeypatch):
    ctl, store, sc, clock, tick = _scaler_rig(tmp_path, monkeypatch)
    # Grow to max first.
    tick(9.0)
    tick(9.0)
    tick(9.0)
    assert ctl.runner.model_target(
        store.read_cell("default", "default", "default", "llm")) == 3

    # Idle traffic: the down rule needs the 2-minute PEAK under the floor
    # held for a minute — the recent high-pressure samples block it, so
    # the first ~18 idle ticks must produce zero scale-downs (hysteresis:
    # no flap right after a storm).
    downs = []
    for i in range(30):
        downs += [(i, e) for e in tick(0.0)]
        if downs:
            break
    assert downs, "scale-down never happened"
    first_i, first = downs[0]
    assert first_i >= 17, f"scale-down after only {first_i + 1} idle ticks"
    assert (first["direction"], first["result"], first["to"]) == \
        ("down", "ok", 2)
    # Keeps shrinking one step per tick down to minReplicas, never below.
    evs = tick(0.0)
    assert [e["to"] for e in evs] == [1]
    for _ in range(3):
        assert tick(0.0) == []
    assert ctl.runner.model_target(
        store.read_cell("default", "default", "default", "llm")) == 1
    # The drained victims really stopped.
    rec = store.read_cell("default", "default", "default", "llm")
    assert rec.status.container("model-server-2").state == "exited"
    assert rec.status.container("model-server-1").state == "exited"


def test_scale_down_aborts_when_victim_will_not_drain(tmp_path,
                                                      monkeypatch):
    """A replica that keeps serving past the drain timeout is KEPT (result
    aborted, retried next tick) — removing it would lose its in-flight
    requests, the exact hole the drain-first order exists to prevent."""
    base = _free_port_block(3)
    ctl, store, sc, clock, tick = _scaler_rig(tmp_path, monkeypatch,
                                              port=base, mx=2)
    sc.drain_timeout_s = 0.4
    tick(9.0)
    tick(9.0)        # -> active 2 (the bound)
    assert ctl.runner.model_target(
        store.read_cell("default", "default", "default", "llm")) == 2
    # The victim (replica index 1) answers HTTP but never drains.
    stuck = SimReplica(port=base + 2, drainable=False)
    try:
        downs = []
        for _ in range(30):
            downs += [e for e in tick(0.0) if e["direction"] == "down"]
            if downs:
                break
        assert downs and downs[0]["result"] == "aborted"
        assert "still serving" in downs[0]["reason"]
        # Capacity was NOT holed: target unchanged, container untouched.
        rec = store.read_cell("default", "default", "default", "llm")
        assert ctl.runner.model_target(rec) == 2
        ev_m = ctl.runner.registry.get("kukeon_scaler_events_total")
        assert ev_m.value(cell=KEY, direction="down", result="aborted") >= 1
    finally:
        stuck.kill()


def test_scaler_tick_chaos_degrades_never_wedges(tmp_path):
    """The scaler.tick fault point armed: every telemetry tick still
    completes (alerts evaluated, scrape health recorded), the crash is
    counted, and no scaling happens — a dead scaler is a no-op, not a
    dead daemon."""
    ctl, backend, store = _controller(tmp_path)
    ctl.create_cell(_autoscaled_doc(9300))
    clock = _Clock()
    telem = FleetTelemetry(ctl, clock=clock)
    os.environ[faults.ENV] = "scaler.tick"
    for _ in range(3):
        clock.now += 10
        telem.tick()          # must not raise
    assert faults.fired("scaler.tick") == 3
    reg = ctl.runner.registry
    assert reg.get("kukeon_scaler_errors_total").value() == 3
    assert reg.get("kukeon_daemon_scrape_ticks_total").value() == 3
    rec = store.read_cell("default", "default", "default", "llm")
    assert rec.status.target_replicas is None      # fleet untouched


# --- gateway spillover -------------------------------------------------------


def test_spillover_absorbs_all_shed_storm_zero_429(monkeypatch):
    """Acceptance: every replica sheds for a brief storm; parked requests
    all complete 200 once a replica frees — the client never sees a 429."""
    a, b = FakeReplica(), FakeReplica()
    a.shed_429 = True
    b.shed_429 = True
    gw, port = _gateway([a, b])
    statuses: list[int] = []
    lock = threading.Lock()

    def req(i: int):
        status, _raw, _ = _post(port, "/v1/generate",
                                {"prompt": "x", "deadlineS": 20}, timeout=30)
        with lock:
            statuses.append(status)

    try:
        threads = [threading.Thread(target=req, args=(i,)) for i in range(6)]
        for th in threads:
            th.start()
        time.sleep(0.4)                    # the storm
        b.shed_429 = False                 # capacity returns
        for th in threads:
            th.join(timeout=30)
        assert not any(th.is_alive() for th in threads), "request hung"
        assert statuses == [200] * 6, statuses
        reg = gw.registry
        assert reg.get("kukeon_gateway_spill_total").value(
            outcome="recovered") == 6
        assert reg.get("kukeon_gateway_spill_total").value(
            outcome="timeout") == 0
        # The spill wait is visible as latency, and the spans carry the
        # park/resume story.
        _counts, _total, n = reg.get(
            "kukeon_gateway_spill_wait_seconds").snapshot()
        assert n == 6
        spans = gw.tracer.recent(20)
        parked = [s for s in spans
                  if any(e["event"] == "spill_park"
                         for e in s.get("events", []))]
        assert parked and any(
            e["event"] == "spill_resume" for e in parked[0]["events"])
    finally:
        _teardown(gw, a, b)


def test_spillover_timeout_is_in_band(monkeypatch):
    """Past the request deadline the gateway answers the timeout terminal
    itself: 504 + timedOut for a plain request, a 200 ndjson terminal line
    for a stream — mirroring the serving cell's deadline contract."""
    a = FakeReplica()
    a.shed_429 = True
    gw, port = _gateway([a])
    try:
        t0 = time.monotonic()
        status, raw, _ = _post(port, "/v1/generate",
                               {"prompt": "x", "deadlineS": 0.4}, timeout=30)
        assert status == 504
        assert json.loads(raw)["timedOut"] is True
        assert time.monotonic() - t0 >= 0.35
        status, raw, headers = _post(
            port, "/v1/generate",
            {"prompt": "x", "deadlineS": 0.4, "stream": True}, timeout=30)
        assert status == 200
        assert headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(x) for x in raw.decode().splitlines()]
        assert lines == [{"error": lines[0]["error"], "timedOut": True,
                          "numTokens": 0}]
        assert gw.registry.get("kukeon_gateway_spill_total").value(
            outcome="timeout") == 2
    finally:
        _teardown(gw, a)


def test_spillover_overflow_and_fault_degrade_to_passthrough(monkeypatch):
    """A full spill queue (capacity 0 here) and the armed gateway.spill
    fault point both degrade to the pre-spillover contract: the replica's
    429 passes through with its Retry-After — immediately, never a hang."""
    a = FakeReplica()
    a.shed_429 = True
    gw, port = _gateway([a], spill_capacity=0)
    try:
        t0 = time.monotonic()
        status, _raw, headers = _post(port, "/v1/generate",
                                      {"prompt": "x"}, timeout=10)
        assert status == 429 and "Retry-After" in headers
        assert time.monotonic() - t0 < 2.0
        assert gw.registry.get("kukeon_gateway_spill_total").value(
            outcome="overflow") == 1
    finally:
        _teardown(gw, a)
    # Chaos seam: spillover itself failing must not take requests with it.
    b = FakeReplica()
    b.shed_429 = True
    gw2, port2 = _gateway([b])
    try:
        os.environ[faults.ENV] = "gateway.spill"
        status, _raw, headers = _post(port2, "/v1/generate",
                                      {"prompt": "x"}, timeout=10)
        assert status == 429 and "Retry-After" in headers
        assert faults.fired("gateway.spill") == 1
        assert gw2.registry.get("kukeon_gateway_spill_total").value(
            outcome="fault") == 1
    finally:
        os.environ.pop(faults.ENV, None)
        _teardown(gw2, b)


# --- rollout abort summary (satellite) ---------------------------------------


def test_rollout_abort_carries_per_step_outcomes(tmp_path, monkeypatch):
    """An aborted rollout names which replicas finished and which one
    stalled — through rolling_restart's RolloutError.results, the
    RolloutCell RPC payload, and the CLI output — so it is resumable by
    hand instead of a mystery."""
    from kukeon_tpu.runtime import cli
    from kukeon_tpu.runtime import daemon as dmod

    ctl, backend, store = _controller(tmp_path)
    base = _free_port_block(3)
    ctl.create_cell(t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                          replicas=2, port=base))))
    replicas = {0: FakeReplica(port=base + 1), 1: FakeReplica(port=base + 2)}
    real_restart = dmod._rollout_restart

    def restart_and_respawn(ctl_, rec, cname):
        i = int(cname.rsplit("-", 1)[1])
        replicas[i].kill()
        real_restart(ctl_, rec, cname)
        if i == 0:
            replicas[i] = FakeReplica(port=base + 1 + i)
        # replica 1 never comes back: the rollout must stop there.

    monkeypatch.setattr(dmod, "_rollout_restart", restart_and_respawn)
    service = dmod.RPCService(ctl)
    out = service.RolloutCell("default", "default", "default", "llm",
                              drainTimeoutS=5.0, readyTimeoutS=0.8)
    try:
        assert out["aborted"] is True
        assert "model-server-1" in out["error"]
        assert [r["replica"] for r in out["replicas"]] == [
            "model-server-0", "model-server-1"]
        assert "readyS" in out["replicas"][0]          # finished cleanly
        assert "not ready" in out["replicas"][1]["error"]

        class _Client:
            def call(self, method, **params):
                assert method == "RolloutCell"
                return out

        monkeypatch.setattr(cli, "_client", lambda args: _Client())
        args = argparse.Namespace(name="llm", json=False, realm=None,
                                  space=None, stack=None, drain_timeout=5.0,
                                  ready_timeout=0.8)
        assert cli.cmd_rollout(args) == 1
    finally:
        for r in replicas.values():
            r.kill()


# --- the acceptance spine: diurnal ramp through the full loop ----------------


class _Sim:
    """The fake-backend fleet simulator: an autoscaled model cell whose
    replica HTTP servers are SimReplicas, fronted by a REAL GatewayCell
    (spillover included), sensed and scaled by a REAL FleetTelemetry +
    FleetScaler on an injectable clock. The only fake is the backend under
    the containers and the load model that sets each replica's queue
    gauge; every byte of the sense->debounce->act loop is production
    code."""

    def __init__(self, tmp_path, monkeypatch):
        self.base = _free_port_block(4)
        self.ctl, self.backend, self.store = _controller(tmp_path)
        self.ctl.create_cell(_autoscaled_doc(self.base, max_pending=10))
        self.sims: dict[int, SimReplica] = {0: SimReplica(port=self.base + 1)}

        real_mat = scaler_mod._materialize_replica

        def mat_and_spawn(ctl_, rec, target):
            real_mat(ctl_, rec, target)
            i = target - 1
            self.sims[i] = SimReplica(port=self.base + 1 + i)

        monkeypatch.setattr(scaler_mod, "_materialize_replica",
                            mat_and_spawn)

        self.gw = GatewayCell(
            "tiny", [f"http://127.0.0.1:{self.base + 1 + i}"
                     for i in range(3)],
            poll_interval_s=0.05, request_timeout_s=30.0)
        self.gw.start()
        self.gw_srv = ThreadingHTTPServer(
            ("127.0.0.1", self.base), make_gateway_handler(self.gw))
        threading.Thread(target=self.gw_srv.serve_forever,
                         daemon=True).start()
        self.gw.router.poll_once()

        self.svc = RPCService(self.ctl)
        self.clock = _Clock()
        self.svc.telemetry = FleetTelemetry(self.ctl, clock=self.clock)
        self.telem = self.svc.telemetry
        self.transitions: list[dict] = []
        self.scale_events: list[dict] = []

    def active(self) -> int:
        rec = self.store.read_cell("default", "default", "default", "llm")
        return self.ctl.runner.model_target(rec)

    def tick(self, demand: float, dt: float = 10.0) -> None:
        """One scrape interval: the load model spreads `demand` queued
        requests over the live replicas, then the daemon ticks (scrape ->
        ingest -> alerts -> scaler)."""
        self.clock.now += dt
        active = self.active()
        per = min(10.0, demand / max(1, active))
        for i, sim in self.sims.items():
            sim.queue_depth = per if i < active else 0.0
        n_events = len(self.telem.scaler.events(1000))
        self.transitions += self.telem.tick()
        self.scale_events += self.telem.scaler.events(1000)[n_events:]

    def close(self):
        self.gw_srv.shutdown()
        self.gw_srv.server_close()
        self.gw.stop()
        for sim in self.sims.values():
            sim.kill()


@pytest.fixture
def sim(tmp_path, monkeypatch):
    s = _Sim(tmp_path, monkeypatch)
    yield s
    s.close()


def test_acceptance_diurnal_ramp(sim, monkeypatch, capsys):
    """ISSUE 12 acceptance: traffic triples -> replicas grow min->max ->
    SLO burn stays under the firing threshold -> scale-down drains with
    zero lost requests -> `kuke alerts --check` exits 0 throughout."""
    from kukeon_tpu.runtime import cli

    # Night: modest steady load, fleet stays at min.
    for _ in range(4):
        sim.tick(demand=2.0)
    assert sim.active() == 1
    assert sim.scale_events == []

    # Morning spike: traffic triples+ — replicas must grow to the bound,
    # debounced (never on the first breaching tick).
    peak_ticks = 0
    while sim.active() < 3 and peak_ticks < 10:
        sim.tick(demand=18.0)
        peak_ticks += 1
    assert sim.active() == 3, sim.scale_events
    assert peak_ticks >= 2          # pending -> firing -> act, per step
    ups = [e for e in sim.scale_events if e["direction"] == "up"]
    assert [e["to"] for e in ups] == [2, 3]
    assert all(e["result"] == "ok" for e in ups)
    # The new replicas actually serve: the gateway's census sees 3 ready.
    sim.gw.router.poll_once()
    assert sim.gw.router.ready_count() == 3

    # Hold the peak briefly: stable at max, no flapping.
    for _ in range(3):
        sim.tick(demand=18.0)
    assert sim.active() == 3

    # Evening trough under a live request flood: the fleet shrinks back
    # to min by DRAINING each victim through the gateway — the flood must
    # see nothing but 200s (and honest 429s), never an error or a hang.
    statuses: list[int] = []
    errors: list[str] = []
    lock = threading.Lock()
    stop = threading.Event()

    def flood(i: int):
        while not stop.is_set():
            try:
                status, _raw, _ = _post(sim.base, "/v1/generate",
                                        {"prompt": "x", "deadlineS": 20,
                                         "prefixId": f"sess-{i}"},
                                        timeout=30)
                with lock:
                    statuses.append(status)
            except Exception as e:  # noqa: BLE001 — transport error = lost request
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=flood, args=(i,)) for i in range(4)]
    for th in threads:
        th.start()
    try:
        down_ticks = 0
        while sim.active() > 1 and down_ticks < 40:
            sim.tick(demand=0.0)
            down_ticks += 1
    finally:
        time.sleep(0.2)
        stop.set()
        for th in threads:
            th.join(timeout=60)
    assert not any(th.is_alive() for th in threads), "flood thread hung"
    assert sim.active() == 1, sim.scale_events
    downs = [e for e in sim.scale_events if e["direction"] == "down"]
    assert [e["to"] for e in downs] == [2, 1]
    assert all(e["result"] == "ok" for e in downs)
    # Hysteresis: the storm's pressure keeps the down rule quiet for the
    # 2-minute window + 1-minute hold before the first shrink.
    assert down_ticks >= 17
    # Every drained victim finished its in-flight work before removal.
    assert sim.sims[2].drained and sim.sims[1].drained
    # ZERO lost requests: only 200/429 ever reached a client.
    assert not errors, errors
    assert statuses and set(statuses) <= {200, 429}, sorted(set(statuses))
    assert statuses.count(200) > 0

    # The error budget survived: no alert fired at any point in the ramp,
    # and `kuke alerts --check` gates green.
    fired = [tr for tr in sim.transitions if tr["state"] == "firing"]
    assert fired == [], fired

    class _Client:
        def call(self, method, **params):
            return getattr(sim.svc, method)(**params)

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    assert cli.cmd_alerts(argparse.Namespace(json=False, transitions=50,
                                             check=True)) == 0
    out = capsys.readouterr().out
    assert "fleet healthy" in out

    # `kuke scale` renders the loop's state + event history.
    assert cli.cmd_scale(argparse.Namespace(json=False, name=None)) == 0
    out = capsys.readouterr().out
    assert KEY in out
    assert "recent scale events" in out
    assert "+1 -> 2" in out and "-1 -> 1" in out

    # The scaler's own telemetry fed the TSDB like any other signal.
    series = sim.telem.tsdb.query("kukeon_scaler_queue_ratio", 3600,
                                  "max", at=sim.clock.now)
    assert {labels["cell"] for labels, _v in series} == {KEY}

    # ScrapeCells decorates the gateway row with the scale state.
    rows = {r["cell"]: r for r in sim.svc.ScrapeCells()["cells"]}
    assert rows[KEY]["scale"] == {"desired": 1, "min": 1, "max": 3}


def test_scale_down_drain_target_killed_mid_flood(sim, monkeypatch):
    """Satellite: the drain victim DIES instead of draining. Unreachable
    means drained (a dead replica holds no requests to lose), so the
    scaler completes the removal; meanwhile the flood sees only 200/429 —
    the survivors and the spillover queue absorb the blip."""
    # Grow to 2 first.
    while sim.active() < 2:
        sim.tick(demand=18.0)
    assert sim.active() == 2

    statuses: list[int] = []
    errors: list[str] = []
    lock = threading.Lock()
    stop = threading.Event()

    def flood(i: int):
        while not stop.is_set():
            try:
                status, _raw, _ = _post(sim.base, "/v1/generate",
                                        {"prompt": "x", "deadlineS": 20},
                                        timeout=30)
                with lock:
                    statuses.append(status)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=flood, args=(i,)) for i in range(4)]
    for th in threads:
        th.start()
    try:
        time.sleep(0.2)
        # The victim (highest index = replica 1) crashes outright.
        sim.sims[1].kill()
        down_ticks = 0
        while sim.active() > 1 and down_ticks < 40:
            sim.tick(demand=0.0)
            down_ticks += 1
    finally:
        time.sleep(0.3)
        stop.set()
        for th in threads:
            th.join(timeout=60)
    assert not any(th.is_alive() for th in threads), "flood thread hung"
    assert sim.active() == 1
    downs = [e for e in sim.scale_events if e["direction"] == "down"]
    assert downs and downs[-1]["result"] == "ok"
    assert not errors, errors
    assert statuses and set(statuses) <= {200, 429}, sorted(set(statuses))
