"""Training checkpoint/resume: sharded save -> restore into the resuming
mesh's layout (incl. a DIFFERENT mesh), training continues bit-identical."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh, set_mesh
from kukeon_tpu.training import (
    create_train_state,
    latest_step,
    make_train_step,
    restore_checkpoint,
    save_checkpoint,
)
from kukeon_tpu.training.train_step import make_optimizer


def _batch(cfg, mesh, batch_sharding, B=4, S=32):
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size),
        batch_sharding,
    )
    return tokens, jnp.roll(tokens, -1, axis=1), jax.device_put(
        jnp.ones((B, S), jnp.float32), batch_sharding)


def test_save_restore_resume_identical(tmp_path):
    cfg = llama.llama_tiny()
    mesh = make_mesh(tensor=2, fsdp=2, data=2)
    root = str(tmp_path / "ckpts")
    with set_mesh(mesh):
        opt = make_optimizer(warmup_steps=1, total_steps=10)
        state, opt = create_train_state(cfg, mesh, jax.random.key(0), opt)
        step_fn, bsh = make_train_step(cfg, mesh, opt)
        tokens, targets, mask = _batch(cfg, mesh, bsh)
        state, _ = step_fn(state, tokens, targets, mask)

        save_checkpoint(root, state)
        assert latest_step(root) == 1

        # Continue the ORIGINAL run one more step -> reference.
        ref_state, ref_loss = step_fn(state, tokens, targets, mask)

    # Resume in a "fresh job": new state tree on the same mesh, restored.
    with set_mesh(mesh):
        fresh, opt2 = create_train_state(cfg, mesh, jax.random.key(9), opt)
        restored = restore_checkpoint(root, fresh)
        assert int(restored.step) == 1
        step2, bsh2 = make_train_step(cfg, mesh, opt2)
        tokens, targets, mask = _batch(cfg, mesh, bsh2)
        got_state, got_loss = step2(restored, tokens, targets, mask)

    assert float(got_loss) == float(ref_loss)
    for a, b in zip(jax.tree.leaves(got_state.params),
                    jax.tree.leaves(ref_state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_onto_different_mesh(tmp_path):
    """A checkpoint written under tensor=2/fsdp=2 restores onto a
    tensor=4/data=2 mesh — resharding is transparent (the abstract target
    carries the new shardings)."""
    cfg = llama.llama_tiny()
    root = str(tmp_path / "ckpts")
    mesh_a = make_mesh(tensor=2, fsdp=2, data=2)
    with set_mesh(mesh_a):
        opt = make_optimizer(warmup_steps=1, total_steps=10)
        state, opt = create_train_state(cfg, mesh_a, jax.random.key(0), opt)
        save_checkpoint(root, state)
        want = [np.asarray(x) for x in jax.tree.leaves(state.params)]

    mesh_b = make_mesh(tensor=4, data=2)
    with set_mesh(mesh_b):
        fresh, _ = create_train_state(cfg, mesh_b, jax.random.key(7), opt)
        restored = restore_checkpoint(root, fresh)
        got = [np.asarray(x) for x in jax.tree.leaves(restored.params)]
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_latest_step_empty_and_missing(tmp_path):
    assert latest_step(str(tmp_path / "nope")) is None
    (tmp_path / "c").mkdir()
    assert latest_step(str(tmp_path / "c")) is None


@pytest.mark.faults
def test_interrupted_save_preserves_previous_checkpoint(tmp_path):
    """A save killed between writing and publishing (fault seam
    ``checkpoint.save`` = SIGKILL mid-save) must leave the PREVIOUS
    checkpoint as the newest complete one: latest_step never sees the
    partial write, restore still succeeds, and a later healthy save of the
    same step goes through."""
    import dataclasses
    import os

    from kukeon_tpu import faults

    cfg = llama.llama_tiny()
    mesh = make_mesh(tensor=2, data=4)
    root = str(tmp_path / "ckpts")
    with set_mesh(mesh):
        opt = make_optimizer(warmup_steps=1, total_steps=10)
        state, opt = create_train_state(cfg, mesh, jax.random.key(0), opt)
        save_checkpoint(root, state)                    # step 0: the survivor
        assert latest_step(root) == 0
        want = [np.asarray(x) for x in jax.tree.leaves(state.params)]

        bumped = dataclasses.replace(state, step=state.step + 1)
        os.environ[faults.ENV] = "checkpoint.save:1:1"
        with pytest.raises(faults.FaultInjected):
            save_checkpoint(root, bumped)               # killed mid-save

        # The interrupted write published nothing and left no debris that
        # a resume would mistake for a checkpoint.
        assert latest_step(root) == 0
        assert sorted(os.listdir(root)) == ["step_00000000"]

        restored = restore_checkpoint(root, state)
        assert int(restored.step) == 0
        got = [np.asarray(x) for x in jax.tree.leaves(restored.params)]
        for a, b in zip(got, want):
            np.testing.assert_array_equal(a, b)

        # Fault exhausted (count=1): the retried save completes and wins.
        save_checkpoint(root, bumped)
        assert latest_step(root) == 1
