"""End-to-end distributed tracing (ISSUE 9): W3C-style context
propagation gateway -> replica -> engine, federated trace reconstruction,
tail sampling, histogram exemplars, cold-start boot spans, and the
`kuke trace` timeline renderer.

The acceptance spine lives in
test_retry_on_second_replica_yields_one_trace: a request issued through
the gateway that is retried onto a second replica yields ONE trace whose
union (gateway proxy span + both replica attempts + engine phase spans)
reconstructs across components, with the engine phases partitioning the
request's wall time.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import numpy as np
import pytest

from kukeon_tpu.models import llama
from kukeon_tpu.obs import (
    Registry,
    Tracer,
    expo,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    render,
)
from kukeon_tpu.obs import federate as fed
from kukeon_tpu.obs import trace as obs_trace
from kukeon_tpu.parallel import make_mesh
from kukeon_tpu.serving import SamplingParams, ServingEngine

PROMPT = np.arange(1, 9, dtype=np.int32)


def _tiny_engine(**kw):
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    kw.setdefault("num_slots", 1)
    return ServingEngine(cfg, params, mesh, max_seq_len=96,
                         decode_chunk=4, **kw)


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, raw


def _post(port, path, body, headers=None, timeout=60.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json",
                          **(headers or {})})
    resp = conn.getresponse()
    raw = resp.read()
    conn.close()
    return resp.status, raw


# --- context plumbing --------------------------------------------------------


def test_traceparent_roundtrip_and_rejects_garbage():
    tid, sid = new_trace_id(), new_span_id()
    assert len(tid) == 32 and len(sid) == 16
    ctx = parse_traceparent(format_traceparent(tid, sid))
    assert ctx is not None
    assert ctx.trace_id == tid and ctx.span_id == sid
    for bad in (None, "", "junk", "00-short-deadbeef00000000-01",
                "00-" + "g" * 32 + "-" + "0" * 16 + "-01",
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero ids
                format_traceparent(tid, sid) + "-extra"):
        assert parse_traceparent(bad) is None, bad


def test_span_joins_context_and_mints_when_absent():
    t = Tracer()
    ctx = obs_trace.TraceContext(trace_id=new_trace_id(),
                                 span_id=new_span_id())
    child = t.begin(1, 4, trace_ctx=ctx)
    assert child.trace_id == ctx.trace_id
    assert child.parent_span_id == ctx.span_id
    assert child.span_id != ctx.span_id
    root = t.begin(2, 4)
    assert len(root.trace_id) == 32 and root.parent_span_id is None
    d = t.finish(child, "ok").to_dict()
    assert d["traceId"] == ctx.trace_id
    assert d["parentSpanId"] == ctx.span_id
    assert d["spanId"] == child.span_id


# --- tail sampling -----------------------------------------------------------


def _span_with_e2e(t: Tracer, rid: int, e2e_s: float, **kw):
    """A span whose e2e is pinned by back-dating its root event."""
    return t.begin(rid, 4, start_mono=time.monotonic() - e2e_s, **kw)


def test_tail_sampler_flood_keeps_what_matters():
    """Acceptance: under a flood with keep-probability 0 the sampler
    provably retains 100% of error/preempted/retried traces and the slow
    tail while dropping every boring fast-path one."""
    # Boring spans pin a ~40ms e2e by back-dating the root event: the few
    # microseconds between begin and finish ride on top, so the pinned
    # value sits mid-bucket ((32ms, 64ms]) with ~24ms of scheduler-jitter
    # headroom — a loaded CI box can't accidentally promote one into a
    # higher bucket and trip the keep-the-slow-tail rule.
    t = Tracer(capacity=2048, keep_probability=0.0)
    boring = [t.finish(_span_with_e2e(t, i, 0.04), "ok")
              for i in range(300)]
    errors = [t.finish(_span_with_e2e(t, 1000 + i, 0.04), "error")
              for i in range(40)]
    timeouts = [t.finish(_span_with_e2e(t, 2000 + i, 0.04), "timeout")
                for i in range(40)]
    preempted = []
    for i in range(40):
        s = _span_with_e2e(t, 3000 + i, 0.04)
        s.event("preempted")
        preempted.append(t.finish(s, "ok"))
    retried = []
    for i in range(40):
        s = _span_with_e2e(t, 4000 + i, 0.04)
        s.attrs["retries"] = 1
        retried.append(t.finish(s, "ok"))
    # One genuinely slow ok span: kept by the p95+ rule alone.
    slow = t.finish(_span_with_e2e(t, 9999, 10.0), "ok")

    kept_ids = {d["spanId"] for d in t.recent(4096)}
    for group in (errors, timeouts, preempted, retried):
        assert all(s.span_id in kept_ids for s in group)   # 100% retention
    assert slow.span_id in kept_ids
    assert not any(s.span_id in kept_ids for s in boring)
    assert t.sample_stats["dropped"] == len(boring)
    assert t.sample_stats["kept"] == 161


def test_tail_sampler_default_keeps_everything():
    t = Tracer(capacity=64)   # KUKEON_TRACE_SAMPLE unset -> keep 1.0
    for i in range(10):
        t.finish(_span_with_e2e(t, i, 0.0006), "ok")
    assert len(t) == 10 and t.sample_stats["dropped"] == 0


def test_tail_sampler_verdict_is_deterministic_per_trace():
    """The probabilistic decision hashes the trace id, so every component
    of one trace (gateway + N engines) reaches the same verdict."""
    t1 = Tracer(keep_probability=0.5)
    t2 = Tracer(keep_probability=0.5)
    for i in range(64):
        tid = new_trace_id()
        ctx = obs_trace.TraceContext(trace_id=tid, span_id=new_span_id())
        t1.finish(t1.begin(i, 1, trace_ctx=ctx), "ok")
        t2.finish(t2.begin(i, 1, trace_ctx=ctx), "ok")
        in1 = bool(t1.for_trace(tid))
        in2 = bool(t2.for_trace(tid))
        assert in1 == in2


# --- engine integration ------------------------------------------------------


def test_engine_span_joins_propagated_context_and_attaches_exemplars():
    eng = _tiny_engine()
    ctx = obs_trace.TraceContext(trace_id=new_trace_id(),
                                 span_id=new_span_id())
    req = eng.submit(PROMPT, SamplingParams(max_new_tokens=4),
                     trace_ctx=ctx)
    while not req.done.is_set():
        eng.step()
    spans = eng.tracer.for_trace(ctx.trace_id)
    assert len(spans) == 1
    span = spans[0]
    assert span["parentSpanId"] == ctx.span_id
    assert span["outcome"] == "ok" and span["tokens"] == 4
    # Phase durations partition the request's wall time.
    assert abs(sum(span["phasesS"].values()) - span["e2eS"]) < 1e-3
    # TTFT and e2e histograms carry the trace id as a bucket exemplar.
    for metric in ("kukeon_engine_ttft_seconds", "kukeon_engine_e2e_seconds"):
        ex = eng.registry.get(metric).exemplars()
        assert ctx.trace_id in {tid for _v, tid in ex.values()}, metric
    # The exemplar rides the exposition as a parseable comment line and
    # the tail-sampler verdict family is rendered.
    fams = fed.parse(render(eng.registry))
    assert any(tid == ctx.trace_id for _n, _l, tid, _v
               in fams["kukeon_engine_ttft_seconds"].exemplars)
    kept = {lab["decision"]: float(v) for _n, lab, v in
            fams["kukeon_trace_tail_sampled_total"].samples}
    assert kept["kept"] >= 1


def test_engine_shed_span_joins_the_callers_trace():
    """A 429'd hop is part of the SAME trace: a gateway retry that sheds
    on replica A and succeeds on replica B leaves a shed span on A with
    the shared trace id."""
    eng = _tiny_engine(max_pending=1)
    ctx = obs_trace.TraceContext(trace_id=new_trace_id(),
                                 span_id=new_span_id())
    held = eng.submit(PROMPT, SamplingParams(max_new_tokens=2))
    from kukeon_tpu.serving import RejectedError

    with pytest.raises(RejectedError):
        eng.submit(PROMPT, SamplingParams(max_new_tokens=2), trace_ctx=ctx)
    spans = eng.tracer.for_trace(ctx.trace_id)
    assert [s["outcome"] for s in spans] == ["shed"]
    assert spans[0]["parentSpanId"] == ctx.span_id
    held.cancel()
    while not held.done.is_set():
        eng.step()


def test_preempt_resume_keeps_one_continuous_span(monkeypatch):
    """Paged-KV preemption continuity: the victim's span survives the
    preempt+resume cycle as ONE span (same trace id), its events record
    the preemption and the re-prefill, and the tail sampler keeps it even
    at keep-probability 0."""
    monkeypatch.setenv(obs_trace.TRACE_SAMPLE_ENV, "0")
    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = make_mesh(tensor=1, devices=jax.devices()[:1])
    eng = ServingEngine(cfg, params, mesh, num_slots=3, max_seq_len=128,
                        decode_chunk=4, kv_page_tokens=16, kv_pool_pages=8,
                        prefix_cache_size=0)
    assert eng.tracer.keep_probability == 0.0
    sp = SamplingParams(max_new_tokens=40, temperature=0.8)
    reqs = [eng.submit(np.arange(1, 40, dtype=np.int32), sp)
            for _ in range(3)]
    n = 0
    while not all(r.done.is_set() for r in reqs) and n < 800:
        eng.step()
        n += 1
    assert all(r.done.is_set() and r.error is None for r in reqs)
    victims = [r for r in reqs if r.preemptions > 0]
    assert victims
    for r in victims:
        spans = eng.tracer.for_trace(r.trace.trace_id)
        assert len(spans) == 1                   # one continuous span
        events = [e["event"] for e in spans[0]["events"]]
        assert "preempted" in events
        # Resume re-prefills: a second prefill_dispatched after preempted.
        assert events.index("preempted") < len(events) - 1
        assert events.count("prefill_dispatched") >= 2
        assert spans[0]["outcome"] == "ok"


# --- gateway propagation -----------------------------------------------------


class _Replica:
    """Minimal serving-cell stand-in: records every traceparent header it
    receives; scripted to shed 429 or stream exact bytes."""

    def __init__(self, shed_429: bool = False,
                 stream_script: bytes | None = None):
        self.shed_429 = shed_429
        self.stream_script = stream_script
        self.traceparents: list[str | None] = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):
                pass

            def _json(self, code, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/v1/stats":
                    self._json(200, {"model": "tiny", "ready": True,
                                     "draining": False, "queueDepth": 0})
                else:
                    self._json(200, {"status": "ok"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                outer.traceparents.append(self.headers.get("traceparent"))
                if outer.shed_429:
                    self._json(429, {"error": "queue full"},
                               {"Retry-After": "1"})
                    return
                if req.get("stream") and outer.stream_script is not None:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.end_headers()
                    self.wfile.write(outer.stream_script)
                    self.wfile.flush()
                    return
                self._json(200, {"tokens": [1, 2], "text": "xx",
                                 "numTokens": 2, "seconds": 0.0})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def kill(self):
        self.server.shutdown()
        self.server.server_close()


def _gateway(urls):
    from kukeon_tpu.gateway.cell import GatewayCell, make_gateway_handler

    # spill_capacity=0: these tests pin the shed SPAN story; an all-shed
    # request must terminate immediately instead of parking in the
    # spillover queue (whose spans are covered in tests/test_scaler.py).
    gw = GatewayCell("tiny", urls, poll_interval_s=0.05,
                     request_timeout_s=30.0, spill_capacity=0)
    gw.start()
    srv = ThreadingHTTPServer(("127.0.0.1", 0), make_gateway_handler(gw))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    gw.router.poll_once()
    return gw, srv, srv.server_address[1]


def test_gateway_mints_context_and_propagates_downstream():
    rep = _Replica()
    gw, srv, port = _gateway([rep.url])
    try:
        status, _ = _post(port, "/v1/generate",
                          {"promptTokens": [1, 2], "maxNewTokens": 2})
        assert status == 200
        assert len(rep.traceparents) == 1
        ctx = parse_traceparent(rep.traceparents[0])
        assert ctx is not None                       # minted at the gateway
        spans = gw.tracer.for_trace(ctx.trace_id)
        assert len(spans) == 1
        span = spans[0]
        assert span["component"] == "gateway"
        assert span["spanId"] == ctx.span_id         # engine hangs under it
        assert span["outcome"] == "ok"
        assert span["attrs"]["replica"] == "r0"
        events = [e["event"] for e in span["events"]]
        assert "proxy_attempt" in events
    finally:
        srv.shutdown()
        srv.server_close()
        gw.stop()
        rep.kill()


def test_gateway_joins_client_supplied_traceparent():
    rep = _Replica()
    gw, srv, port = _gateway([rep.url])
    client_tid, client_sid = new_trace_id(), new_span_id()
    try:
        status, _ = _post(
            port, "/v1/generate",
            {"promptTokens": [1, 2], "maxNewTokens": 2},
            headers={"traceparent":
                     format_traceparent(client_tid, client_sid)})
        assert status == 200
        spans = gw.tracer.for_trace(client_tid)
        assert len(spans) == 1
        assert spans[0]["parentSpanId"] == client_sid
        # Downstream got the GATEWAY's span as parent, same trace id.
        ctx = parse_traceparent(rep.traceparents[0])
        assert ctx.trace_id == client_tid
        assert ctx.span_id == spans[0]["spanId"]
    finally:
        srv.shutdown()
        srv.server_close()
        gw.stop()
        rep.kill()


def test_stream_passthrough_stays_byte_exact_with_trace_context():
    """Context travels in headers, never the body: the ndjson relay is
    byte-for-byte identical while the trace context still reaches the
    replica and the gateway span records the streamed outcome."""
    script = (b'{"token": 1, "text": "\xc3\xa9"}\n'
              b'{"error": "mid-stream"}\n'
              b'{"done": true, "numTokens": 1}\n')
    rep = _Replica(stream_script=script)
    gw, srv, port = _gateway([rep.url])
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/generate",
                     body=json.dumps({"promptTokens": [1], "stream": True}),
                     headers={"Content-Type": "application/json",
                              "traceparent": format_traceparent(
                                  new_trace_id(), new_span_id())})
        resp = conn.getresponse()
        raw = resp.read()
        conn.close()
        assert resp.status == 200
        assert raw == script                         # byte-exact
        assert parse_traceparent(rep.traceparents[0]) is not None
        span = gw.tracer.recent(1)[0]
        assert span["outcome"] == "ok" and span["attrs"].get("stream")
    finally:
        srv.shutdown()
        srv.server_close()
        gw.stop()
        rep.kill()


def test_gateway_trace_endpoint_serves_proxy_spans():
    rep = _Replica(shed_429=True)
    gw, srv, port = _gateway([rep.url])
    try:
        status, _ = _post(port, "/v1/generate",
                          {"promptTokens": [1], "maxNewTokens": 1})
        assert status == 429                         # all replicas shed
        status, raw = _get(port, "/v1/trace?n=5")
        assert status == 200
        spans = json.loads(raw)["spans"]
        assert spans and spans[0]["outcome"] == "shed"
        events = [e["event"] for e in spans[0]["events"]]
        assert "proxy_retry" in events and "proxy_shed" in events
        # trace_id / request_id filters answer too.
        tid = spans[0]["traceId"]
        status, raw = _get(port, f"/v1/trace?trace_id={tid}")
        assert json.loads(raw)["spans"][0]["traceId"] == tid
        status, raw = _get(port, "/v1/trace?request_id=abc")
        assert status == 400
    finally:
        srv.shutdown()
        srv.server_close()
        gw.stop()
        rep.kill()


# --- the acceptance spine: retry onto a second replica = ONE trace -----------


@pytest.fixture(scope="module")
def real_cell():
    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    cell = ServingCell("tiny", num_slots=2, max_seq_len=96, checkpoint=None,
                       dtype=None, max_pending=8)
    # Warmup before the engine thread starts (step() is single-driver);
    # also stamps the compile/warmup boot marks finish_boot() exports.
    cell.warmup(prompt_len=16)
    cell.engine.start()
    cell.mark_ready()
    server = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(cell))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield cell, server.server_address[1]
    server.shutdown()
    server.server_close()
    cell.engine.stop()


def test_retry_on_second_replica_yields_one_trace(real_cell):
    """A request retried onto a second replica yields ONE trace: the
    gateway proxy span records both replica attempts and the retry hop,
    the winning replica's engine span joins as a child, the federated
    union reconstructs the whole timeline, and the engine phases
    partition the request's wall time."""
    from kukeon_tpu.runtime import daemon as d
    from kukeon_tpu.runtime.cli import render_trace

    cell, cell_port = real_cell
    shedding = _Replica(shed_429=True)               # becomes r0 (tie-break)
    gw, srv, port = _gateway([shedding.url, f"http://127.0.0.1:{cell_port}"])
    try:
        status, raw = _post(port, "/v1/generate",
                            {"promptTokens": [1, 2, 3], "maxNewTokens": 3})
        assert status == 200 and json.loads(raw)["numTokens"] == 3

        # The gateway span: two attempts, one retry hop, outcome ok on r1.
        gspan = next(s for s in gw.tracer.recent(10)
                     if s["outcome"] == "ok")
        tid = gspan["traceId"]
        attempts = [e["attrs"]["replica"] for e in gspan["events"]
                    if e["event"] == "proxy_attempt"]
        assert attempts == ["r0", "r1"]
        retries = [e for e in gspan["events"] if e["event"] == "proxy_retry"]
        assert len(retries) == 1
        assert retries[0]["attrs"]["reason"] == "status_429"
        assert gspan["attrs"]["retries"] == 1

        # Both hops carried the SAME trace id downstream.
        assert [parse_traceparent(h).trace_id
                for h in shedding.traceparents] == [tid]

        # The winning replica's engine span is a child of the gateway span.
        # (The HTTP response can race the engine thread's span finish by a
        # few microseconds — the terminal token is emitted before the span
        # moves into the ring — so poll briefly.)
        deadline = time.monotonic() + 5.0
        espans = cell.engine.tracer.for_trace(tid)
        while not espans and time.monotonic() < deadline:
            time.sleep(0.01)
            espans = cell.engine.tracer.for_trace(tid)
        assert len(espans) == 1
        espan = espans[0]
        assert espan["parentSpanId"] == gspan["spanId"]
        assert espan["outcome"] == "ok" and espan["tokens"] == 3
        assert abs(sum(espan["phasesS"].values()) - espan["e2eS"]) < 1e-3

        # Federated reconstruction (the Traces RPC's machinery) unions the
        # gateway ring and the replica ring into one timeline.
        endpoints = [("default/default/default/llm",
                      f"http://127.0.0.1:{port}", {}),
                     ("default/default/default/llm/r1",
                      f"http://127.0.0.1:{cell_port}", {})]
        spans = d.fetch_traces(endpoints, trace_id=tid, timeout_s=10.0)
        assert {s["cell"] for s in spans} == {e[0] for e in endpoints}
        assert {s["component"] for s in spans} == {"gateway", "engine"}
        assert all(s["traceId"] == tid for s in spans)
        # Sorted by wall-clock start: the gateway span leads.
        assert spans[0]["component"] == "gateway"

        # The `kuke trace` renderer lays the whole thing out.
        out = render_trace(tid, spans)
        assert "gateway" in out and "engine" in out
        assert "attempts r0!status_429 -> r1" in out
        assert "default/default/default/llm/r1" in out
        assert "3 tokens" in out
    finally:
        srv.shutdown()
        srv.server_close()
        gw.stop()
        shedding.kill()


def test_fetch_traces_skips_dead_and_traceless_cells():
    """Federation degrades span-by-span: an endpoint that 404s (embedding
    flavor) or refuses the connection contributes nothing, never an
    error."""
    from kukeon_tpu.runtime import daemon as d

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *a):
            pass

        def do_GET(self):
            body = json.dumps({"error": "no tracer"}).encode()
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        spans = d.fetch_traces(
            [("a", f"http://127.0.0.1:{srv.server_address[1]}", {}),
             ("b", "http://127.0.0.1:9", {})],       # connection refused
            trace_id="ab" * 16, timeout_s=2.0)
        assert spans == []
    finally:
        srv.shutdown()
        srv.server_close()


# --- kuke trace CLI ----------------------------------------------------------


def test_cmd_trace_renders_timeline(capsys, monkeypatch):
    import argparse

    from kukeon_tpu.runtime import cli

    tid = new_trace_id()
    gsid = new_span_id()
    spans = [
        {"traceId": tid, "spanId": gsid, "component": "gateway",
         "cell": "default/default/default/llm", "requestId": 0,
         "startedAt": 100.0, "outcome": "ok", "e2eS": 0.2,
         "attrs": {"retries": 1, "replica": "r1"},
         "events": [
             {"event": "submitted", "atS": 0.0},
             {"event": "proxy_attempt", "atS": 0.001,
              "attrs": {"replica": "r0"}},
             {"event": "proxy_retry", "atS": 0.002,
              "attrs": {"replica": "r0", "reason": "status_429"}},
             {"event": "proxy_attempt", "atS": 0.003,
              "attrs": {"replica": "r1"}},
             {"event": "finished", "atS": 0.2}],
         "phasesS": {"submitted": 0.2}},
        {"traceId": tid, "spanId": new_span_id(), "parentSpanId": gsid,
         "component": "engine", "cell": "default/default/default/llm/r1",
         "requestId": 7, "startedAt": 100.01, "outcome": "ok",
         "tokens": 3, "e2eS": 0.19, "events": [],
         "phasesS": {"queued": 0.01, "prefill_wait": 0.08, "decode": 0.1}},
    ]

    class _Client:
        def call(self, method, **params):
            assert method == "Traces" and params["traceId"] == tid
            return {"spans": spans}

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    assert cli.cmd_trace(argparse.Namespace(trace_id=tid, json=False)) == 0
    out = capsys.readouterr().out
    assert f"trace {tid}" in out
    assert "attempts r0!status_429 -> r1" in out
    assert "decode 100.0ms" in out
    # The engine child renders indented under its gateway parent.
    glines = [ln for ln in out.splitlines() if " gateway " in ln]
    elines = [ln for ln in out.splitlines() if " engine " in ln]
    assert glines and elines
    assert (len(elines[0]) - len(elines[0].lstrip())
            > len(glines[0]) - len(glines[0].lstrip()))

    # Unknown trace -> nonzero exit and a clear message.
    class _Empty:
        def call(self, method, **params):
            return {"spans": []}

    monkeypatch.setattr(cli, "_client", lambda args: _Empty())
    assert cli.cmd_trace(argparse.Namespace(trace_id="00" * 16,
                                            json=False)) == 1


# --- exemplars through federation + kuke top ---------------------------------


def test_exemplars_survive_federation_and_reach_top_summary():
    reg = Registry()
    reg.gauge("kukeon_cell_info", "id", labels=("model", "kind")).set(
        1, model="tiny", kind="decoder")
    reg.gauge("kukeon_cell_uptime_seconds", "up").set(10.0)
    h = reg.histogram("kukeon_engine_ttft_seconds", "ttft")
    fast_tid, slow_tid = new_trace_id(), new_trace_id()
    for _ in range(20):
        h.observe(0.001, exemplar=fast_tid)
    h.observe(2.0, exemplar=slow_tid)
    text = expo.render(reg)
    fams = fed.parse(text)
    # Relabel + merge + re-render round-trips the exemplars.
    fed.inject_label(fams, cell="r/s/st/llm")
    merged = fed.merge([fams])
    out = fed.render(merged)
    fams2 = fed.parse(out)
    exs = fams2["kukeon_engine_ttft_seconds"].exemplars
    assert {e[2] for e in exs} == {fast_tid, slow_tid}
    assert all(e[1]["cell"] == "r/s/st/llm" for e in exs)
    # The `kuke top` summary picks the top-bucket exemplar: the slow one.
    from kukeon_tpu.runtime.daemon import summarize_cell_scrape

    row = summarize_cell_scrape(fams2)
    assert row["ttftP95TraceId"] == slow_tid


def test_kuke_top_cell_row_links_p95_exemplar(capsys, monkeypatch):
    import argparse

    from kukeon_tpu.runtime import cli

    tid = new_trace_id()
    rows = [{"cell": "default/default/default/llm", "ok": True,
             "model": "tiny", "ready": True, "qps": 6.2, "queueDepth": 1,
             "ttftP50S": 0.01, "ttftP95S": 0.09, "ttftP95TraceId": tid,
             "phase": "ready", "restarts": 0}]

    class _Client:
        def call(self, method, **params):
            return {"cells": rows}

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    assert cli.cmd_top(argparse.Namespace(json=False)) == 0
    out = capsys.readouterr().out
    assert f"(p95 trace={tid})" in out


# --- cold-start boot spans ---------------------------------------------------


def test_finish_boot_exports_phases_and_boot_span(real_cell):
    cell, _port = real_cell
    phases = cell.finish_boot()
    assert set(phases) >= {"imports", "init", "compile", "warmup", "serve"}
    assert all(v >= 0 for v in phases.values())
    reg = cell.registry
    total = reg.get("kukeon_cold_start_seconds").value()
    assert total > 0
    # The phases partition the total (same clock, exact by construction).
    assert abs(sum(phases.values()) - total) < 0.5
    g = reg.get("kukeon_cold_start_phase_seconds")
    assert g.value(phase="compile") == phases["compile"]
    # The boot span landed in the trace ring as its own component.
    boot = [s for s in cell.engine.tracer.recent(50)
            if s["component"] == "boot"]
    assert boot
    events = [e["event"] for e in boot[0]["events"]]
    assert {"boot_imports", "boot_init", "boot_compile",
            "boot_warmup"} <= set(events)
    # bench.py's cold-start phase parses these off /metrics.
    fams = fed.parse(expo.render(reg))
    got = {lab["phase"] for _n, lab, _v
           in fams["kukeon_cold_start_phase_seconds"].samples}
    assert {"imports", "init", "compile", "warmup", "serve"} <= got


# --- JSON log correlation ----------------------------------------------------


def test_json_logs_carry_trace_id():
    import io
    import logging

    from kukeon_tpu.runtime import logging_setup

    buf = io.StringIO()
    logging_setup.setup(level="debug", stream=buf, fmt="json")
    try:
        eng = _tiny_engine()
        ctx = obs_trace.TraceContext(trace_id=new_trace_id(),
                                     span_id=new_span_id())
        req = eng.submit(PROMPT, SamplingParams(max_new_tokens=2),
                         trace_ctx=ctx)
        while not req.done.is_set():
            eng.step()
        records = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        done = [r for r in records
                if r.get("request_id") == req.id and "ok" in r.get("msg", "")]
        assert done, records
        assert done[0]["trace_id"] == ctx.trace_id
    finally:
        logging_setup.setup(level="info", stream=None, fmt="text")
        logging.getLogger("kukeon").setLevel(logging.INFO)
