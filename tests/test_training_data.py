"""Token-dataset loader: determinism (resume alignment), sharded placement,
and an end-to-end train loop over real data with checkpoint resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kukeon_tpu.models import llama
from kukeon_tpu.parallel import make_mesh, set_mesh
from kukeon_tpu.training import (
    TokenDataset,
    batches,
    create_train_state,
    make_train_step,
    restore_checkpoint,
    sample_batch,
    save_checkpoint,
)
from kukeon_tpu.training.train_step import make_optimizer


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("data") / "toks.bin")
    rng = np.random.default_rng(0)
    return TokenDataset.write(path, rng.integers(0, 512, size=50_000))


def test_write_read_roundtrip(tmp_path):
    ds = TokenDataset.write(str(tmp_path / "t.bin"), np.arange(1000) % 512)
    assert len(ds) == 1000
    assert ds.tokens.dtype == np.uint16
    big = TokenDataset.write(str(tmp_path / "b.bin"), np.array([70_000, 3]))
    assert big.tokens.dtype == np.uint32


def test_batches_deterministic_and_resumable(dataset):
    """Batch at step N is a pure function of (seed, N): restarting the
    iterator at step 2 reproduces the original schedule exactly."""
    run1 = [t for _, t, _, _ in batches(dataset, 4, 64, num_steps=4, seed=7)]
    run2 = [t for _, t, _, _ in batches(dataset, 4, 64, start_step=2,
                                        num_steps=2, seed=7)]
    np.testing.assert_array_equal(run1[2], run2[0])
    np.testing.assert_array_equal(run1[3], run2[1])
    # Different seed -> different schedule.
    other = next(iter(batches(dataset, 4, 64, seed=8)))[1]
    assert not np.array_equal(run1[0], other)


def test_targets_shifted_by_one(dataset):
    tokens, targets, mask = sample_batch(dataset, 0, 2, 32, seed=1)
    assert tokens.shape == targets.shape == (2, 32)
    # target[i] is the next token of tokens[i] in the source stream: check
    # via the underlying memmap (offsets are deterministic for the seed).
    rng = np.random.default_rng([1, 0])
    offs = rng.integers(0, len(dataset) - 32, size=2)
    np.testing.assert_array_equal(
        targets[0], np.asarray(dataset.tokens[offs[0] + 1:offs[0] + 33]))
    assert mask.all()


def test_too_short_dataset_rejected(tmp_path):
    ds = TokenDataset.write(str(tmp_path / "s.bin"), np.arange(10))
    with pytest.raises(ValueError, match="tokens"):
        sample_batch(ds, 0, 1, 32)


def test_train_loop_with_resume_on_real_data(dataset, tmp_path):
    """Full story: train 2 steps on dataset batches, checkpoint, resume in
    a fresh state, continue on the SAME schedule — loss trajectory of the
    resumed run matches an uninterrupted run."""
    cfg = llama.llama_tiny()
    mesh = make_mesh(tensor=2, data=4)
    root = str(tmp_path / "ck")

    def run(n_steps, state=None, start=0, step_fn=None, bsh=None, opt=None):
        losses = []
        for step, tok, tgt, m in batches(dataset, 8, 32, start_step=start,
                                         num_steps=n_steps, seed=3,
                                         sharding=bsh):
            state, loss = step_fn(state, tok, tgt, m)
            losses.append(float(loss))
        return state, losses

    with set_mesh(mesh):
        opt = make_optimizer(warmup_steps=1, total_steps=10)
        state, opt = create_train_state(cfg, mesh, jax.random.key(0), opt)
        step_fn, bsh = make_train_step(cfg, mesh, opt)
        state, l01 = run(2, state, 0, step_fn, bsh)
        save_checkpoint(root, state)
        _, l23_cont = run(2, state, 2, step_fn, bsh)

    # "Fresh job": new process state, restore, continue at step 2.
    with set_mesh(mesh):
        fresh, opt2 = create_train_state(cfg, mesh, jax.random.key(5), opt)
        restored = restore_checkpoint(root, fresh)
        step_fn2, bsh2 = make_train_step(cfg, mesh, opt2)
        _, l23_resumed = run(2, restored, 2, step_fn2, bsh2)

    assert l23_resumed == l23_cont


def test_training_cli_end_to_end(dataset, tmp_path):
    """`python -m kukeon_tpu.training.cli`: train, checkpoint, resume —
    black-box over a subprocess (the operator's actual entrypoint)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo
    env["JAX_PLATFORMS"] = "cpu"
    ck = str(tmp_path / "ck")
    base = [sys.executable, "-m", "kukeon_tpu.training.cli",
            "--dataset", dataset.path, "--model", "tiny",
            "--batch", "4", "--seq-len", "32", "--log-every", "2",
            "--ckpt-dir", ck]

    p = subprocess.run(base + ["--steps", "4", "--save-every", "2"],
                       capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "step 4 loss" in p.stdout
    assert "checkpoint at step 4" in p.stdout

    p2 = subprocess.run(base + ["--steps", "6"],
                        capture_output=True, text=True, timeout=600, env=env)
    assert p2.returncode == 0, p2.stderr[-2000:]
    assert "resumed from step 4" in p2.stdout
    assert "step 6 loss" in p2.stdout
