"""Logging subsystem (reference: internal/logging ReformatHandler,
handler.go:28-40): one timestamped quoted-message text format, level
resolution, idempotent setup, noop mode."""

from __future__ import annotations

import io
import json
import logging
import os
import re

from kukeon_tpu.runtime import logging_setup


def _fresh_root():
    root = logging.getLogger("kukeon")
    root.handlers = []
    root.setLevel(logging.NOTSET)
    return root


class TestReformat:
    def test_line_shape(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        logging.getLogger("kukeon.runner").info('cell %s started', "web")
        line = buf.getvalue().strip()
        assert re.match(
            r'^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3}Z INFO '
            r'"cell web started" logger=kukeon\.runner$', line
        ), line

    def test_quotes_escaped(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        logging.getLogger("kukeon.net").warning('bad "name" given')
        assert '\\"name\\"' in buf.getvalue()

    def test_level_filtering_and_names(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("warn", stream=buf)
        log = logging.getLogger("kukeon.x")
        log.info("hidden")
        log.warning("shown")
        out = buf.getvalue()
        assert "hidden" not in out and "shown" in out

    def test_setup_idempotent(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        logging_setup.setup("info", stream=buf)
        logging.getLogger("kukeon.y").info("once")
        assert buf.getvalue().count("once") == 1

    def test_noop_swallows(self):
        _fresh_root()
        logging_setup.noop()
        logging.getLogger("kukeon.z").error("nothing")  # must not raise/print

    def test_exception_appended(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        try:
            raise ValueError("boom")
        except ValueError:
            logging.getLogger("kukeon.e").exception("it failed")
        out = buf.getvalue()
        assert '"it failed"' in out and "ValueError: boom" in out


class TestJsonFormat:
    """KUKEON_LOG_FORMAT=json: one JSON object per line with correlation
    fields (request_id/cell/phase) matching the trace spans' ids."""

    def test_env_selects_json_and_line_shape(self):
        _fresh_root()
        buf = io.StringIO()
        os.environ["KUKEON_LOG_FORMAT"] = "json"
        try:
            logging_setup.setup("info", stream=buf)
            logging.getLogger("kukeon.serving.engine").info(
                "request %d ok", 7,
                extra={"request_id": 7, "phase": "ok"})
        finally:
            del os.environ["KUKEON_LOG_FORMAT"]
        obj = json.loads(buf.getvalue().strip())
        assert obj["level"] == "INFO"
        assert obj["msg"] == "request 7 ok"
        assert obj["logger"] == "kukeon.serving.engine"
        assert obj["request_id"] == 7 and obj["phase"] == "ok"
        assert re.match(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3}Z$",
                        obj["ts"])

    def test_cell_field_from_runner_env(self):
        _fresh_root()
        buf = io.StringIO()
        os.environ["KUKEON_CELL"] = "llm-0"
        try:
            logging_setup.setup("info", stream=buf, fmt="json")
            logging.getLogger("kukeon.x").info("hello")
        finally:
            del os.environ["KUKEON_CELL"]
        assert json.loads(buf.getvalue().strip())["cell"] == "llm-0"

    def test_multiline_exception_stays_one_line(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf, fmt="json")
        try:
            raise RuntimeError("boom\nwith newline")
        except RuntimeError:
            logging.getLogger("kukeon.e").exception("failed")
        lines = [ln for ln in buf.getvalue().splitlines() if ln]
        assert len(lines) == 1, "a JSON record must never span lines"
        obj = json.loads(lines[0])
        assert "RuntimeError: boom" in obj["exc"]

    def test_plain_text_remains_default(self):
        _fresh_root()
        buf = io.StringIO()
        assert "KUKEON_LOG_FORMAT" not in os.environ
        logging_setup.setup("info", stream=buf)
        logging.getLogger("kukeon.y").info("plain")
        line = buf.getvalue().strip()
        assert '"plain"' in line and not line.startswith("{")

    def test_resetup_switches_format(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        logging_setup.setup("info", stream=buf, fmt="json")
        logging.getLogger("kukeon.z").info("switched")
        assert json.loads(buf.getvalue().strip())["msg"] == "switched"
