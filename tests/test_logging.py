"""Logging subsystem (reference: internal/logging ReformatHandler,
handler.go:28-40): one timestamped quoted-message text format, level
resolution, idempotent setup, noop mode."""

from __future__ import annotations

import io
import logging
import re

from kukeon_tpu.runtime import logging_setup


def _fresh_root():
    root = logging.getLogger("kukeon")
    root.handlers = []
    root.setLevel(logging.NOTSET)
    return root


class TestReformat:
    def test_line_shape(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        logging.getLogger("kukeon.runner").info('cell %s started', "web")
        line = buf.getvalue().strip()
        assert re.match(
            r'^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3}Z INFO '
            r'"cell web started" logger=kukeon\.runner$', line
        ), line

    def test_quotes_escaped(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        logging.getLogger("kukeon.net").warning('bad "name" given')
        assert '\\"name\\"' in buf.getvalue()

    def test_level_filtering_and_names(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("warn", stream=buf)
        log = logging.getLogger("kukeon.x")
        log.info("hidden")
        log.warning("shown")
        out = buf.getvalue()
        assert "hidden" not in out and "shown" in out

    def test_setup_idempotent(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        logging_setup.setup("info", stream=buf)
        logging.getLogger("kukeon.y").info("once")
        assert buf.getvalue().count("once") == 1

    def test_noop_swallows(self):
        _fresh_root()
        logging_setup.noop()
        logging.getLogger("kukeon.z").error("nothing")  # must not raise/print

    def test_exception_appended(self):
        _fresh_root()
        buf = io.StringIO()
        logging_setup.setup("info", stream=buf)
        try:
            raise ValueError("boom")
        except ValueError:
            logging.getLogger("kukeon.e").exception("it failed")
        out = buf.getvalue()
        assert '"it failed"' in out and "ValueError: boom" in out
