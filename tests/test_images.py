"""Image subsystem: store CRUD, tar load/save, Kukefile builder, FROM
chains, prune keep-sets, and image-backed container resolution."""

import os
import subprocess
import tarfile

import pytest

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.cells.fake import FakeBackend
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.errors import InvalidArgument, NotFound
from kukeon_tpu.runtime.images import (
    ImageBuilder,
    ImageManifest,
    ImageStore,
    base_of,
    parse_kukefile,
    split_ref,
)
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import Runner
from kukeon_tpu.runtime.store import ResourceStore


@pytest.fixture
def store(tmp_path):
    return ImageStore(str(tmp_path))


class TestRefs:
    def test_split_ref(self):
        assert split_ref("busybox") == ("busybox", "latest")
        assert split_ref("busybox:1.36") == ("busybox", "1.36")
        assert split_ref("reg.example.com:5000/ns/app:v2") \
            == ("reg.example.com:5000/ns/app", "v2")


class TestStore:
    def test_put_get_list_delete(self, store):
        store.put(ImageManifest(name="a", tag="v1", env={"X": "1"}))
        store.put(ImageManifest(name="b", tag="v1"))
        assert store.get("a:v1").env == {"X": "1"}
        assert [m.ref for m in store.list()] == ["a:v1", "b:v1"]
        store.delete("a:v1")
        with pytest.raises(NotFound):
            store.get("a:v1")

    def test_prune_keeps_in_use_and_parents(self, store):
        store.put(ImageManifest(name="base", tag="v1"))
        store.put(ImageManifest(name="app", tag="v1", parent="base:v1"))
        store.put(ImageManifest(name="orphan", tag="v1"))
        removed = store.prune(in_use={"app:v1"})
        assert removed == ["orphan:v1"]
        assert store.exists("base:v1") and store.exists("app:v1")

    def test_tar_roundtrip(self, store, tmp_path):
        m = ImageManifest(name="x", tag="v1", entrypoint=["/bin/run"],
                          env={"A": "b"}, workdir="/w")
        d = store.put(m)
        with open(os.path.join(d, "rootfs", "hello.txt"), "w") as f:
            f.write("hi")
        store.put(m)
        tar = str(tmp_path / "x.tar")
        store.save_tar("x:v1", tar)
        store2 = ImageStore(str(tmp_path / "other"))
        got = store2.load_tar(tar, "y:v2")
        assert got.entrypoint == ["/bin/run"]
        assert got.env == {"A": "b"}
        assert open(os.path.join(store2.rootfs("y:v2"), "hello.txt")).read() == "hi"


class TestKukefile:
    def test_parse_and_continuation(self):
        instrs = parse_kukefile("FROM scratch\nRUN echo a \\\n  b\n# c\n")
        assert [i.op for i in instrs] == ["FROM", "RUN"]
        assert instrs[1].args[0] == "echo a b"

    def test_unknown_instruction(self):
        with pytest.raises(InvalidArgument, match="VOLUME"):
            parse_kukefile("VOLUME /data\n")

    def test_base_of_with_args(self, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("ARG REGISTRY=reg.local\nFROM ${REGISTRY}/base:v1\n")
        assert base_of(str(kf)) == "reg.local/base:v1"
        assert base_of(str(kf), {"REGISTRY": "other"}) == "other/base:v1"

    def test_base_of_scratch(self, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\n")
        assert base_of(str(kf)) == ""


class TestBuilder:
    @pytest.fixture
    def ctx(self, tmp_path):
        c = tmp_path / "ctx"
        c.mkdir()
        (c / "app.sh").write_text("#!/bin/sh\necho app\n")
        return str(c)

    def test_build_scratch_with_copy_env_entry(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text(
            "FROM scratch\n"
            "COPY app.sh /bin/app.sh\n"
            "ENV MODE=prod\n"
            "WORKDIR /srv\n"
            "LABEL team=demo\n"
            'ENTRYPOINT ["/bin/sh", "/bin/app.sh"]\n'
        )
        m = ImageBuilder(store).build(str(kf), ctx, "app:v1")
        assert m.env == {"MODE": "prod"}
        assert m.workdir == "/srv"
        assert m.labels == {"team": "demo"}
        assert m.entrypoint == ["/bin/sh", "/bin/app.sh"]
        assert os.path.exists(os.path.join(store.rootfs("app:v1"), "bin/app.sh"))

    def test_build_from_chains_inherit(self, store, ctx, tmp_path):
        base_kf = tmp_path / "Base"
        base_kf.write_text("FROM scratch\nENV BASE=1\nCMD [\"/bin/base\"]\n")
        ImageBuilder(store).build(str(base_kf), ctx, "base:v1")
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM base:v1\nENV APP=2\n")
        m = ImageBuilder(store).build(str(kf), ctx, "app:v1")
        assert m.parent == "base:v1"
        assert m.env == {"BASE": "1", "APP": "2"}
        assert m.cmd == ["/bin/base"]

    def test_run_executes_in_rootfs(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nRUN echo built > marker.txt\n")
        ImageBuilder(store).build(str(kf), ctx, "r:v1")
        assert open(os.path.join(store.rootfs("r:v1"), "marker.txt")).read() \
            == "built\n"

    def test_run_failure_raises_with_output(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nRUN false\n")
        with pytest.raises(InvalidArgument, match="RUN"):
            ImageBuilder(store).build(str(kf), ctx, "f:v1")

    def test_copy_escape_rejected(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nCOPY ../../etc/passwd /pw\n")
        with pytest.raises(InvalidArgument, match="escapes"):
            ImageBuilder(store).build(str(kf), ctx, "e:v1")

    def test_missing_base_errors(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM nope:v9\n")
        with pytest.raises(NotFound):
            ImageBuilder(store).build(str(kf), ctx, "x:v1")


class TestImageBackedCell:
    def test_container_inherits_image_runtime(self, tmp_path):
        rp = str(tmp_path / "rp")
        istore = ImageStore(rp)
        istore.put(ImageManifest(
            name="tool", tag="v1",
            entrypoint=["/bin/sh", "-c", "echo from-image"],
            env={"IMG_ENV": "yes"}, workdir="/tmp",
        ))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        runner = Runner(store, backend)
        ctl = Controller(store, runner)
        ctl.bootstrap()
        doc = t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(name="c1", realm=consts.DEFAULT_REALM,
                                space=consts.DEFAULT_SPACE,
                                stack=consts.DEFAULT_STACK),
            spec=t.CellSpec(containers=[
                t.ContainerSpec(name="main", image="tool:v1"),
            ]),
        )
        ctl.create_cell(doc)
        ctx = backend.started[-1]
        assert ctx.command == ["/bin/sh", "-c", "echo from-image"]
        assert ctx.env["IMG_ENV"] == "yes"
        assert ctx.env["KUKEON_IMAGE"] == "tool:v1"
        assert ctx.workdir == "/tmp"

    def test_spec_args_replace_image_cmd_keep_entrypoint(self, tmp_path):
        rp = str(tmp_path / "rp")
        ImageStore(rp).put(ImageManifest(name="tool", tag="v1",
                                         entrypoint=["/bin/app"],
                                         cmd=["--serve"]))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        ctl = Controller(store, Runner(store, backend))
        ctl.bootstrap()
        doc = t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(name="c3", realm=consts.DEFAULT_REALM,
                                space=consts.DEFAULT_SPACE,
                                stack=consts.DEFAULT_STACK),
            spec=t.CellSpec(containers=[
                t.ContainerSpec(name="main", image="tool:v1",
                                args=["--migrate"]),
            ]),
        )
        ctl.create_cell(doc)
        assert backend.started[-1].command == ["/bin/app", "--migrate"]

    def test_build_with_relative_context_dir(self, store, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        os.makedirs("relctx")
        with open("relctx/f.txt", "w") as f:
            f.write("x")
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nCOPY f.txt /f.txt\n")
        ImageBuilder(store).build(str(kf), "relctx", "rel:v1")
        assert os.path.exists(os.path.join(store.rootfs("rel:v1"), "f.txt"))

    def test_spec_command_wins_over_image(self, tmp_path):
        rp = str(tmp_path / "rp")
        ImageStore(rp).put(ImageManifest(name="tool", tag="v1",
                                         entrypoint=["/bin/img"]))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        ctl = Controller(store, Runner(store, backend))
        ctl.bootstrap()
        doc = t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(name="c2", realm=consts.DEFAULT_REALM,
                                space=consts.DEFAULT_SPACE,
                                stack=consts.DEFAULT_STACK),
            spec=t.CellSpec(containers=[
                t.ContainerSpec(name="main", image="tool:v1",
                                command=["/bin/mine"]),
            ]),
        )
        ctl.create_cell(doc)
        assert backend.started[-1].command == ["/bin/mine"]
