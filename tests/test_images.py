"""Image subsystem: store CRUD, tar load/save, Kukefile builder, FROM
chains, prune keep-sets, and image-backed container resolution."""

import os
import subprocess
import tarfile

import pytest

from kukeon_tpu.runtime import consts
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.cells.fake import FakeBackend
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.errors import InvalidArgument, NotFound
from kukeon_tpu.runtime.images import (
    ImageBuilder,
    ImageManifest,
    ImageStore,
    base_of,
    parse_kukefile,
    split_ref,
)
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import Runner
from kukeon_tpu.runtime.store import ResourceStore


@pytest.fixture
def store(tmp_path):
    return ImageStore(str(tmp_path))


class TestRefs:
    def test_split_ref(self):
        assert split_ref("busybox") == ("busybox", "latest")
        assert split_ref("busybox:1.36") == ("busybox", "1.36")
        assert split_ref("reg.example.com:5000/ns/app:v2") \
            == ("reg.example.com:5000/ns/app", "v2")


class TestStore:
    def test_put_get_list_delete(self, store):
        store.put(ImageManifest(name="a", tag="v1", env={"X": "1"}))
        store.put(ImageManifest(name="b", tag="v1"))
        assert store.get("a:v1").env == {"X": "1"}
        assert [m.ref for m in store.list()] == ["a:v1", "b:v1"]
        store.delete("a:v1")
        with pytest.raises(NotFound):
            store.get("a:v1")

    def test_prune_keeps_in_use_and_parents(self, store):
        store.put(ImageManifest(name="base", tag="v1"))
        store.put(ImageManifest(name="app", tag="v1", parent="base:v1"))
        store.put(ImageManifest(name="orphan", tag="v1"))
        removed = store.prune(in_use={"app:v1"})
        assert removed == ["orphan:v1"]
        assert store.exists("base:v1") and store.exists("app:v1")

    def test_tar_roundtrip(self, store, tmp_path):
        m = ImageManifest(name="x", tag="v1", entrypoint=["/bin/run"],
                          env={"A": "b"}, workdir="/w")
        d = store.put(m)
        with open(os.path.join(d, "rootfs", "hello.txt"), "w") as f:
            f.write("hi")
        store.put(m)
        tar = str(tmp_path / "x.tar")
        store.save_tar("x:v1", tar)
        store2 = ImageStore(str(tmp_path / "other"))
        got = store2.load_tar(tar, "y:v2")
        assert got.entrypoint == ["/bin/run"]
        assert got.env == {"A": "b"}
        assert open(os.path.join(store2.rootfs("y:v2"), "hello.txt")).read() == "hi"


class TestKukefile:
    def test_parse_and_continuation(self):
        instrs = parse_kukefile("FROM scratch\nRUN echo a \\\n  b\n# c\n")
        assert [i.op for i in instrs] == ["FROM", "RUN"]
        assert instrs[1].args[0] == "echo a b"

    def test_unknown_instruction(self):
        with pytest.raises(InvalidArgument, match="VOLUME"):
            parse_kukefile("VOLUME /data\n")

    def test_base_of_with_args(self, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("ARG REGISTRY=reg.local\nFROM ${REGISTRY}/base:v1\n")
        assert base_of(str(kf)) == "reg.local/base:v1"
        assert base_of(str(kf), {"REGISTRY": "other"}) == "other/base:v1"

    def test_base_of_scratch(self, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\n")
        assert base_of(str(kf)) == ""


class TestBuilder:
    @pytest.fixture
    def ctx(self, tmp_path):
        c = tmp_path / "ctx"
        c.mkdir()
        (c / "app.sh").write_text("#!/bin/sh\necho app\n")
        return str(c)

    def test_build_scratch_with_copy_env_entry(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text(
            "FROM scratch\n"
            "COPY app.sh /bin/app.sh\n"
            "ENV MODE=prod\n"
            "WORKDIR /srv\n"
            "LABEL team=demo\n"
            'ENTRYPOINT ["/bin/sh", "/bin/app.sh"]\n'
        )
        m = ImageBuilder(store).build(str(kf), ctx, "app:v1")
        assert m.env == {"MODE": "prod"}
        assert m.workdir == "/srv"
        assert m.labels == {"team": "demo"}
        assert m.entrypoint == ["/bin/sh", "/bin/app.sh"]
        assert os.path.exists(os.path.join(store.rootfs("app:v1"), "bin/app.sh"))

    def test_build_from_chains_inherit(self, store, ctx, tmp_path):
        base_kf = tmp_path / "Base"
        base_kf.write_text("FROM scratch\nENV BASE=1\nCMD [\"/bin/base\"]\n")
        ImageBuilder(store).build(str(base_kf), ctx, "base:v1")
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM base:v1\nENV APP=2\n")
        m = ImageBuilder(store).build(str(kf), ctx, "app:v1")
        assert m.parent == "base:v1"
        assert m.env == {"BASE": "1", "APP": "2"}
        assert m.cmd == ["/bin/base"]

    def test_run_executes_in_rootfs(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nRUN echo built > marker.txt\n")
        ImageBuilder(store).build(str(kf), ctx, "r:v1")
        assert open(os.path.join(store.rootfs("r:v1"), "marker.txt")).read() \
            == "built\n"

    def test_run_failure_raises_with_output(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nRUN false\n")
        with pytest.raises(InvalidArgument, match="RUN"):
            ImageBuilder(store).build(str(kf), ctx, "f:v1")

    def test_copy_escape_rejected(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nCOPY ../../etc/passwd /pw\n")
        with pytest.raises(InvalidArgument, match="escapes"):
            ImageBuilder(store).build(str(kf), ctx, "e:v1")

    def test_missing_base_errors(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM nope:v9\n")
        with pytest.raises(NotFound):
            ImageBuilder(store).build(str(kf), ctx, "x:v1")


class TestImageBackedCell:
    def test_container_inherits_image_runtime(self, tmp_path):
        rp = str(tmp_path / "rp")
        istore = ImageStore(rp)
        istore.put(ImageManifest(
            name="tool", tag="v1",
            entrypoint=["/bin/sh", "-c", "echo from-image"],
            env={"IMG_ENV": "yes"}, workdir="/tmp",
        ))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        runner = Runner(store, backend)
        ctl = Controller(store, runner)
        ctl.bootstrap()
        doc = t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(name="c1", realm=consts.DEFAULT_REALM,
                                space=consts.DEFAULT_SPACE,
                                stack=consts.DEFAULT_STACK),
            spec=t.CellSpec(containers=[
                t.ContainerSpec(name="main", image="tool:v1"),
            ]),
        )
        ctl.create_cell(doc)
        ctx = backend.started[-1]
        assert ctx.command == ["/bin/sh", "-c", "echo from-image"]
        assert ctx.env["IMG_ENV"] == "yes"
        assert ctx.env["KUKEON_IMAGE"] == "tool:v1"
        assert ctx.workdir == "/tmp"

    def test_spec_args_replace_image_cmd_keep_entrypoint(self, tmp_path):
        rp = str(tmp_path / "rp")
        ImageStore(rp).put(ImageManifest(name="tool", tag="v1",
                                         entrypoint=["/bin/app"],
                                         cmd=["--serve"]))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        ctl = Controller(store, Runner(store, backend))
        ctl.bootstrap()
        doc = t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(name="c3", realm=consts.DEFAULT_REALM,
                                space=consts.DEFAULT_SPACE,
                                stack=consts.DEFAULT_STACK),
            spec=t.CellSpec(containers=[
                t.ContainerSpec(name="main", image="tool:v1",
                                args=["--migrate"]),
            ]),
        )
        ctl.create_cell(doc)
        assert backend.started[-1].command == ["/bin/app", "--migrate"]

    def test_build_with_relative_context_dir(self, store, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        os.makedirs("relctx")
        with open("relctx/f.txt", "w") as f:
            f.write("x")
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nCOPY f.txt /f.txt\n")
        ImageBuilder(store).build(str(kf), "relctx", "rel:v1")
        assert os.path.exists(os.path.join(store.rootfs("rel:v1"), "f.txt"))

    def test_spec_command_wins_over_image(self, tmp_path):
        rp = str(tmp_path / "rp")
        ImageStore(rp).put(ImageManifest(name="tool", tag="v1",
                                         entrypoint=["/bin/img"]))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        ctl = Controller(store, Runner(store, backend))
        ctl.bootstrap()
        doc = t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(name="c2", realm=consts.DEFAULT_REALM,
                                space=consts.DEFAULT_SPACE,
                                stack=consts.DEFAULT_STACK),
            spec=t.CellSpec(containers=[
                t.ContainerSpec(name="main", image="tool:v1",
                                command=["/bin/mine"]),
            ]),
        )
        ctl.create_cell(doc)
        assert backend.started[-1].command == ["/bin/mine"]


class TestBuildSafety:
    """Regressions for the review findings: dst traversal, atomic builds,
    stale-rootfs merging, prune ref normalization."""

    @pytest.fixture
    def ctx(self, tmp_path):
        c = tmp_path / "ctx"
        c.mkdir()
        (c / "app.sh").write_text("#!/bin/sh\necho app\n")
        return str(c)

    def test_copy_dst_escape_rejected(self, store, ctx, tmp_path):
        kf = tmp_path / "Kukefile"
        kf.write_text("FROM scratch\nCOPY app.sh ../../escape.sh\n")
        outside = tmp_path / "escape.sh"
        with pytest.raises(InvalidArgument, match="dst escapes"):
            ImageBuilder(store).build(str(kf), ctx, "bad:v1")
        assert not outside.exists()
        assert not store.exists("bad:v1")

    def test_failed_build_preserves_previous_image(self, store, ctx, tmp_path):
        good = tmp_path / "Good"
        good.write_text('FROM scratch\nENV V=1\nENTRYPOINT ["/bin/true"]\n')
        ImageBuilder(store).build(str(good), ctx, "app:v1")

        bad = tmp_path / "Bad"
        bad.write_text("FROM scratch\nRUN exit 9\n")
        with pytest.raises(InvalidArgument, match="failed"):
            ImageBuilder(store).build(str(bad), ctx, "app:v1")
        # Old image survives untouched, no staging leftovers.
        m = store.get("app:v1")
        assert m.env == {"V": "1"}
        assert m.entrypoint == ["/bin/true"]
        assert not [e for e in os.listdir(store.root) if e.startswith(".staging")]

    def test_rebuild_replaces_rootfs_wholesale(self, store, ctx, tmp_path):
        kf1 = tmp_path / "K1"
        kf1.write_text("FROM scratch\nCOPY app.sh /bin/old.sh\n")
        ImageBuilder(store).build(str(kf1), ctx, "app:v1")
        assert os.path.exists(os.path.join(store.rootfs("app:v1"), "bin/old.sh"))

        kf2 = tmp_path / "K2"
        kf2.write_text("FROM scratch\nCOPY app.sh /bin/new.sh\n")
        ImageBuilder(store).build(str(kf2), ctx, "app:v1")
        rootfs = store.rootfs("app:v1")
        assert os.path.exists(os.path.join(rootfs, "bin/new.sh"))
        assert not os.path.exists(os.path.join(rootfs, "bin/old.sh"))

    def test_reload_tar_replaces_rootfs(self, store, tmp_path):
        d1 = tmp_path / "t1"
        d1.mkdir()
        (d1 / "a.txt").write_text("a")
        tar1 = tmp_path / "t1.tar"
        with tarfile.open(tar1, "w") as tf:
            tf.add(d1 / "a.txt", arcname="a.txt")
        store.load_tar(str(tar1), "img:v1")
        assert os.path.exists(os.path.join(store.rootfs("img:v1"), "a.txt"))

        d2 = tmp_path / "t2"
        d2.mkdir()
        (d2 / "b.txt").write_text("b")
        tar2 = tmp_path / "t2.tar"
        with tarfile.open(tar2, "w") as tf:
            tf.add(d2 / "b.txt", arcname="b.txt")
        store.load_tar(str(tar2), "img:v1")
        rootfs = store.rootfs("img:v1")
        assert os.path.exists(os.path.join(rootfs, "b.txt"))
        assert not os.path.exists(os.path.join(rootfs, "a.txt"))

    def test_prune_normalizes_bare_refs(self, store):
        store.put(ImageManifest(name="tool", tag="latest"))
        # A cell spec saying `image: tool` must keep tool:latest.
        removed = store.prune(in_use={"tool"})
        assert removed == []
        assert store.exists("tool:latest")

    def test_reconcile_survives_stale_image_ref(self, tmp_path):
        """One cell with a deleted image must not stall reconciliation for
        the cells after it (review finding: uncaught NotFound aborted the
        whole pass)."""
        rp = str(tmp_path / "rp")
        istore = ImageStore(rp)
        istore.put(ImageManifest(name="tool", tag="v1", entrypoint=["/bin/true"]))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        runner = Runner(store, backend)
        ctl = Controller(store, runner)
        ctl.bootstrap()
        for name in ("a-broken", "b-ok"):
            ctl.create_cell(t.Document(
                kind=t.KIND_CELL,
                metadata=t.Metadata(name=name, realm=consts.DEFAULT_REALM,
                                    space=consts.DEFAULT_SPACE,
                                    stack=consts.DEFAULT_STACK),
                spec=t.CellSpec(containers=[
                    t.ContainerSpec(name="main", image="tool:v1",
                                    restart_policy=t.RestartPolicy(
                                        policy="always", backoff_seconds=0.0)),
                ]),
            ))
        istore.delete("tool:v1")
        # Exit both so refresh hits the restart path (image resolution).
        for name in ("a-broken", "b-ok"):
            backend.exit(store.container_dir(
                consts.DEFAULT_REALM, consts.DEFAULT_SPACE,
                consts.DEFAULT_STACK, name, "main"), 1)
        counts = ctl.reconcile_cells()
        # Both cells error on image resolution, but the pass completes and
        # counts them instead of raising.
        assert counts.get("error") == 2

    def test_load_tar_with_dot_slash_prefix(self, store, tmp_path):
        """`tar -cf x.tar -C bundle .` layouts (./rootfs/...) must import as
        structured, not nest under rootfs/./rootfs."""
        bundle = tmp_path / "bundle"
        (bundle / "rootfs" / "bin").mkdir(parents=True)
        (bundle / "rootfs" / "bin" / "x.sh").write_text("echo x")
        (bundle / "kukeon-manifest.json").write_text(
            '{"entrypoint": ["/bin/sh", "/bin/x.sh"], "env": {"A": "1"}}'
        )
        tar = tmp_path / "img.tar"
        subprocess.run(["tar", "-cf", str(tar), "-C", str(bundle), "."], check=True)
        m = store.load_tar(str(tar), "dotted:v1")
        assert m.entrypoint == ["/bin/sh", "/bin/x.sh"]
        assert m.env == {"A": "1"}
        rootfs = store.rootfs("dotted:v1")
        assert os.path.exists(os.path.join(rootfs, "bin/x.sh"))
        assert not os.path.exists(os.path.join(rootfs, "rootfs"))

    def test_blueprint_images_survive_prune(self, tmp_path):
        """Images referenced only by a stored CellBlueprint template must be
        kept by prune (a config can materialize from it at any time)."""
        rp = str(tmp_path / "rp")
        istore = ImageStore(rp)
        istore.put(ImageManifest(name="bp-tool", tag="v1", entrypoint=["/bin/true"]))
        istore.put(ImageManifest(name="orphan", tag="v1"))
        store = ResourceStore(MetadataStore(rp))
        ctl = Controller(store, Runner(store, FakeBackend()))
        ctl.bootstrap()
        ctl.put_blueprint(t.Document(
            kind=t.KIND_CELL_BLUEPRINT, metadata=t.Metadata(name="bp"),
            spec=t.CellBlueprintSpec(cell=t.CellSpec(containers=[
                t.ContainerSpec(name="m", image="bp-tool:v1"),
            ])),
        ))
        removed = istore.prune(ctl.images_in_use())
        assert removed == ["orphan:v1"]
        assert istore.exists("bp-tool:v1")


class TestReviewRound3:
    @pytest.fixture
    def ctx(self, tmp_path):
        c = tmp_path / "ctx"
        c.mkdir()
        (c / "app.sh").write_text("#!/bin/sh\necho app\n")
        return str(c)

    def test_env_label_space_form_and_lone_key_rejected(self, store, ctx, tmp_path):
        kf = tmp_path / "K"
        kf.write_text("FROM scratch\nENV MODE prod\nLABEL team demo\n")
        m = ImageBuilder(store).build(str(kf), ctx, "sf:v1")
        assert m.env == {"MODE": "prod"}
        assert m.labels == {"team": "demo"}

        kf.write_text("FROM scratch\nENV LONELY\n")
        with pytest.raises(InvalidArgument, match="ENV wants"):
            ImageBuilder(store).build(str(kf), ctx, "sf:v2")

    def test_continuation_with_comment_and_blank_lines(self, tmp_path):
        instrs = parse_kukefile(
            "RUN echo a \\\n"
            "# interleaved comment\n"
            "\n"
            "    b\n"
        )
        assert len(instrs) == 1
        assert instrs[0].op == "RUN"
        assert instrs[0].args == ["echo a b"]

    def test_parameterized_blueprint_image_kept_by_prune(self, tmp_path):
        rp = str(tmp_path / "rp")
        istore = ImageStore(rp)
        istore.put(ImageManifest(name="tool", tag="v1", entrypoint=["/bin/true"]))
        store = ResourceStore(MetadataStore(rp))
        ctl = Controller(store, Runner(store, FakeBackend()))
        ctl.bootstrap()
        ctl.put_blueprint(t.Document(
            kind=t.KIND_CELL_BLUEPRINT, metadata=t.Metadata(name="bp"),
            spec=t.CellBlueprintSpec(
                params=[t.BlueprintParam(name="img", default="tool:v1")],
                cell=t.CellSpec(containers=[
                    t.ContainerSpec(name="m", image="${img}"),
                ]),
            ),
        ))
        assert "tool:v1" in ctl.images_in_use()
        removed = istore.prune(ctl.images_in_use())
        assert removed == []

    def test_image_workdir_resolves_in_rootfs(self, tmp_path):
        """Image WORKDIR /srv must chdir inside the rootfs (created on
        demand), not on the host."""
        rp = str(tmp_path / "rp")
        istore = ImageStore(rp)
        istore.put(ImageManifest(name="wd", tag="v1", entrypoint=["pwd"],
                                 workdir="/srv-nonexistent-on-host"))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        ctl = Controller(store, Runner(store, backend))
        ctl.bootstrap()
        ctl.create_cell(t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(name="c1", realm=consts.DEFAULT_REALM,
                                space=consts.DEFAULT_SPACE,
                                stack=consts.DEFAULT_STACK),
            spec=t.CellSpec(containers=[t.ContainerSpec(name="main", image="wd:v1")]),
        ))
        ctx = backend.started[-1]
        # Runner passes the manifest workdir through; the PROCESS backend
        # maps it into the rootfs at start. The fake backend records the
        # pre-overlay context, so exercise the mapping helper directly.
        from kukeon_tpu.runtime.cells.process import ProcessBackend

        mapped = ProcessBackend._overlay_workdir(ctx)
        rootfs = istore.rootfs("wd:v1")
        assert mapped == os.path.join(rootfs, "srv-nonexistent-on-host")
        assert os.path.isdir(mapped)


class TestReviewRound4:
    def test_config_values_image_kept_by_prune(self, tmp_path):
        """A stored CellConfig overriding a blueprint image param keeps THAT
        image alive through prune, not just the param default."""
        rp = str(tmp_path / "rp")
        istore = ImageStore(rp)
        istore.put(ImageManifest(name="tool", tag="v1", entrypoint=["/bin/true"]))
        istore.put(ImageManifest(name="tool", tag="v2", entrypoint=["/bin/true"]))
        store = ResourceStore(MetadataStore(rp))
        ctl = Controller(store, Runner(store, FakeBackend()))
        ctl.bootstrap()
        ctl.put_blueprint(t.Document(
            kind=t.KIND_CELL_BLUEPRINT, metadata=t.Metadata(name="bp"),
            spec=t.CellBlueprintSpec(
                params=[t.BlueprintParam(name="img", default="tool:v1")],
                cell=t.CellSpec(containers=[
                    t.ContainerSpec(name="m", image="${img}"),
                ]),
            ),
        ))
        ctl.put_config(t.Document(
            kind=t.KIND_CELL_CONFIG, metadata=t.Metadata(name="cfg"),
            spec=t.CellConfigSpec(blueprint="bp", values={"img": "tool:v2"}),
        ))
        in_use = ctl.images_in_use()
        assert {"tool:v1", "tool:v2"} <= in_use
        assert istore.prune(in_use) == []

    def test_rebuild_keeps_displaced_bundle_until_gc(self, store, tmp_path):
        ctx = tmp_path / "ctx"
        ctx.mkdir()
        (ctx / "a.sh").write_text("echo a")
        kf = tmp_path / "K"
        kf.write_text("FROM scratch\nCOPY a.sh /bin/a.sh\n")
        ImageBuilder(store).build(str(kf), str(ctx), "app:v1")
        old_rootfs = store.rootfs("app:v1")
        # A "running cell" holds a file open in the old rootfs.
        held = os.path.join(old_rootfs, "bin/a.sh")
        assert os.path.exists(held)
        ImageBuilder(store).build(str(kf), str(ctx), "app:v1")
        # Displaced bundle moved to .trash, not deleted: the old tree still
        # exists until gc, and NEVER shows up in list()/prune().
        trash = os.path.join(store.root, ".trash")
        olds = os.listdir(trash)
        assert len(olds) == 1
        assert os.path.exists(os.path.join(trash, olds[0], "rootfs/bin/a.sh"))
        assert [m.ref for m in store.list()] == ["app:v1"]   # no phantom dup
        # Prune with the ref unused deletes it exactly once (regression:
        # the .old duplicate made the second delete raise NotFound).
        assert store.prune(in_use=set()) == ["app:v1"]
        assert store.gc_old() == 0   # delete->prune already gc'd the trash

    def test_bare_env_is_build_error(self, store, tmp_path):
        ctx = tmp_path / "ctx"
        ctx.mkdir()
        kf = tmp_path / "K"
        kf.write_text("FROM scratch\nENV\n")
        with pytest.raises(InvalidArgument, match="ENV wants"):
            ImageBuilder(store).build(str(kf), str(ctx), "x:v1")

    def test_image_workdir_ignores_existing_host_dir(self, tmp_path):
        """WORKDIR /tmp (exists on every host) must STILL resolve into the
        rootfs for an image-backed container."""
        from kukeon_tpu.runtime.cells.process import ProcessBackend

        rp = str(tmp_path / "rp")
        istore = ImageStore(rp)
        istore.put(ImageManifest(name="wd", tag="v1", entrypoint=["pwd"],
                                 workdir="/tmp"))
        store = ResourceStore(MetadataStore(rp))
        backend = FakeBackend()
        ctl = Controller(store, Runner(store, backend))
        ctl.bootstrap()
        ctl.create_cell(t.Document(
            kind=t.KIND_CELL,
            metadata=t.Metadata(name="c1", realm=consts.DEFAULT_REALM,
                                space=consts.DEFAULT_SPACE,
                                stack=consts.DEFAULT_STACK),
            spec=t.CellSpec(containers=[t.ContainerSpec(name="main", image="wd:v1")]),
        ))
        mapped = ProcessBackend._overlay_workdir(backend.started[-1])
        assert mapped == os.path.join(istore.rootfs("wd:v1"), "tmp")


class TestAdviceRound2:
    def test_workdir_escape_rejected(self, tmp_path):
        """A tar-imported manifest workdir with '..' must not resolve to a
        host path outside the image rootfs (ADVICE r1, medium)."""
        from kukeon_tpu.runtime.cells.backend import ContainerContext
        from kukeon_tpu.runtime.cells.process import ProcessBackend

        rootfs = tmp_path / "rootfs"
        rootfs.mkdir()
        ctx = ContainerContext(
            container_dir=str(tmp_path),
            env={"KUKEON_IMAGE_ROOTFS": str(rootfs)},
            workdir="/../../pwned",
        )
        with pytest.raises(InvalidArgument, match="escapes"):
            ProcessBackend._overlay_workdir(ctx)
        assert not (tmp_path.parent / "pwned").exists()

    def test_workdir_dotdot_inside_rootfs_ok(self, tmp_path):
        from kukeon_tpu.runtime.cells.backend import ContainerContext
        from kukeon_tpu.runtime.cells.process import ProcessBackend

        rootfs = tmp_path / "rootfs"
        rootfs.mkdir()
        ctx = ContainerContext(
            container_dir=str(tmp_path),
            env={"KUKEON_IMAGE_ROOTFS": str(rootfs)},
            workdir="/a/../b",
        )
        assert ProcessBackend._overlay_workdir(ctx) == str(rootfs / "b")
