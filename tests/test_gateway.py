"""Replica gateway (ISSUE 5): prefix-affinity routing, retry-on-sibling,
ndjson streaming passthrough, rolling restarts with stable chip grants, and
the scrape/CLI/bench surfaces that ride along.

Replica failure is always *scripted* (shed flags, RST injection, abrupt
server close), never timed — the same philosophy as the resilience suite."""

from __future__ import annotations

import http.client
import json
import os
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kukeon_tpu import obs
from kukeon_tpu.gateway.cell import GatewayCell, make_gateway_handler
from kukeon_tpu.gateway.router import (
    POLICY_AFFINITY,
    POLICY_AFFINITY_FALLBACK,
    POLICY_LEAST_LOADED,
    Router,
)
from kukeon_tpu.runtime.api import types as t
from kukeon_tpu.runtime.cells import FakeBackend
from kukeon_tpu.runtime.controller import Controller
from kukeon_tpu.runtime.devices import TPUDeviceManager
from kukeon_tpu.runtime.metadata import MetadataStore
from kukeon_tpu.runtime.runner import Runner, RunnerOptions
from kukeon_tpu.runtime.store import ResourceStore

from test_obs import _parse_expo


# --- fake replica ------------------------------------------------------------


class FakeReplica:
    """A serving cell stand-in speaking exactly the surface the gateway and
    the rollout machinery consume — /v1/generate (+stream), /v1/stats,
    /readyz, /healthz, /drain — with scripted failure modes:

    - ``shed_429``: every generate sheds 429 + Retry-After (queue full)
    - ``stream_script``: exact bytes to emit as the stream body (the
      byte-for-byte passthrough fixtures)
    - ``stream_rst_after``: emit K ndjson lines then RST the connection
      (a replica process dying mid-stream)
    - ``drain``: stops admitting (503), waits out in-flight work, then
      shuts its HTTP server down — like the real cell exiting post-drain.
    """

    def __init__(self, port: int = 0, tokens: int = 3, delay_s: float = 0.0):
        self.tokens = tokens
        self.delay_s = delay_s
        self.ready = True
        self.draining = False
        self.drained = False
        self.queue_depth = 0
        self.shed_429 = False
        self.stream_script: bytes | None = None
        self.stream_rst_after: int | None = None
        self.requests = 0
        self.prefix_ids: list[str | None] = []
        self.inflight = 0
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):
                pass

            def _json(self, code, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/readyz":
                    if outer.ready and not outer.draining:
                        self._json(200, {"ready": True})
                    else:
                        self._json(503, {"ready": False, "reason":
                                         "draining" if outer.draining
                                         else "not ready"})
                elif self.path == "/v1/stats":
                    self._json(200, outer.stats())
                elif self.path in ("/healthz", "/v1/health"):
                    self._json(200, {"status": "ok"})
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/drain":
                    self._json(200, {"draining": True,
                                     "started": outer.begin_drain()})
                    return
                if self.path != "/v1/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                if outer.draining or not outer.ready:
                    self._json(503, {"error": "not admitting: draining"},
                               {"Retry-After": "1"})
                    return
                if outer.shed_429:
                    self._json(429, {"error": "queue full"},
                               {"Retry-After": "1"})
                    return
                with outer._lock:
                    outer.requests += 1
                    outer.prefix_ids.append(req.get("prefixId"))
                    outer.inflight += 1
                try:
                    if outer.delay_s:
                        time.sleep(outer.delay_s)
                    if req.get("stream"):
                        self._stream()
                        return
                    self._json(200, {"tokens": list(range(outer.tokens)),
                                     "text": "x" * outer.tokens,
                                     "numTokens": outer.tokens,
                                     "seconds": 0.0})
                finally:
                    with outer._lock:
                        outer.inflight -= 1

            def _stream(self):
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.end_headers()
                if outer.stream_script is not None:
                    self.wfile.write(outer.stream_script)
                    self.wfile.flush()
                    return
                for i in range(outer.tokens):
                    if (outer.stream_rst_after is not None
                            and i >= outer.stream_rst_after):
                        # RST, not FIN: a dying process, not a clean close.
                        # The pause lets the gateway relay the flushed
                        # lines first (an RST discards data still sitting
                        # in the receiver's kernel buffer).
                        self.wfile.flush()
                        time.sleep(0.2)
                        self.connection.setsockopt(
                            socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
                        self.connection.close()
                        return
                    self.wfile.write((json.dumps(
                        {"token": i, "text": f"t{i}"}) + "\n").encode())
                    self.wfile.flush()
                self.wfile.write((json.dumps(
                    {"done": True, "numTokens": outer.tokens}) + "\n"
                ).encode())

        self.server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def stats(self) -> dict:
        return {"model": "tiny",
                "ready": self.ready and not self.draining,
                "draining": self.draining,
                "queueDepth": self.queue_depth,
                "inflight": self.inflight}

    def begin_drain(self) -> bool:
        if self.draining:
            return False
        self.draining = True

        def _loop():
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and self.inflight:
                time.sleep(0.02)
            self.drained = True
            self.kill()

        threading.Thread(target=_loop, daemon=True).start()
        return True

    def kill(self) -> None:
        """Stop serving (new dials get connection refused)."""
        try:
            self.server.shutdown()
            self.server.server_close()
        except OSError:
            pass


def _gateway(replicas: list[FakeReplica], **kw) -> tuple[GatewayCell, int]:
    kw.setdefault("poll_interval_s", 0.05)
    kw.setdefault("request_timeout_s", 30.0)
    gw = GatewayCell("tiny", [r.url for r in replicas], **kw)
    gw.start()
    srv = ThreadingHTTPServer(("127.0.0.1", 0), make_gateway_handler(gw))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    gw.router.poll_once()
    gw._test_server = srv   # keep a handle for teardown
    return gw, srv.server_address[1]


def _teardown(gw: GatewayCell, *replicas: FakeReplica) -> None:
    gw._test_server.shutdown()
    gw._test_server.server_close()
    gw.stop()
    for r in replicas:
        r.kill()


def _post(port: int, path: str, body: dict, timeout: float = 30.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    raw = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, raw, headers


# --- router units ------------------------------------------------------------


def _static_router(n=3) -> Router:
    r = Router([(f"r{i}", f"http://127.0.0.1:{20000 + i}")
                for i in range(n)])
    for rep in r.replicas:
        rep.ready = True
    return r


def test_router_picks_least_loaded():
    r = _static_router()
    r.by_name["r0"].queue_depth = 5
    r.by_name["r1"].queue_depth = 1
    r.by_name["r2"].queue_depth = 3
    rep, policy = r.pick()
    assert (rep.name, policy) == ("r1", POLICY_LEAST_LOADED)
    # Gateway-side inflight breaks the polled tie.
    r.by_name["r1"].queue_depth = 3
    r.by_name["r1"].begin()
    r.by_name["r2"].queue_depth = 3
    rep, _ = r.pick()
    assert rep.name == "r2"


def test_router_affinity_is_stable_and_falls_back():
    r = _static_router()
    picks = {r.pick(prefix_id=f"sess-{i}")[0].name for _ in range(5)
             for i in range(8)}
    # Same prefix always lands on the same replica...
    for i in range(8):
        first = r.pick(prefix_id=f"sess-{i}")
        assert first[1] == POLICY_AFFINITY
        for _ in range(5):
            assert r.pick(prefix_id=f"sess-{i}")[0].name == first[0].name
    assert len(picks) > 1          # ...and 8 sessions spread over >1 replica
    # Unready affine replica: fall back to least-loaded, and the mapping
    # SNAPS BACK once it recovers (rendezvous hashes the full set).
    sess = "sess-0"
    home = r.affine(sess)
    home.ready = False
    rep, policy = r.pick(prefix_id=sess)
    assert policy == POLICY_AFFINITY_FALLBACK and rep.name != home.name
    home.ready = True
    assert r.pick(prefix_id=sess)[0].name == home.name
    # Nothing ready: nothing routable.
    for rep in r.replicas:
        rep.ready = False
    assert r.pick(prefix_id=sess) == (None, None)


# --- gateway proxy -----------------------------------------------------------


def test_gateway_proxies_and_counts_per_replica():
    a, b = FakeReplica(), FakeReplica()
    gw, port = _gateway([a, b])
    try:
        for i in range(6):
            status, raw, _ = _post(port, "/v1/generate",
                                   {"prompt": "hi", "maxNewTokens": 3})
            assert status == 200
            assert json.loads(raw)["numTokens"] == 3
        assert a.requests + b.requests == 6
        # /v1/stats mirrors the routing view; /metrics golden-parses and
        # carries the per-replica families.
        stats_conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        stats_conn.request("GET", "/v1/stats")
        stats = json.loads(stats_conn.getresponse().read())
        stats_conn.close()
        assert stats["kind"] == "gateway"
        assert stats["readyReplicas"] == 2
        assert len(stats["replicas"]) == 2
        mconn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        mconn.request("GET", "/metrics")
        fams = _parse_expo(mconn.getresponse().read().decode())
        mconn.close()
        assert "kukeon_gateway_requests_total" in fams
        ready = {lab["replica"]: float(v) for _n, lab, v
                 in fams["kukeon_gateway_replica_ready"]["samples"]}
        assert ready == {"r0": 1.0, "r1": 1.0}
    finally:
        _teardown(gw, a, b)


def test_gateway_readyz_and_healthz():
    a = FakeReplica()
    gw, port = _gateway([a])
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/readyz")
        assert conn.getresponse().status == 200
        conn.close()
        a.ready = False
        gw.router.poll_once()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/readyz")
        resp = conn.getresponse()
        assert resp.status == 503
        conn.close()
        # Liveness never depends on the replicas.
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 200
        conn.close()
    finally:
        _teardown(gw, a)


def test_prefix_affinity_sticks_through_the_gateway():
    """Acceptance: each prefix_id lands on exactly ONE replica (per-replica
    request counters), and the gateway's choice matches the router policy."""
    a, b = FakeReplica(), FakeReplica()
    gw, port = _gateway([a, b])
    try:
        prefixes = [f"agent-{i}" for i in range(8)]
        for _round in range(3):
            for p in prefixes:
                status, _raw, _ = _post(port, "/v1/generate",
                                        {"prompt": "x", "prefixId": p})
                assert status == 200
        by_replica = {"r0": set(a.prefix_ids), "r1": set(b.prefix_ids)}
        for p in prefixes:
            seen = [name for name, ids in by_replica.items() if p in ids]
            assert len(seen) == 1, f"{p} split across replicas: {seen}"
            assert seen[0] == gw.router.affine(p).name
        # 8 sessions spread over both replicas (deterministic hash).
        assert a.prefix_ids and b.prefix_ids
        fams = _parse_expo(obs.expo.render(gw.registry))
        routing = {lab["policy"]: float(v) for _n, lab, v
                   in fams["kukeon_gateway_routing_total"]["samples"]}
        assert routing.get("affinity") == 24.0
    finally:
        _teardown(gw, a, b)


def test_retry_on_shedding_replica_then_passthrough_when_all_shed():
    # spill_capacity=0: this test pins the PASSTHROUGH contract (what an
    # all-shed storm degrades to when the spillover queue is full); the
    # spillover queue itself is covered in tests/test_scaler.py.
    a, b = FakeReplica(), FakeReplica()
    gw, port = _gateway([a, b], spill_capacity=0)
    try:
        # Aim at a prefix whose home is r0, then make r0 shed.
        sess = next(p for p in (f"s{i}" for i in range(64))
                    if gw.router.affine(p).name == "r0")
        a.shed_429 = True
        status, raw, _ = _post(port, "/v1/generate",
                               {"prompt": "x", "prefixId": sess})
        assert status == 200                    # retried onto r1
        assert b.requests == 1 and a.requests == 0
        assert gw.registry.get("kukeon_gateway_retries_total").value(
            reason="status_429") == 1
        assert gw.registry.get("kukeon_gateway_requests_total").value(
            replica="r0", outcome="shed") == 1
        # Both shedding: the last replica's 429 passes through, with
        # Retry-After intact, so the client backs off instead of erroring.
        b.shed_429 = True
        status, raw, headers = _post(port, "/v1/generate", {"prompt": "x"})
        assert status == 429
        assert "Retry-After" in headers
        assert "queue full" in json.loads(raw)["error"]
    finally:
        _teardown(gw, a, b)


def test_draining_replica_leaves_rotation_and_503_retries():
    a, b = FakeReplica(), FakeReplica()
    gw, port = _gateway([a, b])
    try:
        sess = next(p for p in (f"s{i}" for i in range(64))
                    if gw.router.affine(p).name == "r0")
        # The replica turns draining BETWEEN polls: the gateway's first
        # contact is the 503, which must demote + retry transparently.
        a.draining = True
        status, _raw, _ = _post(port, "/v1/generate",
                                {"prompt": "x", "prefixId": sess})
        assert status == 200
        assert b.requests == 1
        assert gw.registry.get("kukeon_gateway_retries_total").value(
            reason="status_503") == 1
        assert not gw.router.by_name["r0"].ready   # demoted on the spot
    finally:
        _teardown(gw, a, b)


def test_no_replica_available_sheds_503_with_retry_after():
    # spill_capacity=0 pins the terminal 503 shape (see the spillover
    # suite in tests/test_scaler.py for the parking behavior).
    a, b = FakeReplica(), FakeReplica()
    gw, port = _gateway([a, b], spill_capacity=0)
    try:
        a.ready = False
        b.ready = False
        gw.router.poll_once()
        status, raw, headers = _post(port, "/v1/generate", {"prompt": "x"})
        assert status == 503
        assert "Retry-After" in headers
        assert gw.registry.get("kukeon_gateway_shed_total").value() == 1
        assert a.requests == b.requests == 0
    finally:
        _teardown(gw, a, b)


# --- streaming passthrough (PR-1 fixtures through the proxy) -----------------


def test_stream_passthrough_is_byte_exact():
    """The two PR-1 streaming invariants must survive the proxy BYTE FOR
    BYTE: raw multi-byte UTF-8 in a delta (the split-codepoint holdback
    shape) and an in-band terminal {"error": ...} line."""
    script = ('{"token": 104, "text": "h"}\n'
              '{"token": 195, "text": ""}\n'
              '{"token": 169, "text": "é"}\n'
              '{"token": 33, "text": "!"}\n'
              '{"error": "RuntimeError: device lost mid-stream"}\n'
              ).encode()
    a = FakeReplica()
    a.stream_script = script
    gw, port = _gateway([a])
    try:
        status, raw, headers = _post(port, "/v1/generate",
                                     {"prompt": "x", "stream": True})
        assert status == 200
        assert headers["Content-Type"] == "application/x-ndjson"
        assert raw == script
        # A script WITHOUT a trailing newline is also untouched (the
        # gateway only ever appends on a mid-stream failure).
        a.stream_script = b'{"token": 1, "text": "a"}\n{"done": true}'
        _status, raw, _ = _post(port, "/v1/generate",
                                {"prompt": "x", "stream": True})
        assert raw == a.stream_script
    finally:
        _teardown(gw, a)


def test_stream_through_gateway_from_real_cell_holds_back_split_utf8():
    """End-to-end with the REAL serving cell streaming machinery (the PR-1
    split-codepoint fixture): deltas that cross the gateway must join to
    the exact final text with no U+FFFD ever on the wire."""
    from http.server import ThreadingHTTPServer as HS

    from kukeon_tpu.runtime.serving_cell import ServingCell, make_handler

    cell = ServingCell("tiny", num_slots=2, max_seq_len=64,
                       checkpoint=None, dtype=None)
    script = [0x68] + list("é".encode()) + [0x21]     # "h", é split, "!"

    class FakeReq:
        def __init__(self):
            self.done = threading.Event()
            self.error = None
            self.cancelled = False
            self.timed_out = False

        def cancel(self):
            self.cancelled = True

    class FakeEngine:
        # The cell's /v1/stats (which the gateway polls for routing) reads
        # these engine fields; keep the surface the real engine presents.
        _running = True
        _requests: dict = {}
        prefix_hits = 0
        prefix_misses = 0
        _prefix_cache: dict = {}
        decode_chunk = 4
        kv_cache_int8 = False
        page_tokens = 0
        kv_pool_pages = 0
        _pool = None
        tune = None
        max_pending = None
        shed_stats = {"rejected": 0, "timed_out": 0, "kv_exhausted": 0}

        def submit(self, prompt, sp, emit=None, prefix_id=None,
                   deadline_s=None, trace_ctx=None):
            r = FakeReq()
            for i, tok in enumerate(script):
                emit(tok, i == len(script) - 1)
            r.done.set()
            return r

    cell.engine = FakeEngine()
    cell.mark_ready()
    srv = HS(("127.0.0.1", 0), make_handler(cell))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    rep_url = f"http://127.0.0.1:{srv.server_address[1]}"
    gw = GatewayCell("tiny", [rep_url], poll_interval_s=0.05)
    gw.start()
    gsrv = ThreadingHTTPServer(("127.0.0.1", 0), make_gateway_handler(gw))
    threading.Thread(target=gsrv.serve_forever, daemon=True).start()
    gw.router.poll_once()
    try:
        status, raw, _ = _post(gsrv.server_address[1], "/v1/generate",
                               {"prompt": "x", "maxNewTokens": 8,
                                "stream": True})
        assert status == 200
        lines = [json.loads(x) for x in raw.decode().splitlines()]
        deltas = [r["text"] for r in lines[:-1]]
        assert deltas == ["h", "", "é", "!"]
        assert "".join(deltas) == "hé!" == lines[-1]["text"]
        assert not any("�" in d for d in deltas)
    finally:
        gsrv.shutdown()
        gsrv.server_close()
        gw.stop()
        srv.shutdown()
        srv.server_close()


def test_midstream_replica_death_surfaces_in_band():
    """A replica dying mid-stream (RST) must produce an in-band terminal
    error line — never a retry (bytes already reached the client), never a
    second status line, never a hang."""
    a = FakeReplica(tokens=6)
    a.stream_rst_after = 2
    gw, port = _gateway([a])
    try:
        status, raw, _ = _post(port, "/v1/generate",
                               {"prompt": "x", "stream": True})
        assert status == 200
        assert b"HTTP/" not in raw
        lines = [json.loads(x) for x in raw.decode().splitlines()]
        assert lines[0] == {"token": 0, "text": "t0"}
        assert lines[1] == {"token": 1, "text": "t1"}
        assert "replica failed mid-stream" in lines[-1]["error"]
        assert a.requests == 1            # no second replica, no retry
        assert gw.registry.get("kukeon_gateway_requests_total").value(
            replica="r0", outcome="stream_error") == 1
    finally:
        _teardown(gw, a)


# --- acceptance: kill a replica mid-flood ------------------------------------


def test_kill_replica_mid_flood_yields_only_429_or_in_band():
    """Acceptance: 2 replicas under flood, one killed mid-flood — every
    non-stream response is 200/429 (no 500s, no gateway mystery codes), no
    request hangs, and the survivor absorbs the traffic."""
    a, b = FakeReplica(delay_s=0.005), FakeReplica(delay_s=0.005)
    gw, port = _gateway([a, b])
    statuses: list[int] = []
    errors: list[str] = []
    lock = threading.Lock()
    stop = threading.Event()

    def flood(i: int):
        while not stop.is_set():
            try:
                status, _raw, _ = _post(port, "/v1/generate",
                                        {"prompt": "x",
                                         "prefixId": f"sess-{i}"},
                                        timeout=30)
                with lock:
                    statuses.append(status)
            except Exception as e:  # noqa: BLE001 — a transport error is a failure
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

    try:
        threads = [threading.Thread(target=flood, args=(i,))
                   for i in range(6)]
        for th in threads:
            th.start()
        time.sleep(0.3)
        a.kill()                          # one replica dies mid-flood
        time.sleep(0.6)
        stop.set()
        for th in threads:
            th.join(timeout=60)
        assert not any(th.is_alive() for th in threads), "flood thread hung"
        assert not errors, errors
        assert statuses, "flood produced no responses"
        bad = [s for s in statuses if s not in (200, 429)]
        assert not bad, f"non-200/429 statuses: {sorted(set(bad))}"
        # The survivor actually took traffic after the kill.
        assert b.requests > 0
    finally:
        stop.set()
        _teardown(gw, a, b)


# --- rolling restart ---------------------------------------------------------


@pytest.fixture
def replicated_ctl(tmp_path):
    """Controller (fake backend, 4 chips) — the chip/lifecycle half of the
    rollout story; HTTP replicas ride separately per test."""
    store = ResourceStore(MetadataStore(str(tmp_path)))
    backend = FakeBackend()
    devices = TPUDeviceManager(store.ms, chips=[0, 1, 2, 3])
    runner = Runner(store, backend, cgroups=None, devices=devices,
                    options=RunnerOptions(stop_grace_s=0.2),
                    registry=obs.Registry())
    ctl = Controller(store, runner)
    ctl.bootstrap()
    return ctl, backend, store, devices


def _free_port_block(n: int) -> int:
    """Base of n consecutive free TCP ports (the replicated ModelSpec's
    port..port+n layout needs real contiguous ports in these tests)."""
    for _attempt in range(50):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
        s.close()
        probes = []
        try:
            for p in range(base, base + n):
                x = socket.socket()
                x.bind(("127.0.0.1", p))
                probes.append(x)
            return base
        except OSError:
            continue
        finally:
            for x in probes:
                x.close()
    raise RuntimeError("no contiguous port block found")


def test_runner_materializes_replicas_and_gateway(replicated_ctl):
    ctl, backend, store, devices = replicated_ctl
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                          replicas=2, port=9300)),
    )
    ctl.create_cell(doc)
    started = {c.spec.name: c for c in backend.started}
    assert set(started) == {"model-server-0", "model-server-1", "gateway"}
    # Base-port scheme: replicas above the base, gateway ON the base.
    assert "9301" in " ".join(started["model-server-0"].command)
    assert "9302" in " ".join(started["model-server-1"].command)
    gcmd = started["gateway"].command
    assert "kukeon_tpu.gateway.cell" in " ".join(gcmd)
    assert gcmd[gcmd.index("--port") + 1] == "9300"
    assert [u for f, u in zip(gcmd, gcmd[1:]) if f == "--replica"] == [
        "http://127.0.0.1:9301", "http://127.0.0.1:9302"]
    # Chips partition deterministically; the gateway gets none.
    assert started["model-server-0"].env["TPU_VISIBLE_DEVICES"] == "0"
    assert started["model-server-1"].env["TPU_VISIBLE_DEVICES"] == "1"
    assert "TPU_VISIBLE_DEVICES" not in started["gateway"].env
    rec = store.read_cell("default", "default", "default", "llm")
    assert rec.status.tpu_chips == [0, 1]


def test_runner_materializes_disagg_roles(replicated_ctl):
    """`role: "prefill,decode"` assigns one role atom per replica in
    declaration order (the same order the base-port scheme assigns ports);
    the gateway container gets NO role flags — it discovers pools from
    each cell's /v1/stats census."""
    ctl, backend, _store, _devices = replicated_ctl
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                          replicas=2, port=9300,
                                          role="prefill,decode")),
    )
    ctl.create_cell(doc)
    started = {c.spec.name: c for c in backend.started}
    cmd0 = started["model-server-0"].command
    cmd1 = started["model-server-1"].command
    assert cmd0[cmd0.index("--role") + 1] == "prefill"
    assert cmd1[cmd1.index("--role") + 1] == "decode"
    assert "--role" not in started["gateway"].command
    # The mixed default stays flag-free: byte-identical to before roles.
    from kukeon_tpu.runtime.api.types import ModelSpec
    from kukeon_tpu.runtime.runner import Runner  # noqa: F401 — ctl.runner

    for c in ctl.runner._model_containers(
            ModelSpec(model="tiny", chips=1, replicas=2, port=9400)):
        assert "--role" not in c.command


def test_rolling_restart_under_flood_zero_failures(replicated_ctl,
                                                   monkeypatch):
    """Acceptance + satellite: flood the gateway while RolloutCell rolls
    both replicas; zero non-429 failures, and every replica comes back on
    its exact chip grant."""
    from kukeon_tpu.runtime import daemon as dmod

    ctl, backend, store, devices = replicated_ctl
    base = _free_port_block(3)
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                          replicas=2, port=base)),
    )
    ctl.create_cell(doc)

    replicas = {0: FakeReplica(port=base + 1, delay_s=0.003),
                1: FakeReplica(port=base + 2, delay_s=0.003)}
    gw, gport = _gateway([replicas[0], replicas[1]])

    grants: dict[str, list[str]] = {}
    real_restart = dmod._rollout_restart

    def restart_and_respawn(ctl_, rec, cname):
        i = int(cname.rsplit("-", 1)[1])
        # The drained fake shut its server down (kill() is the idempotent
        # backstop — wait_drained can win the race against the drain
        # loop's own shutdown, and the port must be free before respawn);
        # a real drained cell exits 0 — mirror that in the fake backend
        # before the runner restart.
        replicas[i].kill()
        cdir = store.container_dir(rec.realm, rec.space, rec.stack,
                                   rec.name, cname)
        backend.exit(cdir, 0)
        real_restart(ctl_, rec, cname)
        grants.setdefault(cname, []).append(
            backend.started[-1].env["TPU_VISIBLE_DEVICES"])
        replicas[i] = FakeReplica(port=base + 1 + i, delay_s=0.003)

    monkeypatch.setattr(dmod, "_rollout_restart", restart_and_respawn)
    service = dmod.RPCService(ctl)

    statuses: list[int] = []
    errors: list[str] = []
    lock = threading.Lock()
    stop = threading.Event()

    def flood(i: int):
        while not stop.is_set():
            try:
                status, _raw, _ = _post(gport, "/v1/generate",
                                        {"prompt": "x",
                                         "prefixId": f"sess-{i}"},
                                        timeout=30)
                with lock:
                    statuses.append(status)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=flood, args=(i,)) for i in range(4)]
    for th in threads:
        th.start()
    try:
        out = service.RolloutCell("default", "default", "default", "llm",
                                  drainTimeoutS=15.0, readyTimeoutS=15.0)
    finally:
        time.sleep(0.2)
        stop.set()
        for th in threads:
            th.join(timeout=60)
        _teardown(gw, *replicas.values())
    assert not any(th.is_alive() for th in threads), "flood thread hung"

    # The rollout touched both replicas, in order, and reported readiness.
    assert [r["replica"] for r in out["replicas"]] == [
        "model-server-0", "model-server-1"]
    assert all(r["drained"] for r in out["replicas"])
    # Zero failed requests: every response 200 (or an honest 429 shed).
    assert not errors, errors
    assert statuses, "flood produced no responses"
    bad = [s for s in statuses if s not in (200, 429)]
    assert not bad, f"non-200/429 statuses during rollout: {sorted(set(bad))}"
    # Each replica came back on ITS chip grant.
    assert grants == {"model-server-0": ["0"], "model-server-1": ["1"]}
    rec = store.read_cell("default", "default", "default", "llm")
    assert rec.status.tpu_chips == [0, 1]
    assert rec.status.container("model-server-0").restarts == 1
    assert rec.status.container("model-server-1").restarts == 1


def test_rollout_rejects_unreplicated_cell(replicated_ctl):
    from kukeon_tpu.runtime import daemon as dmod
    from kukeon_tpu.runtime.errors import FailedPrecondition

    ctl, _backend, _store, _devices = replicated_ctl
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="solo"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1, port=9400)),
    )
    ctl.create_cell(doc)
    service = dmod.RPCService(ctl)
    with pytest.raises(FailedPrecondition, match="replicas"):
        service.RolloutCell("default", "default", "default", "solo")


def test_rolling_restart_aborts_when_replica_never_ready():
    from kukeon_tpu.gateway import RolloutError, RolloutStep, rolling_restart

    a = FakeReplica()
    step = RolloutStep(name="model-server-0", url=a.url,
                       restart=lambda: None)    # nothing comes back up
    with pytest.raises(RolloutError, match="did not become ready"):
        rolling_restart([step], drain_timeout_s=3.0, ready_timeout_s=0.5,
                        poll_s=0.05)


# --- federation / scrape / CLI surfaces --------------------------------------


def test_model_cell_endpoints_cover_gateway_and_replicas(replicated_ctl):
    from kukeon_tpu.runtime.daemon import model_cell_endpoints

    ctl, _backend, _store, _devices = replicated_ctl
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                          replicas=2, port=9300)),
    )
    ctl.create_cell(doc)
    eps = {key: url for key, url, _rec in model_cell_endpoints(ctl)}
    assert eps == {
        "default/default/default/llm": "http://127.0.0.1:9300",
        "default/default/default/llm/r0": "http://127.0.0.1:9301",
        "default/default/default/llm/r1": "http://127.0.0.1:9302",
    }


def test_scrape_cells_renders_gateway_row(replicated_ctl):
    """ScrapeCells summarizes a gateway endpoint with aggregate QPS,
    retries, and the replica-ready census; the (dead here) replica rows
    still appear instead of silently vanishing."""
    from kukeon_tpu.runtime import daemon as dmod

    ctl, _backend, _store, _devices = replicated_ctl
    live = FakeReplica()
    gw = GatewayCell("tiny", [live.url, "http://127.0.0.1:9"],
                     poll_interval_s=0.05)
    gsrv = ThreadingHTTPServer(("127.0.0.1", 0), make_gateway_handler(gw))
    threading.Thread(target=gsrv.serve_forever, daemon=True).start()
    gw.router.poll_once()
    gport = gsrv.server_address[1]
    # A couple of proxied requests so QPS/retry counters are non-trivial.
    for _ in range(3):
        assert _post(gport, "/v1/generate", {"prompt": "x"})[0] == 200
    doc = t.Document(
        kind=t.KIND_CELL, metadata=t.Metadata(name="llm"),
        spec=t.CellSpec(model=t.ModelSpec(model="tiny", chips=1,
                                          replicas=2, port=gport)),
    )
    ctl.create_cell(doc)
    service = dmod.RPCService(ctl)
    try:
        rows = {r["cell"]: r for r in service.ScrapeCells()["cells"]}
        g = rows["default/default/default/llm"]
        assert g["ok"] and g["kind"] == "gateway"
        assert g["model"] == "tiny"
        assert g["replicas"] == 2 and g["readyReplicas"] == 1
        assert g["ready"] is True
        assert g["qps"] is not None and g["qps"] > 0
        assert "retries" in g
        # Replica rows ride along (down in this fixture, visibly so).
        assert "default/default/default/llm/r0" in rows
        assert "default/default/default/llm/r1" in rows
    finally:
        gsrv.shutdown()
        gsrv.server_close()
        gw.stop()
        live.kill()


def test_kuke_top_renders_gateway_row(capsys, monkeypatch):
    import argparse

    from kukeon_tpu.runtime import cli

    rows = [
        {"cell": "default/default/default/llm", "ok": True,
         "kind": "gateway", "model": "tiny", "qps": 12.5, "retries": 3,
         "readyReplicas": 2, "replicas": 2, "ready": True,
         "phase": "ready", "restarts": 0},
        {"cell": "default/default/default/llm/r0", "ok": True,
         "model": "tiny", "ready": True, "qps": 6.2, "queueDepth": 1,
         "phase": "ready", "restarts": 0},
    ]

    class _Client:
        def call(self, method, **params):
            assert method == "ScrapeCells"
            return {"cells": rows}

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    assert cli.cmd_top(argparse.Namespace(json=False)) == 0
    out = capsys.readouterr().out
    assert "2/2" in out
    assert "gateway, retries=3" in out
    assert "default/default/default/llm/r0" in out


def test_cmd_rollout_prints_replica_progress(capsys, monkeypatch):
    import argparse

    from kukeon_tpu.runtime import cli

    class _Client:
        def call(self, method, **params):
            assert method == "RolloutCell"
            assert params["name"] == "llm"
            return {"cell": "default/default/default/llm",
                    "replicas": [
                        {"replica": "model-server-0", "drained": True,
                         "readyS": 0.4},
                        {"replica": "model-server-1", "drained": True,
                         "readyS": 0.5},
                    ]}

    monkeypatch.setattr(cli, "_client", lambda args: _Client())
    args = argparse.Namespace(name="llm", json=False, realm=None, space=None,
                              stack=None, drain_timeout=60.0,
                              ready_timeout=300.0)
    assert cli.cmd_rollout(args) == 0
    out = capsys.readouterr().out
    assert "model-server-0" in out and "model-server-1" in out
    assert "rollout complete (2 replicas)" in out


# --- bench artifact schema ---------------------------------------------------


def _load_bench():
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "kukeon_bench", os.path.join(root, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_artifact_v7_and_backcompat(tmp_path):
    bench = _load_bench()
    serve = {"backend": "cpu", "n_chips": 2, "model": "tiny",
             "model_id": "tiny", "sessions": 4, "tok_per_s": 100.0,
             "trials": [100.0], "replicas": 3,
             "kv_page_tokens": 16, "max_sessions": 9,
             "ttft_p95_s": 0.25,
             "mesh": {"chips": 2, "tensor": 2, "kv_sharded": True}}
    out = tmp_path / "BENCH_rXX.json"
    bench.write_artifact(str(out), serve,
                         {"vs_baseline": 0.5, "handoff_ms_p50": 12.5,
                          "disagg": {"arms": {}},
                          "diurnal": {"peak_p95_s": 0.8, "failed": 0}})
    art = bench.read_artifact(str(out))
    assert art["schema"] == "kukeon-bench/v7"
    assert art["replicas"] == 3
    assert art["kv_page_tokens"] == 16
    assert art["max_sessions"] == 9
    assert art["ttft_p95_s"] == 0.25
    assert art["handoff_ms_p50"] == 12.5
    assert art["disagg"] == {"arms": {}}
    assert art["diurnal"] == {"peak_p95_s": 0.8, "failed": 0}
    assert art["mesh"] == {"chips": 2, "tensor": 2, "kv_sharded": True}

    # A v1 point (pre-gateway, single engine) reads back as v5: replicas=1,
    # legacy contiguous KV (kv_page_tokens=0), every session resident, no
    # handoff and no diurnal section (neither existed).
    v1 = tmp_path / "BENCH_r05.json"
    v1.write_text(json.dumps({"schema": "kukeon-bench/v1", "backend": "cpu",
                              "tok_per_s": 50.0, "sessions": 4}))
    art = bench.read_artifact(str(v1))
    assert art["schema"] == "kukeon-bench/v7"
    assert art["replicas"] == 1
    assert art["tok_per_s"] == 50.0
    assert art["kv_page_tokens"] == 0
    assert art["max_sessions"] == 4
    assert art["ttft_p95_s"] is None
    assert art["handoff_ms_p50"] is None
    assert art["disagg"] is None
    assert art["diurnal"] is None
    assert art["mesh"] is None

    # A v2 point (pre-paged-KV) keeps its replicas and gains the later
    # fields; its TTFT p95 lifts from the latency percentiles it recorded.
    v2 = tmp_path / "BENCH_r06.json"
    v2.write_text(json.dumps({"schema": "kukeon-bench/v2", "backend": "cpu",
                              "tok_per_s": 60.0, "sessions": 2,
                              "replicas": 2,
                              "latency_s": {"ttft": {"p95": 0.4}}}))
    art = bench.read_artifact(str(v2))
    assert art["schema"] == "kukeon-bench/v7"
    assert art["replicas"] == 2
    assert art["kv_page_tokens"] == 0
    assert art["max_sessions"] == 2
    assert art["ttft_p95_s"] == 0.4

    # A v3 point (pre-disaggregation) gains the v4 and v5 fields.
    v3 = tmp_path / "BENCH_r07.json"
    v3.write_text(json.dumps({"schema": "kukeon-bench/v3", "backend": "cpu",
                              "tok_per_s": 70.0, "sessions": 2,
                              "replicas": 1, "kv_page_tokens": 16,
                              "max_sessions": 4}))
    art = bench.read_artifact(str(v3))
    assert art["schema"] == "kukeon-bench/v7"
    assert art["kv_page_tokens"] == 16
    assert art["max_sessions"] == 4
    assert art["handoff_ms_p50"] is None
    assert art["diurnal"] is None

    # A v4 point (pre-autoscaling) gains only the diurnal section.
    v4 = tmp_path / "BENCH_r08.json"
    v4.write_text(json.dumps({"schema": "kukeon-bench/v4", "backend": "cpu",
                              "tok_per_s": 80.0, "sessions": 2,
                              "replicas": 2, "kv_page_tokens": 16,
                              "max_sessions": 4, "ttft_p95_s": 0.3,
                              "handoff_ms_p50": 10.0,
                              "disagg": {"arms": {}}}))
    art = bench.read_artifact(str(v4))
    assert art["schema"] == "kukeon-bench/v7"
    assert art["ttft_p95_s"] == 0.3
    assert art["handoff_ms_p50"] == 10.0
    assert art["disagg"] == {"arms": {}}
    assert art["diurnal"] is None

    # A v5 point (pre-streamed-boot) gains only the cold-start load
    # sub-phase ledger: explicit None — no disk/cast/upload existed.
    v5 = tmp_path / "BENCH_r09.json"
    v5.write_text(json.dumps({"schema": "kukeon-bench/v5", "backend": "cpu",
                              "tok_per_s": 90.0, "sessions": 2,
                              "replicas": 2, "kv_page_tokens": 16,
                              "max_sessions": 4, "ttft_p95_s": 0.3,
                              "diurnal": {"peak_p95_s": 0.8, "failed": 0},
                              "cold_start": {"p50_s": 30.0}}))
    art = bench.read_artifact(str(v5))
    assert art["schema"] == "kukeon-bench/v7"
    assert art["diurnal"] == {"peak_p95_s": 0.8, "failed": 0}
    assert art["cold_start"] == {"p50_s": 30.0, "load_s": None}
    assert art["mesh"] is None

    # A v6 point (pre-multi-chip) gains only the mesh section: explicit
    # None — single-chip engines had no sharding layout to record.
    v6 = tmp_path / "BENCH_r10.json"
    v6.write_text(json.dumps({"schema": "kukeon-bench/v6", "backend": "cpu",
                              "tok_per_s": 95.0, "sessions": 2,
                              "replicas": 2, "kv_page_tokens": 16,
                              "max_sessions": 4, "ttft_p95_s": 0.3,
                              "cold_start": {"p50_s": 30.0,
                                             "load_s": {"disk": 1.0}}}))
    art = bench.read_artifact(str(v6))
    assert art["schema"] == "kukeon-bench/v7"
    assert art["mesh"] is None
    assert art["cold_start"] == {"p50_s": 30.0, "load_s": {"disk": 1.0}}

    bad = tmp_path / "BENCH_bad.json"
    bad.write_text(json.dumps({"schema": "nope/v9"}))
    with pytest.raises(ValueError, match="schema"):
        bench.read_artifact(str(bad))
