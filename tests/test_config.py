"""Config registry precedence + configuration-document lifecycle
(reference: cmd/config/env.go, internal/serverconfig, internal/clientconfig)."""

import os

import pytest

from kukeon_tpu.runtime import config
from kukeon_tpu.runtime.errors import InvalidArgument


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in config.REGISTRY:
        monkeypatch.delenv(var.env, raising=False)


class TestPrecedence:
    def test_default(self):
        s = config.Settings()
        assert s.get("KUKEOND_RECONCILE_INTERVAL") == 30.0

    def test_doc_beats_default(self):
        s = config.Settings({"reconcileInterval": 5.0})
        assert s.get("KUKEOND_RECONCILE_INTERVAL") == 5.0

    def test_env_beats_doc(self, monkeypatch):
        monkeypatch.setenv("KUKEOND_RECONCILE_INTERVAL", "7.5")
        s = config.Settings({"reconcileInterval": 5.0})
        assert s.get("KUKEOND_RECONCILE_INTERVAL") == 7.5

    def test_flag_beats_env(self, monkeypatch):
        monkeypatch.setenv("KUKEOND_RECONCILE_INTERVAL", "7.5")
        s = config.Settings({"reconcileInterval": 5.0})
        assert s.get("KUKEOND_RECONCILE_INTERVAL", flag_value=2.0) == 2.0

    def test_bool_parsing(self, monkeypatch):
        s = config.Settings()
        for raw, want in (("true", True), ("1", True), ("yes", True),
                          ("false", False), ("0", False), ("off", False)):
            monkeypatch.setenv("KUKEON_NO_DAEMON", raw)
            assert s.get("KUKEON_NO_DAEMON") is want

    def test_doc_string_coerced_to_number(self):
        s = config.Settings({"diskPressureBlockPct": "90"})
        assert s.get("KUKEOND_DISK_PRESSURE_BLOCK_PCT") == 90.0

    def test_bad_env_value_raises(self, monkeypatch):
        monkeypatch.setenv("KUKEOND_RECONCILE_INTERVAL", "soon")
        with pytest.raises(InvalidArgument, match="KUKEOND_RECONCILE_INTERVAL"):
            config.Settings().get("KUKEOND_RECONCILE_INTERVAL")


class TestDocuments:
    def test_absent_file_is_empty_spec(self, tmp_path):
        assert config.load_configuration(str(tmp_path / "nope.yaml"),
                                         config.KIND_SERVER) == {}

    def test_wrong_kind_rejected(self, tmp_path):
        p = tmp_path / "c.yaml"
        p.write_text("kind: Cell\nspec: {}\n")
        with pytest.raises(InvalidArgument, match="kind"):
            config.load_configuration(str(p), config.KIND_SERVER)

    def test_invalid_yaml_is_error_not_silent(self, tmp_path):
        p = tmp_path / "c.yaml"
        p.write_text(":\n  - {broken")
        with pytest.raises(InvalidArgument):
            config.load_configuration(str(p), config.KIND_SERVER)

    def test_write_default_once_and_roundtrip(self, tmp_path):
        p = str(tmp_path / "kukeond.yaml")
        created = config.write_default_server_configuration(
            p, {"runPath": "/x", "reconcileInterval": 12.0}
        )
        assert created is True
        # Never overwrites.
        assert config.write_default_server_configuration(p, {"runPath": "/y"}) is False
        spec = config.load_configuration(p, config.KIND_SERVER)
        assert spec["runPath"] == "/x"
        assert spec["reconcileInterval"] == 12.0
        # Every registry knob with a doc key is present in the document.
        for var in config.REGISTRY:
            if var.key:
                assert var.key in spec, f"missing {var.key}"

    def test_server_settings_feed_resolution(self, tmp_path, monkeypatch):
        rp = str(tmp_path)
        monkeypatch.setenv("KUKEOND_CONFIGURATION", os.path.join(rp, "srv.yaml"))
        with open(os.path.join(rp, "srv.yaml"), "w") as f:
            f.write(
                "kind: ServerConfiguration\n"
                "spec:\n  reconcileInterval: 3.5\n  stopGraceSeconds: 1.0\n"
            )
        s = config.server_settings(rp)
        assert s.get("KUKEOND_RECONCILE_INTERVAL") == 3.5
        assert s.get("KUKEON_STOP_GRACE_SECONDS") == 1.0
        # Env still wins over the document.
        monkeypatch.setenv("KUKEOND_RECONCILE_INTERVAL", "9")
        assert s.get("KUKEOND_RECONCILE_INTERVAL") == 9.0
